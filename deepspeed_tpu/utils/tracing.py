"""Profiler tracing hooks (the reference's nvtx instrumentation, TPU-native).

Role parity with ``deepspeed/utils/nvtx.py:25 instrument_w_nvtx`` (decorator
pushing an nvtx range around every hot function) and the accelerator
``range_push/pop`` API — expressed with ``jax.profiler``: host-side spans use
``TraceAnnotation``, traced-code regions use ``jax.named_scope`` (which names
the HLO ops so device traces attribute time to framework phases), and whole
training windows are captured with ``start_trace``/``stop_trace`` driven by
the engine's ``tracing`` config (viewable in TensorBoard/XProf/Perfetto).
"""

from __future__ import annotations

import functools

import jax

# traced-code scope: names HLO ops (device-side attribution)
named_scope = jax.named_scope


def instrument(name: str | None = None):
    """Decorator: host-side profiler span around the call
    (``instrument_w_nvtx`` analog)."""

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with jax.profiler.TraceAnnotation(label):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def range_push(name: str):
    """Imperative span begin (reference ``accelerator.range_push``). Returns
    the annotation object; pass it to :func:`range_pop`."""
    ann = jax.profiler.TraceAnnotation(name)
    ann.__enter__()
    return ann


def range_pop(ann) -> None:
    ann.__exit__(None, None, None)


class StepTracer:
    """Drives a bounded ``jax.profiler`` capture window over training steps
    (config ``tracing``: start at ``start_step``, run ``num_steps``, write to
    ``trace_dir``), annotating each step for the trace viewer's step view."""

    def __init__(self, cfg, sync_fn=None):
        self.cfg = cfg
        # called before stop_trace: block on in-flight device work so the
        # capture contains the traced steps' device activity (the engine
        # pipelines steps without per-step sync)
        self.sync_fn = sync_fn
        self._active = False
        self._done = False
        self._started_at = 0
        self._step_ann = None
        if cfg.enabled:
            # the capture is only written at stop_trace; guarantee it lands
            # even if the run ends inside the window
            import atexit

            atexit.register(self.close)

    def before_step(self, step: int) -> None:
        if not self.cfg.enabled or self._done:
            return
        # a step that raised mid-window never reached after_step: exit the
        # stale annotation before opening a new one
        self._exit_step_ann()
        # >= so a resumed run (global step already past start_step) still
        # captures its first window
        if not self._active and step >= self.cfg.start_step:
            try:
                jax.profiler.start_trace(self.cfg.trace_dir)
            except Exception as e:
                from deepspeed_tpu.utils.logging import logger

                logger.warning(f"StepTracer: start_trace failed ({e}); "
                               "capture disabled for this run")
                self._finish()
                return
            self._active = True
            self._started_at = step
        if self._active:
            self._step_ann = jax.profiler.StepTraceAnnotation(
                "train_step", step_num=step)
            self._step_ann.__enter__()

    def after_step(self, step: int) -> None:
        self._exit_step_ann()
        if self._active and step >= self._started_at + self.cfg.num_steps - 1:
            self.stop_trace()
            self._finish()

    def stop_trace(self) -> None:
        """End the capture window if one is open. Idempotent and
        exception-safe: a failed step inside the window must not leave an
        unmatched ``jax.profiler.start_trace`` wedging the next capture."""
        self._exit_step_ann()
        if not self._active:
            return
        # flip first: even if the sync or the profiler raises, we never
        # attempt a second stop on the same window
        self._active = False
        try:
            if self.sync_fn is not None:
                self.sync_fn()
        except Exception:
            # device work from the failed step may be poisoned; still try to
            # finalize the capture file
            pass
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            from deepspeed_tpu.utils.logging import logger

            logger.warning(f"StepTracer: stop_trace failed ({e}); "
                           "capture for this window is lost")

    def _exit_step_ann(self) -> None:
        if self._step_ann is not None:
            ann, self._step_ann = self._step_ann, None
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass

    def _finish(self) -> None:
        """Capture complete: drop the engine-capturing sync closure and the
        atexit registration so the tracer doesn't pin the engine (and its
        device arrays) for process lifetime."""
        self._done = True
        self.sync_fn = None
        import atexit

        try:
            atexit.unregister(self.close)
        except Exception:
            pass

    def close(self) -> None:
        self.stop_trace()
        self._finish()
