"""Runtime probing of optional XLA_FLAGS.

Some environments preload a PJRT plugin (e.g. a TPU tunnel) whose shared
library parses ``XLA_FLAGS`` with its *own* flag registry — typically built
against an older XLA than the installed jaxlib.  ``parse_flags_from_env.cc``
F-aborts the whole process on any flag unknown to that registry, so a flag
that is perfectly valid for jaxlib can still be fatal.  The only safe way to
use optional flags is to probe them in a throwaway subprocess and adopt only
what survives.

Mirrors the capability-probe philosophy of the reference's accelerator
selection (``/root/reference/accelerator/real_accelerator.py:51``) applied to
XLA flags instead of device backends.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import tempfile

_PROBE_SNIPPET = (
    "import jax; jax.config.update('jax_platforms', 'cpu'); jax.devices()"
)

# parse_flags_from_env.cc's F-abort message — the one *definitive* rejection
# signal.  Anything else (timeout, import crash, OSError) may be transient and
# must not be cached.
_REJECT_MARKER = b"Unknown flag"


def _cache_path(key: str) -> str:
    return os.path.join(
        tempfile.gettempdir(), f"dstpu_xla_flag_probe_{key}.json"
    )


def probe_extra_xla_flags(
    candidates: list[str],
    base_flags: str = "",
    timeout: float = 120.0,
    use_cache: bool = True,
    env_overrides: dict[str, str | None] | None = None,
    keep_transient: bool = False,
) -> list[str]:
    """Return the subset of ``candidates`` this environment's XLA flag parsers accept.

    Spawns ``python -c "import jax; jax.devices()"`` with
    ``XLA_FLAGS = base_flags + candidates``; on a clean exit all candidates are
    adopted.  Candidates already present in ``base_flags`` are skipped (the
    caller/user set them explicitly — don't second-guess or duplicate them).
    Only *definitive* verdicts are cached on disk: a clean exit, or a child
    that died printing ``Unknown flag``.  Transient failures (timeout, import
    crash) adopt nothing but leave the cache alone so the next run re-probes.

    ``env_overrides`` lets the caller make the probe child's environment match
    the real child it is probing on behalf of (value ``None`` = unset).

    ``keep_transient`` flips the default-deny stance for transient verdicts:
    candidates whose probe fails *indeterminately* (timeout, import crash) are
    adopted instead of dropped.  Use it when the candidates were already in
    the environment — there, dropping on a flaky probe silently changes the
    user's configuration, so only a definitive ``Unknown flag`` rejection may
    remove a flag.  Transient verdicts are never cached either way, so the
    cache stays verdict-pure and shared across both stances.
    """
    base_names = {f.split("=", 1)[0] for f in base_flags.split()}
    candidates = [
        c for c in candidates if c and c.split("=", 1)[0] not in base_names
    ]
    if not candidates:
        return []

    try:
        import jax

        jax_ver = jax.__version__
    except Exception:  # pragma: no cover - jax is a hard dep everywhere else
        jax_ver = "unknown"

    # Key on what determines acceptance: the candidate set, the flag-parser
    # registries in play (proxied by interpreter + jax version), and the env
    # overrides (they change which PJRT plugins load, hence which registries
    # parse the flags).  base_flags is deliberately excluded — acceptance of a
    # flag doesn't depend on which other valid flags accompany it, and
    # including it would fragment the cache across e.g. different
    # --xla_force_host_platform_device_count values.
    # env vars that change which PJRT plugins (and hence flag registries) load
    plugin_env = {
        k: os.environ.get(k)
        for k in ("JAX_PLATFORMS", "JAX_PLATFORM_NAME", "LD_PRELOAD",
                  "TPU_LIBRARY_PATH", "TPU_NAME", "PJRT_DEVICE")
    }
    key_src = json.dumps(
        [sorted(candidates), sys.executable, jax_ver,
         sorted(plugin_env.items(), key=str),
         sorted((env_overrides or {}).items(), key=str)]
    )
    key = hashlib.sha256(key_src.encode()).hexdigest()[:16]
    cache = _cache_path(key)
    if use_cache and os.path.exists(cache):
        try:
            with open(cache) as f:
                return json.load(f)
        except (json.JSONDecodeError, OSError):
            pass

    def _probe(flags: list[str]) -> str:
        """-> 'ok' | 'rejected' | 'transient'"""
        env = dict(os.environ)
        env["XLA_FLAGS"] = (base_flags + " " + " ".join(flags)).strip()
        env.pop("PYTEST_CURRENT_TEST", None)
        for k, v in (env_overrides or {}).items():
            if v is None:
                env.pop(k, None)
            else:
                env[k] = v
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SNIPPET],
                env=env,
                capture_output=True,
                timeout=timeout,
            )
        except (subprocess.TimeoutExpired, OSError):
            return "transient"
        if proc.returncode == 0:
            return "ok"
        if _REJECT_MARKER in proc.stderr or _REJECT_MARKER in proc.stdout:
            return "rejected"
        return "transient"

    verdict = _probe(candidates)
    definitive = verdict != "transient"
    # A transient batch verdict can hide a definitively-bad flag; under
    # keep_transient that flag would otherwise ride through and kill the real
    # child, so bisect on transient batches too, not just rejected ones.
    bisect = len(candidates) > 1 and (
        verdict == "rejected" or (verdict == "transient" and keep_transient)
    )
    if verdict == "ok":
        accepted = list(candidates)
    elif bisect:
        accepted = []
        for c in candidates:
            v = _probe([c])
            if v == "ok":
                accepted.append(c)
            elif v == "transient":
                definitive = False
                if keep_transient:
                    accepted.append(c)
    elif verdict == "transient" and keep_transient:
        accepted = list(candidates)
    else:
        accepted = []

    if use_cache and definitive:
        try:
            with open(cache, "w") as f:
                json.dump(accepted, f)
        except OSError:
            pass
    return accepted


# --xla_<platform>_* flags register only when that platform's backend links
# in, so a child forced onto a different platform F-aborts on them before
# any probe could help.  Used by sanitize_xla_flags to pre-drop statically.
_PLATFORM_PREFIXES = {"cpu": "--xla_cpu", "gpu": "--xla_gpu",
                      "tpu": "--xla_tpu"}


def sanitize_xla_flags(
    flags: str,
    target_platform: str = "cpu",
    timeout: float = 120.0,
    use_cache: bool = True,
    env_overrides: dict[str, str | None] | None = None,
) -> str:
    """Filter an *inherited* ``XLA_FLAGS`` string down to what a child forced
    onto ``target_platform`` can actually parse.

    The failure this guards against: a parent running under TPU (or a stale
    probe cache) leaves platform-specific flags in the environment; a
    subprocess spawned with ``JAX_PLATFORMS=cpu`` then dies in
    ``parse_flags_from_env.cc`` with ``Unknown flag in XLA_FLAGS: ...``
    before running a single line of user code.

    Two passes.  Flags carrying another platform's name prefix
    (``--xla_tpu*`` when forcing CPU, and so on) are dropped statically — the
    target backend never registers them, and probing each costs a subprocess.
    The survivors are then probed in the child's environment
    (``env_overrides`` should match the real child) with
    ``keep_transient=True``: these flags were already in the environment, so
    only a definitive ``Unknown flag`` rejection removes one; flaky probes
    keep it.  Order is preserved.  Returns the sanitized flag string.
    """
    toks = [t for t in flags.split() if t]
    if not toks:
        return ""
    wrong = tuple(p for plat, p in _PLATFORM_PREFIXES.items()
                  if plat != target_platform)
    survivors = [t for t in toks if not t.startswith(wrong)]
    if not survivors:
        return ""
    kept = set(probe_extra_xla_flags(
        survivors, timeout=timeout, use_cache=use_cache,
        env_overrides=env_overrides, keep_transient=True,
    ))
    return " ".join(t for t in survivors if t in kept)
