"""Runtime probing of optional XLA_FLAGS.

Some environments preload a PJRT plugin (e.g. a TPU tunnel) whose shared
library parses ``XLA_FLAGS`` with its *own* flag registry — typically built
against an older XLA than the installed jaxlib.  ``parse_flags_from_env.cc``
F-aborts the whole process on any flag unknown to that registry, so a flag
that is perfectly valid for jaxlib can still be fatal.  The only safe way to
use optional flags is to probe them in a throwaway subprocess and adopt only
what survives.

Mirrors the capability-probe philosophy of the reference's accelerator
selection (``/root/reference/accelerator/real_accelerator.py:51``) applied to
XLA flags instead of device backends.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import tempfile

_PROBE_SNIPPET = (
    "import jax; jax.config.update('jax_platforms', 'cpu'); jax.devices()"
)

# parse_flags_from_env.cc's F-abort message — the one *definitive* rejection
# signal.  Anything else (timeout, import crash, OSError) may be transient and
# must not be cached.
_REJECT_MARKER = b"Unknown flag"


def _cache_path(key: str) -> str:
    return os.path.join(
        tempfile.gettempdir(), f"dstpu_xla_flag_probe_{key}.json"
    )


def probe_extra_xla_flags(
    candidates: list[str],
    base_flags: str = "",
    timeout: float = 120.0,
    use_cache: bool = True,
    env_overrides: dict[str, str | None] | None = None,
) -> list[str]:
    """Return the subset of ``candidates`` this environment's XLA flag parsers accept.

    Spawns ``python -c "import jax; jax.devices()"`` with
    ``XLA_FLAGS = base_flags + candidates``; on a clean exit all candidates are
    adopted.  Candidates already present in ``base_flags`` are skipped (the
    caller/user set them explicitly — don't second-guess or duplicate them).
    Only *definitive* verdicts are cached on disk: a clean exit, or a child
    that died printing ``Unknown flag``.  Transient failures (timeout, import
    crash) adopt nothing but leave the cache alone so the next run re-probes.

    ``env_overrides`` lets the caller make the probe child's environment match
    the real child it is probing on behalf of (value ``None`` = unset).
    """
    base_names = {f.split("=", 1)[0] for f in base_flags.split()}
    candidates = [
        c for c in candidates if c and c.split("=", 1)[0] not in base_names
    ]
    if not candidates:
        return []

    try:
        import jax

        jax_ver = jax.__version__
    except Exception:  # pragma: no cover - jax is a hard dep everywhere else
        jax_ver = "unknown"

    # Key on what determines acceptance: the candidate set, the flag-parser
    # registries in play (proxied by interpreter + jax version), and the env
    # overrides (they change which PJRT plugins load, hence which registries
    # parse the flags).  base_flags is deliberately excluded — acceptance of a
    # flag doesn't depend on which other valid flags accompany it, and
    # including it would fragment the cache across e.g. different
    # --xla_force_host_platform_device_count values.
    # env vars that change which PJRT plugins (and hence flag registries) load
    plugin_env = {
        k: os.environ.get(k)
        for k in ("JAX_PLATFORMS", "JAX_PLATFORM_NAME", "LD_PRELOAD",
                  "TPU_LIBRARY_PATH", "TPU_NAME", "PJRT_DEVICE")
    }
    key_src = json.dumps(
        [sorted(candidates), sys.executable, jax_ver,
         sorted(plugin_env.items(), key=str),
         sorted((env_overrides or {}).items(), key=str)]
    )
    key = hashlib.sha256(key_src.encode()).hexdigest()[:16]
    cache = _cache_path(key)
    if use_cache and os.path.exists(cache):
        try:
            with open(cache) as f:
                return json.load(f)
        except (json.JSONDecodeError, OSError):
            pass

    def _probe(flags: list[str]) -> str:
        """-> 'ok' | 'rejected' | 'transient'"""
        env = dict(os.environ)
        env["XLA_FLAGS"] = (base_flags + " " + " ".join(flags)).strip()
        env.pop("PYTEST_CURRENT_TEST", None)
        for k, v in (env_overrides or {}).items():
            if v is None:
                env.pop(k, None)
            else:
                env[k] = v
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SNIPPET],
                env=env,
                capture_output=True,
                timeout=timeout,
            )
        except (subprocess.TimeoutExpired, OSError):
            return "transient"
        if proc.returncode == 0:
            return "ok"
        if _REJECT_MARKER in proc.stderr or _REJECT_MARKER in proc.stdout:
            return "rejected"
        return "transient"

    verdict = _probe(candidates)
    definitive = verdict != "transient"
    if verdict == "ok":
        accepted = list(candidates)
    elif verdict == "rejected" and len(candidates) > 1:
        accepted = []
        for c in candidates:
            v = _probe([c])
            if v == "ok":
                accepted.append(c)
            elif v == "transient":
                definitive = False
    else:
        accepted = []

    if use_cache and definitive:
        try:
            with open(cache, "w") as f:
                json.dump(accepted, f)
        except OSError:
            pass
    return accepted
