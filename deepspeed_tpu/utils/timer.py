"""Wall-clock and throughput timers.

Role parity with the reference's ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer``, ``ThroughputTimer``). On TPU there are no CUDA
events; synchronization is ``jax.block_until_ready`` on a token array, and
device-side timing belongs to ``jax.profiler`` traces. These timers measure the
host-visible step wall clock, which under JAX async dispatch is the true step
time as long as each step consumes the previous step's outputs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from deepspeed_tpu.utils.logging import log_dist

FORWARD_TIMERS = ["forward"]
BACKWARD_TIMERS = ["backward"]
STEP_TIMERS = ["step"]


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.started = False
        self._start = 0.0
        self._elapsed = 0.0
        self._count = 0

    def start(self, sync: bool = False) -> None:
        if sync:
            _sync_device()
        self._start = time.perf_counter()
        self.started = True

    def stop(self, sync: bool = False) -> None:
        if not self.started:
            return
        if sync:
            _sync_device()
        self._elapsed += time.perf_counter() - self._start
        self._count += 1
        self.started = False

    def reset(self) -> None:
        self.started = False
        self._elapsed = 0.0
        self._count = 0

    def elapsed(self, reset: bool = True) -> float:
        value = self._elapsed
        if reset:
            self.reset()
        return value

    def mean(self) -> float:
        return self._elapsed / max(self._count, 1)


def _sync_device() -> None:
    try:
        from deepspeed_tpu.accelerator.real_accelerator import get_accelerator

        get_accelerator().synchronize()
    except Exception:
        pass


class SynchronizedWallClockTimer:
    """Named-timer registry; ``log()`` prints elapsed ms per timer."""

    def __init__(self) -> None:
        self.timers: dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has(self, name: str) -> bool:
        return name in self.timers

    def log(self, names: list[str] | None = None, reset: bool = True, ranks=None) -> None:
        names = names if names is not None else list(self.timers)
        parts = []
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0
                parts.append(f"{name}: {elapsed:.2f}ms")
        if parts:
            log_dist("time " + " | ".join(parts), ranks=ranks or [0])


@dataclass
class ThroughputTimer:
    """Samples/sec and TFLOPS per step (reference: ``utils/timer.py:199``)."""

    batch_size: int = 1
    steps_per_output: int = 100
    monitor_memory: bool = False
    logging_fn: object = None
    total_elapsed: float = field(default=0.0, init=False)
    step_count: int = field(default=0, init=False)
    # steps stopped with exclude=True (compile-bearing): counted separately
    # so compile stalls don't drag the steady-state throughput average
    excluded_elapsed: float = field(default=0.0, init=False)
    excluded_count: int = field(default=0, init=False)
    _start: float = field(default=0.0, init=False)
    _started: bool = field(default=False, init=False)
    flops_per_sample: float = field(default=0.0, init=False)
    last_duration: float = field(default=0.0, init=False)  # most recent start->stop

    def start(self) -> None:
        self._start = time.perf_counter()
        self._started = True

    def stop(self, global_step: bool = True, report_speed: bool = True,
             exclude: bool = False) -> None:
        if not self._started:
            # stop() before any start(): _start would be the process epoch
            # and the "duration" garbage — drop the sample
            return
        self._started = False
        duration = time.perf_counter() - self._start
        self.last_duration = duration
        if exclude:
            if global_step:
                self.excluded_elapsed += duration
                self.excluded_count += 1
            return
        self.total_elapsed += duration
        if global_step:
            self.step_count += 1
            if report_speed and self.steps_per_output and self.step_count % self.steps_per_output == 0:
                log_dist(
                    f"step={self.step_count} samples/sec={self.throughput():.2f} "
                    f"avg_step_ms={1000 * self.total_elapsed / max(self.step_count, 1):.1f}",
                    ranks=[0],
                )

    def throughput(self) -> float:
        if self.step_count <= 0 or self.total_elapsed <= 0:
            return 0.0
        return self.batch_size * self.step_count / self.total_elapsed

    def tflops(self) -> float:
        if self.flops_per_sample <= 0:
            return 0.0
        return self.flops_per_sample * self.throughput() / 1e12
