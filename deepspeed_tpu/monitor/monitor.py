"""Experiment monitoring: rank-0-gated fan-out to TensorBoard / CSV / W&B / Comet.

Role parity with the reference ``monitor/monitor.py:13,30`` (``Monitor`` ABC +
``MonitorMaster`` multiplexing TensorBoard/W&B/Comet/CSV writers). Every
writer degrades to disabled-with-a-log-line when its SDK is absent or fails
to initialize. The event format matches the reference:
``write_events([(tag, value, global_step), ...])``.
"""

from __future__ import annotations

import csv
import os
from typing import Any

from deepspeed_tpu.config.config import MonitorConfig
from deepspeed_tpu.utils.logging import log_dist


def _is_rank0() -> bool:
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


class Monitor:
    """Writer protocol (reference ``monitor/monitor.py:13``)."""

    enabled = False

    def write_events(self, event_list: list[tuple[str, Any, int]]) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self.flush()


class TensorBoardMonitor(Monitor):
    def __init__(self, cfg: dict):
        self.enabled = False
        if not _is_rank0():
            return
        try:
            from torch.utils.tensorboard import SummaryWriter
        except Exception:
            log_dist("tensorboard writer unavailable; disabling", ranks=[0])
            return
        path = os.path.join(cfg.get("output_path", "./runs"), cfg.get("job_name", "dstpu"))
        self.writer = SummaryWriter(log_dir=path)
        self.enabled = True

    def write_events(self, event_list):
        if not self.enabled:
            return
        for tag, value, step in event_list:
            self.writer.add_scalar(tag, float(value), int(step))

    def flush(self):
        if self.enabled:
            self.writer.flush()

    def close(self):
        if self.enabled:
            self.writer.close()


class CSVMonitor(Monitor):
    def __init__(self, cfg: dict):
        self.enabled = False
        self._files: dict[str, Any] = {}  # tag -> (handle, csv.writer)
        if not _is_rank0():
            return
        self.dir = os.path.join(cfg.get("output_path", "./csv_logs"),
                                cfg.get("job_name", "dstpu"))
        os.makedirs(self.dir, exist_ok=True)
        self.enabled = True

    def write_events(self, event_list):
        if not self.enabled:
            return
        touched = set()
        for tag, value, step in event_list:
            # one cached append handle per tag: reopening the file for every
            # event turns each scalar into an open/close syscall pair
            entry = self._files.get(tag)
            if entry is None:
                fname = os.path.join(self.dir, tag.replace("/", "_") + ".csv")
                new = not os.path.exists(fname)
                f = open(fname, "a", newline="")
                entry = self._files[tag] = (f, csv.writer(f))
                if new:
                    entry[1].writerow(["step", tag])
            entry[1].writerow([int(step), float(value)])
            touched.add(tag)
        for tag in touched:
            # one flush per batch keeps the file readable between steps
            # (readers tail these CSVs mid-run) without per-event reopens
            self._files[tag][0].flush()

    def flush(self):
        for f, _ in self._files.values():
            f.flush()

    def close(self):
        for f, _ in self._files.values():
            f.close()
        self._files.clear()


class WandbMonitor(Monitor):
    def __init__(self, cfg: dict):
        self.enabled = False
        if not _is_rank0():
            return
        try:
            import wandb
        except Exception:
            log_dist("wandb unavailable; disabling", ranks=[0])
            return
        wandb.init(project=cfg.get("project", "deepspeed_tpu"),
                   group=cfg.get("group"), config=cfg)
        self._wandb = wandb
        self.enabled = True

    def write_events(self, event_list):
        if not self.enabled:
            return
        for tag, value, step in event_list:
            self._wandb.log({tag: value}, step=int(step))


class CometMonitor(Monitor):
    """Comet writer (reference ``monitor/comet.py``): rank-0 gated, lazily
    imported, disabled with a log line when the SDK is absent."""

    def __init__(self, cfg: dict):
        self.enabled = False
        if not _is_rank0():
            return
        try:
            import comet_ml

            self._experiment = comet_ml.Experiment(
                api_key=cfg.get("api_key"),
                project_name=cfg.get("project", "deepspeed_tpu"),
                workspace=cfg.get("workspace"),
            )
            if cfg.get("experiment_name"):
                self._experiment.set_name(cfg["experiment_name"])
        except Exception as e:
            # missing SDK, missing API key, offline — monitoring must never
            # take down training startup
            log_dist(f"comet disabled: {e}", ranks=[0])
            return
        self.enabled = True

    def write_events(self, event_list):
        if not self.enabled:
            return
        for tag, value, step in event_list:
            self._experiment.log_metric(tag, value, step=int(step))

    def flush(self):
        if self.enabled:
            self._experiment.flush()


class MonitorMaster(Monitor):
    """Fan-out to every enabled writer (reference ``MonitorMaster:30``)."""

    def __init__(self, config: MonitorConfig):
        self.writers: list[Monitor] = []
        if config.enabled:
            if config.tensorboard.get("enabled"):
                self.writers.append(TensorBoardMonitor(config.tensorboard))
            if config.csv_monitor.get("enabled"):
                self.writers.append(CSVMonitor(config.csv_monitor))
            if config.wandb.get("enabled"):
                self.writers.append(WandbMonitor(config.wandb))
            if config.comet.get("enabled"):
                self.writers.append(CometMonitor(config.comet))
        self.enabled = any(w.enabled for w in self.writers)

    def write_events(self, event_list):
        for w in self.writers:
            w.write_events(event_list)

    def flush(self):
        for w in self.writers:
            w.flush()

    def close(self):
        for w in self.writers:
            w.close()
