"""KnobSpace: the registry of tunable performance knobs (docs/AUTOTUNING.md).

The framework grew ~20 interacting perf knobs across two engines (dispatch
mode x sched_steps x spec_draft x prefill_tile x fused_chunk x kv budgets x
quant codec x grad_overlap bucket/sharding x pipeline shape x headroom
guard). The search driver (autotuner.KnobSearch) needs three facts per knob
that the config dataclasses don't carry:

- its **domain** — the candidate values worth measuring;
- the **subsystem it patches** — a dotted train-config path or a
  ``RaggedConfig`` field, which is also how a persisted profile is applied
  back at startup (profiles.py);
- a **cost-model hint** — extra device bytes a value costs relative to the
  knob's default, so the headroom pruner can reject a candidate *before*
  paying a compile. Train-side memory is modeled by ``ModelInfo``
  (state_bytes/activation_bytes) instead of per-knob hints because the
  stage x micro-batch x remat x sharded-update interaction is one formula,
  not a sum of independent costs.

The registry is versioned: its signature is folded into the profile content
key, so a knob-space change invalidates persisted profiles instead of
silently replaying overrides whose meaning moved.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

KNOBSPACE_VERSION = 1

TRAIN = "train"
SERVE = "serve"


@dataclass(frozen=True)
class Knob:
    """One tunable: domain + patch target + cost hint.

    ``name`` doubles as the patch address: a dotted ``Config`` path for
    train knobs (``zero_optimization.grad_overlap.bucket_bytes``), a
    ``RaggedConfig`` field name for serve knobs (``sched_steps``).
    """

    name: str
    subsystem: str  # TRAIN | SERVE
    domain: tuple
    default: object
    # continuous knobs get a neighborhood-refinement pass around the winner
    continuous: bool = False
    # (value, ctx) -> extra device bytes vs the default; ctx carries
    # model/workload facts the caller knows (kv_pool_bytes, n_dev, ...)
    cost_hint: Callable | None = None
    doc: str = ""

    def cost_bytes(self, value, ctx: dict | None = None) -> float:
        if self.cost_hint is None:
            return 0.0
        try:
            return float(self.cost_hint(value, ctx or {}))
        except Exception:
            return 0.0

    def neighbors(self, value) -> list:
        """Refinement candidates around ``value`` (continuous knobs only):
        halve/double for numeric knobs, clamped to the domain hull so the
        neighborhood never wanders past what the registry declared sane."""
        if not self.continuous or isinstance(value, bool):
            return []
        if isinstance(value, int):
            lo, hi = min(self.domain), max(self.domain)
            return [v for v in (value // 2, value * 2)
                    if lo <= v <= hi and v != value and v > 0]
        if isinstance(value, float):
            lo, hi = min(self.domain), max(self.domain)
            return [round(v, 6) for v in (value / 2, value * 2)
                    if lo <= v <= hi and abs(v - value) > 1e-9]
        return []


class KnobSpace:
    """Ordered knob registry; the order is the coordinate-ascent sweep
    order (upstream knobs first: the micro-batch/stage shape decides what
    the overlap/dispatch knobs even mean)."""

    def __init__(self, version: int = KNOBSPACE_VERSION):
        self.version = version
        self._knobs: dict[str, Knob] = {}

    def register(self, knob: Knob) -> Knob:
        if knob.subsystem not in (TRAIN, SERVE):
            raise ValueError(f"unknown subsystem {knob.subsystem!r}")
        if knob.name in self._knobs:
            raise ValueError(f"knob {knob.name!r} already registered")
        if knob.default not in knob.domain:
            raise ValueError(
                f"knob {knob.name!r}: default {knob.default!r} not in domain")
        self._knobs[knob.name] = knob
        return knob

    def get(self, name: str) -> Knob:
        return self._knobs[name]

    def knobs(self, subsystem: str | None = None,
              names=None) -> list[Knob]:
        out = [k for k in self._knobs.values()
               if subsystem is None or k.subsystem == subsystem]
        if names is not None:
            wanted = list(names)
            missing = [n for n in wanted if n not in self._knobs]
            if missing:
                raise KeyError(f"unknown knobs {missing}")
            out = [k for k in out if k.name in wanted]
            out.sort(key=lambda k: wanted.index(k.name))
        return out

    def defaults(self, subsystem: str) -> dict:
        return {k.name: k.default for k in self.knobs(subsystem)}

    def signature(self) -> str:
        """Stable identity folded into profile content keys: version +
        every (name, domain) pair. Changing a domain or adding a knob
        changes the signature -> old profiles go stale by construction."""
        parts = [f"v{self.version}"]
        for name in sorted(self._knobs):
            k = self._knobs[name]
            parts.append(f"{name}:{k.subsystem}:{tuple(k.domain)!r}")
        return "|".join(parts)


def _kv_pool_scale(multiplier: float):
    """Cost hint for knobs that scale the KV pool's resident bytes."""
    def hint(value, ctx):
        return (multiplier - 1.0) * float(ctx.get("kv_pool_bytes", 0))
    return hint


def _build_default_space() -> KnobSpace:
    s = KnobSpace()
    # ---- train (dotted Config paths; memory interaction modeled by
    # ModelInfo in the driver, so no per-knob cost hints here) ----
    s.register(Knob("zero_optimization.stage", TRAIN, (0, 1, 2, 3), 0,
                    doc="ZeRO partition stage"))
    s.register(Knob("train_micro_batch_size_per_device", TRAIN,
                    (1, 2, 4, 8, 16), 2, continuous=True,
                    doc="per-device micro batch"))
    s.register(Knob("activation_checkpointing.enabled", TRAIN,
                    (False, True), False, doc="remat activations"))
    s.register(Knob("zero_optimization.grad_overlap.enabled", TRAIN,
                    (False, True), False,
                    doc="bucketed async grad collectives"))
    s.register(Knob("zero_optimization.grad_overlap.bucket_bytes", TRAIN,
                    (1 << 20, 4 << 20, 16 << 20), 4 << 20, continuous=True,
                    doc="overlap bucket size"))
    s.register(Knob("zero_optimization.grad_overlap.sharded_update", TRAIN,
                    (True, False), True,
                    doc="ZeRO-1 sharded optimizer update on the overlap path"))
    # ---- serve (RaggedConfig field names) ----
    s.register(Knob("sched_steps", SERVE, (0, 8, 16), 0,
                    doc="device-side multi-step decode scheduler depth"))
    s.register(Knob("fused_chunk", SERVE, (0, 4, 16), 0,
                    doc="fused mixed-chunk dispatch depth"))
    s.register(Knob("decode_run_ahead", SERVE, (0, 8, 32), 0,
                    doc="all-decode run-ahead scan depth"))
    s.register(Knob("prefill_tile", SERVE, (0, 16, 64), 0,
                    doc="tiled prefill kernel tile"))
    s.register(Knob("pipeline_depth", SERVE, (2, 3), 2,
                    doc="fused-chunk pipelining depth"))
    s.register(Knob("spec_draft", SERVE, (0, 4), 0,
                    doc="self-speculative draft depth"))
    s.register(Knob("enable_prefix_cache", SERVE, (False, True), False,
                    doc="block-level prefix cache"))
    s.register(Knob("quant", SERVE, ("off", "int8", "fp8"), "off",
                    # int8/fp8 KV halves the pool's resident bytes
                    cost_hint=_kv_pool_scale(0.5),
                    doc="KV-block quantization codec"))
    s.register(Knob("kv_tier_host_blocks", SERVE, (64, 128, 256), 64,
                    continuous=True,
                    doc="host-RAM KV tier budget (off-device: free on HBM)"))
    s.register(Knob("headroom_guard_fraction", SERVE,
                    (0.02, 0.05, 0.1), 0.05, continuous=True,
                    doc="bytes_limit fraction held back from admission"))
    return s


DEFAULT_SPACE = _build_default_space()
