"""Measurement-driven autotuning (docs/AUTOTUNING.md).

``Autotuner`` is the original in-process training sweep; ``KnobSearch`` is
the general driver over the ``KnobSpace`` registry that tunes both engines
via bounded ``bench.py`` probe legs and persists content-keyed profiles
(``profiles``) that ``deepspeed_tpu.initialize`` and the serving router
load at startup.
"""

from deepspeed_tpu.autotuning import profiles  # noqa: F401
from deepspeed_tpu.autotuning.autotuner import (  # noqa: F401
    Autotuner,
    KnobSearch,
    ModelInfo,
    TrialResult,
    default_probe_runner,
    device_memory_bytes,
    probe_model_info,
)
from deepspeed_tpu.autotuning.knobs import (  # noqa: F401
    DEFAULT_SPACE,
    KNOBSPACE_VERSION,
    SERVE,
    TRAIN,
    Knob,
    KnobSpace,
)
