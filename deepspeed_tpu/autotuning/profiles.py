"""Tuned-profile persistence + startup application (docs/AUTOTUNING.md).

A finished search persists its winner as a **content-keyed** profile under
``runs/autotune/``: the key is a hash of (model fingerprint, topology,
workload class, knob-space signature), so a profile can only ever be
replayed against the exact shape it was measured on — change the model,
the device count, or the knob registry and the lookup simply misses.

Application precedence is explicit and one-directional: **config-file
values always win over tuned values.** A train profile only fills knobs
the user's raw config dict did not write (for programmatic ``Config``
objects, knobs still at their dataclass default); a serving profile only
fills ``RaggedConfig`` fields still at their default. What was applied vs
skipped is logged, and the ``tuned_profile_loaded`` gauge says whether a
profile was in effect at startup.

Writes go through the PR 9 commit protocol (temp + fsync + ``os.replace``)
so a crash mid-persist leaves the old profile or the new one, never a torn
file; the loader additionally tolerates torn/garbage files (treated as
absent) because profile dirs travel between machines by rsync.
"""

from __future__ import annotations

import hashlib
import json
import os

from deepspeed_tpu.autotuning.knobs import DEFAULT_SPACE, SERVE, TRAIN
from deepspeed_tpu.checkpoint.serialization import save_json
from deepspeed_tpu.utils.logging import log_dist

PROFILE_VERSION = 1
DEFAULT_PROFILE_DIR = os.path.join("runs", "autotune")

# train knobs that participate in the batch-size triangle: tuned values for
# these only apply when the raw config pinned NONE of the triangle (a tuned
# micro-batch under a user-pinned train_batch_size would silently change GAS)
_BATCH_TRIANGLE = ("train_batch_size", "train_micro_batch_size_per_device",
                   "gradient_accumulation_steps",
                   "train_micro_batch_size_per_gpu")  # legacy alias


def model_fingerprint(info) -> str:
    """Stable identity of the model the profile was tuned for (ModelInfo
    or anything with num_params/hidden_size/num_layers)."""
    return (f"p{int(getattr(info, 'num_params', 0))}"
            f"-h{int(getattr(info, 'hidden_size', 0))}"
            f"-l{int(getattr(info, 'num_layers', 0))}")


def current_topology() -> str:
    """backend:device_count:device_kind — the facts that change a tuned
    answer (a v5e profile means nothing on a v4 pod or the CPU mesh)."""
    import jax

    devs = jax.devices()
    kind = getattr(devs[0], "device_kind", "unknown") if devs else "none"
    return f"{jax.default_backend()}:{len(devs)}:{kind}"


def profile_key(fingerprint: str, topology: str, workload: str,
                subsystem: str, space=DEFAULT_SPACE) -> str:
    blob = "|".join([f"pv{PROFILE_VERSION}", space.signature(), subsystem,
                     fingerprint, topology, workload])
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def profile_path(profile_dir: str, subsystem: str, key: str) -> str:
    return os.path.join(profile_dir, f"{subsystem}-{key}.json")


def save_profile(profile_dir: str, *, subsystem: str, fingerprint: str,
                 topology: str | None = None, workload: str = "default",
                 overrides: dict, score: float, baseline_score: float,
                 space=DEFAULT_SPACE, extra: dict | None = None) -> str:
    """Persist one winner atomically; returns the committed path."""
    if subsystem not in (TRAIN, SERVE):
        raise ValueError(f"unknown subsystem {subsystem!r}")
    topology = topology if topology is not None else current_topology()
    key = profile_key(fingerprint, topology, workload, subsystem, space)
    path = profile_path(profile_dir, subsystem, key)
    save_json(path, {
        "version": PROFILE_VERSION,
        "key": key,
        "subsystem": subsystem,
        "fingerprint": fingerprint,
        "topology": topology,
        "workload": workload,
        "knobspace": space.signature(),
        "overrides": overrides,
        "score": score,
        "baseline_score": baseline_score,
        **(extra or {}),
    })
    log_dist(f"autotune: persisted {subsystem} profile {path} "
             f"(score {score:.4g} vs default {baseline_score:.4g})",
             ranks=[0])
    return path


def load_profile(profile_dir: str, *, subsystem: str, fingerprint: str,
                 topology: str | None = None, workload: str = "default",
                 space=DEFAULT_SPACE) -> dict | None:
    """Load the profile for (fingerprint, topology, workload) or None.

    Missing, torn (non-JSON), or stale files (recorded identity disagrees
    with the requested one — possible when files are copied between
    machines) all read as "no profile"; stale/torn are logged loudly."""
    topology = topology if topology is not None else current_topology()
    key = profile_key(fingerprint, topology, workload, subsystem, space)
    path = profile_path(profile_dir, subsystem, key)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            prof = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        log_dist(f"autotune: ignoring unreadable profile {path}: {e}",
                 ranks=[0])
        return None
    stale = []
    if prof.get("version") != PROFILE_VERSION:
        stale.append(f"version {prof.get('version')} != {PROFILE_VERSION}")
    if prof.get("fingerprint") != fingerprint:
        stale.append(f"model {prof.get('fingerprint')} != {fingerprint}")
    if prof.get("topology") != topology:
        stale.append(f"topology {prof.get('topology')} != {topology}")
    if prof.get("knobspace") != space.signature():
        stale.append("knob space changed")
    if not isinstance(prof.get("overrides"), dict):
        stale.append("no overrides dict")
    if stale:
        log_dist(f"autotune: rejecting stale profile {path}: "
                 + "; ".join(stale), ranks=[0])
        return None
    return prof


# --------------------------------------------------------------- precedence
def _raw_has(raw: dict, dotted: str) -> bool:
    node = raw
    for part in dotted.split("."):
        if not isinstance(node, dict):
            return False
        # the deprecated "zero" spelling aliases zero_optimization
        if part == "zero_optimization" and part not in node and "zero" in node:
            part = "zero"
        if part not in node:
            return False
        node = node[part]
    return True


def _cfg_at_default(cfg, dotted: str) -> bool:
    from deepspeed_tpu.config.config import Config

    fresh = Config()
    node, ref = cfg, fresh
    for part in dotted.split("."):
        node = getattr(node, part)
        ref = getattr(ref, part)
    return node == ref


def apply_train_profile(cfg, raw: dict | None, profile: dict) -> dict:
    """Fill un-written train knobs from ``profile`` onto a loaded Config.

    ``raw`` is the user's original config dict when one exists (explicit
    keys there ALWAYS win); for programmatic Config objects (raw=None) a
    knob counts as user-written when it differs from the dataclass default.
    Returns ``{"applied": {...}, "skipped": {...}}`` for the log line."""
    applied, skipped = {}, {}
    for dotted, value in (profile.get("overrides") or {}).items():
        if dotted == "train_micro_batch_size_per_device":
            pinned = (any(_raw_has(raw, k) for k in _BATCH_TRIANGLE)
                      if raw is not None
                      else any(not _cfg_at_default(cfg, k)
                               for k in _BATCH_TRIANGLE[:3]))
            if pinned:
                skipped[dotted] = value
                continue
            cfg.train_micro_batch_size_per_device = value
            applied[dotted] = value
            continue
        explicit = (_raw_has(raw, dotted) if raw is not None
                    else not _cfg_at_default(cfg, dotted))
        if explicit:
            skipped[dotted] = value
            continue
        try:
            node = cfg
            parts = dotted.split(".")
            for part in parts[:-1]:
                node = getattr(node, part)
            setattr(node, parts[-1], value)
            applied[dotted] = value
        except AttributeError:
            skipped[dotted] = value
    return {"applied": applied, "skipped": skipped}


def apply_serving_profile(ragged_config, profile: dict) -> dict:
    """Fill still-at-default RaggedConfig fields from a serve profile
    (a field the caller already set keeps its value: config wins)."""
    from dataclasses import MISSING, fields as dc_fields

    defaults = {}
    for f in dc_fields(type(ragged_config)):
        if f.default is not MISSING:
            defaults[f.name] = f.default
        elif f.default_factory is not MISSING:
            defaults[f.name] = f.default_factory()
    applied, skipped = {}, {}
    for name, value in (profile.get("overrides") or {}).items():
        if not hasattr(ragged_config, name):
            skipped[name] = value
            continue
        if getattr(ragged_config, name) != defaults.get(name):
            skipped[name] = value  # caller wrote it: config wins
            continue
        setattr(ragged_config, name, value)
        applied[name] = value
    return {"applied": applied, "skipped": skipped}


def _set_loaded_gauge(kind: str, loaded: bool) -> None:
    from deepspeed_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    if tel.enabled:
        tel.gauge(
            "tuned_profile_loaded",
            "1 when a persisted autotune profile was applied at startup",
        ).set(1.0 if loaded else 0.0, kind=kind)


def maybe_apply_train_profile(cfg, raw: dict | None, model) -> dict | None:
    """The ``deepspeed_tpu.initialize`` hook: when ``cfg.autotuning.enabled``,
    look up the profile for (this model, this topology, the configured
    workload) and apply it under config-file-wins precedence. Returns the
    applied/skipped record (None when no profile matched). Never raises —
    a broken profile store must not stop a training job from starting."""
    try:
        from deepspeed_tpu.autotuning.autotuner import probe_model_info

        info = probe_model_info(model)
        fp = model_fingerprint(info)
        prof = load_profile(cfg.autotuning.profile_dir, subsystem=TRAIN,
                            fingerprint=fp, workload=cfg.autotuning.workload)
        if prof is None:
            _set_loaded_gauge(TRAIN, False)
            log_dist(f"autotune: no train profile for {fp} "
                     f"({current_topology()}, workload="
                     f"{cfg.autotuning.workload!r})", ranks=[0])
            return None
        rec = apply_train_profile(cfg, raw, prof)
        # loaded = a valid profile is in effect, even when every tuned knob
        # was either config-pinned or already the default
        _set_loaded_gauge(TRAIN, True)
        log_dist(f"autotune: loaded train profile {prof['key']} — applied "
                 f"{rec['applied']}, config-file kept {rec['skipped']}",
                 ranks=[0])
        return rec
    except Exception as e:  # pragma: no cover - defensive
        log_dist(f"autotune: profile load failed (ignored): {e}", ranks=[0])
        return None
