"""Autotuner: measured search over the full knob space, both engines.

Role parity with the reference ``autotuning/autotuner.py:42`` (``tune:404``):
the reference first PROFILES the model (param count -> per-stage memory
estimates) to prune the search space, then generates ZeRO-stage x micro-batch
experiments, runs each, and refines around the best
(``run_tuning_micro_batch_sizes:741``). Same shape here, in two drivers:

- ``Autotuner`` — the original in-process training sweep (phase 1 prunes and
  sweeps stage x micro-batch; phase 2 refines the winner across the
  offload/TP/SP/qgZ dimensions; phase 3 a bounded joint sweep).
- ``KnobSearch`` — the general driver over the ``knobs.KnobSpace`` registry
  (docs/AUTOTUNING.md): coordinate-ascent over BOTH engines' knobs, each
  candidate headroom-pruned *before paying a compile* via the
  ``ModelInfo`` memory math + knob cost hints, measured by a short bounded
  ``bench.py`` probe leg in a child process (train legs scored by
  goodput x MFU; serving legs by tokens/s x SLO-good fraction, with the
  census and token-parity gates as hard disqualifiers), refined around the
  winner on the continuous knobs, and persisted as a content-keyed profile
  (profiles.py) that ``deepspeed_tpu.initialize`` and the serving router
  load at startup.

The reference schedules experiments across free cluster nodes via the
launcher; on TPU a trial is a fresh engine in a child process (jit-compiled,
measured for a few steps), so the whole search runs where the job runs. OOMs
and compile failures are caught and recorded as failed trials, exactly like
the reference's experiment records.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field

import numpy as np

from deepspeed_tpu.utils.logging import log_dist

TUNING_METRICS = ("throughput", "latency")

# fp32 master + Adam m/v = 12, fp32 grad accumulator = 4, bf16 compute cast
# = 2 bytes/param on the fused path (matches bench.py's ladder sizing)
_STATE_BYTES_PER_PARAM = 18.0
_SHARDED_BYTES_PER_PARAM = 16.0  # the shardable share (master+opt+grads)


@dataclass
class TrialResult:
    overrides: dict
    samples_per_sec: float = 0.0
    step_ms: float = 0.0
    error: str | None = None
    # KnobSearch probe legs: the scalar objective + the probe's full metric
    # dict (goodput/MFU/overlap or tokens_per_s/SLO burn/gates)
    score: float = 0.0
    metrics: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return self.error is None

    @property
    def skipped(self) -> bool:
        return bool(self.error) and self.error.startswith("pruned:")


@dataclass
class ModelInfo:
    """Reference ``model_info`` analog: what the pruner knows up front."""

    num_params: int
    hidden_size: int
    num_layers: int

    def state_bytes(self, stage: int, shards: int,
                    sharded_update: bool = False) -> float:
        p = float(self.num_params)
        if shards <= 1 or (stage <= 0 and not sharded_update):
            return p * _STATE_BYTES_PER_PARAM
        # stages shard progressively more of the 18 bytes/param:
        # 1: opt state (12), 2: + grads (16), 3: + the bf16 live params (18)
        shardable = ({1: 12.0, 2: 16.0, 3: 18.0}[min(stage, 3)]
                     if stage >= 1 else 0.0)
        # grad_overlap.sharded_update shards the fp32 master + Adam m/v
        # (12 bytes/param, the ZeRO-1 share) even at stage 0 — without this
        # the pruner rejects overlap configs that actually fit (PR 18)
        if sharded_update:
            shardable = max(shardable, 12.0)
        resident = _STATE_BYTES_PER_PARAM - shardable
        return p * (resident + shardable / shards)

    def activation_bytes(self, micro_batch: int, seq_len: int) -> float:
        # ~20 bf16 activation copies of [B, S, H] per layer without remat
        # (attention + MLP intermediates); a deliberate overestimate the
        # remat variant halves — pruning only needs the right order
        return 2.0 * 20 * micro_batch * seq_len * self.hidden_size * self.num_layers


def probe_model_info(model_builder, spec=None) -> ModelInfo:
    """Build the spec once (no weights) and read its static facts."""
    from deepspeed_tpu.models.api import ShardCtx

    if spec is None:
        spec = model_builder(ShardCtx()) if callable(model_builder) else model_builder
    cfg = getattr(spec, "config", None)
    return ModelInfo(
        num_params=int(getattr(spec, "num_params", 0) or 0),
        hidden_size=int(getattr(cfg, "hidden_size", 0) or 0),
        num_layers=int(getattr(cfg, "num_layers", 1) or 1),
    )


def device_memory_bytes() -> float | None:
    """Per-device memory when the backend reports it (TPU does; the CPU test
    mesh does not -> no pruning)."""
    import jax

    try:
        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return float(stats["bytes_limit"])
    except Exception:
        pass
    return None


@dataclass
class Autotuner:
    """Measured config search (call ``tune()``)."""

    model_builder: object
    base_config: dict
    metric: str = "throughput"
    steps_per_trial: int = 3
    results: list = field(default_factory=list)

    def _apply_overrides(self, overrides: dict) -> dict:
        cfg = dict(self.base_config)
        zero = dict(cfg.get("zero_optimization", {}))
        if "zero_stage" in overrides:
            zero["stage"] = overrides["zero_stage"]
        if "offload" in overrides and overrides["offload"] != "none":
            zero["offload_optimizer"] = {"device": overrides["offload"]}
        if overrides.get("quantized_gradients"):
            zero["quantized_gradients"] = True
        cfg["zero_optimization"] = zero
        if "micro_batch" in overrides:
            cfg["train_micro_batch_size_per_device"] = overrides["micro_batch"]
            cfg.pop("train_batch_size", None)
        if "remat" in overrides:
            cfg["activation_checkpointing"] = {"enabled": overrides["remat"]}
        tp = overrides.get("tp", 1)
        sp = overrides.get("sp", 1)
        if tp > 1 or sp > 1:
            mesh = dict(cfg.get("mesh", {}))
            mesh.update({"data": -1, "tensor": tp, "sequence": sp})
            cfg["mesh"] = mesh
        cfg["steps_per_print"] = 0
        return cfg

    def _run_trial(self, overrides: dict, seq_len: int, vocab: int) -> TrialResult:
        import deepspeed_tpu
        from deepspeed_tpu.comm.topology import reset_topology

        cfg = self._apply_overrides(overrides)
        try:
            reset_topology()
            engine, _, _, _ = deepspeed_tpu.initialize(model=self.model_builder, config=cfg)
            # trial timing must not bleed across the async dispatch window:
            # settle every step (the production pipeline keeps _max_inflight)
            engine._max_inflight = 0
            rng = np.random.default_rng(0)

            def batch():
                return {"input_ids": rng.integers(
                    0, vocab, (engine.train_batch_size, seq_len), dtype=np.int32)}

            float(engine.train_batch(batch()))  # compile + settle
            t0 = time.perf_counter()
            for _ in range(self.steps_per_trial):
                loss = engine.train_batch(batch())
            float(loss)  # settle before reading the clock
            dt = (time.perf_counter() - t0) / self.steps_per_trial
            return TrialResult(
                overrides=overrides,
                samples_per_sec=engine.train_batch_size / dt,
                step_ms=dt * 1000,
            )
        except Exception as e:  # OOM / compile failure = failed experiment
            return TrialResult(overrides=overrides, error=f"{type(e).__name__}: {e}"[:300])

    def _record(self, res: TrialResult) -> None:
        self.results.append(res)
        log_dist(
            f"autotune {res.overrides}: "
            + (f"{res.samples_per_sec:.1f} samples/s" if res.ok
               else f"{'SKIPPED' if res.skipped else 'FAILED'} {res.error}"),
            ranks=[0],
        )

    def tune(
        self,
        micro_batch_sizes: list[int] = (1, 2, 4, 8),
        zero_stages: list[int] = (0, 1, 2, 3),
        seq_len: int = 128,
        vocab: int = 1024,
        try_remat: bool = False,
        offload_devices: list[str] = ("none",),
        tp_degrees: list[int] = (1,),
        sp_degrees: list[int] = (1,),
        try_qgz: bool = False,
        memory_bytes: float | None = None,
    ) -> dict:
        """Two-phase measured search; returns the best override dict
        (reference ``tune:404``).

        Phase 1: stage x micro-batch grid, pruned by the model-info memory
        estimate when the device reports its memory (reference model-profile
        pruning); larger micro batches per stage stop at the first OOM.
        Phase 2: the offload/TP/SP/qgZ dimensions sweep AROUND the phase-1
        winner (the reference's refinement loop), each varied independently.
        Phase 3: a bounded JOINT sweep over the dimensions that improved —
        pairwise products + the all-winners combo (capped at 8 trials) — so
        interactions the independent pass misses (offload x remat, tp x sp)
        still get tried, without the reference's full cartesian cost.
        """
        import jax

        self.results = []
        info = probe_model_info(self.model_builder)
        limit = memory_bytes if memory_bytes is not None else device_memory_bytes()
        n_dev = len(jax.devices())

        base_remat = bool(self.base_config.get(
            "activation_checkpointing", {}).get("enabled"))
        for stage in zero_stages:
            for mb in micro_batch_sizes:
                overrides = {"zero_stage": stage, "micro_batch": mb}
                if limit and info.num_params:
                    act = info.activation_bytes(mb, seq_len)
                    if try_remat or base_remat:
                        act /= 2  # prune against the BEST variant to be tried
                    est = info.state_bytes(stage, n_dev) + act
                    if est > 0.9 * limit:
                        self._record(TrialResult(
                            overrides=overrides,
                            error=f"pruned: est {est/1e9:.1f} GB > "
                                  f"0.9 x {limit/1e9:.1f} GB"))
                        continue
                variants = [dict(overrides)]
                if try_remat:
                    variants.append({**overrides, "remat": True})
                oomed = False
                for ov in variants:
                    res = self._run_trial(ov, seq_len, vocab)
                    self._record(res)
                    if not res.ok and "Resource" in (res.error or ""):
                        oomed = True
                if oomed:
                    break  # bigger micro batches will OOM too

        good = [r for r in self.results if r.ok]
        if not good:
            raise RuntimeError("autotuning: every trial failed")
        best = (max(good, key=lambda r: r.samples_per_sec)
                if self.metric == "throughput" else min(good, key=lambda r: r.step_ms))

        # phase 2: refine the winner along the remaining dimensions
        phase1_best = best
        refinements: list[tuple[str, dict]] = []  # (dimension, addition)
        for dev in offload_devices:
            if dev != "none":
                refinements.append(("offload", {"offload": dev}))
        for tp in tp_degrees:
            if tp > 1 and n_dev % tp == 0:
                refinements.append(("tp", {"tp": tp}))
        for sp in sp_degrees:
            if sp > 1 and n_dev % sp == 0 and seq_len % sp == 0:
                refinements.append(("sp", {"sp": sp}))
        if try_qgz and best.overrides.get("zero_stage", 0) >= 1:
            refinements.append(("qgz", {"quantized_gradients": True}))
        dim_best: dict[str, tuple[float, dict]] = {}
        for dim, add in refinements:
            res = self._run_trial({**best.overrides, **add}, seq_len, vocab)
            self._record(res)
            if res.ok and (dim not in dim_best
                           or res.samples_per_sec > dim_best[dim][0]):
                dim_best[dim] = (res.samples_per_sec, add)

        # phase 3: bounded JOINT sweep (round-4 weak #8 — independently
        # varied dimensions never try offload x tp-style interactions, which
        # the reference's fuller product sweep catches). Combine every
        # dimension whose best phase-2 value beat the phase-1 winner:
        # pairwise products plus the all-winners combo, capped.
        better = [(dim, add) for dim, (sps, add) in dim_best.items()
                  if sps > phase1_best.samples_per_sec]
        combos: list[dict] = []
        for i in range(len(better)):
            for j in range(i + 1, len(better)):
                combos.append({**better[i][1], **better[j][1]})
        if len(better) > 2:
            allw: dict = {}
            for _, add in better:
                allw.update(add)
            combos.append(allw)
        tried = {tuple(sorted(r.overrides.items())) for r in self.results}
        for add in combos[:8]:
            ov = {**phase1_best.overrides, **add}
            if tuple(sorted(ov.items())) in tried:
                continue
            res = self._run_trial(ov, seq_len, vocab)
            self._record(res)

        good = [r for r in self.results if r.ok]
        best = (max(good, key=lambda r: r.samples_per_sec)
                if self.metric == "throughput" else min(good, key=lambda r: r.step_ms))
        log_dist(f"autotune best: {best.overrides} ({best.samples_per_sec:.1f} samples/s)",
                 ranks=[0])
        return best.overrides


# ------------------------------------------------------------- knob search
def _bump(name: str, help_text: str) -> None:
    from deepspeed_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    if tel.enabled:
        tel.counter(name, help_text).inc()


def default_probe_runner(kind: str, overrides: dict, steps: int = 3,
                         timeout: float = 180.0,
                         workload: str = "default"):
    """Shell out to ``bench.py --mode probe`` (the ``BENCH_PROBE`` child):
    bounded wall clock, JSON-only result, OOM/compile failures returned as
    structured errors instead of a dead child. Returns ``(dict|None, err)``."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    bench = os.path.join(root, "bench.py")
    env = dict(os.environ)
    env["BENCH_PROBE"] = "1"
    env["BENCH_PROBE_SPEC"] = json.dumps(
        {"kind": kind, "overrides": overrides, "steps": steps,
         "workload": workload})
    try:
        proc = subprocess.run(
            [sys.executable, bench], env=env, capture_output=True,
            text=True, timeout=timeout, cwd=root)
    except subprocess.TimeoutExpired:
        return None, {"reason": f"probe timed out after {timeout:g}s"}
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            try:
                res = json.loads(line)
            except json.JSONDecodeError:
                continue
            if res.get("error"):
                return None, res["error"]
            return res, None
    return None, {"reason": "no JSON in probe output", "rc": proc.returncode,
                  "stderr": (proc.stderr or "")[-2000:]}


@dataclass
class KnobSearch:
    """General measured search over the KnobSpace registry (one subsystem
    per search; run one for each engine). See the module docstring for the
    shape: coordinate ascent + headroom pruning + bounded probe legs +
    neighborhood refinement + content-keyed persistence."""

    subsystem: str  # knobs.TRAIN | knobs.SERVE
    model_info: ModelInfo | None = None
    space: object = None  # KnobSpace; DEFAULT_SPACE when None
    knob_names: tuple | None = None  # trim the sweep for a probe budget
    probe_runner: object = None  # (kind, overrides, steps) -> (dict, err)
    steps: int = 3
    seq_len: int = 128
    # device-byte budget for pruning; None = ask the backend (the CPU test
    # mesh reports none -> pruning off, every candidate is measured)
    memory_bytes: float | None = None
    n_devices: int | None = None
    cost_ctx: dict = field(default_factory=dict)  # knob cost-hint inputs
    workload: str = "default"
    profile_dir: str | None = None  # persist the winner when set
    max_trials: int = 32
    results: list = field(default_factory=list)

    # ----------------------------------------------------------- plumbing
    def _space(self):
        if self.space is None:
            from deepspeed_tpu.autotuning.knobs import DEFAULT_SPACE

            self.space = DEFAULT_SPACE
        return self.space

    def _n_dev(self) -> int:
        if self.n_devices is None:
            import jax

            self.n_devices = len(jax.devices())
        return self.n_devices

    def _knob_default(self, name):
        return self._space().get(name).default

    def _limit(self) -> float | None:
        return (self.memory_bytes if self.memory_bytes is not None
                else device_memory_bytes())

    # ------------------------------------------------------------ pruning
    def _estimate_bytes(self, overrides: dict) -> float | None:
        """Candidate device-byte estimate, paid BEFORE any compile.

        Train: the ModelInfo state/activation formula on the candidate's
        stage x micro-batch x remat x sharded-update corner (the knobs
        interact — one formula, not summed hints). Serve: the sum of the
        knob cost hints over ``cost_ctx`` (extra bytes vs default)."""
        from deepspeed_tpu.autotuning import knobs as K

        ov = overrides
        if self.subsystem == K.TRAIN:
            info = self.model_info
            if info is None or not info.num_params:
                return None
            g = lambda n: ov.get(n, self._knob_default(n))  # noqa: E731
            stage = g("zero_optimization.stage")
            mb = g("train_micro_batch_size_per_device")
            sharded = (g("zero_optimization.grad_overlap.enabled")
                       and g("zero_optimization.grad_overlap.sharded_update"))
            act = info.activation_bytes(mb, self.seq_len)
            if g("activation_checkpointing.enabled"):
                act /= 2
            return (info.state_bytes(stage, self._n_dev(),
                                     sharded_update=sharded) + act)
        est = 0.0
        for name, value in ov.items():
            try:
                est += self._space().get(name).cost_bytes(value, self.cost_ctx)
            except KeyError:
                continue
        return est if est > 0.0 else None

    def _prune_reason(self, overrides: dict) -> str | None:
        limit = self._limit()
        if not limit:
            return None
        est = self._estimate_bytes(overrides)
        if est is not None and est > 0.9 * limit:
            return (f"pruned: est {est/1e9:.2f} GB > "
                    f"0.9 x {limit/1e9:.2f} GB")
        return None

    # ------------------------------------------------------------- trials
    def _record(self, res: TrialResult) -> TrialResult:
        self.results.append(res)
        log_dist(
            f"autotune[{self.subsystem}] {res.overrides}: "
            + (f"score {res.score:.4g}" if res.ok
               else f"{'SKIPPED' if res.skipped else 'FAILED'} {res.error}"),
            ranks=[0],
        )
        return res

    def _probe(self, overrides: dict) -> TrialResult:
        runner = self.probe_runner or default_probe_runner
        _bump("autotune_trials_total",
              "autotune probe legs actually measured (pruned excluded)")
        result, err = runner(self.subsystem, overrides, self.steps)
        if result is None:
            _bump("autotune_failed_total",
                  "autotune probe legs that errored or tripped a gate")
            reason = (err or {}).get("reason") if isinstance(err, dict) else err
            return self._record(TrialResult(
                overrides=overrides, error=str(reason or "probe failed")[:300]))
        # hard disqualifiers: a perf config that changes tokens or leaks
        # memory is a non-result regardless of its score
        gates = [g for g in ("parity_ok", "census_ok")
                 if result.get(g) is False]
        if gates:
            _bump("autotune_failed_total",
                  "autotune probe legs that errored or tripped a gate")
            return self._record(TrialResult(
                overrides=overrides, metrics=result,
                error="gate: " + ", ".join(gates)))
        return self._record(TrialResult(
            overrides=overrides,
            score=float(result.get("score", 0.0)),
            samples_per_sec=float(result.get("samples_per_sec", 0.0) or 0.0),
            step_ms=float(result.get("step_ms", 0.0) or 0.0),
            metrics=result))

    def _try(self, overrides: dict, tried: set, best: TrialResult):
        key = tuple(sorted(overrides.items()))
        if key in tried:
            return best
        tried.add(key)
        measured = sum(1 for r in self.results if not r.skipped)
        if measured >= self.max_trials:
            return best
        reason = self._prune_reason(overrides)
        if reason:
            _bump("autotune_pruned_total",
                  "autotune candidates rejected by the headroom cost model "
                  "before compiling")
            self._record(TrialResult(overrides=overrides, error=reason))
            return best
        res = self._probe(overrides)
        # strict >: ties keep the earlier (simpler / closer-to-default) config
        if res.ok and res.score > best.score:
            return res
        return best

    # -------------------------------------------------------------- search
    def tune(self) -> dict:
        """Run the search; returns the summary dict (winner + bookkeeping).

        Coordinate ascent in registry order: each knob's domain is swept on
        top of the best-so-far override set, then the continuous knobs get a
        halve/double neighborhood pass around the winner. The hand-written
        default is trial 0, so ``best_score >= baseline_score`` holds by
        construction — the tuned profile can only ever match or beat it on
        the probe objective."""
        from deepspeed_tpu.autotuning import knobs as K

        space = self._space()
        sweep = space.knobs(self.subsystem, self.knob_names)
        if not sweep:
            raise ValueError(f"no knobs registered for {self.subsystem!r}")
        self.results = []
        tried: set = {()}
        baseline = self._probe({})
        if not baseline.ok:
            raise RuntimeError(
                f"autotuning: the default-config probe failed: {baseline.error}")
        best = baseline
        for knob in sweep:
            for value in knob.domain:
                cand = dict(best.overrides)
                if value == knob.default:
                    cand.pop(knob.name, None)
                else:
                    cand[knob.name] = value
                best = self._try(cand, tried, best)
        # neighborhood refinement around the winner (continuous knobs only)
        for knob in sweep:
            if not knob.continuous or knob.name not in best.overrides:
                continue
            for nv in knob.neighbors(best.overrides[knob.name]):
                best = self._try({**best.overrides, knob.name: nv},
                                 tried, best)

        pruned = sum(1 for r in self.results if r.skipped)
        failed = sum(1 for r in self.results if not r.ok and not r.skipped)
        gate_failures = sum(1 for r in self.results
                            if (r.error or "").startswith("gate:"))
        summary = {
            "subsystem": self.subsystem,
            "workload": self.workload,
            "best_overrides": best.overrides,
            "best_score": best.score,
            "baseline_score": baseline.score,
            "baseline_metrics": baseline.metrics,
            "best_metrics": best.metrics,
            "trials": len(self.results) - pruned,
            "pruned": pruned,
            "failed": failed,
            "gate_failures": gate_failures,
            # accepted (scored) trials passed every gate by construction;
            # violators are disqualified above and never become the winner
            "gate_violations_accepted": 0,
            "profile_path": None,
        }
        if self.profile_dir and self.model_info is not None:
            from deepspeed_tpu.autotuning import profiles

            summary["profile_path"] = profiles.save_profile(
                self.profile_dir,
                subsystem=(K.TRAIN if self.subsystem == K.TRAIN else K.SERVE),
                fingerprint=profiles.model_fingerprint(self.model_info),
                workload=self.workload,
                overrides=best.overrides,
                score=best.score,
                baseline_score=baseline.score,
                space=space)
        log_dist(
            f"autotune[{self.subsystem}] best: {best.overrides} "
            f"(score {best.score:.4g} vs default {baseline.score:.4g}; "
            f"{summary['trials']} measured, {pruned} pruned, "
            f"{failed} failed)", ranks=[0])
        return summary
