"""Autotuner: measured search over stage x micro-batch x remat x offload x
TP/SP x qgZ configs, with model-info-based pruning.

Role parity with the reference ``autotuning/autotuner.py:42`` (``tune:404``):
the reference first PROFILES the model (param count -> per-stage memory
estimates) to prune the search space, then generates ZeRO-stage x micro-batch
experiments, runs each, and refines around the best
(``run_tuning_micro_batch_sizes:741``). Same shape here: phase 1 prunes and
sweeps stage x micro-batch; phase 2 refines the winner across the
offload/TP/SP/qgZ dimensions. The reference schedules experiments across free
cluster nodes via the launcher; on TPU a trial is a fresh in-process engine
(jit-compiled, measured for a few steps), so the whole search runs where the
job runs. OOMs and compile failures are caught and recorded as failed trials,
exactly like the reference's experiment records.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from deepspeed_tpu.utils.logging import log_dist

TUNING_METRICS = ("throughput", "latency")

# fp32 master + Adam m/v = 12, fp32 grad accumulator = 4, bf16 compute cast
# = 2 bytes/param on the fused path (matches bench.py's ladder sizing)
_STATE_BYTES_PER_PARAM = 18.0
_SHARDED_BYTES_PER_PARAM = 16.0  # the shardable share (master+opt+grads)


@dataclass
class TrialResult:
    overrides: dict
    samples_per_sec: float = 0.0
    step_ms: float = 0.0
    error: str | None = None

    @property
    def ok(self) -> bool:
        return self.error is None

    @property
    def skipped(self) -> bool:
        return bool(self.error) and self.error.startswith("pruned:")


@dataclass
class ModelInfo:
    """Reference ``model_info`` analog: what the pruner knows up front."""

    num_params: int
    hidden_size: int
    num_layers: int

    def state_bytes(self, stage: int, shards: int) -> float:
        p = float(self.num_params)
        if stage <= 0 or shards <= 1:
            return p * _STATE_BYTES_PER_PARAM
        # stages shard progressively more of the 18 bytes/param:
        # 1: opt state (12), 2: + grads (16), 3: + the bf16 live params (18)
        shardable = {1: 12.0, 2: 16.0, 3: 18.0}[min(stage, 3)]
        resident = _STATE_BYTES_PER_PARAM - shardable
        return p * (resident + shardable / shards)

    def activation_bytes(self, micro_batch: int, seq_len: int) -> float:
        # ~20 bf16 activation copies of [B, S, H] per layer without remat
        # (attention + MLP intermediates); a deliberate overestimate the
        # remat variant halves — pruning only needs the right order
        return 2.0 * 20 * micro_batch * seq_len * self.hidden_size * self.num_layers


def probe_model_info(model_builder, spec=None) -> ModelInfo:
    """Build the spec once (no weights) and read its static facts."""
    from deepspeed_tpu.models.api import ShardCtx

    if spec is None:
        spec = model_builder(ShardCtx()) if callable(model_builder) else model_builder
    cfg = getattr(spec, "config", None)
    return ModelInfo(
        num_params=int(getattr(spec, "num_params", 0) or 0),
        hidden_size=int(getattr(cfg, "hidden_size", 0) or 0),
        num_layers=int(getattr(cfg, "num_layers", 1) or 1),
    )


def device_memory_bytes() -> float | None:
    """Per-device memory when the backend reports it (TPU does; the CPU test
    mesh does not -> no pruning)."""
    import jax

    try:
        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return float(stats["bytes_limit"])
    except Exception:
        pass
    return None


@dataclass
class Autotuner:
    """Measured config search (call ``tune()``)."""

    model_builder: object
    base_config: dict
    metric: str = "throughput"
    steps_per_trial: int = 3
    results: list = field(default_factory=list)

    def _apply_overrides(self, overrides: dict) -> dict:
        cfg = dict(self.base_config)
        zero = dict(cfg.get("zero_optimization", {}))
        if "zero_stage" in overrides:
            zero["stage"] = overrides["zero_stage"]
        if "offload" in overrides and overrides["offload"] != "none":
            zero["offload_optimizer"] = {"device": overrides["offload"]}
        if overrides.get("quantized_gradients"):
            zero["quantized_gradients"] = True
        cfg["zero_optimization"] = zero
        if "micro_batch" in overrides:
            cfg["train_micro_batch_size_per_device"] = overrides["micro_batch"]
            cfg.pop("train_batch_size", None)
        if "remat" in overrides:
            cfg["activation_checkpointing"] = {"enabled": overrides["remat"]}
        tp = overrides.get("tp", 1)
        sp = overrides.get("sp", 1)
        if tp > 1 or sp > 1:
            mesh = dict(cfg.get("mesh", {}))
            mesh.update({"data": -1, "tensor": tp, "sequence": sp})
            cfg["mesh"] = mesh
        cfg["steps_per_print"] = 0
        return cfg

    def _run_trial(self, overrides: dict, seq_len: int, vocab: int) -> TrialResult:
        import deepspeed_tpu
        from deepspeed_tpu.comm.topology import reset_topology

        cfg = self._apply_overrides(overrides)
        try:
            reset_topology()
            engine, _, _, _ = deepspeed_tpu.initialize(model=self.model_builder, config=cfg)
            # trial timing must not bleed across the async dispatch window:
            # settle every step (the production pipeline keeps _max_inflight)
            engine._max_inflight = 0
            rng = np.random.default_rng(0)

            def batch():
                return {"input_ids": rng.integers(
                    0, vocab, (engine.train_batch_size, seq_len), dtype=np.int32)}

            float(engine.train_batch(batch()))  # compile + settle
            t0 = time.perf_counter()
            for _ in range(self.steps_per_trial):
                loss = engine.train_batch(batch())
            float(loss)  # settle before reading the clock
            dt = (time.perf_counter() - t0) / self.steps_per_trial
            return TrialResult(
                overrides=overrides,
                samples_per_sec=engine.train_batch_size / dt,
                step_ms=dt * 1000,
            )
        except Exception as e:  # OOM / compile failure = failed experiment
            return TrialResult(overrides=overrides, error=f"{type(e).__name__}: {e}"[:300])

    def _record(self, res: TrialResult) -> None:
        self.results.append(res)
        log_dist(
            f"autotune {res.overrides}: "
            + (f"{res.samples_per_sec:.1f} samples/s" if res.ok
               else f"{'SKIPPED' if res.skipped else 'FAILED'} {res.error}"),
            ranks=[0],
        )

    def tune(
        self,
        micro_batch_sizes: list[int] = (1, 2, 4, 8),
        zero_stages: list[int] = (0, 1, 2, 3),
        seq_len: int = 128,
        vocab: int = 1024,
        try_remat: bool = False,
        offload_devices: list[str] = ("none",),
        tp_degrees: list[int] = (1,),
        sp_degrees: list[int] = (1,),
        try_qgz: bool = False,
        memory_bytes: float | None = None,
    ) -> dict:
        """Two-phase measured search; returns the best override dict
        (reference ``tune:404``).

        Phase 1: stage x micro-batch grid, pruned by the model-info memory
        estimate when the device reports its memory (reference model-profile
        pruning); larger micro batches per stage stop at the first OOM.
        Phase 2: the offload/TP/SP/qgZ dimensions sweep AROUND the phase-1
        winner (the reference's refinement loop), each varied independently.
        Phase 3: a bounded JOINT sweep over the dimensions that improved —
        pairwise products + the all-winners combo (capped at 8 trials) — so
        interactions the independent pass misses (offload x remat, tp x sp)
        still get tried, without the reference's full cartesian cost.
        """
        import jax

        self.results = []
        info = probe_model_info(self.model_builder)
        limit = memory_bytes if memory_bytes is not None else device_memory_bytes()
        n_dev = len(jax.devices())

        base_remat = bool(self.base_config.get(
            "activation_checkpointing", {}).get("enabled"))
        for stage in zero_stages:
            for mb in micro_batch_sizes:
                overrides = {"zero_stage": stage, "micro_batch": mb}
                if limit and info.num_params:
                    act = info.activation_bytes(mb, seq_len)
                    if try_remat or base_remat:
                        act /= 2  # prune against the BEST variant to be tried
                    est = info.state_bytes(stage, n_dev) + act
                    if est > 0.9 * limit:
                        self._record(TrialResult(
                            overrides=overrides,
                            error=f"pruned: est {est/1e9:.1f} GB > "
                                  f"0.9 x {limit/1e9:.1f} GB"))
                        continue
                variants = [dict(overrides)]
                if try_remat:
                    variants.append({**overrides, "remat": True})
                oomed = False
                for ov in variants:
                    res = self._run_trial(ov, seq_len, vocab)
                    self._record(res)
                    if not res.ok and "Resource" in (res.error or ""):
                        oomed = True
                if oomed:
                    break  # bigger micro batches will OOM too

        good = [r for r in self.results if r.ok]
        if not good:
            raise RuntimeError("autotuning: every trial failed")
        best = (max(good, key=lambda r: r.samples_per_sec)
                if self.metric == "throughput" else min(good, key=lambda r: r.step_ms))

        # phase 2: refine the winner along the remaining dimensions
        phase1_best = best
        refinements: list[tuple[str, dict]] = []  # (dimension, addition)
        for dev in offload_devices:
            if dev != "none":
                refinements.append(("offload", {"offload": dev}))
        for tp in tp_degrees:
            if tp > 1 and n_dev % tp == 0:
                refinements.append(("tp", {"tp": tp}))
        for sp in sp_degrees:
            if sp > 1 and n_dev % sp == 0 and seq_len % sp == 0:
                refinements.append(("sp", {"sp": sp}))
        if try_qgz and best.overrides.get("zero_stage", 0) >= 1:
            refinements.append(("qgz", {"quantized_gradients": True}))
        dim_best: dict[str, tuple[float, dict]] = {}
        for dim, add in refinements:
            res = self._run_trial({**best.overrides, **add}, seq_len, vocab)
            self._record(res)
            if res.ok and (dim not in dim_best
                           or res.samples_per_sec > dim_best[dim][0]):
                dim_best[dim] = (res.samples_per_sec, add)

        # phase 3: bounded JOINT sweep (round-4 weak #8 — independently
        # varied dimensions never try offload x tp-style interactions, which
        # the reference's fuller product sweep catches). Combine every
        # dimension whose best phase-2 value beat the phase-1 winner:
        # pairwise products plus the all-winners combo, capped.
        better = [(dim, add) for dim, (sps, add) in dim_best.items()
                  if sps > phase1_best.samples_per_sec]
        combos: list[dict] = []
        for i in range(len(better)):
            for j in range(i + 1, len(better)):
                combos.append({**better[i][1], **better[j][1]})
        if len(better) > 2:
            allw: dict = {}
            for _, add in better:
                allw.update(add)
            combos.append(allw)
        tried = {tuple(sorted(r.overrides.items())) for r in self.results}
        for add in combos[:8]:
            ov = {**phase1_best.overrides, **add}
            if tuple(sorted(ov.items())) in tried:
                continue
            res = self._run_trial(ov, seq_len, vocab)
            self._record(res)

        good = [r for r in self.results if r.ok]
        best = (max(good, key=lambda r: r.samples_per_sec)
                if self.metric == "throughput" else min(good, key=lambda r: r.step_ms))
        log_dist(f"autotune best: {best.overrides} ({best.samples_per_sec:.1f} samples/s)",
                 ranks=[0])
        return best.overrides
