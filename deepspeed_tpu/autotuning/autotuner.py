"""Autotuner: measured search over ZeRO stage x micro-batch x remat configs.

Role parity with the reference ``autotuning/autotuner.py:42`` (``tune:404``:
profile model, generate ZeRO-stage x micro-batch experiments, run each, pick
the best by throughput ``run_tuning_micro_batch_sizes:741``). The reference
schedules experiments across free cluster nodes via the launcher; on TPU a
trial is a fresh in-process engine (jit-compiled, measured for a few steps), so
the whole search runs where the job runs. OOMs and compile failures are caught
and recorded as failed trials, exactly like the reference's experiment records.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from deepspeed_tpu.utils.logging import log_dist

TUNING_METRICS = ("throughput", "latency")


@dataclass
class TrialResult:
    overrides: dict
    samples_per_sec: float = 0.0
    step_ms: float = 0.0
    error: str | None = None

    @property
    def ok(self) -> bool:
        return self.error is None


@dataclass
class Autotuner:
    """Measured config search (call ``tune()``)."""

    model_builder: object
    base_config: dict
    metric: str = "throughput"
    steps_per_trial: int = 3
    results: list = field(default_factory=list)

    def _run_trial(self, overrides: dict, seq_len: int, vocab: int) -> TrialResult:
        import deepspeed_tpu
        from deepspeed_tpu.comm.topology import reset_topology

        cfg = dict(self.base_config)
        zero = dict(cfg.get("zero_optimization", {}))
        if "zero_stage" in overrides:
            zero["stage"] = overrides["zero_stage"]
        cfg["zero_optimization"] = zero
        if "micro_batch" in overrides:
            cfg["train_micro_batch_size_per_device"] = overrides["micro_batch"]
            cfg.pop("train_batch_size", None)
        if "remat" in overrides:
            cfg["activation_checkpointing"] = {"enabled": overrides["remat"]}
        cfg["steps_per_print"] = 0

        try:
            reset_topology()
            engine, _, _, _ = deepspeed_tpu.initialize(model=self.model_builder, config=cfg)
            rng = np.random.default_rng(0)

            def batch():
                return {"input_ids": rng.integers(
                    0, vocab, (engine.train_batch_size, seq_len), dtype=np.int32)}

            engine.train_batch(batch())  # compile
            t0 = time.perf_counter()
            for _ in range(self.steps_per_trial):
                engine.train_batch(batch())
            dt = (time.perf_counter() - t0) / self.steps_per_trial
            return TrialResult(
                overrides=overrides,
                samples_per_sec=engine.train_batch_size / dt,
                step_ms=dt * 1000,
            )
        except Exception as e:  # OOM / compile failure = failed experiment
            return TrialResult(overrides=overrides, error=f"{type(e).__name__}: {e}"[:300])

    def tune(
        self,
        micro_batch_sizes: list[int] = (1, 2, 4, 8),
        zero_stages: list[int] = (0, 1, 2, 3),
        seq_len: int = 128,
        vocab: int = 1024,
        try_remat: bool = False,
    ) -> dict:
        """Grid search; returns the best override dict (reference ``tune:404``).

        Like the reference's micro-batch sweep, larger micro batches are tried
        until one fails (OOM), per stage."""
        self.results = []
        for stage in zero_stages:
            for mb in micro_batch_sizes:
                overrides = {"zero_stage": stage, "micro_batch": mb}
                variants = [dict(overrides)]
                if try_remat:
                    variants.append({**overrides, "remat": True})
                oomed = False
                for ov in variants:
                    res = self._run_trial(ov, seq_len, vocab)
                    self.results.append(res)
                    log_dist(
                        f"autotune {ov}: "
                        + (f"{res.samples_per_sec:.1f} samples/s" if res.ok else f"FAILED {res.error}"),
                        ranks=[0],
                    )
                    if not res.ok and "Resource" in (res.error or ""):
                        oomed = True
                if oomed:
                    break  # bigger micro batches will OOM too
        good = [r for r in self.results if r.ok]
        if not good:
            raise RuntimeError("autotuning: every trial failed")
        best = (max(good, key=lambda r: r.samples_per_sec)
                if self.metric == "throughput" else min(good, key=lambda r: r.step_ms))
        log_dist(f"autotune best: {best.overrides} ({best.samples_per_sec:.1f} samples/s)",
                 ranks=[0])
        return best.overrides
