"""Domino-style TP compute/communication overlap.

Role parity with the reference Domino (``runtime/domino/transformer.py:250
DominoTransformerLayer`` + ``async_linear.py``): split the batch so one
split's tensor-parallel reduction overlaps the other split's compute, hiding
the TP collective behind the MXU.

Why this needs explicit structure on TPU (committed finding, see
``docs/TP_OVERLAP.md`` and ``tests/unit/test_tp_overlap.py``; measured on
XLA's v5e:2x4 AOT target):

1. GSPMD lowers the TP row-parallel reduction to a SYNCHRONOUS ``all-reduce``
   op — no ``all-reduce-start/done`` pair appears in the optimized schedule,
   under any async/LHS compiler flag probed. A sequential decoder chain gives
   the scheduler nothing to overlap anyway (each block depends on the
   previous reduction).
2. Naive split-batch under GSPMD is DEFEATED by the compiler: two half-batch
   chains through the same weights get re-merged (6 expected all-reduces
   compile to 3) — the compiler undoes the Domino restructure.
3. ``collective-permute`` IS async on this target (``-start/-done`` pairs in
   the final schedule, with independent fusions placed inside the windows).

So the TPU-expressible Domino is: a ``shard_map`` manual over the tensor
axis, batch split inside, each split's partial output reduced by an async
ppermute RING whose transfer windows the latency-hiding scheduler fills with
the other split's matmuls. The ring is mathematically the psum (exact, same
reduction order on every rank).
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.topology import AXIS_TENSOR
from deepspeed_tpu.utils.compat import axis_size_compat, shard_map_compat


def ring_all_reduce(x, axis_name: str):
    """Sum-allreduce as n-1 async ppermute hops (collective-permute lowers to
    start/done pairs on TPU — overlappable; sync ``all-reduce`` is not)."""
    n = axis_size_compat(axis_name)
    if n == 1:
        return x
    perm = [(i, (i + 1) % n) for i in range(n)]
    acc = x
    buf = x
    for _ in range(n - 1):
        buf = lax.ppermute(buf, axis_name, perm)
        acc = acc + buf
    return acc


def domino_apply(partial_fn: Callable, x, weights: Sequence,
                 weight_specs: Sequence, mesh, axis: str = AXIS_TENSOR,
                 splits: int = 2):
    """Run ``partial_fn(x_chunk, *weights) -> partial`` over ``splits`` batch
    chunks inside a shard_map manual over ``axis``; each chunk's sum-reduction
    is an async ppermute ring, so chunk k+1's compute fills chunk k's
    transfer windows (the Domino overlap).

    ``weight_specs``: the manual-axis PartitionSpec per weight (other mesh
    axes stay GSPMD-auto). ``x`` enters replicated over ``axis``.
    """
    if x.shape[0] % splits:
        raise ValueError(f"batch {x.shape[0]} not divisible by {splits} splits")

    def local(x, *ws):
        chunks = jnp.split(x, splits, axis=0)
        outs = [ring_all_reduce(partial_fn(c, *ws), axis) for c in chunks]
        return jnp.concatenate(outs, axis=0)

    return shard_map_compat(
        local, mesh=mesh,
        in_specs=(P(),) + tuple(weight_specs),
        out_specs=P(),
        axis_names={axis}, check_vma=False,
    )(x, *weights)


def domino_swiglu_mlp(x, w_gate, w_up, w_down, mesh, axis: str = AXIS_TENSOR,
                      splits: int = 2):
    """Split-batch SwiGLU TP MLP (the Domino transformer's MLP half):
    ``w_gate``/``w_up`` column-parallel on ``axis``, ``w_down`` row-parallel;
    each batch split's down-projection partial rides the async ring."""

    def partial_mlp(h, wg, wu, wd):
        return (jax.nn.silu(h @ wg) * (h @ wu)) @ wd

    return domino_apply(
        partial_mlp, x, (w_gate, w_up, w_down),
        (P(None, axis), P(None, axis), P(axis, None)),
        mesh, axis=axis, splits=splits,
    )
