"""Ulysses sequence parallelism: head<->sequence all-to-all around attention.

Role parity with the reference ``deepspeed/sequence/layer.py``
(``_SeqAllToAll:297``, ``DistributedAttention:351``): activations are sharded on
the sequence dim; before attention an all-to-all converts seq-sharding to
head-sharding (each rank sees the FULL sequence for a subset of heads), the
local attention runs unchanged, and the inverse all-to-all restores
seq-sharding.

TPU-native expression: the all-to-alls are *sharding constraints* — GSPMD emits
``all-to-all`` HLOs over the ``sequence`` ICI axis when an array's sharding
moves from the seq dim to the head dim. No manual collective plumbing, and the
compiler overlaps them with adjacent compute. ``head-granularity`` note: the
head dim must divide by the SP degree (reference uneven-head support
``layer.py:131`` is handled by falling back to gathered attention).
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.comm.topology import AXIS_SEQ, batch_spec_entry
from deepspeed_tpu.ops.attention import attention as _local_attention


def _batch_axes(mesh):
    return batch_spec_entry(mesh)


def _constrain(mesh, x, spec):
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def ulysses_attention(q, k, v, mesh, causal: bool = True, impl: str = "auto",
                      scale=None, local_fn=None):
    """[B, S, H, D] q/k/v seq-sharded in, seq-sharded out; attention computed
    head-sharded over the full sequence (reference ``DistributedAttention``,
    which likewise wraps *any* local attention impl — pass ``local_fn`` to
    substitute one, e.g. FPDT chunked attention)."""
    attn = local_fn or (lambda q, k, v: _local_attention(
        q, k, v, causal=causal, impl=impl, scale=scale))
    sp = mesh.shape.get(AXIS_SEQ, 1)
    if sp <= 1:
        return attn(q, k, v)
    b_ax = _batch_axes(mesh)

    def head_spec(x):
        # uneven heads (reference layer.py:131): a head dim not divisible by the
        # SP degree falls back to replicated heads (sequence still gathered).
        h_ax = AXIS_SEQ if x.shape[2] % sp == 0 else None
        return PartitionSpec(b_ax, None, h_ax, None)

    seq_spec = PartitionSpec(b_ax, AXIS_SEQ, None, None)

    # seq->head all-to-all (GSPMD lowers the resharding to all-to-all on ICI)
    q = _constrain(mesh, q, head_spec(q))
    k = _constrain(mesh, k, head_spec(k))
    v = _constrain(mesh, v, head_spec(v))
    out = attn(q, k, v)
    # head->seq inverse all-to-all
    return _constrain(mesh, out, seq_spec)


def shard_batch_on_sequence(batch: dict, mesh) -> dict:
    """Reference ``UlyssesSPDataLoaderAdapter`` (``runtime/sequence_parallel/
    ulysses_sp.py:564``): incoming [B, S] batches are sharded on the seq dim."""
    b_ax = _batch_axes(mesh)
    out = {}
    for key, val in batch.items():
        spec = PartitionSpec(b_ax, AXIS_SEQ) if val.ndim >= 2 else PartitionSpec(b_ax)
        out[key] = jax.device_put(val, NamedSharding(mesh, spec))
    return out
