"""AutoSP: automatic sequence-parallel insertion for user models not written
against ``ShardCtx``.

Role parity with the reference AutoSP (``deepspeed/sequence/auto_sp.py`` +
``compile/passes/sp_compile.py``): the reference detects
``F.scaled_dot_product_attention`` calls in the torch.compile FX graph
(``autosp_detector.py``) and rewrites them with sequence-parallel
all-to-alls. The JAX analog of "the graph's standard attention entry point"
is ``jax.nn.dot_product_attention``: while an :class:`auto_sp` context is
active (the engine holds it open during tracing when
``sequence_parallel.auto`` is set), calls to it are routed through Ulysses
(or ring) attention over the mesh's ``sequence`` axis — the user's model code
is untouched, exactly the reference's promise.

Hand-rolled attention math (explicit softmax(QK^T)V) is NOT detected — the
same limitation as the reference, whose detector also only matches the sdpa
call. Such models should call ``parallel.ulysses.ulysses_attention``
directly, or be written against ``ShardCtx.attention``.
"""

from __future__ import annotations

import contextvars
import threading

import jax

from deepspeed_tpu.comm.topology import AXIS_SEQ
from deepspeed_tpu.utils.logging import logger

_WARNED = False

# Which (mesh, mode) is active for the CURRENT thread/context. The global
# patch on jax.nn.dot_product_attention is a passive dispatcher: a model
# traced on a thread with no active auto_sp context goes straight to the
# original implementation, so interleaved engines on different meshes never
# leak shardings into each other.
_ACTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "auto_sp_active", default=None)
_PATCH_LOCK = threading.Lock()
_PATCH_DEPTH = 0
_ORIG = None


def _dispatch(query, key, value, bias=None, mask=None, *args,
              is_causal: bool = False, **kwargs):
    global _WARNED
    orig = _ORIG
    active = _ACTIVE.get()
    if active is None:
        return orig(query, key, value, bias, mask, *args,
                    is_causal=is_causal, **kwargs)
    mesh, mode = active
    sp = mesh.shape.get(AXIS_SEQ, 1) if mesh is not None else 1
    if sp <= 1:
        return orig(query, key, value, bias, mask, *args,
                    is_causal=is_causal, **kwargs)
    if bias is not None or mask is not None:
        # a seq-sharded bias/mask would need resharding alongside the
        # activations; fall back loudly rather than compute nonsense
        if not _WARNED:
            _WARNED = True
            logger.warning(
                "auto_sp: dot_product_attention called with "
                "bias/mask — not sequence-parallelized (gathered "
                "attention instead)")
        return orig(query, key, value, bias, mask, *args,
                    is_causal=is_causal, **kwargs)
    if mode == "ring":
        unsupported = [k for k, v in kwargs.items()
                       if k != "scale" and v is not None]
        if args or unsupported:
            # length masks / local windows / implementation pins:
            # the ring kernel has no equivalents — fall back loudly
            if not _WARNED:
                _WARNED = True
                logger.warning(
                    "auto_sp(ring): unsupported dot_product_attention "
                    "options %s — gathered attention instead",
                    unsupported or "positional")
            return orig(query, key, value, bias, mask, *args,
                        is_causal=is_causal, **kwargs)
        from deepspeed_tpu.parallel.ring_attention import ring_attention

        return ring_attention(query, key, value, mesh,
                              causal=is_causal,
                              scale=kwargs.get("scale"))
    from deepspeed_tpu.parallel.ulysses import ulysses_attention

    local = lambda q, k, v: orig(  # noqa: E731
        q, k, v, None, None, *args, is_causal=is_causal, **kwargs)
    return ulysses_attention(query, key, value, mesh,
                             causal=is_causal, local_fn=local)


class auto_sp:
    """Context manager routing ``jax.nn.dot_product_attention`` through
    sequence-parallel attention over ``mesh``. Active only inside the ``with``
    block AND only for the entering thread/context (a ``ContextVar`` carries
    the mesh) — hold it open around model tracing (the engine does this when
    ``sequence_parallel.auto`` is on)."""

    def __init__(self, mesh, mode: str = "ulysses"):
        if mode not in ("ulysses", "ring"):
            raise ValueError(f"auto_sp mode must be ulysses|ring, got {mode!r}")
        self.mesh = mesh
        self.mode = mode
        self._token = None

    def __enter__(self):
        global _PATCH_DEPTH, _ORIG
        with _PATCH_LOCK:
            if _PATCH_DEPTH == 0:
                if jax.nn.dot_product_attention is not _dispatch:
                    _ORIG = jax.nn.dot_product_attention
                jax.nn.dot_product_attention = _dispatch
            _PATCH_DEPTH += 1
        self._token = _ACTIVE.set((self.mesh, self.mode))
        return self

    def __exit__(self, *exc):
        global _PATCH_DEPTH
        _ACTIVE.reset(self._token)
        self._token = None
        with _PATCH_LOCK:
            _PATCH_DEPTH -= 1
            if _PATCH_DEPTH == 0:
                # restore the attribute but KEEP _ORIG: stale references to
                # the dispatcher (captured while a context was open) must
                # keep resolving to the original, not crash on None
                jax.nn.dot_product_attention = _ORIG
        return False


def wrap_loss_fn(loss_fn, mesh, mode: str = "ulysses"):
    """Wrap a ModelSpec loss/forward fn so the AutoSP patch is active
    whenever it is traced (the engine applies this under
    ``sequence_parallel.auto``)."""

    def wrapped(*args, **kwargs):
        with auto_sp(mesh, mode):
            return loss_fn(*args, **kwargs)

    return wrapped
