"""ZeRO++ qwZ: int8 blockwise-quantized weight all-gather on the stage-3 path.

Role parity with the reference's quantized weight gather
(``runtime/zero/partition_parameters.py:1446 all_gather_coalesced`` quantized
path + ``csrc/quantization/swizzled_quantize.cu``): under ZeRO-3 the dominant
collective is the per-layer parameter all-gather; qwZ halves it by gathering
int8 weights + per-block scales instead of bf16, dequantizing after the wire.

TPU-native mechanism (not a port): stage-3 gathers here are not explicit
collectives — they are GSPMD reshardings XLA inserts where the scanned layer
body consumes the fsdp-sharded weight slice. To move that resharding onto an
int8 payload, the layer body routes its weights through
:func:`quantized_gather` (via ``ShardCtx.layer_weights``): quantize the
still-sharded slice shard-locally (``ops/quantizer.quantize_rows``), constrain
the int8 values + scales to the fsdp-DROPPED sharding — forcing the all-gather
to ride int8 — then dequantize to the compute dtype on the far side. XLA's
latency-hiding scheduler still prefetches layer k+1's (now ~2x smaller) gather
during layer k's compute, so the reference's prefetch coordinator remains
subsumed. Backward is straight-through (``jax.custom_vjp`` identity): the
cotangent of the full weight flows back unquantized and the existing grad
sharding constraints reduce-scatter it, exactly the reference semantics (qwZ
quantizes the weight wire, never the gradient math — that is qgZ's job,
``comm/quantized_collectives.py``).

Per-leaf policy: only leaves whose slice is actually fsdp-sharded and at least
``min_size`` elements quantize; tensor/expert-sharded dims KEEP their sharding
in the gather target (qwZ composes with TP — only the fsdp axis is gathered).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.comm.topology import AXIS_FSDP
from deepspeed_tpu.ops.quantizer import dequantize_rows, quantize_rows


def _drop_fsdp(entry):
    """Remove the fsdp axis from one PartitionSpec entry."""
    if entry == AXIS_FSDP:
        return None
    if isinstance(entry, tuple) and AXIS_FSDP in entry:
        rest = tuple(a for a in entry if a != AXIS_FSDP)
        return rest[0] if len(rest) == 1 else (rest if rest else None)
    return entry


def _has_fsdp(spec: PartitionSpec) -> bool:
    return any(e == AXIS_FSDP or (isinstance(e, tuple) and AXIS_FSDP in e)
               for e in spec)


def quantized_gather(w, mesh, slice_spec: PartitionSpec, block: int):
    """quantize -> gather(int8) -> dequantize, straight-through backward.

    ``w``: a layer weight slice (logical full shape) whose sharding includes
    the fsdp axis per ``slice_spec``. Returns the logically-identical weight
    with the fsdp axis gathered, where the resharding payload was int8.
    """
    gathered = PartitionSpec(*(_drop_fsdp(e) for e in slice_spec))
    q_sh = NamedSharding(mesh, gathered)
    # scales [..., nb]: same leading dims, last dim shrinks by the block
    # factor — the gathered spec transfers dim-for-dim
    s_sh = q_sh

    @jax.custom_vjp
    def f(x):
        q, s = quantize_rows(x, block=block)
        q = jax.lax.with_sharding_constraint(q, q_sh)
        s = jax.lax.with_sharding_constraint(s, s_sh)
        return dequantize_rows(q, s, x.dtype, block=block)

    f.defvjp(lambda x: (f(x), None), lambda _, g: (g,))
    return f(w)


def build_layer_hook(mesh, stacked_layer_specs, block: int = 128,
                     min_size: int = 65536):
    """Build the per-layer weight hook the engine installs on ``ShardCtx``.

    ``stacked_layer_specs``: the ``"layers"`` subtree of the plan's
    param_specs — PartitionSpecs of the STACKED leaves (leading layers dim).
    Returns ``hook(lp, dtype) -> lp`` operating on the scan body's sliced
    layer dict (leading dim dropped), quantize-gathering exactly the leaves
    the plan fsdp-shards.
    """
    specs_flat, specs_def = jax.tree_util.tree_flatten(
        stacked_layer_specs, is_leaf=lambda x: isinstance(x, PartitionSpec))

    def hook(lp, dtype):
        del dtype  # slices arrive already compute-cast
        lp_flat, lp_def = jax.tree_util.tree_flatten(lp)
        if lp_def != specs_def:
            # structure mismatch (e.g. a model passing a sub-dict): skip
            # rather than mis-pair leaves
            return lp
        out = []
        for w, spec in zip(lp_flat, specs_flat):
            sl = PartitionSpec(*spec[1:]) if len(spec) > 0 else PartitionSpec()
            if (not hasattr(w, "ndim") or w.ndim < 2 or w.size < min_size
                    or not _has_fsdp(sl)
                    or not jnp.issubdtype(w.dtype, jnp.floating)):
                out.append(w)
            else:
                out.append(quantized_gather(w, mesh, sl, block))
        return jax.tree_util.tree_unflatten(lp_def, out)

    return hook
