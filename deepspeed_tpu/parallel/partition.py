"""Sharding planner: ZeRO stages + tensor parallelism as PartitionSpecs.

This is the TPU-native replacement for three reference subsystems at once:
- ZeRO partitioning machinery (``runtime/zero/stage_1_and_2.py:134``,
  ``stage3.py:148``, ``partition_parameters.py:884``): stages become
  *declarative sharding choices* over the ``fsdp`` mesh axis; XLA's SPMD
  partitioner inserts the allgather/reduce-scatter that the reference
  hand-orchestrates with hooks and bucket streams.
- AutoTP (``module_inject/auto_tp.py:194``, kv-head aware ``tp_shard.py``):
  models declare logical axes per param dim; the planner maps them to the
  ``tensor`` axis, with unit-granularity checks (a kv-head dim is only sharded
  if the *head count*, not just the dim size, divides the axis).
- The ZeRO-3 prefetch coordinator (``partitioned_param_coordinator.py:73``):
  per-layer gather/release/prefetch falls out of scanning over a
  layer-stacked param pytree whose within-layer dims are fsdp-sharded — XLA's
  latency-hiding scheduler prefetches the next layer's allgather during the
  current layer's compute.

Stage semantics (reference ``runtime/zero/config.py:401``):
  0: params/grads/opt-state replicated (pure DP; grads psum)
  1: opt-state sharded
  2: + grads sharded (psum -> reduce-scatter at the accumulation boundary)
  3: + params sharded (allgather-on-use per scan step)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.comm.topology import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPE,
    AXIS_SEQ,
    AXIS_TENSOR,
    MeshTopology,
)

# Logical param axis -> mesh axis for model parallelism.
TP_LOGICAL_TO_MESH = {
    "heads": AXIS_TENSOR,
    "kv_heads": AXIS_TENSOR,
    "ffn": AXIS_TENSOR,
    "vocab": AXIS_TENSOR,
    "experts": AXIS_EXPERT,
}
# Axes the fsdp planner may not claim.
_FSDP_EXCLUDED = {"layers", "experts"}


def _spec_for_param(
    axes: tuple,
    shape: tuple,
    topo: MeshTopology,
    shard_params_fsdp: bool,
    use_tp: bool,
    dim_units: dict,
    persistence_threshold: int,
    pp_fsdp: bool = False,
    hierarchical: bool = False,
) -> PartitionSpec:
    assign: list = [None] * len(shape)
    size = 1
    for s in shape:
        size *= s
    # Pipelined layer stacks always shard the layer dim on the pipeline axis.
    # Under the GPipe collective pipeline the stage body is fully-manual SPMD,
    # so within a stage the weights must be whole (no TP/fsdp) — the
    # reference's PP (x) ZeRO<=1 composition constraint. The 1F1B schedule is
    # manual over `pipeline` ONLY, leaving fsdp GSPMD-auto inside the stage
    # block, so fsdp sharding of the stacked weights is allowed there
    # (pp_fsdp=True, set when pipeline.schedule == "1f1b").
    if topo.size(AXIS_PIPE) > 1 and "layers" in axes:
        i = axes.index("layers")
        if shape[i] % topo.size(AXIS_PIPE) == 0:
            assign[i] = AXIS_PIPE
        if pp_fsdp and shard_params_fsdp:
            fsdp_n = topo.size(AXIS_FSDP)
            if fsdp_n > 1 and size > persistence_threshold:
                cands = [j for j in range(len(shape))
                         if assign[j] is None and axes[j] not in _FSDP_EXCLUDED
                         and shape[j] % fsdp_n == 0]
                if cands:
                    assign[max(cands, key=lambda j: shape[j])] = AXIS_FSDP
        return PartitionSpec(*assign)
    for i, logical in enumerate(axes):
        if logical is None:
            continue
        if logical == "layers":
            continue
        mesh_axis = TP_LOGICAL_TO_MESH.get(logical)
        if mesh_axis is None:
            continue
        if mesh_axis == AXIS_TENSOR and not use_tp:
            continue
        n = topo.size(mesh_axis)
        if n <= 1 or shape[i] % n != 0:
            continue
        # unit-granularity check (reference tp_shard.py kv-head awareness):
        # only shard if whole units land on each rank.
        units = dim_units.get(logical)
        if units is not None and units % n != 0:
            continue
        assign[i] = mesh_axis

    fsdp = topo.size(AXIS_FSDP)
    if shard_params_fsdp and fsdp > 1 and size > persistence_threshold:
        # hierarchical (MiCS/hpZ): optimizer/grad state shards over the FULL
        # world (data x fsdp) while the live-param layout keeps fsdp only, so
        # parameter gathers ride the fast intra-group axis
        entry = AXIS_FSDP
        div = fsdp
        if hierarchical and topo.size(AXIS_DATA) > 1:
            # fsdp-major order: each live fsdp shard is SUBDIVIDED along the
            # data axis, so the master->live gather is a pure data-axis
            # collective per fsdp coordinate (the hpZ fast-axis property)
            entry = (AXIS_FSDP, AXIS_DATA)
            div = fsdp * topo.size(AXIS_DATA)
        candidates = [
            i
            for i in range(len(shape))
            if assign[i] is None
            and (axes[i] not in _FSDP_EXCLUDED)
            and shape[i] % div == 0
        ]
        if candidates:
            best = max(candidates, key=lambda i: shape[i])
            assign[best] = entry
        elif hierarchical:
            # fall back to fsdp-only sharding if the world size doesn't divide
            fall = [i for i in range(len(shape))
                    if assign[i] is None and axes[i] not in _FSDP_EXCLUDED
                    and shape[i] % fsdp == 0]
            if fall:
                assign[max(fall, key=lambda i: shape[i])] = AXIS_FSDP
    return PartitionSpec(*assign)


@dataclass
class ShardingPlan:
    """Per-pytree PartitionSpec trees + their NamedShardings."""

    topo: MeshTopology
    param_specs: Any          # sharding of live params (per ZeRO stage)
    shard_specs: Any          # fully sharded layout (stage-3 style) for opt/grad state
    grad_specs: Any           # gradient layout (stage>=2: shard_specs, else param_specs)
    batch_spec: PartitionSpec = field(default=None)

    def named(self, spec_tree):
        mesh = self.topo.mesh
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            spec_tree,
            is_leaf=lambda s: isinstance(s, PartitionSpec),
        )

    @property
    def param_shardings(self):
        return self.named(self.param_specs)

    @property
    def grad_shardings(self):
        return self.named(self.grad_specs)

    @property
    def shard_shardings(self):
        return self.named(self.shard_specs)

    @property
    def batch_sharding(self):
        return NamedSharding(self.topo.mesh, self.batch_spec)

    def replicated(self):
        return NamedSharding(self.topo.mesh, PartitionSpec())


def plan_sharding(
    logical_axes: Any,
    abstract_params: Any,
    topo: MeshTopology,
    zero_stage: int = 0,
    use_tp: bool = True,
    dim_units: dict | None = None,
    persistence_threshold: int = 0,
    pp_fsdp: bool = False,
    hierarchical: bool = False,
) -> ShardingPlan:
    """Build the full sharding plan for a model's parameter pytree.

    ``logical_axes``: pytree congruent to params, leaves = tuples of logical
    axis names. ``abstract_params``: params or ShapeDtypeStructs.
    """
    dim_units = dim_units or {}
    axes_leaves = jax.tree_util.tree_leaves(
        logical_axes, is_leaf=lambda x: isinstance(x, tuple)
    )
    param_leaves = jax.tree_util.tree_leaves(abstract_params)
    if len(axes_leaves) != len(param_leaves):
        raise ValueError(
            f"logical_axes tree ({len(axes_leaves)} leaves) does not match params "
            f"({len(param_leaves)} leaves)"
        )
    treedef = jax.tree_util.tree_structure(abstract_params)

    def build(shard_fsdp: bool, hier: bool = False):
        specs = [
            _spec_for_param(
                ax, tuple(p.shape), topo, shard_fsdp, use_tp, dim_units,
                persistence_threshold, pp_fsdp=pp_fsdp, hierarchical=hier,
            )
            for ax, p in zip(axes_leaves, param_leaves)
        ]
        return jax.tree_util.tree_unflatten(treedef, specs)

    shard_specs = build(shard_fsdp=True, hier=hierarchical)
    if zero_stage >= 3:
        if hierarchical:
            # hierarchical keeps LIVE params on the fast (fsdp) axis only —
            # the hpZ secondary partition (partition_parameters.py:1806).
            # Derived from shard_specs by DROPPING the data axis so live and
            # master layouts shard the SAME dim (live is a refinement).
            def _drop_data(spec):
                entries = []
                for e in spec:
                    if isinstance(e, tuple) and AXIS_DATA in e:
                        rest = tuple(a for a in e if a != AXIS_DATA)
                        entries.append(rest[0] if len(rest) == 1
                                       else (rest if rest else None))
                    elif e == AXIS_DATA:
                        entries.append(None)
                    else:
                        entries.append(e)
                return PartitionSpec(*entries)

            param_specs = jax.tree_util.tree_map(
                _drop_data, shard_specs,
                is_leaf=lambda x: isinstance(x, PartitionSpec))
        else:
            param_specs = shard_specs
    else:
        param_specs = build(shard_fsdp=False)
    grad_specs = shard_specs if zero_stage >= 2 else param_specs

    from deepspeed_tpu.comm.topology import batch_spec_entry

    seq_axis = AXIS_SEQ if topo.size(AXIS_SEQ) > 1 else None
    batch_spec = PartitionSpec(batch_spec_entry(topo.mesh), seq_axis)
    return ShardingPlan(
        topo=topo,
        param_specs=param_specs,
        shard_specs=shard_specs,
        grad_specs=grad_specs,
        batch_spec=batch_spec,
    )


def opt_state_shardings(optimizer, abstract_params, plan: ShardingPlan):
    """Optimizer-state shardings: moment buffers inherit the fully-sharded
    (stage-3 style) param layout, scalars replicate.

    This is how ZeRO-1/2 shard optimizer state while keeping live params
    replicated (reference: ``stage_1_and_2.py`` flat fp32 partitions). optax
    states embed param-congruent subtrees (e.g. ``ScaleByAdamState.mu``); each
    state leaf is matched to its param by *path suffix* + shape, so any chain
    of transforms works without optimizer-specific knowledge.
    """
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), abstract_params
    )
    abstract_state = jax.eval_shape(optimizer.init, abstract)

    param_index: dict = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(abstract)[0]:
        key = tuple(str(k) for k in path)
        spec = _lookup_spec(plan.shard_specs, path)
        param_index[key] = (tuple(leaf.shape), spec)

    mesh = plan.topo.mesh
    replicated = NamedSharding(mesh, PartitionSpec())

    def spec_for_state_leaf(path, leaf):
        key = tuple(str(k) for k in path)
        shape = tuple(leaf.shape)
        for start in range(len(key)):
            hit = param_index.get(key[start:])
            if hit is not None and hit[0] == shape:
                return NamedSharding(mesh, hit[1])
        return replicated

    flat, treedef = jax.tree_util.tree_flatten_with_path(abstract_state)
    shardings = [spec_for_state_leaf(path, leaf) for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, shardings)


def grouped_opt_state_shardings(optimizer, group_leaves: tuple, group_shardings,
                                mesh):
    """Shardings for an optimizer state over a TUPLE of param leaves (the
    offload sub-group representation): state leaves congruent to the i-th
    group leaf (matched by trailing tuple index + shape) inherit its sharding,
    scalars replicate."""
    abstract = tuple(
        jax.ShapeDtypeStruct(tuple(x.shape), x.dtype) for x in group_leaves
    )
    abstract_state = jax.eval_shape(optimizer.init, abstract)
    replicated = NamedSharding(mesh, PartitionSpec())

    def spec(path, leaf):
        last = path[-1] if path else None
        i = getattr(last, "idx", None)
        if (i is not None and i < len(group_leaves)
                and tuple(leaf.shape) == tuple(group_leaves[i].shape)):
            return group_shardings[i]
        return replicated

    flat, treedef = jax.tree_util.tree_flatten_with_path(abstract_state)
    return jax.tree_util.tree_unflatten(
        treedef, [spec(p, l) for p, l in flat]
    )


def _lookup_spec(spec_tree, path):
    node = spec_tree
    for k in path:
        if hasattr(k, "key"):
            node = node[k.key]
        elif hasattr(k, "idx"):
            node = node[k.idx]
        else:
            node = node[k.name]
    return node


def shard_params(params, plan: ShardingPlan):
    """Place (or re-place) a parameter pytree according to the plan."""
    return jax.device_put(params, plan.param_shardings)
