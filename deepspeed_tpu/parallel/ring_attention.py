"""Ring attention: context parallelism over the sequence axis.

The reference has NO ring/context-parallel implementation (SURVEY.md §5.7) —
its long-context answer is Ulysses all-to-all plus chunked/offloaded attention
(FPDT). On TPU, ring attention over an ICI ring is the idiomatic counterpart:
KV shards rotate around the ``sequence`` axis with ``ppermute`` while each rank
accumulates blockwise-softmax partial attention for its local queries — comm is
fully overlappable with the block compute, and per-device memory stays
O(S/P). Offered as ``sequence_parallel.mode = "ring"``.

Implementation: ``shard_map`` over the sequence axis; fp32 online-softmax
accumulation (same math as flash attention's outer loop, with the KV loop
distributed). Causality is enforced by global-position masking, so the result
is exact vs. single-device causal attention.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.topology import AXIS_SEQ, batch_spec_entry
from deepspeed_tpu.ops.attention import repeat_kv

_NEG_INF = -1e30


def _ring_attention_local(q, k, v, axis_name: str, causal: bool, scale):
    """Runs inside shard_map: q/k/v are local seq shards [B, S_loc, H, D]."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    qf = (q * scale).astype(jnp.float32)
    q_pos = my * s_loc + jnp.arange(s_loc)  # global positions of local queries

    # accumulator state: running max m, denom l, weighted sum o (all fp32)
    o0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)

    def step(carry, i):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        src = (my - i) % n  # which global KV block we currently hold
        k_pos = src * s_loc + jnp.arange(s_loc)

        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, k_cur.astype(jnp.float32))
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, _NEG_INF)

        m_blk = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m_acc, m_blk)
        # guard fully-masked rows (m_new == -inf): exp(_NEG_INF - _NEG_INF) -> use safe sub
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m_acc - m_new)
        l_new = l_acc * corr + jnp.sum(p, axis=-1)
        o_blk = jnp.einsum("bhqk,bkhd->bqhd", p, v_cur.astype(jnp.float32))
        o_new = o_acc * corr.transpose(0, 2, 1)[..., None] + o_blk

        perm = [(j, (j + 1) % n) for j in range(n)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    (o, _, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def ring_attention(q, k, v, mesh, causal: bool = True, scale=None):
    """[B, S, H, D] seq-sharded in/out; exact causal attention over the ring."""
    sp = mesh.shape.get(AXIS_SEQ, 1)
    if sp <= 1:
        from deepspeed_tpu.ops.attention import xla_attention

        return xla_attention(q, k, v, causal=causal, scale=scale)
    k = repeat_kv(k, q.shape[2] // k.shape[2])
    v = repeat_kv(v, q.shape[2] // v.shape[2])

    spec = P(None, AXIS_SEQ, None, None)
    fn = functools.partial(_ring_attention_local, axis_name=AXIS_SEQ,
                           causal=causal, scale=scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={AXIS_SEQ},
                         check_vma=False)(q, k, v)
