"""Ring attention: context parallelism over the sequence axis.

The reference has NO ring/context-parallel implementation (SURVEY.md §5.7) —
its long-context answer is Ulysses all-to-all plus chunked/offloaded attention
(FPDT, ``/root/reference/deepspeed/sequence/fpdt_layer.py:545`` — chunked
online-softmax with recompute, the memory behavior matched here). On TPU, ring
attention over an ICI ring is the idiomatic counterpart: KV shards rotate
around the ``sequence`` axis with ``ppermute`` while each rank accumulates
blockwise-softmax partial attention for its local queries — comm is fully
overlappable with the block compute, and per-device memory stays O(S/P).
Offered as ``sequence_parallel.mode = "ring"``.

Implementation: ``shard_map`` over the sequence axis; fp32 online-softmax
accumulation (same math as flash attention's outer loop, with the KV loop
distributed). Causality is enforced by global-position masking, so the result
is exact vs. single-device causal attention.

Memory: the op carries a **custom VJP**. Autodiff through the forward scan
would save every ring step's ``[S_loc, S_loc]`` score block (O(S_loc²·n)
backward memory — the exact quadratic blow-up flash attention exists to
avoid). Instead the forward saves only ``(q, k, v, o, lse)`` — O(S_loc·d) —
and the backward re-runs the ring, recomputing each block's probabilities
from the saved log-sum-exp while dk/dv accumulators travel around the ring
with their KV block. Within each ring step the query dimension is processed
in fixed-size chunks (an inner ``lax.scan``) so transient score blocks are
``[chunk, S_loc]``, never ``[S_loc, S_loc]``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.topology import AXIS_SEQ
from deepspeed_tpu.ops.attention import repeat_kv
from deepspeed_tpu.parallel.sequence_tiling import (
    _from_tiles as _unchunk_seq,
    _to_tiles,
)
from deepspeed_tpu.utils.compat import axis_size_compat, shard_map_compat

_NEG_INF = -1e30
_MAX_Q_CHUNK = 2048


def _pick_chunk(s_loc: int) -> int:
    """Largest divisor of s_loc not exceeding _MAX_Q_CHUNK."""
    c = min(s_loc, _MAX_Q_CHUNK)
    while s_loc % c:
        c -= 1
    return c


def _chunk_seq(x, c):
    """[b, s, ...] -> [nc, b, c, ...] (chunk axis leading, for scan)."""
    return _to_tiles(x, c)


def _chunk_rows(x, c):
    """[b, h, s] -> [nc, b, h, c]"""
    b, h, s = x.shape
    return x.reshape(b, h, s // c, c).transpose(2, 0, 1, 3)


def _unchunk_rows(x):
    """[nc, b, h, c] -> [b, h, s]"""
    nc, b, h, c = x.shape
    return x.transpose(1, 2, 0, 3).reshape(b, h, nc * c)


def _rotate(x, axis_name, n):
    return lax.ppermute(x, axis_name, [(j, (j + 1) % n) for j in range(n)])


def _ring_fwd_compute(q, k, v, axis_name: str, causal: bool, scale):
    """Online-softmax ring forward. Returns (o [b,s,h,d] in q.dtype, lse [b,h,s] fp32)."""
    n = axis_size_compat(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    c = _pick_chunk(s_loc)
    nc = s_loc // c

    qf = _chunk_seq((q * scale).astype(jnp.float32), c)  # [nc,b,c,h,d]
    pos_c = jnp.arange(s_loc).reshape(nc, c)  # local q positions per chunk

    o0 = jnp.zeros((nc, b, c, h, d), jnp.float32)
    m0 = jnp.full((nc, b, h, c), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((nc, b, h, c), jnp.float32)

    def ring_step(carry, i):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        src = (my - i) % n  # which global KV block we currently hold
        k_pos = src * s_loc + jnp.arange(s_loc)
        kf = k_cur.astype(jnp.float32)
        vf = v_cur.astype(jnp.float32)

        def chunk_step(_, xs):
            qc, oc, mc, lc, pc = xs
            scores = jnp.einsum("bqhd,bkhd->bhqk", qc, kf)
            if causal:
                q_pos = my * s_loc + pc
                mask = q_pos[:, None] >= k_pos[None, :]
                scores = jnp.where(mask[None, None], scores, _NEG_INF)
            m_blk = jnp.max(scores, axis=-1)
            m_new = jnp.maximum(mc, m_blk)
            p = jnp.exp(scores - m_new[..., None])
            corr = jnp.exp(mc - m_new)
            l_new = lc * corr + jnp.sum(p, axis=-1)
            o_new = (oc * corr.transpose(0, 2, 1)[..., None]
                     + jnp.einsum("bhqk,bkhd->bqhd", p, vf))
            return None, (o_new, m_new, l_new)

        def compute(ops):
            o_a, m_a, l_a = ops
            _, out = lax.scan(chunk_step, None, (qf, o_a, m_a, l_a, pos_c))
            return out

        if causal:
            # blocks strictly in the future of every local query are fully
            # masked — skip their compute, just rotate
            o_acc, m_acc, l_acc = lax.cond(
                src <= my, compute, lambda ops: ops, (o_acc, m_acc, l_acc)
            )
        else:
            o_acc, m_acc, l_acc = compute((o_acc, m_acc, l_acc))

        return (o_acc, m_acc, l_acc,
                _rotate(k_cur, axis_name, n), _rotate(v_cur, axis_name, n)), None

    (o, m, l, _, _), _ = lax.scan(ring_step, (o0, m0, l0, k, v), jnp.arange(n))
    denom = jnp.maximum(l, 1e-30)  # [nc,b,h,c]
    lse = m + jnp.log(denom)
    o = o / denom.transpose(0, 1, 3, 2)[..., None]
    return _unchunk_seq(o).astype(q.dtype), _unchunk_rows(lse)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_attention_local(q, k, v, axis_name: str, causal: bool, scale):
    """Runs inside shard_map: q/k/v are local seq shards [B, S_loc, H, D]."""
    o, _ = _ring_fwd_compute(q, k, v, axis_name, causal, scale)
    return o


def _ring_fwd_rule(q, k, v, axis_name, causal, scale):
    o, lse = _ring_fwd_compute(q, k, v, axis_name, causal, scale)
    return o, (q, k, v, o, lse)


def _ring_bwd_rule(axis_name, causal, scale, res, do):
    q, k, v, o, lse = res
    n = axis_size_compat(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    c = _pick_chunk(s_loc)
    nc = s_loc // c

    qf = _chunk_seq((q * scale).astype(jnp.float32), c)  # [nc,b,c,h,d]
    do_c = _chunk_seq(do.astype(jnp.float32), c)
    # delta_i = sum_d do_i * o_i  (rescaling term of the softmax backward)
    delta = _chunk_rows(
        jnp.einsum("bshd,bshd->bhs", do.astype(jnp.float32), o.astype(jnp.float32)), c
    )  # [nc,b,h,c]
    lse_c = _chunk_rows(lse, c)
    pos_c = jnp.arange(s_loc).reshape(nc, c)

    dq0 = jnp.zeros((nc, b, c, h, d), jnp.float32)
    dk0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
    dv0 = jnp.zeros((b, s_loc, h, d), jnp.float32)

    def ring_step(carry, i):
        dq_acc, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (my - i) % n
        k_pos = src * s_loc + jnp.arange(s_loc)
        kf = k_cur.astype(jnp.float32)
        vf = v_cur.astype(jnp.float32)

        def chunk_step(carry2, xs):
            dk_a, dv_a = carry2
            qc, dqc, doc, deltac, lsec, pc = xs
            scores = jnp.einsum("bqhd,bkhd->bhqk", qc, kf)
            if causal:
                q_pos = my * s_loc + pc
                mask = q_pos[:, None] >= k_pos[None, :]
                scores = jnp.where(mask[None, None], scores, _NEG_INF)
            # recompute probabilities from the saved global log-sum-exp;
            # masked entries underflow to exactly 0
            p = jnp.exp(scores - lsec[..., None])  # [b,h,c,S_loc]
            dv_a = dv_a + jnp.einsum("bhqk,bqhd->bkhd", p, doc)
            dp = jnp.einsum("bqhd,bkhd->bhqk", doc, vf)
            ds = p * (dp - deltac[..., None])
            dq_new = dqc + jnp.einsum("bhqk,bkhd->bqhd", ds, kf) * scale
            # qc is pre-scaled, so ds^T @ qc already carries the scale factor
            dk_a = dk_a + jnp.einsum("bhqk,bqhd->bkhd", ds, qc)
            return (dk_a, dv_a), dq_new

        def compute(ops):
            dq_a, dk_a, dv_a = ops
            (dk_n, dv_n), dq_n = lax.scan(
                chunk_step, (dk_a, dv_a), (qf, dq_a, do_c, delta, lse_c, pos_c)
            )
            return dq_n, dk_n, dv_n

        if causal:
            dq_acc, dk_cur, dv_cur = lax.cond(
                src <= my, compute, lambda ops: ops, (dq_acc, dk_cur, dv_cur)
            )
        else:
            dq_acc, dk_cur, dv_cur = compute((dq_acc, dk_cur, dv_cur))

        # dk/dv accumulators travel with their KV block; after n rotations the
        # block (and its fully-accumulated gradient) is back at its owner
        return (dq_acc,
                _rotate(k_cur, axis_name, n), _rotate(v_cur, axis_name, n),
                _rotate(dk_cur, axis_name, n), _rotate(dv_cur, axis_name, n)), None

    (dq, _, _, dk, dv), _ = lax.scan(ring_step, (dq0, k, v, dk0, dv0), jnp.arange(n))
    return (_unchunk_seq(dq).astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


_ring_attention_local.defvjp(_ring_fwd_rule, _ring_bwd_rule)


def ring_attention(q, k, v, mesh, causal: bool = True, scale=None):
    """[B, S, H, D] seq-sharded in/out; exact causal attention over the ring."""
    sp = mesh.shape.get(AXIS_SEQ, 1)
    if sp <= 1:
        from deepspeed_tpu.ops.attention import xla_attention

        return xla_attention(q, k, v, causal=causal, scale=scale)
    k = repeat_kv(k, q.shape[2] // k.shape[2])
    v = repeat_kv(v, q.shape[2] // v.shape[2])

    spec = P(None, AXIS_SEQ, None, None)

    def fn(q, k, v):  # custom_vjp nondiff args must be positional
        return _ring_attention_local(q, k, v, AXIS_SEQ, causal, scale)
    return shard_map_compat(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={AXIS_SEQ},
                         check_vma=False)(q, k, v)
