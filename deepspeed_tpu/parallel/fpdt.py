"""FPDT: fully-pipelined chunked attention with host-offloaded residuals.

Role parity with the reference FPDT
(``/root/reference/deepspeed/sequence/fpdt_layer.py:545
_FPDTGPUOffloadingAttentionImpl_``): the local sequence is processed in
``num_chunks`` chunks with online-softmax accumulation across chunks, and the
Q/K/V/O tensors are offloaded to host DRAM between uses so device memory holds
O(S·S/num_chunks) transients instead of O(S²) score blocks or O(S) residual
sets. Composes with Ulysses SP exactly like the reference (FPDT runs on the
post-all-to-all head-sharded/full-sequence layout) to reach multi-million
token contexts with a small SP degree.

TPU-native mechanism (not a port): the reference hand-drives CUDA streams and
pinned-buffer double buffering. Here a **custom VJP** stores the residuals in
the host memory space (``jax.memory.Space.Host``) and the backward streams
them back chunk-by-chunk as ``lax.scan`` inputs — XLA's latency-hiding
scheduler overlaps each chunk's host->HBM transfer with the previous chunk's
compute, which is the double-buffering the reference builds manually. The
probabilities are recomputed from the saved per-row log-sum-exp (flash-style),
never stored.

Degrees of freedom vs ``parallel/ring_attention.py``: ring distributes the KV
loop over the ``sequence`` mesh axis (comm = ppermute); FPDT chunks it in
time on one device (comm = host DMA). They solve the same O(S²) memory
problem at different scales and compose: ring/Ulysses across chips, FPDT
within a chip.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def host_offload_supported() -> bool:
    """Functional probe: can this backend round-trip an array through the
    host memory space inside jit? (capability-probe pattern, like
    ``offload.supports_memory_kinds``)."""
    global _HOST_PROBE
    try:
        return _HOST_PROBE
    except NameError:
        pass
    try:
        out = jax.jit(
            lambda x: jax.device_put(
                jax.device_put(x, jax.memory.Space.Host),
                jax.memory.Space.Device) + 1
        )(jnp.zeros((8,)))
        jax.block_until_ready(out)
        _HOST_PROBE = True
    except Exception:
        _HOST_PROBE = False
    return _HOST_PROBE


def _chunk(x, nc):
    """[b, s, ...] -> [nc, b, c, ...]"""
    b, s = x.shape[:2]
    return x.reshape((b, nc, s // nc) + x.shape[2:]).swapaxes(0, 1)


def _unchunk(x):
    """[nc, b, c, ...] -> [b, s, ...]"""
    nc, b, c = x.shape[:3]
    return x.swapaxes(0, 1).reshape((b, nc * c) + x.shape[3:])


def _to_host(x, offload: bool):
    return jax.device_put(x, jax.memory.Space.Host) if offload else x


def _to_device(x, offload: bool):
    return jax.device_put(x, jax.memory.Space.Device) if offload else x


def _fpdt_fwd_compute(q, k, v, nc: int, causal: bool, scale):
    """Chunked online-softmax forward (reference FPDT forward loop).

    Outer scan over KV chunks, inner scan over Q chunks; fully-masked
    (j > i) pairs are skipped with ``lax.cond``. K/V may have fewer (GQA)
    heads — they are expanded per-chunk on device, never materialized at
    full size. Returns (o [b,s,h,d] in q.dtype, lse [nc,b,h,c] fp32).
    """
    from deepspeed_tpu.ops.attention import repeat_kv

    b, s, h, d = q.shape
    rep = h // k.shape[2]
    c = s // nc
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    qf = _chunk((q * scale).astype(jnp.float32), nc)  # [nc,b,c,h,d]
    kcs = _chunk(k, nc)
    vcs = _chunk(v, nc)

    o0 = jnp.zeros((nc, b, c, h, d), jnp.float32)
    m0 = jnp.full((nc, b, h, c), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((nc, b, h, c), jnp.float32)
    pos = jnp.arange(c)

    def kv_step(carry, xs):
        o_acc, m_acc, l_acc = carry
        kj, vj, j = xs
        kf = repeat_kv(kj.astype(jnp.float32), rep)
        vf = repeat_kv(vj.astype(jnp.float32), rep)
        k_pos = j * c + pos

        def q_step(_, ys):
            qc, oc, mc, lc, i = ys

            def compute(ops):
                oc, mc, lc = ops
                scores = jnp.einsum("bqhd,bkhd->bhqk", qc, kf)
                if causal:
                    q_pos = i * c + pos
                    mask = q_pos[:, None] >= k_pos[None, :]
                    scores = jnp.where(mask[None, None], scores, _NEG_INF)
                m_blk = jnp.max(scores, axis=-1)
                m_new = jnp.maximum(mc, m_blk)
                p = jnp.exp(scores - m_new[..., None])
                corr = jnp.exp(mc - m_new)
                l_new = lc * corr + jnp.sum(p, axis=-1)
                o_new = (oc * corr.transpose(0, 2, 1)[..., None]
                         + jnp.einsum("bhqk,bkhd->bqhd", p, vf))
                return o_new, m_new, l_new

            if causal:
                oc, mc, lc = lax.cond(j <= i, compute, lambda ops: ops,
                                      (oc, mc, lc))
            else:
                oc, mc, lc = compute((oc, mc, lc))
            return None, (oc, mc, lc)

        _, (o_acc, m_acc, l_acc) = lax.scan(
            q_step, None, (qf, o_acc, m_acc, l_acc, jnp.arange(nc)))
        return (o_acc, m_acc, l_acc), None

    (o, m, l), _ = lax.scan(kv_step, (o0, m0, l0),
                            (kcs, vcs, jnp.arange(nc)))
    denom = jnp.maximum(l, 1e-30)
    lse = m + jnp.log(denom)
    o = o / denom.transpose(0, 1, 3, 2)[..., None]
    return _unchunk(o).astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fpdt_attention(q, k, v, nc: int, causal: bool, scale, offload: bool):
    o, _ = _fpdt_fwd_compute(q, k, v, nc, causal, scale)
    return o


def _fpdt_fwd_rule(q, k, v, nc, causal, scale, offload):
    o, lse = _fpdt_fwd_compute(q, k, v, nc, causal, scale)
    # residuals live in host DRAM between fwd and bwd (the reference's
    # pinned-memory chunk pool); lse is small and stays on device
    res = (_to_host(_chunk(q, nc), offload), _to_host(_chunk(k, nc), offload),
           _to_host(_chunk(v, nc), offload), _to_host(_chunk(o, nc), offload),
           lse)
    return o, res


def _fpdt_bwd_rule(nc, causal, scale, offload, res, do):
    from deepspeed_tpu.ops.attention import repeat_kv

    q_h, k_h, v_h, o_h, lse = res
    _, b, c, h, d = q_h.shape
    hkv = k_h.shape[3]
    rep = h // hkv
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    do_c = _chunk(do.astype(jnp.float32), nc)
    pos = jnp.arange(c)

    # delta_i = sum_d do_i * o_i, precomputed in ONE streaming pass over the
    # host-resident O chunks — O never enters the (i, j) pair loop, so it
    # crosses the host link once, not nc/2 times
    def delta_step(_, ys):
        oc_h, doc = ys
        oc = _to_device(oc_h, offload).astype(jnp.float32)
        return None, jnp.einsum("bqhd,bqhd->bhq", doc, oc)

    _, delta = lax.scan(delta_step, None, (o_h, do_c))  # [nc,b,h,c]

    dq0 = jnp.zeros((nc, b, c, h, d), jnp.float32)

    def kv_step(dq_acc, xs):
        kj_h, vj_h, j = xs
        # stream this KV chunk back from host; XLA overlaps the transfer
        # with the previous chunk's compute (reference double buffering)
        kf = repeat_kv(_to_device(kj_h, offload).astype(jnp.float32), rep)
        vf = repeat_kv(_to_device(vj_h, offload).astype(jnp.float32), rep)
        k_pos = j * c + pos
        dk0 = jnp.zeros((b, c, h, d), jnp.float32)
        dv0 = jnp.zeros((b, c, h, d), jnp.float32)

        def q_step(carry2, ys):
            dk_a, dv_a = carry2
            qc_h, dqc, doc, deltac, lsec, i = ys

            def compute(ops):
                dk_a, dv_a, dqc = ops
                qc = _to_device(qc_h, offload).astype(jnp.float32) * scale
                scores = jnp.einsum("bqhd,bkhd->bhqk", qc, kf)
                if causal:
                    q_pos = i * c + pos
                    mask = q_pos[:, None] >= k_pos[None, :]
                    scores = jnp.where(mask[None, None], scores, _NEG_INF)
                p = jnp.exp(scores - lsec[..., None])  # saved global lse
                dv_a = dv_a + jnp.einsum("bhqk,bqhd->bkhd", p, doc)
                dp = jnp.einsum("bqhd,bkhd->bhqk", doc, vf)
                ds = p * (dp - deltac[..., None])
                dq_new = dqc + jnp.einsum("bhqk,bkhd->bqhd", ds, kf) * scale
                # qc carries the scale factor already
                dk_a = dk_a + jnp.einsum("bhqk,bqhd->bkhd", ds, qc)
                return dk_a, dv_a, dq_new

            if causal:
                dk_a, dv_a, dqc = lax.cond(
                    j <= i, compute, lambda ops: ops, (dk_a, dv_a, dqc))
            else:
                dk_a, dv_a, dqc = compute((dk_a, dv_a, dqc))
            return (dk_a, dv_a), dqc

        (dkj, dvj), dq_acc = lax.scan(
            q_step, (dk0, dv0),
            (q_h, dq_acc, do_c, delta, lse, jnp.arange(nc)))
        # reduce the repeated-head gradient back onto the true KV heads
        dkj = dkj.reshape(b, c, hkv, rep, d).sum(3)
        dvj = dvj.reshape(b, c, hkv, rep, d).sum(3)
        return dq_acc, (dkj, dvj)

    dq, (dk, dv) = lax.scan(kv_step, dq0, (k_h, v_h, jnp.arange(nc)))
    return (_unchunk(dq).astype(q_h.dtype), _unchunk(dk).astype(k_h.dtype),
            _unchunk(dv).astype(v_h.dtype))


_fpdt_attention.defvjp(_fpdt_fwd_rule, _fpdt_bwd_rule)


def fpdt_attention(q, k, v, num_chunks: int, causal: bool = True, scale=None,
                   offload: bool | None = None):
    """Chunked causal attention, [B, S, H, D] -> [B, S, H, D]; exact vs dense.

    ``num_chunks`` divides S — the sequence *as seen by this attention call*
    (under Ulysses that is the full post-all-to-all sequence, not the
    per-rank shard). GQA K/V stay at their true head count end-to-end (host
    residuals are NOT head-repeated). ``offload=None`` auto-detects
    host-space support; pass False to keep residuals in HBM (chunked
    compute only).
    """
    b, s, h, d = q.shape
    if s % num_chunks:
        raise ValueError(f"sequence length {s} not divisible by "
                         f"num_chunks {num_chunks}")
    if h % k.shape[2]:
        raise ValueError(f"q heads {h} not a multiple of kv heads {k.shape[2]}")
    if offload is None:
        offload = host_offload_supported()
    return _fpdt_attention(q, k, v, num_chunks, causal, scale, bool(offload))
