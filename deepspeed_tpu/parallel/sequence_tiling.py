"""ALST sequence-tiled compute: tiled fused logits loss + tiled MLP.

Long-context training OOMs on the loss head long before attention: a
``[B, S, V]`` logits tensor at 128K tokens is tens of GB regardless of how well
attention is sharded. The reference solves this with
``TiledFusedLogitsLoss`` / ``TiledMLP`` (``/root/reference/deepspeed/runtime/
sequence_parallel/ulysses_sp.py:1065,943``), autograd.Function wrappers that
shard the sequence dim and recompute each shard in backward.

TPU-native design: a ``lax.scan`` over sequence tiles with ``jax.checkpoint``
on the tile body. Forward materializes one ``[B, tile, V]`` logits block at a
time (XLA reuses the buffer across scan iterations); backward recomputes each
tile's logits and accumulates the head/hidden cotangents through the scan —
the same memory shape as the reference's shard-by-shard ``torch.autograd.grad``
loop, but compiled as one XLA program instead of a Python loop over shards.

Composes with Ulysses/ring sequence parallelism (``S`` here is the local
sequence shard) and with the GAS microbatch scan in the engine.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def _pad_seq(x: jnp.ndarray, tile_size: int, pad_value=0):
    """Pad dim 1 (sequence) up to a multiple of tile_size."""
    pad = (-x.shape[1]) % tile_size
    if pad == 0:
        return x, 0
    widths = [(0, 0)] * x.ndim
    widths[1] = (0, pad)
    return jnp.pad(x, widths, constant_values=pad_value), pad


def _to_tiles(x: jnp.ndarray, tile_size: int) -> jnp.ndarray:
    """[B, S, ...] -> [S/tile, B, tile, ...] (scan axis leading)."""
    b, s = x.shape[:2]
    n = s // tile_size
    return x.reshape((b, n, tile_size) + x.shape[2:]).swapaxes(0, 1)


def _from_tiles(x: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`_to_tiles`: [N, B, tile, ...] -> [B, N*tile, ...]."""
    n, b, t = x.shape[:3]
    return x.swapaxes(0, 1).reshape((b, n * t) + x.shape[3:])


def tiled_causal_lm_loss(
    hidden: jnp.ndarray,
    head: jnp.ndarray,
    input_ids: jnp.ndarray | None = None,
    labels: jnp.ndarray | None = None,
    *,
    ignore_index: int = -100,
    z_loss: float = 0.0,
    tile_size: int = 1024,
) -> jnp.ndarray:
    """Next-token cross entropy without materializing ``[B, S, V]`` logits.

    Numerically equivalent to ``causal_lm_loss(hidden @ head, input_ids,
    labels)`` (``models/api.py``): fp32 log-softmax, ignore_index masking,
    mean over unmasked targets, optional z-loss. ``hidden`` is the final
    (post-norm) hidden state ``[B, S, D]``; ``head`` the ``[D, V]`` projection.
    """
    b, s, _ = hidden.shape
    if labels is None:
        if input_ids is None:
            raise ValueError("tiled_causal_lm_loss needs input_ids or labels")
        # shift left; final position has no target (masked via ignore_index)
        targets = jnp.concatenate(
            [input_ids[:, 1:], jnp.full((b, 1), ignore_index, input_ids.dtype)], axis=1
        )
    else:
        targets = labels

    hidden, _ = _pad_seq(hidden, tile_size)
    targets, _ = _pad_seq(targets, tile_size, pad_value=ignore_index)
    xt = _to_tiles(hidden, tile_size)
    tt = _to_tiles(targets, tile_size)

    def tile_body(carry, xs_ts):
        xs, ts = xs_ts
        logits = (xs @ head.astype(xs.dtype)).astype(jnp.float32)
        mask = (ts != ignore_index).astype(jnp.float32)
        safe = jnp.where(ts == ignore_index, 0, ts)
        logz = jax.nn.logsumexp(logits, axis=-1)
        true_logit = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        nll_sum, z_sum, cnt = carry
        nll_sum = nll_sum + ((logz - true_logit) * mask).sum()
        z_sum = z_sum + ((logz * mask) ** 2).sum()
        cnt = cnt + mask.sum()
        return (nll_sum, z_sum, cnt), None

    zero = jnp.float32(0.0)
    (nll_sum, z_sum, cnt), _ = lax.scan(
        jax.checkpoint(tile_body), (zero, zero, zero), (xt, tt)
    )
    denom = jnp.maximum(cnt, 1.0)
    loss = nll_sum / denom
    if z_loss > 0.0:
        loss = loss + z_loss * z_sum / denom
    return loss


def tiled_apply(
    fn: Callable[[jnp.ndarray], jnp.ndarray],
    x: jnp.ndarray,
    tile_size: int,
) -> jnp.ndarray:
    """Apply a token-local function over sequence tiles with per-tile remat
    (reference ``TiledMLP``, ``ulysses_sp.py:943``).

    ``fn`` must act independently per token position (MLPs, norms,
    projections — not attention). Forward peak shrinks from ``[B, S, F]``
    intermediates to ``[B, tile, F]``; backward recomputes per tile.
    """
    b, s = x.shape[:2]
    xp, pad = _pad_seq(x, tile_size)
    xt = _to_tiles(xp, tile_size)

    def tile_body(carry, xs):
        return carry, fn(xs)

    _, yt = lax.scan(jax.checkpoint(tile_body), None, xt)
    y = yt.swapaxes(0, 1).reshape((b, s + pad) + yt.shape[3:])
    return y[:, :s] if pad else y


# reference-parity alias (TiledMLP is tiled_apply over the MLP body)
tiled_mlp = tiled_apply
