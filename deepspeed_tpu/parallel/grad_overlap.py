"""Overlap-first data-parallel backward: bucketed async gradient collectives.

The committed TP-overlap finding (docs/TP_OVERLAP.md findings 1-4) showed that
GSPMD's gradient all-reduce lowers *synchronously* on the v5e target — one
fused reduction over the whole grad tree, dependent on every leaf, with
nothing for the latency-hiding scheduler to move — while ``collective-permute``
rings lower to async ``-start/-done`` pairs with independent fusions scheduled
inside the transfer windows.

This module is the gradient-sync half of that consequence (T3-style
fine-grained overlap, arxiv 2401.16677): partition the grad tree into
size-targeted buckets and reduce each bucket with its own ppermute ring inside
a ``shard_map`` manual region over the data axis.  Each bucket's ring depends
only on that bucket's grad leaves — NOT on the full tree — so XLA is free to
issue bucket k's transfer while the backward is still producing bucket k+1's
grads (the backward walks last-layer-first; path-ordered buckets put the
early-produced grads in late buckets, and the scheduler fills the windows
either way because the rings carry no cross-bucket dependency).

The bucket plan is deterministic: leaves are keyed and ordered by their pytree
key-path, so the same param tree always yields the same assignment — across
processes and across restarts — which is what lets the ZeRO-1 flat optimizer
state (`runtime/engine.py _init_overlap_opt_state`) survive checkpoint/resume.

Numerics: the ring reduce-scatter accumulates each chunk's contributions in
ring order (rank r's chunk sums contributions in the order r+1, r+2, ..., r).
For dp=2 this is bit-identical to any all-reduce (two-term fp addition is
commutative); for dp>2 it is a documented fp-reordering of the same exact sum
— bounded, not approximate.  The exactness kill switch
(``zero_optimization.grad_overlap.exact``) routes the engine back through the
fused baseline program, which is bit-identical by construction.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepspeed_tpu.utils.compat import shard_map_compat  # noqa: F401 (re-export)

__all__ = [
    "Bucket",
    "BucketPlan",
    "shard_map_compat",
    "plan_buckets",
    "ordered_leaves",
    "pack_bucket",
    "unpack_bucket",
    "unflatten_buckets",
    "local_shard",
    "ring_reduce_scatter_sum",
    "ring_all_gather",
    "wire_bytes_per_element",
]

# flat bucket lengths pad to a multiple of dp * _PAD so every rank's shard is
# lane-aligned; the waste is bounded by dp * _PAD * 4 bytes per bucket
_PAD = 128

# qgZ blockwise codec geometry (comm/quantized_collectives.py default block):
# each quantized wire stage carries one fp32 scale per block of elements
_QGZ_BLOCK = 64


def wire_bytes_per_element(codec: str, block: int = _QGZ_BLOCK) -> float:
    """Wire bytes one gradient element costs under the reduction codec.

    ``fp32`` is the dense 4 B/elem wire.  ``int8``/``int4``/``int1`` are the
    qgZ quantized wires: payload bits plus the per-block fp32 scales of the
    two quantized stages (all-to-all reduce + all-gather re-broadcast).
    """
    if codec == "fp32":
        return 4.0
    if not codec.startswith("int"):
        raise ValueError(f"unknown reduction codec {codec!r}")
    bits = int(codec[3:])
    return bits / 8.0 + 2 * 4.0 / block


@dataclass(frozen=True)
class BucketLeaf:
    """One grad leaf's slot inside a bucket."""

    path: str          # rendered pytree key-path (the deterministic sort key)
    pos: int           # index into the plan's path-ordered leaf list
    shape: tuple       # leaf shape
    size: int          # element count
    offset: int        # flat offset inside the bucket


@dataclass(frozen=True)
class Bucket:
    index: int
    leaves: tuple      # tuple[BucketLeaf]
    elems: int         # payload elements (sum of leaf sizes)
    padded: int        # flat length after dp*_PAD alignment
    shard: int         # padded // dp — one rank's slice
    codec: str         # "fp32" or "int{bits}" (qgZ)
    wire_bytes: int    # per-step ring reduce wire bytes for this bucket


@dataclass(frozen=True)
class BucketPlan:
    buckets: tuple     # tuple[Bucket]
    paths: tuple       # tuple[str], path-ordered
    order: tuple       # order[j] = original flatten position of ordered leaf j
    dp: int
    target_bytes: int  # the pow2-capped effective target
    codec: str

    @property
    def total_elems(self) -> int:
        return sum(b.elems for b in self.buckets)

    def describe(self) -> str:
        sizes = [b.elems * 4 for b in self.buckets]
        return (f"{len(self.buckets)} buckets over {len(self.paths)} leaves, "
                f"target {self.target_bytes} B (pow2-capped), "
                f"sizes {min(sizes)}..{max(sizes)} B, codec {self.codec}")


def _pow2_cap(target_bytes: int) -> int:
    """Round the requested bucket size down to a power of two, so nearby
    config values collapse to the same plan and the ring chunk sizes stay
    friendly to the DMA engines."""
    if target_bytes < 1:
        raise ValueError(f"bucket target must be positive, got {target_bytes}")
    return 1 << (int(target_bytes).bit_length() - 1)


def plan_buckets(tree, dp: int, target_bytes: int,
                 codec: str = "fp32") -> BucketPlan:
    """Partition a grad/param tree into size-targeted buckets.

    Deterministic by construction: leaves are sorted by their rendered pytree
    key-path (a pure function of the tree structure — independent of dict
    insertion order, process, or restart) and packed greedily in that order
    into buckets capped at the pow2-floored ``target_bytes``.  An oversized
    leaf gets a bucket of its own rather than splitting (leaf boundaries keep
    unpacking trivial and the plan stable under small model edits).
    """
    if dp < 1:
        raise ValueError(f"dp must be >= 1, got {dp}")
    leaves_with_path, _ = jax.tree_util.tree_flatten_with_path(tree)
    if not leaves_with_path:
        raise ValueError("cannot plan buckets over an empty tree")
    rendered = []
    for orig_pos, (path, leaf) in enumerate(leaves_with_path):
        dt = getattr(leaf, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.floating):
            raise ValueError(
                f"grad_overlap buckets hold float leaves only; "
                f"{jax.tree_util.keystr(path)} has dtype {dt}")
        rendered.append((jax.tree_util.keystr(path), orig_pos,
                         tuple(leaf.shape)))
    rendered.sort(key=lambda r: r[0])

    target = _pow2_cap(int(target_bytes))
    pad_quantum = dp * _PAD
    buckets = []
    cur: list[BucketLeaf] = []
    cur_bytes = 0

    def close():
        nonlocal cur, cur_bytes
        if not cur:
            return
        elems = sum(l.size for l in cur)
        padded = -(-elems // pad_quantum) * pad_quantum
        wire = int(wire_bytes_per_element(codec) * padded * (dp - 1)
                   / max(dp, 1))
        buckets.append(Bucket(
            index=len(buckets), leaves=tuple(cur), elems=elems,
            padded=padded, shard=padded // dp, codec=codec, wire_bytes=wire))
        cur, cur_bytes = [], 0

    for j, (path, orig_pos, shape) in enumerate(rendered):
        size = int(np.prod(shape)) if shape else 1
        nbytes = 4 * size  # grads accumulate fp32
        if cur and cur_bytes + nbytes > target:
            close()
        cur.append(BucketLeaf(path=path, pos=j, shape=shape, size=size,
                              offset=sum(l.size for l in cur)))
        cur_bytes += nbytes
        if cur_bytes >= target:
            close()
    close()

    return BucketPlan(
        buckets=tuple(buckets),
        paths=tuple(r[0] for r in rendered),
        order=tuple(r[1] for r in rendered),
        dp=dp, target_bytes=target, codec=codec)


def ordered_leaves(tree, plan: BucketPlan):
    """Flatten ``tree`` into the plan's path order. Returns (leaves, treedef)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if len(leaves) != len(plan.order):
        raise ValueError(
            f"tree has {len(leaves)} leaves; plan was built over "
            f"{len(plan.order)}")
    # order[j] is the flatten position of ordered leaf j — flatten order is
    # itself deterministic, so this indexing IS the path sort
    return [leaves[i] for i in plan.order], treedef


def pack_bucket(leaves, bucket: Bucket) -> jnp.ndarray:
    """Concatenate a bucket's (path-ordered) leaves into one padded fp32 flat
    vector. ``leaves`` is the full ordered leaf list from ``ordered_leaves``."""
    parts = [leaves[l.pos].reshape(-1).astype(jnp.float32)
             for l in bucket.leaves]
    pad = bucket.padded - bucket.elems
    if pad:
        parts.append(jnp.zeros((pad,), jnp.float32))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def unpack_bucket(flat: jnp.ndarray, bucket: Bucket, dtypes=None):
    """Invert ``pack_bucket``: slice the flat vector back into leaf arrays
    (static offsets — no gather). ``dtypes``: optional per-leaf target dtypes
    keyed by the leaf's ordered position."""
    out = []
    for l in bucket.leaves:
        x = lax.slice(flat, (l.offset,), (l.offset + l.size,)).reshape(l.shape)
        if dtypes is not None:
            x = x.astype(dtypes[l.pos])
        out.append((l.pos, x))
    return out


def unflatten_buckets(flats, plan: BucketPlan, treedef, dtypes=None):
    """Rebuild the original tree from per-bucket flat vectors."""
    ordered = [None] * len(plan.order)
    for flat, b in zip(flats, plan.buckets):
        for pos, x in unpack_bucket(flat, b, dtypes=dtypes):
            ordered[pos] = x
    orig = [None] * len(plan.order)
    for j, i in enumerate(plan.order):
        orig[i] = ordered[j]
    return jax.tree_util.tree_unflatten(treedef, orig)


def local_shard(flat: jnp.ndarray, axis_name: str, n: int) -> jnp.ndarray:
    """This rank's 1/n slice of a (replicated-value) flat bucket."""
    if n == 1:
        return flat
    shard = flat.shape[0] // n
    r = lax.axis_index(axis_name)
    return lax.dynamic_slice_in_dim(flat, r * shard, shard)


def ring_reduce_scatter_sum(flat: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Ring reduce-scatter over ``axis_name``: rank r returns the fully
    summed chunk r of ``flat`` (length ``flat.size // n``).

    n-1 ppermute hops, each moving one chunk per rank — the bandwidth-optimal
    (n-1)/n wire — and each hop's add is independent per bucket, which is what
    lets the TPU scheduler run the hops as async collective-permute-start/done
    pairs under unrelated backward compute (docs/TP_OVERLAP.md finding 4).

    The message destined for chunk r starts at rank r+1 and walks the ring
    picking up every rank's contribution; contributions therefore sum in ring
    order (r+1, r+2, ..., r).  Exact for dp=2 (two-term fp addition is
    commutative); an fp reorder of the same sum for dp>2.
    """
    n = lax.psum(1, axis_name)
    if n == 1:
        return flat
    if flat.shape[0] % n:
        raise ValueError(
            f"flat length {flat.shape[0]} not divisible by ring size {n}")
    r = lax.axis_index(axis_name)
    chunks = flat.reshape(n, -1)
    perm = [(i, (i + 1) % n) for i in range(n)]
    # my message starts as my contribution to chunk (r - 1) mod n — the chunk
    # that is n-1 hops downstream of me
    acc = jnp.take(chunks, (r - 1) % n, axis=0)
    for h in range(1, n):
        acc = lax.ppermute(acc, axis_name, perm)
        # after hop h the message at rank r is destined for chunk (r - h - 1)
        # mod n; add my local contribution to that chunk
        acc = acc + jnp.take(chunks, (r - h - 1) % n, axis=0)
    return acc


def ring_all_gather(shard: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Ring all-gather over ``axis_name``: every rank returns the rank-ordered
    concatenation [shard_0, ..., shard_{n-1}] (flat). Same async ppermute
    lowering as the reduce-scatter; (n-1)/n wire."""
    n = lax.psum(1, axis_name)
    if n == 1:
        return shard
    r = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    parts = [shard]
    buf = shard
    for _ in range(n - 1):
        buf = lax.ppermute(buf, axis_name, perm)
        parts.append(buf)
    # parts[k] at rank r is rank (r - k) mod n's shard; reorder to rank order
    stack = jnp.stack(parts)
    idx = (r - jnp.arange(n)) % n
    return jnp.take(stack, idx, axis=0).reshape(-1)
