"""1F1B pipeline schedule inside one jit program.

Role parity with the reference ``runtime/pipe/schedule.py:189 TrainSchedule``
(non-interleaved 1F1B: each stage warms up with P-1-s forwards, then
alternates one-forward-one-backward, then drains) — the schedule that bounds
in-flight activations at P microbatches instead of GPipe's M.

TPU-native expression: no instruction interpreter — one ``lax.scan`` over
``2M + 2(P-1)`` slots inside a shard_map that is manual over the ``pipeline``
axis ONLY. Slot membership is closed-form:

    warmup  F of microbatch i at slot t = s + i          (i < P - s)
    steady  F of microbatch i at slot t = 2i + s         (i >= P - s)
    B       of microbatch j at slot t = 2j + 2P - 1 - s

F and B slots have opposite parity in steady state, so each slot runs at most
one of them (a 2-way ``lax.cond``). The backward recomputes the stage block
from the stashed stage INPUT via ``jax.vjp`` (activation remat), so per-stage
activation memory is a P-deep ring of stage inputs — the 1F1B bound.

Because only ``pipeline`` is manual, every other mesh axis (fsdp/tensor/
data/...) stays GSPMD-auto inside the body: stage parameters may be
fsdp-sharded and XLA inserts the gather/reduce-scatter around the stage block
— the PP x ZeRO composition the reference reaches via groups plumbing.

The loss head runs ON the last stage (reference ``PipelineModule`` puts
``loss_fn`` there) and the embedding on stage 0, so the backward seeds itself
— no separate full-model forward.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.topology import AXIS_PIPE
from deepspeed_tpu.utils.compat import shard_map_compat

tree_map = jax.tree_util.tree_map


def bubble_fraction(n_stages: int, num_microbatches: int) -> float:
    """Idle fraction of each stage's timeline: 2(P-1) of 2M + 2(P-1) slots."""
    p, m = n_stages, num_microbatches
    return (2 * (p - 1)) / (2 * m + 2 * (p - 1))


def _zeros_like_tree(t):
    return tree_map(jnp.zeros_like, t)


def _select(pred, a, b):
    return tree_map(lambda x, y: jnp.where(pred, x, y), a, b)


def pipeline_train_grads(
    stage0_fn,      # (extras, mb_in) -> x          (embedding etc.)
    block_fn,       # (layer_slice, extras, x) -> y (this stage's L/P layers)
    last_fn,        # (extras, y, mb_tgt) -> scalar loss for the microbatch
    stacked_params,  # leaves [L, ...]
    extras,          # non-layer params (embed/head/norms), replicated
    mb_in,           # pytree, leaves [M, ...] microbatched inputs
    mb_tgt,          # pytree, leaves [M, ...] microbatched targets
    mesh,
):
    """Full fwd+bwd under the 1F1B schedule.

    Returns ``(mean_loss, stacked_param_grads, extras_grads)`` — gradients of
    ``mean over microbatches of last_fn``, exactly matching autodiff of the
    unpipelined model.
    """
    n_stages = int(mesh.shape.get(AXIS_PIPE, 1))
    m = jax.tree_util.tree_leaves(mb_in)[0].shape[0]
    if m < n_stages:
        raise ValueError(f"1F1B needs microbatches ({m}) >= stages ({n_stages})")

    def local(stacked_local, extras, mb_in, mb_tgt):
        s = lax.axis_index(AXIS_PIPE)
        p = n_stages
        slots = 2 * m + 2 * (p - 1)
        is_first = s == 0
        is_last = s == p - 1

        # probe shapes: what a stage input/output looks like (one microbatch)
        mb0 = tree_map(lambda a: a[0], mb_in)
        x_shape = jax.eval_shape(stage0_fn, extras, mb0)
        x0 = tree_map(lambda sd: jnp.zeros(sd.shape, sd.dtype), x_shape)

        stash0 = tree_map(
            lambda a: jnp.zeros((p,) + a.shape, a.dtype), x0)
        acc_layers0 = _zeros_like_tree(stacked_local)
        acc_extras0 = _zeros_like_tree(extras)
        fwd_perm = [(i, (i + 1) % p) for i in range(p)]
        bwd_perm = [(i, (i - 1) % p) for i in range(p)]

        def fwd_only(x):
            return block_fn(stacked_local, extras, x)

        def slot(carry, t):
            recv_f, recv_b, stash, accl, acce, loss_acc = carry

            # ---- schedule membership (closed form above)
            i_w = t - s                      # warmup F index
            f_warm = (i_w >= 0) & (i_w < jnp.minimum(m, p - s))
            even = ((t - s) % 2) == 0
            i_s = (t - s) // 2               # steady F index
            f_steady = even & (i_s >= p - s) & (i_s < m)
            do_f = f_warm | f_steady
            fi = jnp.clip(jnp.where(f_warm, i_w, i_s), 0, m - 1)

            tb = t - (2 * p - 1 - s)
            do_b = (tb >= 0) & (tb % 2 == 0) & (tb // 2 < m)
            bj = jnp.clip(tb // 2, 0, m - 1)

            # ---- F branch
            def run_f(ops):
                stash, loss_acc = ops
                mb_i = tree_map(lambda a: lax.dynamic_index_in_dim(
                    a, fi, 0, keepdims=False), mb_in)
                x_in = _select(is_first, stage0_fn(extras, mb_i), recv_f)
                y = fwd_only(x_in)
                stash = tree_map(
                    lambda buf, v: lax.dynamic_update_index_in_dim(
                        buf, v, fi % p, 0),
                    stash, x_in)
                # last stage: report the microbatch loss (value only; its
                # gradient is recomputed at the B slot)
                tgt_i = tree_map(lambda a: lax.dynamic_index_in_dim(
                    a, fi, 0, keepdims=False), mb_tgt)
                mb_loss = last_fn(extras, y, tgt_i)
                loss_acc = loss_acc + jnp.where(is_last, mb_loss, 0.0)
                return stash, loss_acc, y

            def skip_f(ops):
                stash, loss_acc = ops
                return stash, loss_acc, x0

            stash, loss_acc, y_out = lax.cond(
                do_f, run_f, skip_f, (stash, loss_acc))

            # ---- B branch (recompute from stashed input + vjp)
            def run_b(ops):
                accl, acce = ops
                x_j = tree_map(lambda buf: lax.dynamic_index_in_dim(
                    buf, bj % p, 0, keepdims=False), stash)
                tgt_j = tree_map(lambda a: lax.dynamic_index_in_dim(
                    a, bj, 0, keepdims=False), mb_tgt)

                mb_j = tree_map(lambda a: lax.dynamic_index_in_dim(
                    a, bj, 0, keepdims=False), mb_in)

                def last_stage_loss(lp, e, x):
                    return last_fn(e, block_fn(lp, e, x), tgt_j)

                def mid_stage(lp, e, x):
                    return block_fn(lp, e, x)

                def first_stage(lp, e):
                    # include the embedding so its extras get gradients
                    return block_fn(lp, e, stage0_fn(e, mb_j))

                def b_last(_):
                    _, vjp = jax.vjp(last_stage_loss, stacked_local, extras, x_j)
                    return vjp(jnp.float32(1.0) / m)

                def b_first(_):
                    _, vjp = jax.vjp(first_stage, stacked_local, extras)
                    gl, ge = vjp(recv_b)
                    return gl, ge, x0

                def b_mid(_):
                    _, vjp = jax.vjp(mid_stage, stacked_local, extras, x_j)
                    return vjp(recv_b)

                gl, ge, gx = lax.cond(
                    is_last, b_last,
                    lambda op: lax.cond(is_first, b_first, b_mid, op), None)
                accl = tree_map(jnp.add, accl, gl)
                acce = tree_map(jnp.add, acce, ge)
                return accl, acce, gx

            def skip_b(ops):
                accl, acce = ops
                return accl, acce, x0

            accl, acce, gx_out = lax.cond(do_b, run_b, skip_b, (accl, acce))

            # ---- stage transfer: activations forward, gradients backward.
            # A receive buffer is only REPLACED when the sender actually
            # computed that slot (the did-flag travels with the payload);
            # otherwise it holds its value across the sender's idle slots
            # (e.g. the warmup->steady seam).
            sent_f = lax.ppermute(do_f.astype(jnp.float32), AXIS_PIPE, fwd_perm)
            got_f = tree_map(lambda v: lax.ppermute(v, AXIS_PIPE, fwd_perm),
                             y_out)
            recv_f = _select(sent_f > 0, got_f, recv_f)
            sent_b = lax.ppermute(do_b.astype(jnp.float32), AXIS_PIPE, bwd_perm)
            got_b = tree_map(lambda v: lax.ppermute(v, AXIS_PIPE, bwd_perm),
                             gx_out)
            recv_b = _select(sent_b > 0, got_b, recv_b)
            return (recv_f, recv_b, stash, accl, acce, loss_acc), None

        carry0 = (x0, x0, stash0, acc_layers0, acc_extras0, jnp.float32(0.0))
        (_, _, _, accl, acce, loss_acc), _ = lax.scan(
            slot, carry0, jnp.arange(slots))

        # losses live on the last stage, extras grads are partial per stage
        loss = lax.psum(jnp.where(is_last, loss_acc, 0.0), AXIS_PIPE) / m
        acce = tree_map(lambda g: lax.psum(g, AXIS_PIPE), acce)
        return loss, accl, acce

    param_specs = tree_map(lambda _: P(AXIS_PIPE), stacked_params)
    rep = tree_map(lambda _: P(), extras)
    in_rep = tree_map(lambda _: P(), mb_in)
    tgt_rep = tree_map(lambda _: P(), mb_tgt)
    return shard_map_compat(
        local, mesh=mesh,
        in_specs=(param_specs, rep, in_rep, tgt_rep),
        out_specs=(P(), param_specs, tree_map(lambda _: P(), extras)),
        axis_names={AXIS_PIPE}, check_vma=False,
    )(stacked_params, extras, mb_in, mb_tgt)
