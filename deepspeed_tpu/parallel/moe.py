"""Mixture-of-Experts: GShard-style gating + expert-parallel dispatch.

Role parity with the reference ``deepspeed/moe`` (``sharded_moe.py``:
``top1gating:184``, ``top2gating:291``, ``topkgating:375``, ``MOELayer:536``,
einsum dispatch/combine, ``_AllToAll:97``; expert groups
``utils/groups.py:304``). Exact semantics preserved: capacity =
``capacity_factor * tokens / experts`` floored at ``min_capacity``, slot-ordered
token dropping, top-k probability renormalization, GShard load-balancing aux
loss ``E * sum(me * ce)``.

TPU-native expression: dispatch/combine are dense einsums against a
``[tokens, experts, capacity]`` routing tensor; with the expert dim sharded over
the ``expert`` mesh axis and tokens sharded over the batch axes, XLA lowers the
einsum pair to the same all-to-all exchange the reference performs explicitly
(``_AllToAll``), fused with the expert GEMMs. Expert weights are stacked
``[E, ...]`` so the expert FFN is one batched GEMM on the MXU.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.config.config import MoEConfig


class GatingResult(NamedTuple):
    combine: jnp.ndarray    # [T, E, C] f32 combine weights (prob * kept)
    dispatch: jnp.ndarray   # [T, E, C] f32 0/1 dispatch mask
    aux_loss: jnp.ndarray   # scalar load-balancing loss
    dropped_frac: jnp.ndarray  # scalar fraction of routed slots dropped


def compute_capacity(tokens: int, num_experts: int, capacity_factor: float,
                     min_capacity: int) -> int:
    """Reference ``sharded_moe.py`` capacity math."""
    cap = int(capacity_factor * tokens / num_experts)
    return max(cap, min_capacity)


def top_k_gating(
    logits: jnp.ndarray,
    k: int,
    capacity: int,
    jitter_eps: float = 0.0,
    rng=None,
) -> GatingResult:
    """[T, E] router logits -> routing tensors (reference ``topkgating:375``).

    Slot-sequential capacity assignment: slot-0 (top-1) choices fill expert
    queues first, then slot-1, etc. — matching the reference's drop policy.
    """
    t, e = logits.shape
    logits = logits.astype(jnp.float32)
    if jitter_eps > 0.0 and rng is not None:
        noise = jax.random.uniform(rng, logits.shape, jnp.float32,
                                   1.0 - jitter_eps, 1.0 + jitter_eps)
        logits = logits + jnp.log(noise)
    probs = jax.nn.softmax(logits, axis=-1)

    masked = probs
    slot_masks, slot_probs = [], []
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)
        slot_masks.append(onehot)
        slot_probs.append(jnp.sum(probs * onehot, axis=-1))
        masked = masked * (1.0 - onehot)

    denom = sum(slot_probs) + 1e-9
    norm_probs = [p / denom for p in slot_probs]

    combine = jnp.zeros((t, e, capacity), jnp.float32)
    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    fill = jnp.zeros((e,), jnp.float32)
    kept_slots = jnp.float32(0.0)
    for i in range(k):
        mask = slot_masks[i]
        pos_in_slot = jnp.cumsum(mask, axis=0) - mask          # [T, E]
        pos = pos_in_slot + fill[None, :]
        fill = fill + jnp.sum(mask, axis=0)
        within = (pos < capacity) * mask                        # [T, E]
        kept_slots = kept_slots + jnp.sum(within)
        loc = jnp.minimum(pos, capacity - 1).astype(jnp.int32)
        slot_onehot = jax.nn.one_hot(loc, capacity, dtype=jnp.float32) * within[..., None]
        dispatch = dispatch + slot_onehot
        combine = combine + norm_probs[i][:, None, None] * slot_onehot

    # GShard aux loss on the top-1 assignment (reference top1gating):
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(slot_masks[0], axis=0)
    aux = e * jnp.sum(me * ce)
    dropped = 1.0 - kept_slots / (t * k)
    return GatingResult(combine=combine, dispatch=dispatch, aux_loss=aux,
                        dropped_frac=dropped)


def moe_ffn(
    x: jnp.ndarray,           # [B, S, D]
    router_w: jnp.ndarray,    # [D, E]
    w_gate: jnp.ndarray,      # [E, D, F]
    w_up: jnp.ndarray,        # [E, D, F]
    w_down: jnp.ndarray,      # [E, F, D]
    cfg: MoEConfig,
    train: bool = True,
    rng=None,
    ctx=None,
):
    """SwiGLU expert FFN with top-k routing (reference ``MOELayer:536`` +
    ``experts.py``). Returns ``(y [B,S,D], aux_loss)``."""
    b, s, d = x.shape
    e = router_w.shape[-1]
    tokens = x.reshape(b * s, d)
    cap_factor = cfg.capacity_factor if train else cfg.eval_capacity_factor
    capacity = compute_capacity(b * s, e, cap_factor, cfg.min_capacity)
    if not cfg.drop_tokens:
        capacity = b * s  # dropless: every token fits

    router_logits = tokens.astype(jnp.float32) @ router_w.astype(jnp.float32)
    gate = top_k_gating(
        router_logits, cfg.top_k, capacity,
        jitter_eps=cfg.router_jitter if train else 0.0, rng=rng,
    )

    dtype = x.dtype
    dispatch = gate.dispatch.astype(dtype)
    combine = gate.combine.astype(dtype)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, tokens)
    if ctx is not None:
        expert_in = ctx.constrain(expert_in, "experts_act", None, "embed_act")
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, w_gate.astype(dtype)))
    h = h * jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(dtype))
    expert_out = jnp.einsum("ecf,efd->ecd", h, w_down.astype(dtype))
    y = jnp.einsum("ecd,tec->td", expert_out, combine)
    return y.reshape(b, s, d), gate.aux_loss
