"""Pipeline parallelism: microbatched stage pipeline inside one jit program.

Role parity with the reference ``runtime/pipe`` (``PipelineModule`` layer
partitioning ``module.py:393``, ``PipelineEngine`` instruction schedules
``schedule.py:189 TrainSchedule``, P2P stage transfer ``p2p.py``).

TPU-native design — no instruction interpreter, no P2P handshakes: the layer
stack is stacked ``[L, ...]`` and sharded over the ``pipeline`` mesh axis (each
stage owns ``L/P`` contiguous layers); a ``shard_map`` (manual over the pipeline
axis only, all other axes still GSPMD-auto) runs the classic collective
pipeline: ``M + P - 1`` ticks, each tick runs the local layer block and
``ppermute``s activations to the next stage. Microbatch streaming, the bubble,
and the reverse (backward) schedule all fall out of ``lax.scan`` + autodiff —
the reference's ``_INSTRUCTION_MAP`` dispatch (``engine.py:1367``) becomes
compiler-scheduled dataflow. Schedule is GPipe-shaped (all-forward then
all-backward); activation memory is bounded by remat on the layer body.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.topology import AXIS_PIPE
from deepspeed_tpu.utils.compat import shard_map_compat

tree_map = jax.tree_util.tree_map


def _select(pred, a, b):
    return tree_map(lambda x, y: jnp.where(pred, x, y), a, b)


def _pipeline_local(layer_fn, n_stages: int, params_local, x_mb):
    """Runs inside shard_map: ``params_local`` is this stage's [L/P, ...] slice,
    ``x_mb`` the full microbatch stack (pytree, leading dim M)."""
    stage = lax.axis_index(AXIS_PIPE)
    m = jax.tree_util.tree_leaves(x_mb)[0].shape[0]
    ticks = m + n_stages - 1

    def run_block(x):
        return lax.scan(lambda c, lp: (layer_fn(c, lp), None), x, params_local)[0]

    zero_mb = tree_map(lambda x: jnp.zeros_like(x[0]), x_mb)
    outputs0 = tree_map(jnp.zeros_like, x_mb)
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        recv, outputs = carry
        # stage 0 ingests microbatch t (clamped once the stream is drained)
        safe_t = jnp.clip(t, 0, m - 1)
        inp = tree_map(lambda x: lax.dynamic_index_in_dim(x, safe_t, 0, keepdims=False), x_mb)
        x = _select(stage == 0, inp, recv)
        y = run_block(x)
        # last stage commits microbatch t-(P-1) to the output buffer
        widx = t - (n_stages - 1)
        safe_w = jnp.clip(widx, 0, m - 1)
        committed = tree_map(
            lambda buf, val: lax.dynamic_update_index_in_dim(buf, val, safe_w, 0),
            outputs, y,
        )
        outputs = _select(widx >= 0, committed, outputs)
        recv = tree_map(lambda v: lax.ppermute(v, AXIS_PIPE, fwd_perm), y)
        return (recv, outputs), None

    (_, outputs), _ = lax.scan(tick, (zero_mb, outputs0), jnp.arange(ticks))
    # expose per-stage buffers through an explicit leading stage dim; the
    # caller slices stage P-1 (the only buffer holding real outputs)
    return tree_map(lambda o: o[None], outputs)


def pipeline_apply(layer_fn, stacked_params, x, mesh, num_microbatches: int = 0):
    """Run ``x`` through the pipelined layer stack.

    ``layer_fn(carry, layer_params) -> carry`` (carry may be a pytree whose
    leaves have a leading batch dim). ``stacked_params`` leaves are [L, ...],
    L divisible by the pipeline degree. Batch dim must divide num_microbatches.
    """
    n_stages = int(mesh.shape.get(AXIS_PIPE, 1))
    if n_stages <= 1:
        return lax.scan(lambda c, lp: (layer_fn(c, lp), None), x, stacked_params)[0]

    m = num_microbatches or n_stages
    batch = jax.tree_util.tree_leaves(x)[0].shape[0]
    if batch % m:
        raise ValueError(f"batch {batch} not divisible by {m} pipeline microbatches")

    x_mb = tree_map(lambda a: a.reshape((m, batch // m) + a.shape[1:]), x)
    fn = functools.partial(_pipeline_local, layer_fn, n_stages)

    # Fully-manual shard_map: stage params are sharded on the pipeline axis,
    # activations on the batch axes; unmentioned axes replicate (their grad
    # cotangents are psum'd by the shard_map transpose rule). Layer params must
    # be replicated within a stage — the planner keeps TP/fsdp off pipelined
    # stacks, mirroring the reference's PP (x) ZeRO<=1 composition rule.
    from deepspeed_tpu.comm.topology import batch_spec_entry

    b_entry = batch_spec_entry(mesh)
    param_specs = tree_map(lambda _: P(AXIS_PIPE), stacked_params)
    data_specs = tree_map(lambda a: P(*([None, b_entry] + [None] * (a.ndim - 2))), x_mb)
    out_specs = tree_map(lambda a: P(*([AXIS_PIPE, None, b_entry] + [None] * (a.ndim - 2))), x_mb)
    out = shard_map_compat(
        fn,
        mesh=mesh,
        in_specs=(param_specs, data_specs),
        out_specs=out_specs,
        check_vma=False,
    )(stacked_params, x_mb)
    out = tree_map(lambda a: a[n_stages - 1], out)
    return tree_map(lambda a: a.reshape((batch,) + a.shape[2:]), out)
