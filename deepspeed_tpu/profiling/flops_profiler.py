"""FLOPs profiler: compiled-program cost analysis + analytic module breakdown.

Role parity with the reference ``profiling/flops_profiler/profiler.py:30``
(``FlopsProfiler``: per-module hooks counting FLOPs/MACs/params/latency,
``get_model_profile``). The hook mechanism doesn't exist in a functional
framework and isn't needed: XLA's cost model reports exact FLOPs/bytes for the
*compiled* program (``compiled.cost_analysis()``), and the per-module tree is
computed analytically from the model config — both are exact for static-shape
programs, unlike hook-based counting which misses fused ops.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import jax

from deepspeed_tpu.utils.logging import log_dist


def program_cost(fn, *args, **kwargs) -> dict:
    """FLOPs / bytes-accessed / peak-memory of ``jit(fn)(*args)`` from XLA's
    cost model. Returns {} keys that the backend doesn't report. When a
    memory ledger is configured the compiled program's temp/argument/output
    footprint is also recorded under its function name."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    try:
        from deepspeed_tpu.telemetry import get_telemetry

        led = get_telemetry().memledger
        if led is not None:
            led.note_program(getattr(fn, "__name__", "program"), compiled)
    except Exception:
        pass
    analyses = compiled.cost_analysis()
    analysis = analyses[0] if isinstance(analyses, (list, tuple)) else analyses
    out = {}
    if analysis:
        for key in ("flops", "bytes accessed", "optimal_seconds"):
            if key in analysis:
                out[key.replace(" ", "_")] = float(analysis[key])
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            out["peak_memory_bytes"] = int(
                getattr(mem, "temp_size_in_bytes", 0)
                + getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0)
            )
    except Exception:
        pass
    return out


@dataclass
class ProfileResult:
    params: int
    flops_fwd: float          # analytic forward FLOPs for the given shape
    macs_fwd: float
    compiled: dict = field(default_factory=dict)  # XLA cost analysis
    breakdown: dict = field(default_factory=dict)  # module -> flops

    def print_profile(self) -> None:
        log_dist(self.format_profile(), ranks=[0])

    def format_profile(self) -> str:
        lines = [
            "---------------- Flops Profile ----------------",
            f"params:            {self.params:,}",
            f"fwd flops:         {self.flops_fwd:.3e}",
            f"fwd MACs:          {self.macs_fwd:.3e}",
        ]
        if self.compiled:
            for k, v in self.compiled.items():
                lines.append(f"compiled {k}: {v:.4g}" if isinstance(v, float)
                             else f"compiled {k}: {v}")
        if self.breakdown:
            lines.append("per-module fwd flops:")
            total = sum(self.breakdown.values()) or 1.0
            for name, fl in sorted(self.breakdown.items(), key=lambda kv: -kv[1]):
                lines.append(f"  {name:<12} {fl:.3e}  ({100 * fl / total:.1f}%)")
        return "\n".join(lines)


def _decoder_breakdown(cfg, batch: int, seq: int) -> dict:
    """Analytic per-module fwd FLOPs for the llama/gpt2/mixtral family."""
    d = cfg.hidden_size
    nl = cfg.num_layers
    hd = getattr(cfg, "hd", d // cfg.num_heads)
    hq = cfg.num_heads
    hkv = getattr(cfg, "num_kv_heads", hq)
    f = getattr(cfg, "intermediate_size", getattr(cfg, "ffn", 4 * d))
    t = batch * seq
    qkvo = 2 * t * d * hd * (2 * hq + 2 * hkv) * nl
    attn = 2 * 2 * t * (seq / 2) * hq * hd * nl  # causal QK^T + AV
    experts = getattr(cfg, "num_experts", 0)
    mlp_mult = getattr(cfg, "top_k", 1) if experts else 1
    n_mats = 3 if hasattr(cfg, "intermediate_size") else 2  # swiglu vs gelu
    mlp = n_mats * 2 * t * d * f * nl * mlp_mult
    vocab = 2 * t * d * cfg.vocab_size
    return {"qkv+out": qkvo, "attention": attn, "mlp": mlp, "lm_head": vocab}


# get_model_profile memo: the result is pure in (model_spec, shape), and the
# engine's analytic-flops fallback is scraped per tflops() read — recomputing
# the breakdown (and worse, a with_compiled lowering) per scrape is waste.
# The stored model_spec reference pins the id() key against reuse-after-gc.
_PROFILE_CACHE: dict = {}
_PROFILE_CACHE_LOCK = threading.Lock()


def get_model_profile(model_spec, batch: int, seq: int, with_compiled: bool = True,
                      ) -> ProfileResult:
    """Reference ``get_model_profile`` analog for a ModelSpec. Memoized on
    (model_spec identity, batch, seq, with_compiled)."""
    import jax.numpy as jnp

    key = (id(model_spec), int(batch), int(seq), bool(with_compiled))
    with _PROFILE_CACHE_LOCK:
        hit = _PROFILE_CACHE.get(key)
        if hit is not None and hit[0] is model_spec:
            return hit[1]

    breakdown = {}
    try:
        breakdown = _decoder_breakdown(model_spec.config, batch, seq)
    except AttributeError:
        pass
    flops_fwd = sum(breakdown.values()) if breakdown else (
        (model_spec.flops_per_token(seq) / 3.0) * batch * seq
        if model_spec.flops_per_token else 0.0
    )
    compiled = {}
    if with_compiled:
        params = jax.eval_shape(model_spec.init_fn, jax.random.PRNGKey(0))
        ids = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
        try:
            compiled = program_cost(model_spec.forward_fn, params, ids)
        except Exception as e:  # backend without cost model
            compiled = {"error": str(e)[:100]}
    result = ProfileResult(
        params=model_spec.num_params,
        flops_fwd=flops_fwd,
        macs_fwd=flops_fwd / 2.0,
        compiled=compiled,
        breakdown=breakdown,
    )
    with _PROFILE_CACHE_LOCK:
        _PROFILE_CACHE[key] = (model_spec, result)
    return result


class FlopsProfiler:
    """Engine-attached profiler matching the reference start/stop protocol
    (``start_profile:74`` / ``stop_profile`` / ``print_model_profile:286``)."""

    def __init__(self, engine):
        self.engine = engine
        self.result: ProfileResult | None = None

    def start_profile(self) -> None:
        cfg = self.engine.config
        batch = int(cfg.train_micro_batch_size_per_device or 1)
        seq = int(cfg.sequence_length or self.engine.model_spec.config.max_seq_len)
        self.result = get_model_profile(self.engine.model_spec, batch, seq,
                                        with_compiled=False)

    def stop_profile(self) -> None:
        pass

    def print_model_profile(self) -> None:
        if self.result is None:
            self.start_profile()
        self.result.print_profile()
