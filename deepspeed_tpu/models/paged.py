"""Shared KV-cache plumbing for the model families' inference paths.

One home for the logic every family (llama, gpt2, mixtral) used to carry
verbatim: the paged-pool KV scatter, the decode/tiled-prefill attention
split over the block pool (reference ``inference/v2/ragged_ops`` layout),
and the dense-cache append+attend used by the v1-style engines. A fix to
the paged contract (e.g. the ``_table_view`` width slicing) lands HERE once
instead of three times.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def write_kv_paged(kc, vc, kk, vv, slots, positions, block_tables):
    """Scatter each ragged token's new KV into (block, offset) of its
    sequence's pool blocks. ``kk``/``vv``: [T, Hkv, D]."""
    bs = kc.shape[1]
    blk = block_tables[slots, positions // bs]  # [T]
    off = positions % bs
    kc = kc.at[blk, off].set(kk.astype(kc.dtype))
    vc = vc.at[blk, off].set(vv.astype(vc.dtype))
    return kc, vc


def ragged_pool_attention(q, kc, vc, slots, positions, block_tables,
                          prefill_tiles=None):
    """Attention over the blocked pool for a flat ragged token batch:
    per-token paged kernel for the decode region, the tiled SplitFuse
    kernel for tile-aligned prefill chunks (``prefill_tiles`` =
    ``(n_dec, tile_slot, tile_pos0, tile_valid, tile)``)."""
    from deepspeed_tpu.ops.attention import (
        paged_attention,
        ragged_prefill_attention,
    )

    t_tokens = q.shape[0]
    if prefill_tiles is None:
        return paged_attention(q, kc, vc, slots, positions, block_tables)
    n_dec, ts, tp, tv, ct = prefill_tiles
    parts = []
    if n_dec:
        parts.append(paged_attention(q[:n_dec], kc, vc, slots[:n_dec],
                                     positions[:n_dec], block_tables))
    if t_tokens > n_dec:
        parts.append(ragged_prefill_attention(
            q[n_dec:], kc, vc, ts, tp, tv, block_tables, ct))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


def append_kv_and_attend(q, kk, vv, k_cache, v_cache, start_pos, max_len):
    """Dense-cache decode/prefill step: write new KV at ``start_pos``,
    attend over the cache prefix under absolute-position causal masking.
    ``q``/``kk``/``vv``: [B, T, H*, D]; returns (o, k_cache, v_cache)."""
    from deepspeed_tpu.ops.attention import xla_attention

    t = q.shape[1]
    k_cache = lax.dynamic_update_slice(
        k_cache, kk.astype(k_cache.dtype), (0, start_pos, 0, 0))
    v_cache = lax.dynamic_update_slice(
        v_cache, vv.astype(v_cache.dtype), (0, start_pos, 0, 0))
    q_pos = start_pos + jnp.arange(t)[:, None]
    k_pos = jnp.arange(max_len)[None, :]
    bias = jnp.where(k_pos <= q_pos, 0.0, -1e30)[None, None]
    o = xla_attention(q, k_cache, v_cache, causal=False, bias=bias)
    return o, k_cache, v_cache
