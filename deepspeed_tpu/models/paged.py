"""Shared KV-cache plumbing for the model families' inference paths.

One home for the logic every family (llama, gpt2, mixtral) used to carry
verbatim: the paged-pool KV scatter, the decode/tiled-prefill attention
split over the block pool (reference ``inference/v2/ragged_ops`` layout),
and the dense-cache append+attend used by the v1-style engines. A fix to
the paged contract (e.g. the ``_table_view`` width slicing) lands HERE once
instead of three times.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def write_kv_paged(kc, vc, kk, vv, slots, positions, block_tables):
    """Scatter each ragged token's new KV into (block, offset) of its
    sequence's pool blocks. ``kk``/``vv``: [T, Hkv, D].

    This is the ONE write site of the paged contract, so it is also the
    ONE quantize site: a low-bit pool (``inference/kvquant.QuantizedKV``)
    quantizes each token row at write time — per-row scales keep the
    incremental scatter exact (rewriting a row never re-rounds another).
    """
    bs = kc.shape[1]
    blk = block_tables[slots, positions // bs]  # [T]
    off = positions % bs
    if getattr(kc, "is_quantized_kv", False):
        return kc.scatter_rows(blk, off, kk), vc.scatter_rows(blk, off, vv)
    kc = kc.at[blk, off].set(kk.astype(kc.dtype))
    vc = vc.at[blk, off].set(vv.astype(vc.dtype))
    return kc, vc


def ragged_pool_attention(q, kc, vc, slots, positions, block_tables,
                          prefill_tiles=None):
    """Attention over the blocked pool for a flat ragged token batch:
    per-token paged kernel for the decode region, the tiled SplitFuse
    kernel for tile-aligned prefill chunks (``prefill_tiles`` =
    ``(n_dec, tile_slot, tile_pos0, tile_valid, tile)``)."""
    from deepspeed_tpu.ops.attention import (
        paged_attention,
        ragged_prefill_attention,
    )

    t_tokens = q.shape[0]
    if prefill_tiles is None:
        return paged_attention(q, kc, vc, slots, positions, block_tables)
    n_dec, ts, tp, tv, ct = prefill_tiles
    parts = []
    if n_dec:
        parts.append(paged_attention(q[:n_dec], kc, vc, slots[:n_dec],
                                     positions[:n_dec], block_tables))
    if t_tokens > n_dec:
        parts.append(ragged_prefill_attention(
            q[n_dec:], kc, vc, ts, tp, tv, block_tables, ct))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


def speculative_lane_layout(cur_tok, draft, pos, live, cap, slots,
                            scratch_slot):
    """Flatten a [T]-row decode batch plus per-row draft proposals into the
    flat verify batch one ragged forward consumes.

    Row ``r`` occupies lanes ``r*(1+D) .. r*(1+D)+D``: lane 0 feeds the
    row's current token at ``pos[r]`` (the plain decode step), lane ``1+i``
    feeds ``draft[r, i]`` at ``pos[r] + 1 + i`` — so one forward scores the
    committed step AND every draft position, and because ``write_kv_paged``
    scatters each lane's KV before attention runs, later lanes attend over
    earlier lanes' keys within the same dispatch. Rejected-draft KV needs no
    rollback: positions are fed strictly monotonically, so a rejected cell
    is always re-scattered by a later dispatch before anything attends to it.

    Lanes of dead rows (``live`` False) and lanes at/past the row's covered
    capacity ``cap[r]`` (first position WITHOUT an allocated block) are
    routed to ``scratch_slot`` at position 0 — their writes land in the
    scratch block and their picks are never surfaced (the emission budget
    clamps first). Returns flat ``(tokens, slots, positions, raw_positions)``
    each [T*(1+D)]; ``raw_positions`` keeps the unrouted positions for
    per-lane sampling-key derivation."""
    t = cur_tok.shape[0]
    d = 0 if draft is None else draft.shape[1]
    lanes = 1 + d
    lane_pos_raw = pos[:, None] + jnp.arange(lanes)[None, :]     # [T, L]
    if d:
        lane_tok = jnp.concatenate([cur_tok[:, None], draft], axis=1)
    else:
        lane_tok = cur_tok[:, None]
    ok = live[:, None] & (lane_pos_raw < cap[:, None])
    lane_slot = jnp.where(ok, slots[:, None], scratch_slot)
    lane_pos = jnp.where(ok, lane_pos_raw, 0)
    return (lane_tok.reshape(-1).astype(jnp.int32),
            lane_slot.reshape(-1).astype(jnp.int32),
            lane_pos.reshape(-1).astype(jnp.int32),
            lane_pos_raw.reshape(-1).astype(jnp.int32))


def append_kv_and_attend(q, kk, vv, k_cache, v_cache, start_pos, max_len):
    """Dense-cache decode/prefill step: write new KV at ``start_pos``,
    attend over the cache prefix under absolute-position causal masking.
    ``q``/``kk``/``vv``: [B, T, H*, D]; returns (o, k_cache, v_cache)."""
    from deepspeed_tpu.ops.attention import xla_attention

    t = q.shape[1]
    k_cache = lax.dynamic_update_slice(
        k_cache, kk.astype(k_cache.dtype), (0, start_pos, 0, 0))
    v_cache = lax.dynamic_update_slice(
        v_cache, vv.astype(v_cache.dtype), (0, start_pos, 0, 0))
    q_pos = start_pos + jnp.arange(t)[:, None]
    k_pos = jnp.arange(max_len)[None, :]
    bias = jnp.where(k_pos <= q_pos, 0.0, -1e30)[None, None]
    o = xla_attention(q, k_cache, v_cache, causal=False, bias=bias)
    return o, k_cache, v_cache
