"""Model API: the contract between models and the engine.

The reference wraps mutable ``nn.Module``s (``runtime/engine.py:235``); the
TPU-native contract is functional: a ``ModelSpec`` bundles pure
``init/forward/loss`` functions over a parameter pytree, plus *logical axis*
names per parameter dimension. The sharding planner (``parallel/partition.py``)
maps logical axes -> mesh axes per ZeRO stage / TP rules — this replaces the
reference's AutoTP module-graph parsing (``module_inject/auto_tp.py:194``):
models declare their sharding structure instead of being reverse-engineered.

Logical axis vocabulary (params):
  "layers"   stacked-layer leading dim (pipeline axis target)
  "embed"    model hidden dim
  "heads"    attention head (q) projection dim       -> TP column-parallel
  "kv_heads" kv projection dim                       -> TP column-parallel
  "ffn"      MLP intermediate dim                    -> TP column-parallel
  "vocab"    vocabulary dim                          -> TP row/column
  "experts"  MoE expert dim                          -> EP
  None       never sharded

Activations: "batch", "seq", "embed_act", "heads_act", "vocab_act".
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

# Mesh-axis mapping for activation sharding constraints (GSPMD hints).
DEFAULT_ACTIVATION_RULES = {
    "batch": ("data", "fsdp", "expert"),
    "seq": "sequence",
    "embed_act": None,
    "heads_act": "tensor",
    "ffn_act": "tensor",
    "vocab_act": "tensor",
    "experts_act": "expert",
}


@dataclass
class ShardCtx:
    """Carries the mesh + activation rules into model code for
    ``with_sharding_constraint`` hints, and dispatches attention through the
    configured sequence-parallel mode. A ``None`` mesh disables constraints
    (single-device or tracing outside the engine)."""

    mesh: Any = None
    rules: dict = field(default_factory=lambda: dict(DEFAULT_ACTIVATION_RULES))
    sp_mode: str = "ulysses"  # ulysses | ring (reference: deepspeed/sequence/)
    attn_impl: str = "auto"
    pp_microbatches: int = 0  # 0 -> pipeline degree
    # activation checkpointing (reference: runtime/activation_checkpointing/):
    # engine fills these from config; model builders default to them
    remat: bool = False
    remat_policy: Any = None
    # ALST sequence tiling (reference ulysses_sp.py TiledMLP/TiledFusedLogitsLoss):
    # 0 = off; otherwise tokens per tile
    loss_tile_size: int = 0
    mlp_tile_size: int = 0
    # FPDT chunked attention w/ host-offloaded residuals (reference
    # sequence/fpdt_layer.py:545): 0 = off; otherwise chunks (>= 2) over the
    # attention-visible sequence (under Ulysses: the full gathered sequence)
    fpdt_chunks: int = 0
    fpdt_offload: bool = True
    # ZeRO++ qwZ hook (parallel/qwz.py): installed by the engine when
    # zero_optimization.quantized_weights is on; applied to each scanned
    # layer's weight slice so the stage-3 gather rides int8
    qwz: Any = None
    # ZeRO-Infinity param-offload hook (runtime/param_offload.py): installed
    # when zero_optimization.offload_param.device != none; streams each
    # scanned layer's host-resident weight slice into HBM + compute-casts it
    param_stream: Any = None

    def layer_weights(self, lp: dict, dtype) -> dict:
        """Per-layer weight preparation, called first thing in layer bodies:
        just-in-time WOQ dequantization (inference), then the ZeRO-Infinity
        host->HBM stream-in (which also compute-casts), then the qwZ quantized
        gather (stage-3 training) when installed and constraints are live."""
        from deepspeed_tpu.ops.quantizer import dequantize_layer

        lp = dequantize_layer(lp, dtype)
        if (self.param_stream is not None
                and not getattr(self, "_suspend_constraints", False)):
            lp = self.param_stream(lp, dtype)
        if self.qwz is not None and not getattr(self, "_suspend_constraints", False):
            lp = self.qwz(lp, dtype)
        return lp

    @property
    def sp_degree(self) -> int:
        if self.mesh is None:
            return 1
        return int(self.mesh.shape.get("sequence", 1))

    @property
    def pp_degree(self) -> int:
        if self.mesh is None:
            return 1
        return int(self.mesh.shape.get("pipeline", 1))

    def layer_stack(self, layer_fn, stacked_params, x, pld_theta=None,
                    pld_rng=None, ltd_keep: int = 0, ltd_rng=None):
        """Run the decoder stack: plain ``lax.scan`` normally, the collective
        microbatch pipeline when the ``pipeline`` mesh axis is active.

        With ``pld_theta`` (a traced scalar) + ``pld_rng``, layers are
        stochastically skipped per Progressive Layer Drop
        (``runtime/progressive_layer_drop.py``): depth-scaled keep
        probability, ``lax.cond`` so dropped layers skip their FLOPs, and
        stochastic-depth rescaling of the kept residual delta.

        With ``ltd_keep`` (STATIC int < seq) + ``ltd_rng``, each layer
        processes only a per-layer random subset of ``ltd_keep`` token
        positions — random layerwise token dropping (reference
        ``runtime/data_pipeline/data_routing/basic_layer.py`` +
        ``csrc/random_ltd`` gather/scatter kernels): dropped tokens BYPASS
        the layer (identity residual), kept tokens are gathered, processed
        with their ORIGINAL positions, and scattered back, so gradients flow
        through both routes. ``ltd_keep`` is static because it is a shape;
        the engine buckets the schedule and compiles once per bucket."""
        import jax.lax as lax

        if ltd_keep and pld_theta is not None:
            raise ValueError("random_ltd and progressive_layer_drop do not "
                             "compose (both rewrite the layer stack)")
        if ltd_keep:
            if self.pp_degree > 1:
                raise ValueError("random_ltd does not compose with pipeline "
                                 "parallelism")
            leaves = jax.tree_util.tree_leaves(stacked_params)
            n_layers = leaves[0].shape[0]
            s = x.shape[1]
            if not 0 < ltd_keep < s:
                raise ValueError(f"ltd_keep must be in (0, seq={s}), got "
                                 f"{ltd_keep}")
            # position-free layers (learned embeddings already in x) take
            # (sub, lp) only. Decide by signature, ONCE, outside the traced
            # body — catching TypeError around the call would also swallow
            # genuine TypeErrors raised inside the layer itself
            import inspect
            try:
                params = inspect.signature(layer_fn).parameters
                takes_positions = "positions" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values())
            except (TypeError, ValueError):
                takes_positions = False  # uninspectable callable (C/builtin)

            def body(carry, inp):
                lp, i = inp
                r = jax.random.fold_in(ltd_rng, i)
                # first position always kept (reference keeps attention
                # sinks stable); remaining K-1 sampled without replacement
                perm = 1 + jax.random.permutation(r, s - 1)[: ltd_keep - 1]
                keep = jnp.sort(jnp.concatenate(
                    [jnp.zeros((1,), perm.dtype), perm]))
                sub = jnp.take(carry, keep, axis=1)
                if takes_positions:
                    pos = jnp.broadcast_to(keep[None, :],
                                           (carry.shape[0], ltd_keep))
                    sub = layer_fn(sub, lp, positions=pos)
                else:
                    sub = layer_fn(sub, lp)
                return carry.at[:, keep].set(sub.astype(carry.dtype)), None

            return lax.scan(body, x,
                            (stacked_params, jnp.arange(n_layers)))[0]

        if pld_theta is not None:
            if self.pp_degree > 1:
                raise ValueError("progressive layer drop does not compose "
                                 "with pipeline parallelism")
            leaves = jax.tree_util.tree_leaves(stacked_params)
            n_layers = leaves[0].shape[0]

            def body(carry, inp):
                lp, i = inp
                frac = (i.astype(jnp.float32) + 1.0) / n_layers
                keep_p = 1.0 - frac * (1.0 - pld_theta)
                keep = jax.random.bernoulli(
                    jax.random.fold_in(pld_rng, i), keep_p)

                def kept(c):
                    delta = layer_fn(c, lp) - c
                    return c + delta / keep_p.astype(delta.dtype)

                return lax.cond(keep, kept, lambda c: c, carry), None

            return lax.scan(body, x,
                            (stacked_params, jnp.arange(n_layers)))[0]

        if self.pp_degree <= 1:
            return lax.scan(lambda c, lp: (layer_fn(c, lp), None), x, stacked_params)[0]
        from deepspeed_tpu.parallel.pipeline import pipeline_apply

        # sharding hints inside the manual-over-pipeline region are suspended;
        # GSPMD still propagates layouts for the auto axes from the inputs
        self._suspend_constraints = True
        try:
            return pipeline_apply(layer_fn, stacked_params, x, self.mesh,
                                  num_microbatches=self.pp_microbatches)
        finally:
            self._suspend_constraints = False

    def attention(self, q, k, v, causal: bool = True, impl: str | None = None):
        """Models call attention through here; with an active ``sequence`` axis
        this routes to Ulysses all-to-all or ring/context-parallel attention."""
        impl = impl or self.attn_impl
        from deepspeed_tpu.ops.attention import attention as local_attention

        if self.fpdt_chunks > 1:
            from deepspeed_tpu.parallel.fpdt import fpdt_attention

            # config True = offload when the backend supports it (probe);
            # False = chunked compute only, residuals stay in HBM
            local = lambda q, k, v: fpdt_attention(  # noqa: E731
                q, k, v, self.fpdt_chunks, causal=causal,
                offload=None if self.fpdt_offload else False)
            if self.sp_degree <= 1:
                return local(q, k, v)
            # FPDT composes with Ulysses (reference FPDT runs on the
            # post-all-to-all full-sequence head-sharded layout)
            from deepspeed_tpu.parallel.ulysses import ulysses_attention

            return ulysses_attention(q, k, v, self.mesh, causal=causal,
                                     local_fn=local)
        if self.sp_degree <= 1:
            return local_attention(q, k, v, causal=causal, impl=impl)
        if self.sp_mode == "ring":
            from deepspeed_tpu.parallel.ring_attention import ring_attention

            return ring_attention(q, k, v, self.mesh, causal=causal)
        from deepspeed_tpu.parallel.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, self.mesh, causal=causal, impl=impl)

    def embed_lookup(self, table: jnp.ndarray, ids: jnp.ndarray,
                     *act_dims: Optional[str]) -> jnp.ndarray:
        """Token-embedding gather with multi-chip-friendly sharding.

        Replicates the (possibly vocab/fsdp-sharded) table for the lookup —
        GSPMD otherwise keeps the gather output sharded on the embed dim and
        falls into "involuntary full rematerialization" resharding it to the
        activation layout — then constrains the result to ``act_dims``.
        """
        if self.mesh is not None and not getattr(self, "_suspend_constraints", False):
            table = jax.lax.with_sharding_constraint(
                table, jax.sharding.NamedSharding(
                    self.mesh, jax.sharding.PartitionSpec()))
        x = table[ids]
        return self.constrain(x, *act_dims) if act_dims else x

    def constrain(self, x: jnp.ndarray, *logical_dims: Optional[str]) -> jnp.ndarray:
        if self.mesh is None or getattr(self, "_suspend_constraints", False):
            return x
        # inside a PARTIAL-manual shard_map (e.g. the qgZ step is manual over
        # the data axis only), constraints stay live for the auto axes but
        # must not mention the manual ones
        manual = getattr(self, "_manual_axes", ()) or ()
        spec = []
        for dim in logical_dims:
            axis = self.rules.get(dim) if dim is not None else None
            # drop axes the mesh doesn't parallelize (size 1) to keep specs clean
            if axis is None:
                spec.append(None)
                continue
            axes = axis if isinstance(axis, tuple) else (axis,)
            active = tuple(a for a in axes
                           if self.mesh.shape.get(a, 1) > 1 and a not in manual)
            spec.append(active if len(active) > 1 else (active[0] if active else None))
        pspec = jax.sharding.PartitionSpec(*spec)
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(self.mesh, pspec)
        )


@dataclass
class ModelSpec:
    """Everything the engine needs to train/evaluate a model."""

    name: str
    config: Any
    # init_fn(rng) -> params pytree (fp32 master weights)
    init_fn: Callable
    # loss_fn(params, batch, rng) -> scalar loss (batch: dict of arrays)
    loss_fn: Callable
    # forward_fn(params, input_ids) -> logits
    forward_fn: Callable
    # pytree congruent to params: tuple of logical axis names per dim
    param_logical_axes: Any = None
    # unit counts per logical axis (e.g. {"kv_heads": 8}) for shard-granularity
    # checks (reference tp_shard.py kv-head-aware sharding)
    logical_dim_units: dict = field(default_factory=dict)
    # analytics for MFU / flops profiler
    num_params: int = 0
    flops_per_token: Callable[[int], float] | None = None
    # inference hooks: init_cache_fn(batch, max_len, dtype) -> cache;
    # decode_fn(params, tokens, cache, start_pos) -> (logits, cache)
    init_cache_fn: Callable | None = None
    decode_fn: Callable | None = None
    # ragged/continuous-batching hooks (reference inference/v2):
    # init_paged_cache_fn(num_blocks, block_size, dtype) -> cache;
    # ragged_forward_fn(params, tokens, slots, positions, block_tables, cache)
    #   -> (logits [T, V], cache)
    init_paged_cache_fn: Callable | None = None
    ragged_forward_fn: Callable | None = None
    # ragged_forward_fn accepts prefill_tiles=(n_dec, tile_slot, tile_pos0,
    # tile_valid, tile) for the tiled-prefill fast path (SplitFuse kernel)
    supports_prefill_tiles: bool = False
    # 1F1B pipeline decomposition (parallel/pipeline_1f1b.py): the tuple
    # (stage0_fn, block_fn, last_fn, split_fn, merge_fn) itself
    pipeline_parts: Any = None
    # MPMD staged runtime (runtime/pipe/): which non-"layers" param key each
    # stage program owns — maps extras key -> "first" | "last". None means
    # the model cannot be staged (e.g. tied embeddings: the shared table
    # would need a cross-stage grad reduction the transport doesn't carry).
    pipeline_extras_owner: dict | None = None
    # whether loss_fn honors batch["pld_theta"] (progressive layer drop);
    # the engine refuses to enable PLD on models that would silently ignore it
    supports_pld: bool = False
    # loss_fn accepts the static ltd_keep kwarg (random layerwise token
    # dropping inside the decoder scan; ShardCtx.layer_stack)
    supports_random_ltd: bool = False
    # param names kept dense under weight-only quantization (tables the model
    # indexes rather than matmuls, e.g. embeddings)
    woq_skip: tuple = ("embed",)


def causal_lm_loss(
    logits: jnp.ndarray,
    input_ids: jnp.ndarray,
    labels: jnp.ndarray | None = None,
    ignore_index: int = -100,
    z_loss: float = 0.0,
) -> jnp.ndarray:
    """Next-token cross entropy in fp32.

    With ``labels=None``, targets are ``input_ids`` shifted left (predict t+1
    from position t). Provided ``labels`` must already be aligned with logits.
    Positions equal to ``ignore_index`` are masked out.
    """
    if labels is None:
        logits = logits[:, :-1]
        targets = input_ids[:, 1:]
    else:
        targets = labels
    logits = logits.astype(jnp.float32)
    mask = (targets != ignore_index).astype(jnp.float32)
    safe_targets = jnp.where(targets == ignore_index, 0, targets)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, safe_targets[..., None], axis=-1)[..., 0]
    nll = (logz - true_logit) * mask
    loss = nll.sum() / jnp.maximum(mask.sum(), 1.0)
    if z_loss > 0.0:
        loss = loss + z_loss * ((logz * mask) ** 2).sum() / jnp.maximum(mask.sum(), 1.0)
    return loss


def count_params(params) -> int:
    return int(sum(x.size for x in jax.tree_util.tree_leaves(params)))
