"""Llama-family causal LM (Llama 2/3 architecture), TPU-first.

The flagship training model (BASELINE.json north star: Llama-3-8B ZeRO-3).
Functional design: parameters are a pytree with a *stacked* leading layer dim,
the decoder runs as one ``lax.scan`` over that stack — one compiled layer body
regardless of depth (fast compiles, natural pipeline partitioning, uniform
remat). The reference has no model zoo for training; its inference engine ships
per-arch implementations (``inference/v2/model_implementations/llama_v2``);
this module is the training+inference source of truth for the family.

Architecture: RMSNorm, SwiGLU MLP, RoPE, grouped-query attention, optional
tied embeddings — matching HF ``LlamaForCausalLM`` semantics.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.models.api import ModelSpec, ShardCtx, causal_lm_loss, count_params
from deepspeed_tpu.ops.attention import apply_rope


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int | None = None
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 4096
    tie_embeddings: bool = False

    @property
    def hd(self) -> int:
        return self.head_dim if self.head_dim is not None else self.hidden_size // self.num_heads

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                           num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=500000.0,
                           max_seq_len=8192)

    @staticmethod
    def tiny(vocab_size: int = 256) -> "LlamaConfig":
        return LlamaConfig(vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
                           num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128)


def init_params(cfg: LlamaConfig, rng) -> dict:
    """fp32 master weights; scaled init on residual-out projections."""
    d, f, hd = cfg.hidden_size, cfg.intermediate_size, cfg.hd
    hq, hkv, nl = cfg.num_heads, cfg.num_kv_heads, cfg.num_layers
    k = iter(jax.random.split(rng, 16))
    std = 0.02
    out_std = std / jnp.sqrt(2.0 * nl)

    def norm(key, *shape, s=std):
        return jax.random.normal(key, shape, jnp.float32) * s

    params = {
        "embed": norm(next(k), cfg.vocab_size, d),
        "layers": {
            "attn_norm": jnp.ones((nl, d), jnp.float32),
            "wq": norm(next(k), nl, d, hq * hd),
            "wk": norm(next(k), nl, d, hkv * hd),
            "wv": norm(next(k), nl, d, hkv * hd),
            "wo": norm(next(k), nl, hq * hd, d, s=out_std),
            "mlp_norm": jnp.ones((nl, d), jnp.float32),
            "w_gate": norm(next(k), nl, d, f),
            "w_up": norm(next(k), nl, d, f),
            "w_down": norm(next(k), nl, f, d, s=out_std),
        },
        "final_norm": jnp.ones((d,), jnp.float32),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(next(k), d, cfg.vocab_size)
    return params


PARAM_LOGICAL_AXES = {
    "embed": ("vocab", "embed"),
    "layers": {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", "embed"),
        "w_gate": ("layers", "embed", "ffn"),
        "w_up": ("layers", "embed", "ffn"),
        "w_down": ("layers", "ffn", "embed"),
    },
    "final_norm": ("embed",),
    "lm_head": ("embed", "vocab"),
}


def rmsnorm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps)).astype(x.dtype) * w.astype(x.dtype)


from deepspeed_tpu.ops.quantizer import dequantize_layer as _dq_layer  # noqa: E402


def _decoder_layer(cfg: LlamaConfig, ctx: ShardCtx, attn_impl: str,
                   x: jnp.ndarray, lp: dict, positions: jnp.ndarray | None = None) -> jnp.ndarray:
    lp = ctx.layer_weights(lp, x.dtype)
    b, s, d = x.shape
    hq, hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))

    h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = (h @ lp["wq"]).reshape(b, s, hq, hd)
    kk = (h @ lp["wk"]).reshape(b, s, hkv, hd)
    vv = (h @ lp["wv"]).reshape(b, s, hkv, hd)
    q = ctx.constrain(q, "batch", "seq", "heads_act", None)
    kk = ctx.constrain(kk, "batch", "seq", "heads_act", None)
    q, kk = apply_rope(q, kk, positions, cfg.rope_theta)
    o = ctx.attention(q, kk, vv, causal=True, impl=attn_impl)
    x = x + o.reshape(b, s, hq * hd) @ lp["wo"]
    x = ctx.constrain(x, "batch", "seq", "embed_act")

    if ctx.mlp_tile_size:
        from deepspeed_tpu.parallel.sequence_tiling import tiled_mlp

        def mlp_fn(xs):
            hs = rmsnorm(xs, lp["mlp_norm"], cfg.rms_norm_eps)
            gate = ctx.constrain(jax.nn.silu(hs @ lp["w_gate"]),
                                 "batch", "seq", "ffn_act")
            up = ctx.constrain(hs @ lp["w_up"], "batch", "seq", "ffn_act")
            return (gate * up) @ lp["w_down"]

        x = x + tiled_mlp(mlp_fn, x, ctx.mlp_tile_size)
    else:
        h = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        gate = jax.nn.silu(h @ lp["w_gate"])
        up = h @ lp["w_up"]
        gate = ctx.constrain(gate, "batch", "seq", "ffn_act")
        x = x + (gate * up) @ lp["w_down"]
    return ctx.constrain(x, "batch", "seq", "embed_act")


def hidden_states(cfg: LlamaConfig, params: dict, input_ids: jnp.ndarray,
                  ctx: ShardCtx | None = None, attn_impl: str = "auto",
                  remat_policy=None, remat: bool = False,
                  pld_theta=None, pld_rng=None, ltd_keep: int = 0,
                  ltd_rng=None) -> jnp.ndarray:
    """[B, S] int tokens -> [B, S, D] final (post-norm) hidden states."""
    ctx = ctx or ShardCtx()
    x = ctx.embed_lookup(params["embed"], input_ids, "batch", "seq", "embed_act")

    layer = partial(_decoder_layer, cfg, ctx, attn_impl)
    if remat:
        layer = jax.checkpoint(layer, policy=remat_policy)

    x = ctx.layer_stack(layer, params["layers"], x,
                        pld_theta=pld_theta, pld_rng=pld_rng,
                        ltd_keep=ltd_keep, ltd_rng=ltd_rng)
    return rmsnorm(x, params["final_norm"], cfg.rms_norm_eps)


def lm_head(cfg: LlamaConfig, params: dict) -> jnp.ndarray:
    if cfg.tie_embeddings:
        return params["embed"].T
    from deepspeed_tpu.ops.quantizer import maybe_dequantize

    return maybe_dequantize(params["lm_head"], jnp.float32)


def forward(cfg: LlamaConfig, params: dict, input_ids: jnp.ndarray,
            ctx: ShardCtx | None = None, attn_impl: str = "auto",
            remat_policy=None, remat: bool = False,
            pld_theta=None, pld_rng=None) -> jnp.ndarray:
    """[B, S] int tokens -> [B, S, V] logits. Decoder is a scan over the layer stack."""
    ctx = ctx or ShardCtx()
    x = hidden_states(cfg, params, input_ids, ctx=ctx, attn_impl=attn_impl,
                      remat_policy=remat_policy, remat=remat,
                      pld_theta=pld_theta, pld_rng=pld_rng)
    logits = x @ lm_head(cfg, params).astype(x.dtype)
    return ctx.constrain(logits, "batch", "seq", "vocab_act")


# ------------------------------------------------------------------ inference
def init_cache(cfg: LlamaConfig, batch: int, max_len: int, dtype=jnp.bfloat16) -> dict:
    """Per-layer KV cache, stacked [L, B, max_len, Hkv, Dh] — the dense
    fixed-shape cache of the v1-style engine (the TPU analog of the reference
    inference KV workspace)."""
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.hd)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _cached_layer(cfg: LlamaConfig, ctx: ShardCtx, x, lp, k_cache, v_cache,
                  start_pos, max_len: int):
    """Decode/prefill layer: append new KV at ``start_pos``, attend over the
    cache prefix with absolute-position causal masking."""
    from deepspeed_tpu.models.paged import append_kv_and_attend

    lp = _dq_layer(lp, x.dtype)
    b, t, d = x.shape
    hq, hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd

    h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = (h @ lp["wq"]).reshape(b, t, hq, hd)
    kk = (h @ lp["wk"]).reshape(b, t, hkv, hd)
    vv = (h @ lp["wv"]).reshape(b, t, hkv, hd)
    positions = start_pos + jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
    q, kk = apply_rope(q, kk, positions, cfg.rope_theta)

    o, k_cache, v_cache = append_kv_and_attend(
        q, kk, vv, k_cache, v_cache, start_pos, max_len)
    x = x + o.reshape(b, t, hq * hd) @ lp["wo"]

    h = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    x = x + (jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])) @ lp["w_down"]
    return x, k_cache, v_cache


def decode_forward(cfg: LlamaConfig, params, tokens, cache, start_pos,
                   ctx: ShardCtx | None = None):
    """[B, T] new tokens + cache -> ([B, T, V] logits, updated cache).

    Works for both prefill (T = prompt length, start_pos = 0) and incremental
    decode (T = 1). Scans over the stacked layers, carrying x and threading the
    per-layer cache through scan xs/ys.
    """
    ctx = ctx or ShardCtx()
    max_len = cache["k"].shape[2]
    # plain per-row gather: decode looks up a handful of tokens per step, so
    # embed_lookup's table replication (a training-scale fix for the gather
    # resharding remat) would all-gather the whole table every step
    x = params["embed"][tokens].astype(cache["k"].dtype)

    def body(x, lp_kv):
        lp, kc, vc = lp_kv
        x, kc, vc = _cached_layer(cfg, ctx, x, lp, kc, vc, start_pos, max_len)
        return x, (kc, vc)

    x, (new_k, new_v) = lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    x = rmsnorm(x, params["final_norm"], cfg.rms_norm_eps)
    head = lm_head(cfg, params)
    logits = x @ head.astype(x.dtype)
    return logits, {"k": new_k, "v": new_v}


def init_paged_cache(cfg: LlamaConfig, num_blocks: int, block_size: int,
                     dtype=jnp.bfloat16) -> dict:
    """Blocked KV pool, stacked [L, num_blocks, block_size, Hkv, Dh] — the
    paged cache of the ragged engine (reference
    ``inference/v2/ragged/kv_cache.py`` blocked KV; block 0 is the scratch
    block padding tokens write into)."""
    shape = (cfg.num_layers, num_blocks, block_size, cfg.num_kv_heads, cfg.hd)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _ragged_layer(cfg: LlamaConfig, x, lp, kc, vc, positions, slots,
                  block_tables, prefill_tiles=None):
    """One decoder layer over a flat ragged token batch.

    ``x`` [T, D] mixes prefill-chunk tokens and decode tokens from different
    sequences (SplitFuse layout, reference ``inference/v2/ragged``). New KV is
    scattered into the block pool *before* attention, so intra-chunk causal
    attention falls out of the position mask with no special casing.

    ``prefill_tiles``: optional ``(n_dec, tile_slot, tile_pos0, tile_valid,
    tile)`` — tokens [0, n_dec) are decodes (per-token kernel), the rest are
    tile-aligned prefill chunks (tiled kernel: one KV-block fetch per tile).
    """
    from deepspeed_tpu.models.paged import (
        ragged_pool_attention,
        write_kv_paged,
    )

    lp = _dq_layer(lp, x.dtype)
    t_tokens, d = x.shape
    hq, hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd

    h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = (h @ lp["wq"]).reshape(t_tokens, hq, hd)
    kk = (h @ lp["wk"]).reshape(t_tokens, hkv, hd)
    vv = (h @ lp["wv"]).reshape(t_tokens, hkv, hd)
    q, kk = apply_rope(q[None], kk[None], positions[None], cfg.rope_theta)
    q, kk = q[0], kk[0]

    kc, vc = write_kv_paged(kc, vc, kk, vv, slots, positions, block_tables)
    o = ragged_pool_attention(q, kc, vc, slots, positions, block_tables,
                              prefill_tiles).astype(x.dtype)
    x = x + o.reshape(t_tokens, hq * hd) @ lp["wo"]

    h = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    x = x + (jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])) @ lp["w_down"]
    return x, kc, vc


def ragged_forward(cfg: LlamaConfig, params, tokens, slots, positions,
                   block_tables, cache, prefill_tiles=None):
    """Flat ragged step: ``[T]`` mixed tokens -> (``[T, V]`` logits, cache).

    Each token carries (slot, absolute position); ``block_tables``
    [max_seqs+1, max_blocks] maps slots to KV pool blocks (row ``max_seqs`` is
    the all-scratch padding row). One static-shape XLA program serves any mix
    of prefill chunks and decodes (reference ``inference/v2/engine_v2.py:30``
    ``put()`` + ``ragged_ops`` kernels). ``prefill_tiles``: see
    ``_ragged_layer`` (tiled-prefill fast path).
    """
    # plain gather (see decode_forward's note: replication is a training fix)
    x = params["embed"][tokens].astype(cache["k"].dtype)

    def body(x, lp_kv):
        lp, kc, vc = lp_kv
        x, kc, vc = _ragged_layer(cfg, x, lp, kc, vc, positions, slots,
                                  block_tables, prefill_tiles=prefill_tiles)
        return x, (kc, vc)

    x, (new_k, new_v) = lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    x = rmsnorm(x, params["final_norm"], cfg.rms_norm_eps)
    head = lm_head(cfg, params)
    logits = x @ head.astype(x.dtype)
    return logits, {"k": new_k, "v": new_v}


# ------------------------------------------------------------------ pipeline
def pipeline_parts(cfg: LlamaConfig, ctx: ShardCtx | None = None,
                   attn_impl: str = "auto"):
    """Stage decomposition for the 1F1B schedule
    (``parallel/pipeline_1f1b.py``): embedding on stage 0, the scanned layer
    block per stage, final-norm + head + loss on the last stage (reference
    ``PipelineModule`` places loss_fn on the last stage).

    Returns ``(stage0_fn, block_fn, last_fn, split_fn, merge_fn)``.
    """
    ctx = ctx or ShardCtx()

    def split_fn(params):
        extras = {k: v for k, v in params.items() if k != "layers"}
        return params["layers"], extras

    def merge_fn(layer_grads, extras_grads):
        return {**extras_grads, "layers": layer_grads}

    def stage0_fn(extras, mb):
        return ctx.embed_lookup(extras["embed"], mb["input_ids"],
                                "batch", "seq", "embed_act")

    def block_fn(layer_slice, extras, x):
        del extras
        layer = partial(_decoder_layer, cfg, ctx, attn_impl)
        return lax.scan(lambda c, lp: (layer(c, lp), None), x, layer_slice)[0]

    def last_fn(extras, y, mb):
        x = rmsnorm(y, extras["final_norm"], cfg.rms_norm_eps)
        head = (extras["embed"].T if cfg.tie_embeddings
                else extras["lm_head"]).astype(x.dtype)
        return causal_lm_loss(x @ head, mb["input_ids"], mb.get("labels"))

    return stage0_fn, block_fn, last_fn, split_fn, merge_fn


def num_params(cfg: LlamaConfig) -> int:
    d, f, hd = cfg.hidden_size, cfg.intermediate_size, cfg.hd
    per_layer = d * hd * (cfg.num_heads * 2 + cfg.num_kv_heads * 2) + 3 * d * f + 2 * d
    total = cfg.vocab_size * d + cfg.num_layers * per_layer + d
    if not cfg.tie_embeddings:
        total += d * cfg.vocab_size
    return total


def flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Training FLOPs/token (PaLM convention): 6*N matmul + 6*L*D*S causal attention
    (12*L*D*S non-causal, halved)."""
    return 6.0 * num_params(cfg) + 12.0 * cfg.num_layers * cfg.hidden_size * seq_len / 2.0


def build(cfg: LlamaConfig, ctx: ShardCtx | None = None, attn_impl: str = "auto",
          remat: bool | None = None, remat_policy=None) -> ModelSpec:
    ctx = ctx or ShardCtx()
    remat = ctx.remat if remat is None else remat
    remat_policy = remat_policy if remat_policy is not None else ctx.remat_policy
    fwd = partial(forward, cfg, ctx=ctx, attn_impl=attn_impl,
                  remat=remat, remat_policy=remat_policy)

    def loss_fn(params, batch, rng=None, ltd_keep: int = 0):
        # progressive layer drop: the engine injects a traced theta into the
        # batch (runtime/progressive_layer_drop.py); rng drives the drops.
        # ltd_keep (STATIC): random layerwise token dropping — the engine
        # passes the bucketed schedule value and compiles per bucket.
        pld = batch.get("pld_theta")
        if pld is not None and rng is None:
            raise ValueError("progressive layer drop needs the loss rng")
        if ltd_keep and rng is None:
            raise ValueError("random_ltd needs the loss rng")
        ltd_rng = (jax.random.fold_in(rng, 0x17D) if ltd_keep else None)
        if ctx.loss_tile_size or ltd_keep:
            from deepspeed_tpu.parallel.sequence_tiling import tiled_causal_lm_loss

            x = hidden_states(cfg, params, batch["input_ids"], ctx=ctx,
                              attn_impl=attn_impl, remat=remat,
                              remat_policy=remat_policy,
                              pld_theta=pld, pld_rng=rng,
                              ltd_keep=ltd_keep, ltd_rng=ltd_rng)
            if ctx.loss_tile_size:
                return tiled_causal_lm_loss(
                    x, lm_head(cfg, params), batch["input_ids"],
                    batch.get("labels"), tile_size=ctx.loss_tile_size,
                )
            logits = x @ lm_head(cfg, params).astype(x.dtype)
            return causal_lm_loss(logits, batch["input_ids"],
                                  batch.get("labels"))
        logits = fwd(params, batch["input_ids"], pld_theta=pld, pld_rng=rng)
        return causal_lm_loss(logits, batch["input_ids"], batch.get("labels"))

    axes = dict(PARAM_LOGICAL_AXES)
    if cfg.tie_embeddings:
        axes = {k: v for k, v in axes.items() if k != "lm_head"}
    return ModelSpec(
        name="llama",
        config=cfg,
        init_fn=partial(init_params, cfg),
        loss_fn=loss_fn,
        forward_fn=fwd,
        param_logical_axes=axes,
        logical_dim_units={"heads": cfg.num_heads, "kv_heads": cfg.num_kv_heads},
        num_params=num_params(cfg),
        flops_per_token=partial(flops_per_token, cfg),
        init_cache_fn=partial(init_cache, cfg),
        decode_fn=partial(decode_forward, cfg, ctx=ctx),
        init_paged_cache_fn=partial(init_paged_cache, cfg),
        ragged_forward_fn=partial(ragged_forward, cfg),
        supports_prefill_tiles=True,
        pipeline_parts=pipeline_parts(cfg, ctx=ctx, attn_impl=attn_impl),
        # MPMD staging: untied models split cleanly (embed grads live on the
        # first stage, head grads on the last); a tied table would need its
        # gradient reduced across both end stages, which the activation
        # transport does not carry — None tells PipeEngine to refuse.
        pipeline_extras_owner=(None if cfg.tie_embeddings else {
            "embed": "first", "final_norm": "last", "lm_head": "last"}),
        supports_pld=True,
        supports_random_ltd=True,
    )
