"""Mixtral-family sparse-MoE causal LM (BASELINE.json EP config: Mixtral-8x7B).

Llama backbone (RMSNorm / RoPE / GQA) with a top-k routed SwiGLU expert FFN in
every layer (reference analog: ``deepspeed/moe/layer.py MoE`` wrapping an HF
model; v2 inference ``model_implementations/mixtral``). Expert weights are
stacked ``[L, E, ...]`` so the expert GEMMs batch on the MXU and the expert dim
shards over the ``expert`` mesh axis.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.config.config import MoEConfig
from deepspeed_tpu.models.api import ModelSpec, ShardCtx, causal_lm_loss
from deepspeed_tpu.models.llama import rmsnorm
from deepspeed_tpu.ops.attention import apply_rope
from deepspeed_tpu.parallel.moe import moe_ffn


@dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    num_experts: int = 8
    top_k: int = 2
    head_dim: int | None = None
    rope_theta: float = 1000000.0
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 4096
    capacity_factor: float = 2.0
    aux_loss_coef: float = 0.01

    @property
    def hd(self) -> int:
        return self.head_dim if self.head_dim is not None else self.hidden_size // self.num_heads

    def moe_config(self) -> MoEConfig:
        return MoEConfig(enabled=True, num_experts=self.num_experts, top_k=self.top_k,
                         capacity_factor=self.capacity_factor,
                         aux_loss_coef=self.aux_loss_coef)

    @staticmethod
    def mixtral_8x7b() -> "MixtralConfig":
        return MixtralConfig()

    @staticmethod
    def tiny(vocab_size: int = 256) -> "MixtralConfig":
        return MixtralConfig(vocab_size=vocab_size, hidden_size=64, intermediate_size=96,
                             num_layers=2, num_heads=4, num_kv_heads=2, num_experts=4,
                             top_k=2, max_seq_len=128)


def init_params(cfg: MixtralConfig, rng) -> dict:
    d, f, hd = cfg.hidden_size, cfg.intermediate_size, cfg.hd
    hq, hkv, nl, e = cfg.num_heads, cfg.num_kv_heads, cfg.num_layers, cfg.num_experts
    k = iter(jax.random.split(rng, 16))
    std = 0.02
    out_std = std / jnp.sqrt(2.0 * nl)

    def norm(key, *shape, s=std):
        return jax.random.normal(key, shape, jnp.float32) * s

    return {
        "embed": norm(next(k), cfg.vocab_size, d),
        "layers": {
            "attn_norm": jnp.ones((nl, d), jnp.float32),
            "wq": norm(next(k), nl, d, hq * hd),
            "wk": norm(next(k), nl, d, hkv * hd),
            "wv": norm(next(k), nl, d, hkv * hd),
            "wo": norm(next(k), nl, hq * hd, d, s=out_std),
            "mlp_norm": jnp.ones((nl, d), jnp.float32),
            "router": norm(next(k), nl, d, e),
            "w_gate": norm(next(k), nl, e, d, f),
            "w_up": norm(next(k), nl, e, d, f),
            "w_down": norm(next(k), nl, e, f, d, s=out_std),
        },
        "final_norm": jnp.ones((d,), jnp.float32),
        "lm_head": norm(next(k), d, cfg.vocab_size),
    }


PARAM_LOGICAL_AXES = {
    "embed": ("vocab", "embed"),
    "layers": {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", "embed"),
        "router": ("layers", "embed", None),
        "w_gate": ("layers", "experts", "embed", "ffn"),
        "w_up": ("layers", "experts", "embed", "ffn"),
        "w_down": ("layers", "experts", "ffn", "embed"),
    },
    "final_norm": ("embed",),
    "lm_head": ("embed", "vocab"),
}


def _layer(cfg: MixtralConfig, moe_cfg: MoEConfig, ctx: ShardCtx, attn_impl: str,
           train: bool, x, lp, positions, rng):
    lp = ctx.layer_weights(lp, x.dtype)  # WOQ dequant + qwZ gather hooks
    b, s, d = x.shape
    hq, hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd

    h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = (h @ lp["wq"]).reshape(b, s, hq, hd)
    kk = (h @ lp["wk"]).reshape(b, s, hkv, hd)
    vv = (h @ lp["wv"]).reshape(b, s, hkv, hd)
    q = ctx.constrain(q, "batch", "seq", "heads_act", None)
    q, kk = apply_rope(q, kk, positions, cfg.rope_theta)
    o = ctx.attention(q, kk, vv, causal=True, impl=attn_impl)
    x = x + o.reshape(b, s, hq * hd) @ lp["wo"]

    h = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    y, aux = moe_ffn(h, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
                     moe_cfg, train=train, rng=rng, ctx=ctx)
    x = x + y
    return ctx.constrain(x, "batch", "seq", "embed_act"), aux


def forward(cfg: MixtralConfig, params, input_ids, ctx: ShardCtx | None = None,
            attn_impl: str = "auto", train: bool = True, rng=None,
            remat: bool = False, remat_policy=None, return_aux: bool = False):
    ctx = ctx or ShardCtx()
    moe_cfg = cfg.moe_config()
    b, s = input_ids.shape
    x = ctx.embed_lookup(params["embed"], input_ids, "batch", "seq", "embed_act")
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    layer = partial(_layer, cfg, moe_cfg, ctx, attn_impl, train)
    if remat:
        layer = jax.checkpoint(layer, policy=remat_policy)

    def body(carry, lp_idx):
        x, aux_sum = carry
        lp, idx = lp_idx
        x, aux = layer(x, lp, positions, jax.random.fold_in(rng, idx))
        return (x, aux_sum + aux), None

    (x, aux_sum), _ = lax.scan(
        body, (x, jnp.float32(0.0)),
        (params["layers"], jnp.arange(cfg.num_layers)),
    )
    x = rmsnorm(x, params["final_norm"], cfg.rms_norm_eps)
    from deepspeed_tpu.ops.quantizer import maybe_dequantize

    logits = x @ maybe_dequantize(params["lm_head"], x.dtype).astype(x.dtype)
    logits = ctx.constrain(logits, "batch", "seq", "vocab_act")
    if return_aux:
        return logits, aux_sum / cfg.num_layers
    return logits


# ------------------------------------------------------------------ inference
def _moe_infer(h: jnp.ndarray, router_w, w_gate, w_up, w_down,
               top_k: int) -> jnp.ndarray:
    """Dropless per-token top-k MoE for the inference paths (``h`` [T, D]
    flat tokens).

    Role parity with the reference's ragged MoE serving stack
    (``inference/v2/model_implementations/mixtral/model.py`` +
    ``inference/v2/kernels/ragged_ops`` top-k gating, MoE gather/scatter):
    the CUDA version compacts tokens per expert with gather/scatter kernels;
    the TPU-native shape is a batched [E] einsum — every expert processes
    every token on the MXU and the router's renormalized top-k weights
    combine the results. Exact (no capacity, no drops), at E/top_k x the
    ideal expert FLOPs — the right trade at serving token counts, where the
    expert GEMMs are small and a compaction pass would serialize; a
    sort-based exact dispatch is the optimization point if prefill chunks
    ever dominate.
    """
    t, d = h.shape
    probs = jax.nn.softmax(
        h.astype(jnp.float32) @ router_w.astype(jnp.float32), axis=-1)
    topv, topi = lax.top_k(probs, top_k)
    topv = topv / (jnp.sum(topv, axis=-1, keepdims=True) + 1e-9)
    e = probs.shape[-1]
    w = jnp.zeros((t, e), jnp.float32).at[
        jnp.arange(t)[:, None], topi].set(topv)
    dtype = h.dtype
    g = jnp.einsum("td,edf->tef", h, w_gate.astype(dtype))
    u = jnp.einsum("td,edf->tef", h, w_up.astype(dtype))
    y = jnp.einsum("tef,efd->ted", jax.nn.silu(g) * u, w_down.astype(dtype))
    return jnp.einsum("ted,te->td", y, w.astype(dtype))


def init_cache(cfg: MixtralConfig, batch: int, max_len: int,
               dtype=jnp.bfloat16) -> dict:
    """Dense fixed-shape KV cache [L, B, max_len, Hkv, Dh] (v1 engine)."""
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.hd)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _cached_layer(cfg: MixtralConfig, x, lp, k_cache, v_cache, start_pos,
                  max_len: int):
    from deepspeed_tpu.models.paged import append_kv_and_attend
    from deepspeed_tpu.ops.quantizer import dequantize_layer

    lp = dequantize_layer(lp, x.dtype)
    b, t, d = x.shape
    hq, hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd

    h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = (h @ lp["wq"]).reshape(b, t, hq, hd)
    kk = (h @ lp["wk"]).reshape(b, t, hkv, hd)
    vv = (h @ lp["wv"]).reshape(b, t, hkv, hd)
    positions = start_pos + jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
    q, kk = apply_rope(q, kk, positions, cfg.rope_theta)
    o, k_cache, v_cache = append_kv_and_attend(
        q, kk, vv, k_cache, v_cache, start_pos, max_len)
    x = x + o.reshape(b, t, hq * hd) @ lp["wo"]

    h = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    y = _moe_infer(h.reshape(b * t, d), lp["router"], lp["w_gate"],
                   lp["w_up"], lp["w_down"], cfg.top_k)
    return x + y.reshape(b, t, d), k_cache, v_cache


def decode_forward(cfg: MixtralConfig, params, tokens, cache, start_pos,
                   ctx: ShardCtx | None = None):
    """[B, T] new tokens + cache -> ([B, T, V] logits, cache); prefill
    (T = prompt) and incremental decode (T = 1) share the program."""
    del ctx
    max_len = cache["k"].shape[2]
    x = params["embed"][tokens].astype(cache["k"].dtype)

    def body(x, lp_kv):
        lp, kc, vc = lp_kv
        x, kc, vc = _cached_layer(cfg, x, lp, kc, vc, start_pos, max_len)
        return x, (kc, vc)

    x, (new_k, new_v) = lax.scan(body, x,
                                 (params["layers"], cache["k"], cache["v"]))
    x = rmsnorm(x, params["final_norm"], cfg.rms_norm_eps)
    from deepspeed_tpu.ops.quantizer import maybe_dequantize

    logits = x @ maybe_dequantize(params["lm_head"], x.dtype).astype(x.dtype)
    return logits, {"k": new_k, "v": new_v}


def init_paged_cache(cfg: MixtralConfig, num_blocks: int, block_size: int,
                     dtype=jnp.bfloat16) -> dict:
    """Blocked KV pool [L, num_blocks, block_size, Hkv, Dh] (ragged engine;
    block 0 is the scratch block padding tokens write into)."""
    shape = (cfg.num_layers, num_blocks, block_size, cfg.num_kv_heads, cfg.hd)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _ragged_layer(cfg: MixtralConfig, x, lp, kc, vc, positions, slots,
                  block_tables, prefill_tiles=None):
    """One decoder layer over a flat ragged token batch [T, D]: paged
    attention identical to the Llama ragged layer, MoE FFN routed per token
    (decode tokens route through the SAME per-token top-k machinery as
    prefill-chunk tokens — MoE over a paged cache is a routing problem only
    in the FFN, which is position-free)."""
    from deepspeed_tpu.models.paged import (
        ragged_pool_attention,
        write_kv_paged,
    )
    from deepspeed_tpu.ops.quantizer import dequantize_layer

    lp = dequantize_layer(lp, x.dtype)
    t_tokens, d = x.shape
    hq, hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd

    h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = (h @ lp["wq"]).reshape(t_tokens, hq, hd)
    kk = (h @ lp["wk"]).reshape(t_tokens, hkv, hd)
    vv = (h @ lp["wv"]).reshape(t_tokens, hkv, hd)
    q, kk = apply_rope(q[None], kk[None], positions[None], cfg.rope_theta)
    q, kk = q[0], kk[0]

    kc, vc = write_kv_paged(kc, vc, kk, vv, slots, positions, block_tables)
    o = ragged_pool_attention(q, kc, vc, slots, positions, block_tables,
                              prefill_tiles).astype(x.dtype)
    x = x + o.reshape(t_tokens, hq * hd) @ lp["wo"]

    h = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    x = x + _moe_infer(h, lp["router"], lp["w_gate"], lp["w_up"],
                       lp["w_down"], cfg.top_k)
    return x, kc, vc


def ragged_forward(cfg: MixtralConfig, params, tokens, slots, positions,
                   block_tables, cache, prefill_tiles=None):
    """Flat ragged step: [T] mixed tokens -> ([T, V] logits, cache) — the
    MoE member of the continuous-batching engine (reference
    ``inference/v2/model_implementations/mixtral``)."""
    x = params["embed"][tokens].astype(cache["k"].dtype)

    def body(x, lp_kv):
        lp, kc, vc = lp_kv
        x, kc, vc = _ragged_layer(cfg, x, lp, kc, vc, positions, slots,
                                  block_tables, prefill_tiles=prefill_tiles)
        return x, (kc, vc)

    x, (new_k, new_v) = lax.scan(body, x,
                                 (params["layers"], cache["k"], cache["v"]))
    x = rmsnorm(x, params["final_norm"], cfg.rms_norm_eps)
    from deepspeed_tpu.ops.quantizer import maybe_dequantize

    logits = x @ maybe_dequantize(params["lm_head"], x.dtype).astype(x.dtype)
    return logits, {"k": new_k, "v": new_v}


def num_params(cfg: MixtralConfig) -> int:
    d, f, hd, e = cfg.hidden_size, cfg.intermediate_size, cfg.hd, cfg.num_experts
    per_layer = (d * hd * (cfg.num_heads * 2 + cfg.num_kv_heads * 2) + d * e
                 + 3 * e * d * f + 2 * d)
    return cfg.vocab_size * d * 2 + cfg.num_layers * per_layer + d


def flops_per_token(cfg: MixtralConfig, seq_len: int) -> float:
    """Active-param flops: attention + top_k of E experts."""
    d, f, hd = cfg.hidden_size, cfg.intermediate_size, cfg.hd
    active_per_layer = (d * hd * (cfg.num_heads * 2 + cfg.num_kv_heads * 2)
                        + cfg.top_k * 3 * d * f + d * cfg.num_experts)
    active = cfg.vocab_size * d * 2 + cfg.num_layers * active_per_layer
    return 6.0 * active + 12.0 * cfg.num_layers * d * seq_len / 2.0


def build(cfg: MixtralConfig, ctx: ShardCtx | None = None, attn_impl: str = "auto",
          remat: bool | None = None, remat_policy=None) -> ModelSpec:
    ctx = ctx or ShardCtx()
    remat = ctx.remat if remat is None else remat
    remat_policy = remat_policy if remat_policy is not None else ctx.remat_policy
    fwd = partial(forward, cfg, ctx=ctx, attn_impl=attn_impl,
                  remat=remat, remat_policy=remat_policy, train=False)

    def loss_fn(params, batch, rng=None):
        logits, aux = forward(cfg, params, batch["input_ids"], ctx=ctx,
                              attn_impl=attn_impl, train=True, rng=rng,
                              remat=remat, remat_policy=remat_policy, return_aux=True)
        lm = causal_lm_loss(logits, batch["input_ids"], batch.get("labels"))
        return lm + cfg.aux_loss_coef * aux

    return ModelSpec(
        name="mixtral",
        config=cfg,
        init_fn=partial(init_params, cfg),
        loss_fn=loss_fn,
        forward_fn=fwd,
        param_logical_axes=PARAM_LOGICAL_AXES,
        logical_dim_units={"heads": cfg.num_heads, "kv_heads": cfg.num_kv_heads,
                           "experts": cfg.num_experts},
        num_params=num_params(cfg),
        flops_per_token=partial(flops_per_token, cfg),
        init_cache_fn=partial(init_cache, cfg),
        decode_fn=partial(decode_forward, cfg, ctx=ctx),
        init_paged_cache_fn=partial(init_paged_cache, cfg),
        ragged_forward_fn=partial(ragged_forward, cfg),
        supports_prefill_tiles=True,
    )
