"""GPT-2 causal LM — the correctness-baseline model (BASELINE.json: GPT-2 125M
ZeRO-1 single-host config).

LayerNorm(+bias), learned positional embeddings, GELU MLP, tied LM head —
matching HF ``GPT2LMHeadModel`` semantics. Same functional stacked-scan design
as ``models/llama.py``.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.models.api import ModelSpec, ShardCtx, causal_lm_loss


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    layer_norm_eps: float = 1e-5

    @property
    def ffn(self) -> int:
        return 4 * self.hidden_size

    @property
    def hd(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def gpt2_125m() -> "GPT2Config":
        return GPT2Config()

    @staticmethod
    def tiny(vocab_size: int = 256) -> "GPT2Config":
        return GPT2Config(vocab_size=vocab_size, hidden_size=64, num_layers=2,
                          num_heads=4, max_seq_len=128)


def init_params(cfg: GPT2Config, rng) -> dict:
    d, f, nl = cfg.hidden_size, cfg.ffn, cfg.num_layers
    k = iter(jax.random.split(rng, 16))
    std = 0.02
    out_std = std / jnp.sqrt(2.0 * nl)

    def norm(key, *shape, s=std):
        return jax.random.normal(key, shape, jnp.float32) * s

    return {
        "wte": norm(next(k), cfg.vocab_size, d),
        "wpe": norm(next(k), cfg.max_seq_len, d, s=0.01),
        "layers": {
            "ln1_g": jnp.ones((nl, d)), "ln1_b": jnp.zeros((nl, d)),
            "wq": norm(next(k), nl, d, d), "bq": jnp.zeros((nl, d)),
            "wk": norm(next(k), nl, d, d), "bk": jnp.zeros((nl, d)),
            "wv": norm(next(k), nl, d, d), "bv": jnp.zeros((nl, d)),
            "wo": norm(next(k), nl, d, d, s=out_std), "bo": jnp.zeros((nl, d)),
            "ln2_g": jnp.ones((nl, d)), "ln2_b": jnp.zeros((nl, d)),
            "w_in": norm(next(k), nl, d, f), "b_in": jnp.zeros((nl, f)),
            "w_out": norm(next(k), nl, f, d, s=out_std), "b_out": jnp.zeros((nl, d)),
        },
        "lnf_g": jnp.ones((d,)), "lnf_b": jnp.zeros((d,)),
    }


PARAM_LOGICAL_AXES = {
    "wte": ("vocab", "embed"),
    "wpe": (None, "embed"),
    "layers": {
        "ln1_g": ("layers", "embed"), "ln1_b": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"), "bq": ("layers", "heads"),
        "wk": ("layers", "embed", "heads"), "bk": ("layers", "heads"),
        "wv": ("layers", "embed", "heads"), "bv": ("layers", "heads"),
        "wo": ("layers", "heads", "embed"), "bo": ("layers", "embed"),
        "ln2_g": ("layers", "embed"), "ln2_b": ("layers", "embed"),
        "w_in": ("layers", "embed", "ffn"), "b_in": ("layers", "ffn"),
        "w_out": ("layers", "ffn", "embed"), "b_out": ("layers", "embed"),
    },
    "lnf_g": ("embed",), "lnf_b": ("embed",),
}


def layernorm(x, g, b, eps):
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
    return (((xf - mu) * lax.rsqrt(var + eps)).astype(x.dtype) * g.astype(x.dtype)
            + b.astype(x.dtype))


def _block(cfg: GPT2Config, ctx: ShardCtx, attn_impl: str, x, lp):
    lp = ctx.layer_weights(lp, x.dtype)  # WOQ dequant + qwZ gather hooks
    b, s, d = x.shape
    h = layernorm(x, lp["ln1_g"], lp["ln1_b"], cfg.layer_norm_eps)
    q = (h @ lp["wq"] + lp["bq"]).reshape(b, s, cfg.num_heads, cfg.hd)
    kk = (h @ lp["wk"] + lp["bk"]).reshape(b, s, cfg.num_heads, cfg.hd)
    vv = (h @ lp["wv"] + lp["bv"]).reshape(b, s, cfg.num_heads, cfg.hd)
    q = ctx.constrain(q, "batch", "seq", "heads_act", None)
    o = ctx.attention(q, kk, vv, causal=True, impl=attn_impl).reshape(b, s, d)
    x = x + o @ lp["wo"] + lp["bo"]
    h = layernorm(x, lp["ln2_g"], lp["ln2_b"], cfg.layer_norm_eps)
    h = jax.nn.gelu(h @ lp["w_in"] + lp["b_in"], approximate=True)
    h = ctx.constrain(h, "batch", "seq", "ffn_act")
    x = x + h @ lp["w_out"] + lp["b_out"]
    return ctx.constrain(x, "batch", "seq", "embed_act")


def forward(cfg: GPT2Config, params, input_ids, ctx: ShardCtx | None = None,
            attn_impl: str = "auto", remat: bool = False, remat_policy=None,
            pld_theta=None, pld_rng=None, ltd_keep: int = 0, ltd_rng=None):
    ctx = ctx or ShardCtx()
    b, s = input_ids.shape
    x = params["wte"][input_ids] + params["wpe"][:s][None, :, :]
    x = ctx.constrain(x, "batch", "seq", "embed_act")

    layer = partial(_block, cfg, ctx, attn_impl)
    if remat:
        layer = jax.checkpoint(layer, policy=remat_policy)
    x = ctx.layer_stack(layer, params["layers"], x,
                        pld_theta=pld_theta, pld_rng=pld_rng,
                        ltd_keep=ltd_keep, ltd_rng=ltd_rng)
    x = layernorm(x, params["lnf_g"], params["lnf_b"], cfg.layer_norm_eps)
    logits = x @ params["wte"].T.astype(x.dtype)  # tied head
    return ctx.constrain(logits, "batch", "seq", "vocab_act")


# ------------------------------------------------------------------ inference
def init_cache(cfg: GPT2Config, batch: int, max_len: int,
               dtype=jnp.bfloat16) -> dict:
    """Dense fixed-shape KV cache [L, B, max_len, H, Dh] (v1 engine)."""
    shape = (cfg.num_layers, batch, max_len, cfg.num_heads, cfg.hd)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _cached_block(cfg: GPT2Config, x, lp, k_cache, v_cache, start_pos,
                  max_len: int):
    from deepspeed_tpu.models.paged import append_kv_and_attend
    from deepspeed_tpu.ops.quantizer import dequantize_layer

    lp = dequantize_layer(lp, x.dtype)
    b, t, d = x.shape
    h = layernorm(x, lp["ln1_g"], lp["ln1_b"], cfg.layer_norm_eps)
    q = (h @ lp["wq"] + lp["bq"]).reshape(b, t, cfg.num_heads, cfg.hd)
    kk = (h @ lp["wk"] + lp["bk"]).reshape(b, t, cfg.num_heads, cfg.hd)
    vv = (h @ lp["wv"] + lp["bv"]).reshape(b, t, cfg.num_heads, cfg.hd)
    o, k_cache, v_cache = append_kv_and_attend(
        q, kk, vv, k_cache, v_cache, start_pos, max_len)
    x = x + o.reshape(b, t, d) @ lp["wo"] + lp["bo"]
    h = layernorm(x, lp["ln2_g"], lp["ln2_b"], cfg.layer_norm_eps)
    h = jax.nn.gelu(h @ lp["w_in"] + lp["b_in"], approximate=True)
    return x + h @ lp["w_out"] + lp["b_out"], k_cache, v_cache


def decode_forward(cfg: GPT2Config, params, tokens, cache, start_pos,
                   ctx: ShardCtx | None = None):
    """[B, T] new tokens + cache -> ([B, T, V] logits, cache)."""
    del ctx
    max_len = cache["k"].shape[2]
    b, t = tokens.shape
    pos = start_pos + jnp.arange(t)
    x = (params["wte"][tokens] + params["wpe"][pos][None]).astype(
        cache["k"].dtype)

    def body(x, lp_kv):
        lp, kc, vc = lp_kv
        x, kc, vc = _cached_block(cfg, x, lp, kc, vc, start_pos, max_len)
        return x, (kc, vc)

    x, (new_k, new_v) = lax.scan(body, x,
                                 (params["layers"], cache["k"], cache["v"]))
    x = layernorm(x, params["lnf_g"], params["lnf_b"], cfg.layer_norm_eps)
    from deepspeed_tpu.ops.quantizer import maybe_dequantize

    logits = x @ maybe_dequantize(params["wte"], x.dtype).astype(x.dtype).T
    return logits, {"k": new_k, "v": new_v}


def init_paged_cache(cfg: GPT2Config, num_blocks: int, block_size: int,
                     dtype=jnp.bfloat16) -> dict:
    """Blocked KV pool [L, num_blocks, block_size, H, Dh] (ragged engine)."""
    shape = (cfg.num_layers, num_blocks, block_size, cfg.num_heads, cfg.hd)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _ragged_block(cfg: GPT2Config, x, lp, kc, vc, positions, slots,
                  block_tables, prefill_tiles=None):
    from deepspeed_tpu.models.paged import (
        ragged_pool_attention,
        write_kv_paged,
    )
    from deepspeed_tpu.ops.quantizer import dequantize_layer

    lp = dequantize_layer(lp, x.dtype)
    t_tokens, d = x.shape
    h = layernorm(x, lp["ln1_g"], lp["ln1_b"], cfg.layer_norm_eps)
    q = (h @ lp["wq"] + lp["bq"]).reshape(t_tokens, cfg.num_heads, cfg.hd)
    kk = (h @ lp["wk"] + lp["bk"]).reshape(t_tokens, cfg.num_heads, cfg.hd)
    vv = (h @ lp["wv"] + lp["bv"]).reshape(t_tokens, cfg.num_heads, cfg.hd)
    kc, vc = write_kv_paged(kc, vc, kk, vv, slots, positions, block_tables)
    o = ragged_pool_attention(q, kc, vc, slots, positions, block_tables,
                              prefill_tiles).astype(x.dtype)
    x = x + o.reshape(t_tokens, d) @ lp["wo"] + lp["bo"]
    h = layernorm(x, lp["ln2_g"], lp["ln2_b"], cfg.layer_norm_eps)
    h = jax.nn.gelu(h @ lp["w_in"] + lp["b_in"], approximate=True)
    return x + h @ lp["w_out"] + lp["b_out"], kc, vc


def ragged_forward(cfg: GPT2Config, params, tokens, slots, positions,
                   block_tables, cache, prefill_tiles=None):
    """Flat ragged step: [T] mixed tokens -> ([T, V] logits, cache).
    Learned positional embeddings ride the per-token ``positions`` the
    ragged layout already carries."""
    x = (params["wte"][tokens] + params["wpe"][positions]).astype(
        cache["k"].dtype)

    def body(x, lp_kv):
        lp, kc, vc = lp_kv
        x, kc, vc = _ragged_block(cfg, x, lp, kc, vc, positions, slots,
                                  block_tables, prefill_tiles=prefill_tiles)
        return x, (kc, vc)

    x, (new_k, new_v) = lax.scan(body, x,
                                 (params["layers"], cache["k"], cache["v"]))
    x = layernorm(x, params["lnf_g"], params["lnf_b"], cfg.layer_norm_eps)
    from deepspeed_tpu.ops.quantizer import maybe_dequantize

    logits = x @ maybe_dequantize(params["wte"], x.dtype).astype(x.dtype).T
    return logits, {"k": new_k, "v": new_v}


def num_params(cfg: GPT2Config) -> int:
    d, f = cfg.hidden_size, cfg.ffn
    per_layer = 4 * d * d + 4 * d + 2 * d * f + d + f + 4 * d
    return cfg.vocab_size * d + cfg.max_seq_len * d + cfg.num_layers * per_layer + 2 * d


def flops_per_token(cfg: GPT2Config, seq_len: int) -> float:
    return 6.0 * num_params(cfg) + 12.0 * cfg.num_layers * cfg.hidden_size * seq_len / 2.0


def build(cfg: GPT2Config, ctx: ShardCtx | None = None, attn_impl: str = "auto",
          remat: bool | None = None, remat_policy=None) -> ModelSpec:
    ctx = ctx or ShardCtx()
    remat = ctx.remat if remat is None else remat
    remat_policy = remat_policy if remat_policy is not None else ctx.remat_policy
    fwd = partial(forward, cfg, ctx=ctx, attn_impl=attn_impl,
                  remat=remat, remat_policy=remat_policy)

    def loss_fn(params, batch, rng=None, ltd_keep: int = 0):
        pld = batch.get("pld_theta")
        if pld is not None and rng is None:
            raise ValueError("progressive layer drop needs the loss rng")
        if ltd_keep and rng is None:
            raise ValueError("random_ltd needs the loss rng")
        logits = fwd(params, batch["input_ids"], pld_theta=pld, pld_rng=rng,
                     ltd_keep=ltd_keep,
                     ltd_rng=(jax.random.fold_in(rng, 0x17D)
                              if ltd_keep else None))
        return causal_lm_loss(logits, batch["input_ids"], batch.get("labels"))

    return ModelSpec(
        name="gpt2",
        config=cfg,
        init_fn=partial(init_params, cfg),
        loss_fn=loss_fn,
        forward_fn=fwd,
        param_logical_axes=PARAM_LOGICAL_AXES,
        logical_dim_units={"heads": cfg.num_heads},
        num_params=num_params(cfg),
        flops_per_token=partial(flops_per_token, cfg),
        supports_pld=True,
        supports_random_ltd=True,
        woq_skip=("wte", "wpe"),
        init_cache_fn=partial(init_cache, cfg),
        decode_fn=partial(decode_forward, cfg, ctx=ctx),
        init_paged_cache_fn=partial(init_paged_cache, cfg),
        ragged_forward_fn=partial(ragged_forward, cfg),
        supports_prefill_tiles=True,
    )
