"""Latent-diffusion UNet family (the reference's diffusers/spatial surface).

Role parity with the reference's diffusers support: the v1 inference engine
wraps diffusers UNet/VAE modules (``model_implementations/diffusers/``) and
``csrc/spatial/csrc/opt_bias_add.cu`` fuses conv bias-adds for them. On TPU
both collapse into this module + XLA:

- the *kernels* (opt_bias_add, group-norm fusions) are XLA fusions — conv +
  bias + nonlinearity fuse natively on the MXU/VPU, so no hand-written
  spatial kernels exist or are needed;
- the *model family* is this UNet: timestep-conditioned resnet blocks with
  self-attention at low resolution, trained by the SAME ``Engine`` as the
  LM families (the loss_fn contract is model-agnostic: noise-prediction MSE
  instead of cross-entropy), with a jitted DDIM sampler for inference.

Conv layout is NHWC (TPU-native); channel dims carry logical axes so the
sharding planner can fsdp/TP-shard conv kernels like any other weight.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.models.api import ModelSpec, ShardCtx


@dataclass(frozen=True)
class UNetConfig:
    image_size: int = 32
    in_channels: int = 4          # latent channels (LDM) or 3 for pixel space
    base_channels: int = 64
    channel_mults: tuple = (1, 2, 4)
    num_res_blocks: int = 2
    attn_resolutions: tuple = (8,)  # self-attention at these spatial sizes
    num_heads: int = 4
    time_embed_dim: int = 256
    diffusion_steps: int = 1000

    @staticmethod
    def tiny() -> "UNetConfig":
        return UNetConfig(image_size=8, in_channels=3, base_channels=16,
                          channel_mults=(1, 2), num_res_blocks=1,
                          attn_resolutions=(4,), num_heads=2,
                          time_embed_dim=32, diffusion_steps=100)


def _conv_init(key, kh, kw, cin, cout, scale=1.0):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * (
        scale / jnp.sqrt(fan_in))


def _resblock_params(key, cin, cout, tdim):
    ks = jax.random.split(key, 4)
    return {
        "conv1": _conv_init(ks[0], 3, 3, cin, cout),
        "conv2": _conv_init(ks[1], 3, 3, cout, cout, scale=1e-2),
        "temb": jax.random.normal(ks[2], (tdim, cout), jnp.float32) * 0.02,
        "skip": (_conv_init(ks[3], 1, 1, cin, cout) if cin != cout else None),
    }


def _attn_params(key, c):
    ks = jax.random.split(key, 2)
    return {
        "qkv": jax.random.normal(ks[0], (c, 3 * c), jnp.float32) * (1 / jnp.sqrt(c)),
        "out": jax.random.normal(ks[1], (c, c), jnp.float32) * 1e-2,
    }


def _plan(cfg: UNetConfig):
    """The static layer plan: (kind, cin, cout, resolution) per block."""
    downs, c = [], cfg.base_channels
    res = cfg.image_size
    chans = [c]
    for i, mult in enumerate(cfg.channel_mults):
        cout = cfg.base_channels * mult
        for _ in range(cfg.num_res_blocks):
            downs.append(("res", c, cout, res))
            if res in cfg.attn_resolutions:
                downs.append(("attn", cout, cout, res))
            c = cout
            chans.append(c)
        if i < len(cfg.channel_mults) - 1:
            downs.append(("down", c, c, res))
            res //= 2
            chans.append(c)
    mid = [("res", c, c, res), ("attn", c, c, res), ("res", c, c, res)]
    ups = []
    for i, mult in reversed(list(enumerate(cfg.channel_mults))):
        cout = cfg.base_channels * mult
        for _ in range(cfg.num_res_blocks + 1):
            skip = chans.pop()
            ups.append(("res", c + skip, cout, res))
            if res in cfg.attn_resolutions:
                ups.append(("attn", cout, cout, res))
            c = cout
        if i > 0:
            ups.append(("up", c, c, res))
            res *= 2
    return downs, mid, ups


def init_params(cfg: UNetConfig, rng) -> dict:
    downs, mid, ups = _plan(cfg)
    keys = iter(jax.random.split(rng, len(downs) + len(mid) + len(ups) + 8))

    def blocks(plan):
        out = []
        for kind, cin, cout, res in plan:
            if kind == "res":
                out.append(_resblock_params(next(keys), cin, cout, cfg.time_embed_dim))
            elif kind == "attn":
                out.append(_attn_params(next(keys), cout))
            elif kind in ("down", "up"):
                out.append({"conv": _conv_init(next(keys), 3, 3, cin, cout)})
        return out

    return {
        "time_mlp": {
            "w1": jax.random.normal(next(keys), (cfg.time_embed_dim,
                                                 cfg.time_embed_dim)) * 0.02,
            "w2": jax.random.normal(next(keys), (cfg.time_embed_dim,
                                                 cfg.time_embed_dim)) * 0.02,
        },
        "conv_in": _conv_init(next(keys), 3, 3, cfg.in_channels, cfg.base_channels),
        "down": blocks(downs),
        "mid": blocks(mid),
        "up": blocks(ups),
        "conv_out": _conv_init(next(keys), 3, 3, cfg.base_channels,
                               cfg.in_channels, scale=1e-2),
    }


def param_logical_axes(cfg: UNetConfig, params: dict):
    """Conv kernels: fsdp on the output-channel dim; attention matrices on
    the head projection dim (same vocabulary the LM families use)."""

    def axes(path, leaf):
        if leaf is None:
            return None
        nd = getattr(leaf, "ndim", 0)
        if nd == 4:
            return (None, None, None, "ffn")
        if nd == 2:
            return (None, "ffn")
        return tuple([None] * nd)

    return jax.tree_util.tree_map_with_path(axes, params)


def _timestep_embedding(t, dim):
    half = dim // 2
    freqs = jnp.exp(-jnp.log(10000.0) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.sin(args), jnp.cos(args)], axis=-1)


def _group_norm(x, groups=8, eps=1e-5):
    b, h, w, c = x.shape
    g = min(groups, c)
    while c % g:
        g -= 1
    xg = x.reshape(b, h, w, g, c // g).astype(jnp.float32)
    mu = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    return ((xg - mu) * lax.rsqrt(var + eps)).reshape(b, h, w, c).astype(x.dtype)


def _conv(x, w, stride=1):
    # NHWC x HWIO: the TPU conv layout; bias-adds and nonlinearities fuse
    # into the conv by XLA (the reference's opt_bias_add kernel, by design)
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _resblock(x, p, temb):
    h = _conv(jax.nn.silu(_group_norm(x)), p["conv1"])
    h = h + (temb @ p["temb"]).astype(h.dtype)[:, None, None, :]
    h = _conv(jax.nn.silu(_group_norm(h)), p["conv2"])
    skip = x if p["skip"] is None else _conv(x, p["skip"])
    return skip + h


def _attn(x, p, num_heads):
    b, hh, ww, c = x.shape
    hn = _group_norm(x).reshape(b, hh * ww, c)
    qkv = (hn @ p["qkv"].astype(x.dtype)).reshape(b, hh * ww, 3, num_heads, c // num_heads)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    from deepspeed_tpu.ops.attention import xla_attention

    o = xla_attention(q, k, v, causal=False)
    o = o.reshape(b, hh * ww, c) @ p["out"].astype(x.dtype)
    return x + o.reshape(b, hh, ww, c)


def forward(cfg: UNetConfig, params, x, t, ctx: ShardCtx | None = None):
    """Predict the noise: ``x`` [B, H, W, C] noisy input, ``t`` [B] steps."""
    downs, mid, ups = _plan(cfg)
    temb = _timestep_embedding(t, cfg.time_embed_dim)
    tm = params["time_mlp"]
    temb = jax.nn.silu(temb @ tm["w1"].astype(temb.dtype)) @ tm["w2"].astype(temb.dtype)

    h = _conv(x, params["conv_in"])
    stack = [h]

    def run(plan, blocks, h, mode):
        for (kind, cin, cout, res), p in zip(plan, blocks):
            if kind == "res":
                if mode == "up":
                    h = _resblock(jnp.concatenate([h, stack.pop()], axis=-1),
                                  p, temb)
                else:
                    h = _resblock(h, p, temb)
                if mode == "down":
                    stack.append(h)
            elif kind == "attn":
                h = _attn(h, p, cfg.num_heads)
                if mode == "down":
                    stack[-1] = h
            elif kind == "down":
                h = _conv(h, p["conv"], stride=2)
                stack.append(h)
            elif kind == "up":
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), "nearest")
                h = _conv(h, p["conv"])
        return h

    h = run(downs, params["down"], h, "down")
    h = run(mid, params["mid"], h, "mid")
    h = run(ups, params["up"], h, "up")
    return _conv(jax.nn.silu(_group_norm(h)), params["conv_out"])


# ------------------------------------------------------------------ schedule
def ddpm_schedule(steps: int):
    """Linear beta schedule (DDPM); returns alphas_bar [T]."""
    betas = jnp.linspace(1e-4, 0.02, steps, dtype=jnp.float32)
    return jnp.cumprod(1.0 - betas)


def diffusion_loss(cfg: UNetConfig, params, batch, rng, ctx=None):
    """Noise-prediction MSE (the standard epsilon objective): the engine's
    model-agnostic loss contract, so every ZeRO stage / offload tier /
    parallelism axis applies to diffusion training unchanged."""
    x0 = batch["images"].astype(jnp.float32)
    b = x0.shape[0]
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    k_t, k_n = jax.random.split(rng)
    t = jax.random.randint(k_t, (b,), 0, cfg.diffusion_steps)
    noise = jax.random.normal(k_n, x0.shape, jnp.float32)
    ab = ddpm_schedule(cfg.diffusion_steps)[t][:, None, None, None]
    xt = jnp.sqrt(ab) * x0 + jnp.sqrt(1.0 - ab) * noise
    pred = forward(cfg, params, xt.astype(x0.dtype), t, ctx=ctx)
    return jnp.mean(jnp.square(pred.astype(jnp.float32) - noise))


def ddim_sample(cfg: UNetConfig, params, rng, batch: int, num_steps: int = 50,
                eta: float = 0.0):
    """Deterministic DDIM sampler as one jittable ``lax.scan`` — the v1
    inference engine's CUDA-graph replay becomes a single compiled program."""
    ab_full = ddpm_schedule(cfg.diffusion_steps)
    ts = jnp.linspace(cfg.diffusion_steps - 1, 0, num_steps).astype(jnp.int32)
    shape = (batch, cfg.image_size, cfg.image_size, cfg.in_channels)
    x = jax.random.normal(rng, shape, jnp.float32)

    def step(x, i):
        t = ts[i]
        t_prev = jnp.where(i + 1 < num_steps, ts[jnp.minimum(i + 1, num_steps - 1)], -1)
        ab_t = ab_full[t]
        ab_prev = jnp.where(t_prev >= 0, ab_full[jnp.maximum(t_prev, 0)], 1.0)
        eps = forward(cfg, params, x, jnp.full((batch,), t), ctx=None)
        eps = eps.astype(jnp.float32)
        x0 = (x - jnp.sqrt(1.0 - ab_t) * eps) / jnp.sqrt(ab_t)
        x = jnp.sqrt(ab_prev) * x0 + jnp.sqrt(1.0 - ab_prev) * eps
        return x, None

    x, _ = lax.scan(step, x, jnp.arange(num_steps))
    return x


def num_params(cfg: UNetConfig) -> int:
    leaves = jax.tree_util.tree_leaves(
        jax.eval_shape(partial(init_params, cfg), jax.random.PRNGKey(0)))
    return int(sum(x.size for x in leaves))


def build(cfg: UNetConfig, ctx: ShardCtx | None = None) -> ModelSpec:
    ctx = ctx or ShardCtx()
    abstract = jax.eval_shape(partial(init_params, cfg), jax.random.PRNGKey(0))
    return ModelSpec(
        name="diffusion-unet",
        config=cfg,
        init_fn=partial(init_params, cfg),
        loss_fn=lambda p, b, rng=None: diffusion_loss(cfg, p, b, rng, ctx=ctx),
        forward_fn=lambda p, x: forward(
            cfg, p, x, jnp.zeros((x.shape[0],), jnp.int32), ctx=ctx),
        param_logical_axes=param_logical_axes(cfg, abstract),
        num_params=num_params(cfg),
    )
