"""HF checkpoint ingestion: safetensors -> sharded parameter pytrees.

Role parity with the reference's real-model loading stack — AutoTP module
parsing + sharded checkpoint loaders (``module_inject/auto_tp.py:194``,
``inference/engine.py`` checkpoint loading, ``module_inject/load_checkpoint.py``)
— rebuilt for the functional pytree world: instead of surgically rewriting
``nn.Module``s, we map HF tensor names to our stacked-layer pytree layout and
place each leaf **directly under the engine's sharding plan**, one leaf at a
time. With safetensors sources, reads are memory-mapped and host memory peaks
at one assembled stacked leaf (~L x one matrix) plus whatever the OS pages in
— never the whole model at once. (The legacy ``pytorch_model.bin`` fallback
has no lazy reader and does load the full state dict; every process currently
assembles every leaf before ``device_put`` keeps only its shard.)

Conventions handled:
- torch ``nn.Linear`` stores [out, in]; our matmuls are x @ W -> transpose.
  GPT-2's ``Conv1D`` already stores [in, out] -> no transpose.
- kv-head-aware: q/k/v projections keep head granularity, so the planner's
  kv-head shard-divisibility checks (reference ``module_inject/tp_shard.py``)
  apply unchanged.
- tied embeddings: ``tie_word_embeddings`` drops the separate lm_head leaf.
- RoPE: this repo's ``apply_rope`` uses the half-split (rotate-half) layout,
  identical to HF Llama/Mixtral — weights map 1:1 with no column permutation.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable

import numpy as np

__all__ = [
    "config_from_hf",
    "load_hf_params",
    "hf_checkpoint_files",
    "from_pretrained",
]


# ------------------------------------------------------------------ file access
def hf_checkpoint_files(model_dir: str) -> list[str]:
    """The checkpoint shard files of an HF model dir (single- or multi-file)."""
    index = os.path.join(model_dir, "model.safetensors.index.json")
    if os.path.exists(index):
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        return sorted({os.path.join(model_dir, v) for v in weight_map.values()})
    single = os.path.join(model_dir, "model.safetensors")
    if os.path.exists(single):
        return [single]
    legacy = os.path.join(model_dir, "pytorch_model.bin")
    if os.path.exists(legacy):
        return [legacy]
    raise FileNotFoundError(f"no safetensors/bin checkpoint under {model_dir}")


class _TensorSource:
    """Lazy per-tensor reader over the checkpoint shards (safetensors
    ``safe_open`` keeps everything memory-mapped; nothing is read until a
    tensor is requested)."""

    def __init__(self, model_dir: str):
        self._handles: list[Any] = []
        self._where: dict[str, Any] = {}
        self._legacy: dict[str, Any] | None = None
        for path in hf_checkpoint_files(model_dir):
            if path.endswith(".bin"):
                import torch

                self._legacy = torch.load(path, map_location="cpu", weights_only=True)
                for name in self._legacy:
                    self._where[name] = "legacy"
                continue
            from safetensors import safe_open

            h = safe_open(path, framework="pt")
            self._handles.append(h)
            for name in h.keys():
                self._where[name] = h

    def names(self):
        return self._where.keys()

    def get(self, name: str) -> np.ndarray:
        src = self._where.get(name)
        if src is None:
            raise KeyError(f"tensor {name!r} not in checkpoint")
        t = self._legacy[name] if src == "legacy" else src.get_tensor(name)
        return t.to(dtype=__import__("torch").float32).numpy()


# ------------------------------------------------------------------ config
def config_from_hf(model_dir: str):
    """HF ``config.json`` -> (family name, our model config dataclass)."""
    with open(os.path.join(model_dir, "config.json")) as f:
        hc = json.load(f)
    arch = (hc.get("architectures") or [""])[0]
    model_type = hc.get("model_type", "")

    if "Llama" in arch or model_type == "llama":
        from deepspeed_tpu.models.llama import LlamaConfig

        return "llama", LlamaConfig(
            vocab_size=hc["vocab_size"],
            hidden_size=hc["hidden_size"],
            intermediate_size=hc["intermediate_size"],
            num_layers=hc["num_hidden_layers"],
            num_heads=hc["num_attention_heads"],
            num_kv_heads=hc.get("num_key_value_heads", hc["num_attention_heads"]),
            head_dim=hc.get("head_dim"),
            rope_theta=hc.get("rope_theta", 10000.0),
            rms_norm_eps=hc.get("rms_norm_eps", 1e-5),
            max_seq_len=hc.get("max_position_embeddings", 4096),
            tie_embeddings=hc.get("tie_word_embeddings", False),
        )
    if "GPT2" in arch or model_type == "gpt2":
        from deepspeed_tpu.models.gpt2 import GPT2Config

        return "gpt2", GPT2Config(
            vocab_size=hc["vocab_size"],
            hidden_size=hc["n_embd"],
            num_layers=hc["n_layer"],
            num_heads=hc["n_head"],
            max_seq_len=hc["n_positions"],
            layer_norm_eps=hc.get("layer_norm_epsilon", 1e-5),
        )
    if "Mixtral" in arch or model_type == "mixtral":
        from deepspeed_tpu.models.mixtral import MixtralConfig

        return "mixtral", MixtralConfig(
            vocab_size=hc["vocab_size"],
            hidden_size=hc["hidden_size"],
            intermediate_size=hc["intermediate_size"],
            num_layers=hc["num_hidden_layers"],
            num_heads=hc["num_attention_heads"],
            num_kv_heads=hc.get("num_key_value_heads", hc["num_attention_heads"]),
            num_experts=hc.get("num_local_experts", 8),
            top_k=hc.get("num_experts_per_tok", 2),
            rope_theta=hc.get("rope_theta", 1e6),
            rms_norm_eps=hc.get("rms_norm_eps", 1e-5),
            max_seq_len=hc.get("max_position_embeddings", 4096),
        )
    raise ValueError(f"unsupported HF architecture {arch or model_type!r}")


# ------------------------------------------------------------------ leaf recipes
def _stack(fmt: str, nl: int, transpose: bool = True) -> Callable:
    """Recipe stacking one tensor per layer into the [L, ...] leaf; torch
    Linears ([out, in]) are transposed for x @ W matmuls."""

    def build(src):
        mats = [src.get(fmt.format(i=i)) for i in range(nl)]
        if transpose:
            mats = [m.T for m in mats]
        return np.stack(mats)

    return build


def _llama_family_recipes(nl: int) -> dict:
    """The embed/attention/norm leaves Llama and Mixtral share."""
    return {
        ("embed",): lambda s: s.get("model.embed_tokens.weight"),
        ("layers", "attn_norm"): _stack(
            "model.layers.{i}.input_layernorm.weight", nl, transpose=False),
        ("layers", "wq"): _stack("model.layers.{i}.self_attn.q_proj.weight", nl),
        ("layers", "wk"): _stack("model.layers.{i}.self_attn.k_proj.weight", nl),
        ("layers", "wv"): _stack("model.layers.{i}.self_attn.v_proj.weight", nl),
        ("layers", "wo"): _stack("model.layers.{i}.self_attn.o_proj.weight", nl),
        ("layers", "mlp_norm"): _stack(
            "model.layers.{i}.post_attention_layernorm.weight", nl, transpose=False),
        ("final_norm",): lambda s: s.get("model.norm.weight"),
    }


def _llama_recipes(cfg) -> dict:
    """Target leaf path -> fn(src) building the host array for that leaf."""
    nl = cfg.num_layers
    recipes = {
        **_llama_family_recipes(nl),
        ("layers", "w_gate"): _stack("model.layers.{i}.mlp.gate_proj.weight", nl),
        ("layers", "w_up"): _stack("model.layers.{i}.mlp.up_proj.weight", nl),
        ("layers", "w_down"): _stack("model.layers.{i}.mlp.down_proj.weight", nl),
    }
    if not cfg.tie_embeddings:
        recipes[("lm_head",)] = lambda s: s.get("lm_head.weight").T
    return recipes


def _gpt2_recipes(cfg) -> dict:
    nl = cfg.num_layers

    def stack(fmt: str) -> Callable:
        # GPT-2 Conv1D already stores [in, out]
        return lambda s: np.stack([s.get(fmt.format(i=i)) for i in range(nl)])

    def split_qkv(part: int, bias: bool) -> Callable:
        def build(src):
            outs = []
            for i in range(nl):
                name = f"transformer.h.{i}.attn.c_attn." + ("bias" if bias else "weight")
                t = src.get(name)
                outs.append(np.split(t, 3, axis=-1)[part])
            return np.stack(outs)

        return build

    return {
        ("wte",): lambda s: s.get("transformer.wte.weight"),
        ("wpe",): lambda s: s.get("transformer.wpe.weight"),
        ("layers", "ln1_g"): stack("transformer.h.{i}.ln_1.weight"),
        ("layers", "ln1_b"): stack("transformer.h.{i}.ln_1.bias"),
        ("layers", "wq"): split_qkv(0, False),
        ("layers", "bq"): split_qkv(0, True),
        ("layers", "wk"): split_qkv(1, False),
        ("layers", "bk"): split_qkv(1, True),
        ("layers", "wv"): split_qkv(2, False),
        ("layers", "bv"): split_qkv(2, True),
        ("layers", "wo"): stack("transformer.h.{i}.attn.c_proj.weight"),
        ("layers", "bo"): stack("transformer.h.{i}.attn.c_proj.bias"),
        ("layers", "ln2_g"): stack("transformer.h.{i}.ln_2.weight"),
        ("layers", "ln2_b"): stack("transformer.h.{i}.ln_2.bias"),
        ("layers", "w_in"): stack("transformer.h.{i}.mlp.c_fc.weight"),
        ("layers", "b_in"): stack("transformer.h.{i}.mlp.c_fc.bias"),
        ("layers", "w_out"): stack("transformer.h.{i}.mlp.c_proj.weight"),
        ("layers", "b_out"): stack("transformer.h.{i}.mlp.c_proj.bias"),
        ("lnf_g",): lambda s: s.get("transformer.ln_f.weight"),
        ("lnf_b",): lambda s: s.get("transformer.ln_f.bias"),
    }


def _mixtral_recipes(cfg) -> dict:
    nl, ne = cfg.num_layers, cfg.num_experts

    def stack_experts(w_name: str) -> Callable:
        # -> [L, E, in, out] from per-expert [out, in] Linears
        def build(src):
            return np.stack([
                np.stack([
                    src.get(
                        f"model.layers.{i}.block_sparse_moe.experts.{j}.{w_name}.weight"
                    ).T
                    for j in range(ne)
                ])
                for i in range(nl)
            ])

        return build

    return {
        **_llama_family_recipes(nl),
        ("layers", "router"): _stack(
            "model.layers.{i}.block_sparse_moe.gate.weight", nl),
        # HF Mixtral: w1 = gate, w3 = up, w2 = down
        ("layers", "w_gate"): stack_experts("w1"),
        ("layers", "w_up"): stack_experts("w3"),
        ("layers", "w_down"): stack_experts("w2"),
        ("lm_head",): lambda s: s.get("lm_head.weight").T,
    }


_RECIPES = {
    "llama": _llama_recipes,
    "gpt2": _gpt2_recipes,
    "mixtral": _mixtral_recipes,
}


# ------------------------------------------------------------------ loading
def _set_path(tree: dict, path: tuple, value) -> None:
    for p in path[:-1]:
        tree = tree.setdefault(p, {})
    tree[path[-1]] = value


def load_hf_params(model_dir: str, family: str | None = None, cfg=None,
                   shardings=None, dtype=np.float32):
    """Load an HF checkpoint dir into this repo's parameter pytree.

    With ``shardings`` (a pytree of ``NamedSharding`` congruent to the params,
    e.g. ``plan.param_shardings``), each leaf is ``device_put`` under the plan
    as soon as it is assembled and the host copy is dropped — peak host memory
    is one stacked leaf, never the model. Without it, returns numpy arrays.
    """
    if family is None or cfg is None:
        family, inferred = config_from_hf(model_dir)
        cfg = cfg or inferred
    if family not in _RECIPES:
        raise ValueError(f"no ingestion recipe for {family!r}")
    src = _TensorSource(model_dir)
    recipes = _RECIPES[family](cfg)

    leaf_shardings = {}
    if shardings is not None:
        import jax

        flat, _ = jax.tree_util.tree_flatten_with_path(shardings)
        for path, sh in flat:
            key = tuple(getattr(p, "key", getattr(p, "name", None)) for p in path)
            leaf_shardings[key] = sh

    params: dict = {}
    for path, build in recipes.items():
        arr = np.asarray(build(src), dtype=dtype)
        if shardings is not None:
            import jax

            arr = jax.device_put(arr, leaf_shardings[path])
        _set_path(params, path, arr)
    return params, cfg


def from_pretrained(model_dir: str, dtype=np.float32, **build_kwargs):
    """One-call ingestion: HF dir -> (model builder, config, params).

    ``builder`` is the ``lambda ctx: build(cfg, ctx=ctx)`` shape every engine
    in this repo accepts; pass ``params`` to the engine (training engines
    re-place them under their plan; inference engines cast to compute dtype).
    """
    family, cfg = config_from_hf(model_dir)
    import importlib

    mod = importlib.import_module(f"deepspeed_tpu.models.{family}")
    params, _ = load_hf_params(model_dir, family=family, cfg=cfg, dtype=dtype)

    def builder(ctx=None):
        return mod.build(cfg, ctx=ctx, **build_kwargs)

    return builder, cfg, params
