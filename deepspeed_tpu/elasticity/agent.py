"""Elastic agent: worker supervision + scale-adaptive restart.

Role parity with the reference ``elasticity/elastic_agent.py:32 DSElasticAgent``
(extends torch-elastic's LocalElasticAgent: starts workers with DS env,
monitor loop polls worker state every ~30s, triggers restart/scale events
``:127``) and the checkpoint-based recovery model (SURVEY §5.3: no in-flight
replication — restart → ``load_checkpoint`` at a possibly different world
size, with the elastic batch math keeping training semantics identical).

TPU-native shape: workers are the per-host training processes the launcher
spawns (``launcher/runner.py``); the agent supervises them, and on worker
death (hardware eviction, preemption, crash) it recomputes an admissible
world size from the surviving hosts via ``elasticity.compute_elastic_config``
and relaunches — resuming from the newest checkpoint (UCP resharding makes
the world-size change free). A ``PreemptionHandler`` gives training loops the
SIGTERM-checkpoint behavior megascale preemption notices need, and lets the
serving tier (``deepspeed_tpu/serving``) register drain callbacks on the same
signal path (SIGTERM → stop admission → finish inflight → exit).
"""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Sequence

from deepspeed_tpu.elasticity.elasticity import get_compatible_world_sizes
from deepspeed_tpu.utils.logging import log_dist


def beacon_ages(heartbeat_dir: str | None,
                now: float | None = None) -> dict[int, float]:
    """Per-rank heartbeat beacon ages (seconds since the freshest write),
    taking the WORST of the rank beacon and any per-stage beacons
    (``heartbeat_{rank}_s{t}.json``) — the same staleness verdict the
    agent's kill decision uses. Ranks with no beacon yet are absent."""
    ages: dict[int, float] = {}
    if not heartbeat_dir or not os.path.isdir(heartbeat_dir):
        return ages
    wall = time.time() if now is None else float(now)
    for path in glob.glob(os.path.join(heartbeat_dir, "heartbeat_*.json")):
        stem = os.path.basename(path)[len("heartbeat_"):-len(".json")]
        try:
            rank = int(stem.split("_s")[0])
        except ValueError:
            continue
        try:
            age = wall - os.path.getmtime(path)
        except OSError:
            continue  # beacon swept between glob and stat
        if rank not in ages or age > ages[rank]:
            ages[rank] = age
    return ages


def publish_heartbeat_ages(heartbeat_dir: str | None,
                           telemetry=None) -> dict[int, float]:
    """Surface beacon ages as ``worker_heartbeat_age_seconds{rank=}``
    gauges (no-op while telemetry is disabled) and return them. The fleet
    aggregator's ``/debug/fleet`` rollup reads these series."""
    if telemetry is None:
        from deepspeed_tpu.telemetry import get_telemetry

        telemetry = get_telemetry()
    ages = beacon_ages(heartbeat_dir)
    if telemetry.enabled and ages:
        g = telemetry.gauge(
            "worker_heartbeat_age_seconds",
            "seconds since each worker rank's freshest heartbeat beacon "
            "(worst of the rank and per-stage beacons)")
        for rank, age in ages.items():
            g.set(age, rank=rank)
    return ages


@dataclass
class WorkerSpec:
    """One supervised worker process."""

    cmd: Sequence[str]
    env: dict | None = None
    proc: subprocess.Popen | None = None
    restarts: int = 0


@dataclass
class ElasticAgent:
    """Supervise worker processes; restart at an admissible world size.

    ``target_batch_size`` + ``micro_batch_candidates`` define the admissible
    world sizes (reference elasticity v0.1/0.2 math); the agent only ever
    runs a worker count from that set, so every restart preserves the batch
    triangle exactly.
    """

    target_batch_size: int
    micro_batch_candidates: Sequence[int]
    make_worker: Callable[[int, int], WorkerSpec]  # (rank, world) -> spec
    max_world_size: int
    min_world_size: int = 1
    poll_interval: float = 1.0
    max_restarts: int = 3
    on_scale_change: Callable[[int], None] | None = None
    workers: list = field(default_factory=list)
    # liveness (runtime/sentinel.py heartbeat protocol): workers write
    # heartbeat_{rank}.json into heartbeat_dir at step boundaries; a live
    # process whose beacon goes stale past heartbeat_timeout is wedged —
    # SIGKILL it and restart the world. 0 disables the check. The grace
    # window covers startup (jit compile happens before the first beat).
    heartbeat_dir: str | None = None
    heartbeat_timeout: float = 0.0
    heartbeat_grace: float = 30.0

    def admissible_world_sizes(self) -> list[int]:
        sizes = get_compatible_world_sizes(
            self.target_batch_size, list(self.micro_batch_candidates),
            self.min_world_size, self.max_world_size,
        )
        if not sizes:
            raise ValueError(
                f"no admissible world size in [{self.min_world_size}, "
                f"{self.max_world_size}] for batch {self.target_batch_size} "
                f"and micro-batches {list(self.micro_batch_candidates)}"
            )
        return sizes

    def _sweep_stale_state(self) -> None:
        """Remove sentinel state a killed worker left behind. Heartbeat
        beacons are per-incarnation liveness — a stale one from a SIGKILL'd
        predecessor would either mask a wedge or trigger an instant false
        kill, so they are always removed. The quarantine list is healing
        MEMORY and is kept — unless it is torn/unparseable (a worker died
        mid-write before the atomic-rename writer existed), in which case a
        fresh start beats honoring garbage."""
        d = self.heartbeat_dir
        if not d or not os.path.isdir(d):
            return
        for name in os.listdir(d):
            path = os.path.join(d, name)
            if name.startswith("heartbeat_"):
                try:
                    os.remove(path)
                except OSError:
                    pass
            elif name == "quarantine.json":
                try:
                    with open(path) as f:
                        if not isinstance(json.load(f), list):
                            raise ValueError("not a list")
                except (OSError, ValueError):
                    try:
                        os.remove(path)
                        log_dist("elastic agent: removed torn quarantine "
                                 "file", ranks=[0])
                    except OSError:
                        pass

    def _launch(self, world: int) -> None:
        self._sweep_stale_state()
        self.workers = []
        self._launch_time = time.monotonic()
        for rank in range(world):
            spec = self.make_worker(rank, world)
            spec.proc = subprocess.Popen(
                list(spec.cmd), env=spec.env,
                stdout=subprocess.DEVNULL if rank else None,
                stderr=subprocess.DEVNULL if rank else None,
            )
            self.workers.append(spec)
        log_dist(f"elastic agent: launched {world} workers", ranks=[0])

    def _stale_workers(self) -> list[int]:
        """Ranks whose process is alive but whose heartbeat beacon is older
        than the deadline (wedged-but-alive: a hung collective, a stuck
        device program — the one failure mode ``proc.poll()`` cannot see)."""
        if not self.heartbeat_dir or self.heartbeat_timeout <= 0:
            return []
        now = time.monotonic()
        wall = time.time()
        stale = []
        for rank, w in enumerate(self.workers):
            if w.proc.poll() is not None:
                continue
            # the rank beacon plus any per-pipeline-stage beacons
            # (heartbeat_{rank}_s{t}.json, one per MPMD stage thread): the
            # staleness verdict is the WORST of them, so a single wedged
            # stage flags the worker even while the step-boundary rank
            # beacon keeps beating
            paths = [os.path.join(self.heartbeat_dir,
                                  f"heartbeat_{rank}.json")]
            paths.extend(sorted(glob.glob(os.path.join(
                self.heartbeat_dir, f"heartbeat_{rank}_s*.json"))))
            ages = []
            for path in paths:
                try:
                    ages.append(wall - os.path.getmtime(path))
                except OSError:
                    # no beacon yet: only the grace window applies
                    ages.append(None)
            age = (None if any(a is None for a in ages)
                   else max(ages))
            in_grace = now - self._launch_time < max(
                self.heartbeat_grace, self.heartbeat_timeout)
            if in_grace:
                continue
            if age is None or age > self.heartbeat_timeout:
                stale.append(rank)
        return stale

    def heartbeat_ages(self) -> dict[int, float]:
        """Current per-rank beacon ages, published as
        ``worker_heartbeat_age_seconds{rank=}`` gauges (the fleet rollup's
        liveness input). Empty when no heartbeat_dir is configured."""
        return publish_heartbeat_ages(self.heartbeat_dir)

    def run(self) -> int:
        """Supervision loop (reference ``_invoke_run:127``): launch at the
        largest admissible world size; on any worker death — a nonzero exit,
        a SIGKILL'd preemption (negative returncode), a crashed host — stop
        the rest and relaunch at the largest size admissible with one fewer
        worker slot. The relaunched workers resume from the newest verified
        checkpoint (``load_checkpoint`` walks the fallback ladder, so even a
        worker killed mid-checkpoint-commit restarts clean). Returns 0 when
        all workers exit cleanly. ``self.restarts`` / ``self.world_size``
        record what supervision did, for harness assertions."""
        world = self.admissible_world_sizes()[-1]
        self.restarts = 0
        self.world_size = world
        self.heartbeat_kills = 0
        self._launch(world)
        while True:
            time.sleep(self.poll_interval)
            if self.heartbeat_dir:
                self.heartbeat_ages()
            for rank in self._stale_workers():
                # wedged-but-alive: poll() sees nothing wrong, the beacon
                # does. SIGKILL (a stuck device program ignores SIGTERM)
                # and let the death branch below run the normal restart.
                w = self.workers[rank]
                log_dist(
                    f"elastic agent: worker {rank} heartbeat stale "
                    f"(> {self.heartbeat_timeout:.0f}s); killing", ranks=[0])
                self.heartbeat_kills += 1
                try:
                    w.proc.kill()
                    w.proc.wait(timeout=30)
                except (OSError, subprocess.TimeoutExpired):
                    pass
            codes = [w.proc.poll() for w in self.workers]
            if all(c == 0 for c in codes):
                log_dist("elastic agent: all workers finished", ranks=[0])
                return 0
            if any(c not in (None, 0) for c in codes):
                dead = [i for i, c in enumerate(codes) if c not in (None, 0)]
                log_dist(
                    f"elastic agent: workers {dead} died "
                    f"(codes {[codes[i] for i in dead]})", ranks=[0],
                )
                for w in self.workers:
                    if w.proc.poll() is None:
                        w.proc.terminate()
                for w in self.workers:
                    try:
                        w.proc.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        w.proc.kill()
                self.restarts += 1
                from deepspeed_tpu.telemetry import get_telemetry

                tel = get_telemetry()
                if tel.enabled:
                    tel.counter(
                        "elastic_restarts_total",
                        "world restarts the elastic agent performed").inc()
                    tel.gauge(
                        "elastic_world_size",
                        "worker count of the supervised world"
                    ).set(self.world_size)
                if self.restarts > self.max_restarts:
                    log_dist("elastic agent: restart budget exhausted", ranks=[0])
                    return 1
                # scale down: CAPACITY shrinks by the dead workers (spare
                # slots above the launched world size remain usable)
                self.max_world_size = max(
                    self.min_world_size, self.max_world_size - len(dead))
                try:
                    world = self.admissible_world_sizes()[-1]
                except ValueError:
                    log_dist("elastic agent: no admissible world size left",
                             ranks=[0])
                    return 1
                if self.on_scale_change is not None:
                    self.on_scale_change(world)
                self.world_size = world
                self._launch(world)


class PreemptionHandler:
    """SIGTERM-triggered graceful-stop hook (megascale preemption notice,
    SURVEY §5.3) shared by the training and serving tiers.

    One signal path, two registration styles:

    - **training** (legacy contract): ``PreemptionHandler(engine, save_dir)``
      registers a ``checkpoint`` callback; poll ``should_stop`` at step
      boundaries and ``checkpoint_if_needed()`` writes at most one
      checkpoint on the way out.
    - **serving / anything else**: ``register(name, fn, immediate=...)``
      adds arbitrary stop hooks. ``immediate=True`` callbacks run inside the
      signal handler itself and must be non-blocking (e.g. "stop admitting
      requests" — flag flips only); the rest run via ``drain()`` at a safe
      boundary. Every callback runs at most once per preemption.

    ``stop_event`` is a ``threading.Event`` set on the signal, so background
    loops (the serving engine loop, a checkpoint writer) can wait on it
    instead of polling ``should_stop``.
    """

    def __init__(self, engine=None, save_dir: str | None = None,
                 signals=(signal.SIGTERM,)):
        if engine is not None and save_dir is None:
            raise ValueError("save_dir is required when an engine is given")
        self.engine = engine
        self.save_dir = save_dir
        self.should_stop = False
        self.stop_event = threading.Event()
        self._callbacks: list[tuple[str, Callable[[], object], bool]] = []
        self._ran: dict[str, object] = {}
        self._prev = {}
        if engine is not None:
            self.register("checkpoint", self._checkpoint)
        for sig in signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)

    def register(self, name: str, fn: Callable[[], object],
                 immediate: bool = False) -> Callable[[], object]:
        """Add a stop hook. ``immediate`` hooks fire inside the signal
        handler (keep them to flag flips / Event sets); deferred hooks run
        from ``drain()``/``checkpoint_if_needed()`` at a step boundary."""
        if any(n == name for n, _, _ in self._callbacks):
            raise ValueError(f"preemption callback {name!r} already registered")
        self._callbacks.append((name, fn, immediate))
        return fn

    def _checkpoint(self):
        """Write the one preempt checkpoint and JOIN any async flush before
        returning: the process is about to exit, and a writer-thread error
        surfaced here is the last chance to see it (a silently dropped flush
        error would leave ``latest`` pointing at the previous checkpoint
        while the operator believes the preempt save landed)."""
        path = self.engine.save_checkpoint(self.save_dir, tag="preempt")
        join = getattr(self.engine, "_join_ckpt_writer", None)
        if join is not None:
            join()  # raises if the async flush failed; do not swallow
        return path

    def _on_signal(self, signum, frame):
        del frame
        log_dist(f"preemption notice (signal {signum}): stop + drain",
                 ranks=[0])
        self.should_stop = True
        self.stop_event.set()
        for name, fn, immediate in self._callbacks:
            if immediate and name not in self._ran:
                self._ran[name] = None
                try:
                    self._ran[name] = fn()
                except Exception as e:  # a failing hook must not mask the signal
                    log_dist(f"preemption hook {name!r} failed: {e!r}",
                             ranks=[0])

    def _run_once(self, name: str, fn: Callable[[], object]):
        if name not in self._ran:
            self._ran[name] = fn()
        return self._ran[name]

    def drain(self) -> dict:
        """Run every registered callback not already fired, each at most
        once; call at a safe boundary after ``should_stop``. Returns
        ``{name: result}`` for everything that has run."""
        if not self.should_stop:
            return {}
        for name, fn, _ in self._callbacks:
            self._run_once(name, fn)
        return dict(self._ran)

    def checkpoint_if_needed(self) -> str | None:
        """Legacy training contract: at most one preempt checkpoint, written
        at the step boundary once ``should_stop`` is set."""
        if not self.should_stop or self.engine is None:
            return None
        if "checkpoint" in self._ran:
            return None
        return self._run_once("checkpoint", self._checkpoint)

    def restore(self):
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)


def main(argv: list[str] | None = None) -> int:  # pragma: no cover - CLI
    """Tiny CLI: ``python -m deepspeed_tpu.elasticity.agent -- <worker cmd>``
    supervises N copies of the worker command with RANK/WORLD_SIZE env."""
    import argparse
    import os

    p = argparse.ArgumentParser()
    p.add_argument("--target-batch-size", type=int, required=True)
    p.add_argument("--micro-batches", type=int, nargs="+", required=True)
    p.add_argument("--max-world-size", type=int, required=True)
    p.add_argument("--min-world-size", type=int, default=1)
    p.add_argument("cmd", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    cmd = [c for c in args.cmd if c != "--"]

    def make(rank, world):
        env = dict(os.environ, RANK=str(rank), WORLD_SIZE=str(world))
        return WorkerSpec(cmd=cmd, env=env)

    agent = ElasticAgent(
        target_batch_size=args.target_batch_size,
        micro_batch_candidates=args.micro_batches,
        make_worker=make,
        max_world_size=args.max_world_size,
        min_world_size=args.min_world_size,
    )
    return agent.run()


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
