"""Elastic training: batch-size-compatible world-size math + preemption-aware
restart policy.

Role parity with the reference ``elasticity/elasticity.py`` (v0.1 ``:83`` /
v0.2 ``:126``: given a target effective batch size and candidate micro-batch
sizes, precompute the set of admissible accelerator counts so a job can restart
at a different scale with identical math; ``compute_elastic_config:233``).
Recovery itself is checkpoint-based: the universal-layout checkpoints
(``checkpoint/``) reshape to any admissible world size at load.
"""

from __future__ import annotations

from dataclasses import dataclass

from deepspeed_tpu.config.base import ConfigError


def get_compatible_world_sizes(
    batch_size: int, micro_batches: list[int], min_world: int, max_world: int
) -> list[int]:
    """World sizes w for which some micro-batch m gives batch = m * gas * w
    exactly (reference ``_get_compatible_gpus_v01``)."""
    valid = set()
    for w in range(min_world, max_world + 1):
        for m in micro_batches:
            if batch_size % (m * w) == 0:
                valid.add(w)
                break
    return sorted(valid)


@dataclass
class ElasticConfig:
    final_batch_size: int
    valid_world_sizes: list[int]
    micro_batch_per_world: dict[int, int]


def compute_elastic_config(
    target_batch_size: int,
    micro_batches: list[int],
    max_world_size: int,
    min_world_size: int = 1,
    prefer_larger_batch: bool = True,
) -> ElasticConfig:
    """Pick an effective batch near the target that maximizes admissible world
    sizes (reference ``compute_elastic_config:233``, v0.1 semantics)."""
    if not micro_batches:
        raise ConfigError("elasticity: micro_batches must be non-empty")
    candidates = sorted(
        range(max(1, target_batch_size // 2), target_batch_size * 2 + 1),
        key=lambda b: (-len(get_compatible_world_sizes(b, micro_batches, min_world_size, max_world_size)),
                       abs(b - target_batch_size),
                       -b if prefer_larger_batch else b),
    )
    best = candidates[0]
    valid = get_compatible_world_sizes(best, micro_batches, min_world_size, max_world_size)
    if not valid:
        raise ConfigError(
            f"elasticity: no world size in [{min_world_size}, {max_world_size}] "
            f"is compatible with batch {target_batch_size} and micros {micro_batches}"
        )
    micro_per_world = {}
    for w in valid:
        for m in sorted(micro_batches, reverse=True):
            if best % (m * w) == 0:
                micro_per_world[w] = m
                break
    return ElasticConfig(final_batch_size=best, valid_world_sizes=valid,
                         micro_batch_per_world=micro_per_world)


def ensure_immutable_elastic_config(runtime_config: dict, frozen: dict) -> None:
    """Elastic params may not change across restarts (reference
    ``ensure_immutable_elastic_config:208``)."""
    for key, expected in frozen.items():
        actual = runtime_config.get(key)
        if actual != expected:
            raise ConfigError(
                f"elastic config field {key!r} changed across restart: "
                f"{expected!r} -> {actual!r}"
            )
