"""Deterministic fault injection for the serving, inference, and training
checkpoint paths.

A process-local :class:`FaultInjector` singleton exposes **named injection
points** at the real seams of the stack — device dispatch, H2D upload,
token readback, block allocation, the engine-loop iteration, the
router→replica submit edge, and the training checkpoint pipeline
(collect / flush / commit / latest-update / load). Production code calls
``fire(point)`` at each seam; with no faults armed this is a single
attribute check and the hot paths pay nothing. Tests, ``bench.py --mode
chaos`` / ``--mode train-chaos``, and CI arm a *schedule* of
:class:`FaultSpec` entries, each of which fires deterministically by hit
count (``after`` / ``every`` / ``times``) or per request (``request_id``),
so a failing run replays exactly.

Fault kinds:

- ``raise`` — raise :class:`FaultError` (transient) or
  :class:`FatalFaultError` (``fatal=True``) at the seam.
- ``hang`` — sleep ``delay_s`` then raise ``TimeoutError`` (models a wedged
  transfer surfacing as a deadline).
- ``latency`` — sleep ``delay_s`` and continue (slow path, no error).
- ``truncate`` — cut the file the seam passed via ``fire(path=)`` to half
  its size and continue (models a torn write the writer never noticed).
- ``corrupt-bytes`` — flip one seeded byte of that file and continue
  (models silent on-disk corruption; checksum verification must catch it).
- ``kill`` — ``SIGKILL`` the calling process at the seam (the train-chaos
  harness's mid-flush / mid-commit kills; nothing downstream of the seam
  runs, exactly like a preemption landing there).
- ``oom`` — raise a ``RESOURCE_EXHAUSTED``-worded :class:`FaultError`
  (models the XLA allocator failing a device allocation; the memory
  ledger's OOM forensics and the watchdog's degradation hint key on the
  status text, exactly as they would for a real PJRT OOM).
- ``wedge`` — sleep ``delay_s`` and continue (models a stuck device
  program / transfer that never surfaces an error: the training loop's
  heartbeat goes stale and the dispatch watchdog's deadline fires —
  unlike ``hang`` this kind raises nothing itself).
- ``nan-grads`` / ``loss-spike`` / ``poison-batch`` — **directive** kinds:
  ``fire()`` returns the kind string instead of raising, and the training
  seam perturbs the step accordingly (the engine folds a loss multiplier
  into the batch: NaN for ``nan-grads``, a large finite factor for
  ``loss-spike``/``poison-batch``). ``poison-batch`` is typically armed
  with ``request_id`` = a batch fingerprint at the ``data.batch`` seam so
  the poison is a property of the *data* — once the sentinel quarantines
  that fingerprint the fault can never fire again, exactly like a bad
  shard dropped from the stream.

``classify_transient`` is the shared error taxonomy used by the dispatch
watchdog (inference/ragged.py) and the router breaker: injected transient
faults, timeouts, connection drops, and XLA "try again" statuses retry;
everything else is fatal and escalates. See docs/FAULT_TOLERANCE.md.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from dataclasses import dataclass, field

from deepspeed_tpu.telemetry import get_telemetry

# Named injection points (the real seams).
POINT_DISPATCH = "engine.dispatch"   # jitted step/chunk/fused program launch
POINT_H2D = "engine.h2d"             # host→device staging upload
POINT_READBACK = "engine.readback"   # device→host token/logits readback
POINT_ALLOC = "engine.alloc"         # KV block allocation
POINT_LOOP = "loop.step"             # engine-loop thread, once per busy tick
POINT_SUBMIT = "router.submit"       # router→replica submit edge

# Training checkpoint seams (runtime/engine.py save/load + checkpoint/engine.py
# commit protocol). The file-mutating kinds (truncate / corrupt-bytes) act on
# the path each seam passes via ``fire(path=)``.
POINT_CKPT_COLLECT = "ckpt.collect"  # device→host shard snapshot
POINT_CKPT_FLUSH = "ckpt.flush"      # fragment/index writes into staging
POINT_CKPT_COMMIT = "ckpt.commit"    # manifest sealed, before dir promote
POINT_CKPT_LATEST = "ckpt.latest"    # latest-pointer update
POINT_CKPT_LOAD = "ckpt.load"        # load/verify entry

# Training-step seams (runtime/engine.py train_batch + runtime/sentinel.py):
# the divergence/liveness faults the self-healing ladder must survive.
POINT_TRAIN_DISPATCH = "train.dispatch"  # fused train step launch/fence
POINT_TRAIN_GRADS = "train.grads"        # grad computation (transient anomaly)
POINT_DATA_BATCH = "data.batch"          # batch admission (content-keyed)
POINT_PIPE_STAGE = "pipe.stage"          # MPMD stage thread, per instruction

POINTS = (
    POINT_DISPATCH,
    POINT_H2D,
    POINT_READBACK,
    POINT_ALLOC,
    POINT_LOOP,
    POINT_SUBMIT,
    POINT_CKPT_COLLECT,
    POINT_CKPT_FLUSH,
    POINT_CKPT_COMMIT,
    POINT_CKPT_LATEST,
    POINT_CKPT_LOAD,
    POINT_TRAIN_DISPATCH,
    POINT_TRAIN_GRADS,
    POINT_DATA_BATCH,
    POINT_PIPE_STAGE,
)

# Kinds whose firing returns the kind string to the seam (which applies the
# perturbation itself) instead of raising/sleeping here.
DIRECTIVE_KINDS = ("nan-grads", "loss-spike", "poison-batch")


class FaultError(RuntimeError):
    """An injected failure. ``transient`` mirrors the real-world class the
    injection models (a retryable transfer/dispatch error)."""

    transient = True

    def __init__(self, message: str, point: str = ""):
        super().__init__(message)
        self.point = point


class FatalFaultError(FaultError):
    """An injected non-retryable failure (poisoned state, bad program)."""

    transient = False


@dataclass
class FaultSpec:
    """One armed fault. Firing is counted per spec: the spec matches the
    ``hits``-th eligible call when ``hits > after``, ``(hits - after - 1)``
    is a multiple of ``every``, and fewer than ``times`` firings have
    happened (``times=0`` = unlimited)."""

    point: str
    kind: str = "raise"              # raise | hang | latency
    after: int = 0                   # skip this many eligible hits first
    times: int = 1                   # max firings (0 = unlimited)
    every: int = 1                   # then fire every N-th eligible hit
    request_id: str | None = None    # only hits carrying this request id
    delay_s: float = 0.05            # hang/latency sleep
    fatal: bool = False              # raise FatalFaultError instead
    probability: float = 1.0         # eligible-hit firing probability
    message: str = ""
    hits: int = field(default=0, init=False)
    fired: int = field(default=0, init=False)

    def __post_init__(self):
        if self.point not in POINTS:
            raise ValueError(
                f"unknown fault point {self.point!r} (known: {POINTS})")
        if self.kind not in ("raise", "hang", "latency", "truncate",
                             "corrupt-bytes", "kill", "oom", "wedge",
                             *DIRECTIVE_KINDS):
            raise ValueError(f"unknown fault kind {self.kind!r}")


class FaultInjector:
    """Deterministic, seedable fault scheduler (module singleton below).

    Off by default: ``fire()`` returns immediately unless ``enabled``.
    Thread-safe — the engine loop, HTTP handler threads, and the router
    all fire through the one instance.
    """

    def __init__(self):
        self.enabled = False
        self._specs: list[FaultSpec] = []
        self._rng = random.Random(0)
        self._fired: dict[str, int] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- arming
    def configure(self, specs, seed: int = 0) -> "FaultInjector":
        """Arm a schedule: a list of :class:`FaultSpec` or plain dicts
        (JSON-loadable, as used by ``bench.py --mode chaos``)."""
        with self._lock:
            self._specs = [
                s if isinstance(s, FaultSpec) else FaultSpec(**s)
                for s in (specs or [])
            ]
            self._rng = random.Random(seed)
            self._fired = {}
            self.enabled = bool(self._specs)
        return self

    def arm(self, point: str, **kw) -> FaultSpec:
        """Arm one additional fault at ``point``."""
        spec = FaultSpec(point=point, **kw)
        with self._lock:
            self._specs.append(spec)
            self.enabled = True
        return spec

    def reset(self) -> None:
        """Disarm everything (test isolation; conftest calls this)."""
        with self._lock:
            self._specs = []
            self._fired = {}
            self._rng = random.Random(0)
            self.enabled = False

    # ------------------------------------------------------------- firing
    def fire(self, point: str, request_id: str | None = None,
             path: str | None = None) -> str | None:
        """Called by production code at the named seam. No-op unless a
        matching armed spec elects this hit. ``path`` names the file the
        seam just touched, for the file-mutating kinds. Directive kinds
        (``nan-grads`` / ``loss-spike`` / ``poison-batch``) return the kind
        string so the seam applies the perturbation; every other kind
        returns ``None`` (callers that ignore the return are unaffected)."""
        if not self.enabled:
            return None
        spec = None
        with self._lock:
            for s in self._specs:
                if s.point != point:
                    continue
                if s.request_id is not None and s.request_id != request_id:
                    continue
                if s.kind in ("truncate", "corrupt-bytes") and path is None:
                    continue  # file kinds only elect hits that carry a path
                s.hits += 1
                if s.times and s.fired >= s.times:
                    continue
                n = s.hits - s.after
                if n <= 0 or (n - 1) % max(1, s.every):
                    continue
                if s.probability < 1.0 and self._rng.random() >= s.probability:
                    continue
                s.fired += 1
                self._fired[point] = self._fired.get(point, 0) + 1
                spec = s
                break
        if spec is None:
            return None
        tel = get_telemetry()
        if tel.enabled:
            tel.counter(
                "fault_injected_total",
                "injected faults fired, by point").inc(point=point,
                                                       kind=spec.kind)
        msg = spec.message or (
            f"injected {spec.kind} fault at {point}"
            f" (hit {spec.hits}, firing {spec.fired})")
        if spec.kind in DIRECTIVE_KINDS:
            return spec.kind
        if spec.kind == "latency":
            time.sleep(spec.delay_s)
            return None
        if spec.kind == "wedge":
            # a stuck dispatch: the seam simply stops making progress — no
            # error to catch, only a stale heartbeat / watchdog deadline
            time.sleep(spec.delay_s)
            return None
        if spec.kind == "kill":
            # a preemption landing exactly at this seam: no cleanup, no
            # flush, no atexit — the process is simply gone
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(60)  # pragma: no cover - death is asynchronous
            return
        if spec.kind == "truncate":
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(size // 2)
            return
        if spec.kind == "corrupt-bytes":
            size = os.path.getsize(path)
            if size:
                with self._lock:
                    off = self._rng.randrange(size)
                with open(path, "r+b") as f:
                    f.seek(off)
                    orig = f.read(1)
                    f.seek(off)
                    f.write(bytes([(orig[0] ^ 0xFF) if orig else 0xFF]))
            return
        if spec.kind == "hang":
            time.sleep(spec.delay_s)
            raise TimeoutError(msg)
        if spec.kind == "oom":
            # worded like a real PJRT allocation failure so every layer
            # (is_resource_exhausted, OOM forensics, degradation hint)
            # treats it exactly like one
            raise FaultError(
                spec.message or (
                    f"RESOURCE_EXHAUSTED: injected out-of-memory at {point} "
                    f"(hit {spec.hits}, firing {spec.fired})"), point)
        if spec.fatal:
            raise FatalFaultError(msg, point)
        raise FaultError(msg, point)

    # ------------------------------------------------------------- introspect
    def counts(self) -> dict:
        """``{point: firings}`` so far (bench/CI assertions)."""
        with self._lock:
            return dict(self._fired)

    def total_fired(self) -> int:
        with self._lock:
            return sum(self._fired.values())


_INJECTOR = FaultInjector()


def get_fault_injector() -> FaultInjector:
    """The process-local injector shared by every seam."""
    return _INJECTOR


# Substrings in real accelerator/runtime error text that indicate a
# retryable condition (XLA/PJRT status codes surface in the message).
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "CANCELLED",
    "TRANSFER",
    "SOCKET CLOSED",
    "CONNECTION RESET",
    "TEMPORARILY",
)


def classify_transient(exc: BaseException) -> bool:
    """Shared transient-vs-fatal taxonomy for the dispatch watchdog and the
    replica breaker. Transient errors are retried with backoff; fatal ones
    escalate (degradation / crash containment / quarantine)."""
    if isinstance(exc, FaultError):
        return exc.transient
    if isinstance(exc, (TimeoutError, ConnectionError, BrokenPipeError)):
        return True
    if isinstance(exc, OSError):
        return True
    msg = str(exc).upper()
    return any(marker in msg for marker in _TRANSIENT_MARKERS)
