"""Production serving tier over the ragged inference engine.

The reference stack splits serving across two repos: DeepSpeed's inference
v2 ragged engine (the scheduler + kernels) and DeepSpeed-MII on top (the
frontend, replica routing, and deployment surface). This package is our
MII-role tier, stdlib-only:

- :mod:`protocol` — request/response dataclasses, validation, SSE framing
- :mod:`engine_loop` — per-replica background step-loop driver
  (``put()``/``step()`` pump, per-request token streams, graceful drain)
- :mod:`router` — least-outstanding-tokens placement + KV-aware admission
  control + bounded queues (429 backpressure)
- :mod:`frontend` — ``http.server`` HTTP surface: ``POST /v1/completions``
  (JSON + SSE), ``GET /healthz``, ``GET /metrics``
- :mod:`faults` — deterministic fault-injection harness (named injection
  points at the real seams; drives the dispatch watchdog, crash
  containment, and replica-failover machinery — docs/FAULT_TOLERANCE.md)
- :mod:`cluster` — disaggregated prefill/decode serving: role-tagged
  replicas, KV-handoff transfer, a cluster-wide prefix index, and an
  SLO-burn-driven decode-pool autoscaler

See docs/SERVING.md for the architecture walkthrough.
"""

from deepspeed_tpu.serving.cluster import (  # noqa: F401
    ClusterConfig,
    ClusterPrefixIndex,
    DecodeAutoscaler,
    InMemoryTransferChannel,
    ServingCluster,
    build_cluster_server,
    transfer_beats_prefill,
)
from deepspeed_tpu.serving.engine_loop import (  # noqa: F401
    EngineLoop,
    ReplicaDraining,
    ReplicaStats,
    StreamError,
    TokenStream,
)
from deepspeed_tpu.serving.frontend import (  # noqa: F401
    ServingFrontend,
    build_server,
)
from deepspeed_tpu.serving.protocol import (  # noqa: F401
    FINISH_CANCELLED,
    FINISH_LENGTH,
    FINISH_STOP,
    FINISH_TIMEOUT,
    CompletionRequest,
    CompletionResponse,
    ProtocolError,
    decode_sse,
    encode_sse,
    sse_done,
)
from deepspeed_tpu.serving.faults import (  # noqa: F401
    POINT_ALLOC,
    POINT_CKPT_COLLECT,
    POINT_CKPT_COMMIT,
    POINT_CKPT_FLUSH,
    POINT_CKPT_LATEST,
    POINT_CKPT_LOAD,
    POINT_DISPATCH,
    POINT_H2D,
    POINT_LOOP,
    POINT_READBACK,
    POINT_SUBMIT,
    FatalFaultError,
    FaultError,
    FaultInjector,
    FaultSpec,
    classify_transient,
    get_fault_injector,
)
from deepspeed_tpu.serving.router import (  # noqa: F401
    DeadlineExceeded,
    Draining,
    Overloaded,
    ReplicaRouter,
    RouterConfig,
    plan_placement,
)
