"""HTTP frontend over the replica router (stdlib ``http.server`` only).

Endpoints:

- ``POST /v1/completions`` — OpenAI-completions-shaped JSON body (token-id
  prompts; see ``protocol.py``). Non-streaming returns one JSON
  ``CompletionResponse``; ``"stream": true`` returns ``text/event-stream``
  with one frame per token, a final frame carrying the full response, then
  the ``[DONE]`` terminator. Backpressure surfaces as 429 + ``Retry-After``
  (admission control) and 503 (draining); client disconnect mid-stream
  cancels the request so its KV blocks free on the next engine step.
- ``GET /healthz`` — ``{"status": ready|degraded|overloaded|draining}``;
  200 when servable, 503 while draining (load-balancer semantics: stop
  sending). With an SLO monitor configured the body embeds per-objective
  burn-rate stats, and a sustained burn flips a ready replica to
  ``degraded`` (still 200 — it can serve, but tail latency is out of
  budget; see docs/SERVING.md).
- ``GET /metrics`` — Prometheus text exposition straight from the PR-1
  telemetry registry (serving + SLO gauges refreshed at scrape time).
  Serving a scrape endpoint here does not flip telemetry on: with
  telemetry disabled the page renders whatever the registry holds
  (typically nothing) and the serving hot path still emits zero metrics.
- ``GET /debug/trace`` — the request-trace span ring as Chrome
  trace-event JSON (load in Perfetto); ``?trace_id=<32hex>`` filters to
  one trace.
- ``GET /debug/memory`` — the memory ledger's live picture: per-owner
  byte breakdown, a fresh ``jax.live_arrays()`` census (attributed vs
  unattributed bytes), per-program temp footprints, device allocator
  stats, and any OOM crash reports written this process. ``{"enabled":
  false}`` when no ledger is configured.
- ``GET /metrics/fleet`` — federated Prometheus view merged across every
  worker's fleet snapshot (counters summed, gauges per-worker-labelled,
  histogram buckets added; see ``telemetry/fleet.py``). 404 until a fleet
  dir is configured.
- ``GET /debug/fleet`` — the cluster rollup JSON: per-worker liveness,
  SLO burn, census drift, circuit-breaker/KV-tier stats, heartbeat ages,
  and the ``fleet_health`` verdict. A non-ok verdict also degrades
  ``/healthz`` (fleet-wide burn visible from any one worker's probe).
- ``GET /debug/tenants`` — the cost meter's per-tenant ledger: cumulative
  request costs, top-K tenants by KV block-seconds, rolling rates and the
  label-cardinality accounting (``telemetry/costmeter.py``). ``{"enabled":
  false}`` until ``telemetry.configure(costmeter={"enabled": True})``.

Tracing: ``POST /v1/completions`` honors an incoming W3C ``traceparent``
header (or head-samples a fresh trace when the tracer is enabled); the
trace id is echoed in a ``traceparent`` response header, the response
body, and every SSE token frame, and the context threads through router →
engine loop → ragged engine so the exported timeline decomposes the
request into queue/admission/dispatch/readback spans.

``ThreadingHTTPServer`` gives a thread per connection, which is what SSE
needs: a streaming response parks its thread on the request's TokenStream
while the single engine-loop thread keeps stepping.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

from deepspeed_tpu.serving.engine_loop import StreamError
from deepspeed_tpu.serving.protocol import (
    CompletionRequest,
    CompletionResponse,
    ProtocolError,
    encode_sse,
    sse_done,
)
from deepspeed_tpu.serving.router import (
    DeadlineExceeded,
    Draining,
    Overloaded,
    ReplicaRouter,
)
from deepspeed_tpu.telemetry import get_telemetry
from deepspeed_tpu.telemetry.exporters import PrometheusExporter
from deepspeed_tpu.telemetry.tracing import format_traceparent
from deepspeed_tpu.utils.logging import log_dist


class ServingFrontend:
    """Bind + serve the HTTP surface for one ReplicaRouter."""

    def __init__(self, router: ReplicaRouter, host: str = "127.0.0.1",
                 port: int = 0, request_timeout_s: float = 300.0,
                 fleet_dir: str | None = None, fleet_ttl_s: float = 30.0):
        self.router = router
        self.request_timeout_s = float(request_timeout_s)
        # fleet rollup surface: explicit dir, else the process's configured
        # FleetReporter's dir (None disables /debug/fleet + /metrics/fleet)
        self._fleet_dir = fleet_dir
        self._fleet_ttl_s = float(fleet_ttl_s)
        handler = _make_handler(self)
        self._server = ThreadingHTTPServer((host, port), handler)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="serving-frontend",
            daemon=True)

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ServingFrontend":
        self._thread.start()
        log_dist(f"serving frontend listening on {self.host}:{self.port}",
                 ranks=[0])
        return self

    def install_preemption_handler(self, handler) -> None:
        """Register drain on an ``elasticity.PreemptionHandler``: SIGTERM →
        stop admitting immediately (flag flips only, signal-safe); inflight
        requests finish and the engine loops exit on their own threads."""
        handler.register("serving-drain", self.router.begin_drain,
                         immediate=True)

    def fleet_aggregator(self):
        """A :class:`FleetAggregator` over the configured fleet dir, or
        None when neither the frontend nor the telemetry singleton has
        fleet reporting configured."""
        fleet_dir = self._fleet_dir
        if fleet_dir is None:
            reporter = get_telemetry().fleet
            if reporter is None:
                return None
            fleet_dir = reporter.out_dir
        from deepspeed_tpu.telemetry.fleet import FleetAggregator

        return FleetAggregator(fleet_dir, ttl_s=self._fleet_ttl_s,
                               registry=get_telemetry().registry)

    def drain(self, timeout: float | None = None) -> bool:
        """Stop admitting, wait for inflight work, stop the HTTP listener."""
        ok = self.router.drain(timeout)
        self.close()
        return ok

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def _make_handler(frontend: ServingFrontend):
    router = frontend.router

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # the request's sampled TraceContext (POST path), echoed on replies
        _trace_ctx = None
        _last_code = 0

        def log_message(self, fmt, *args):  # noqa: A003 - http.server API
            pass  # request logging goes through telemetry, not stderr

        # ------------------------------------------------------- helpers
        def _send_json(self, code: int, payload: dict,
                       headers: dict | None = None) -> None:
            body = json.dumps(payload).encode("utf-8")
            self._last_code = code
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if self._trace_ctx is not None:
                self.send_header("traceparent",
                                 format_traceparent(self._trace_ctx))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_error_json(self, code: int, message: str,
                             headers: dict | None = None, **detail) -> None:
            err = {"message": message, "code": code}
            err.update(detail)
            self._send_json(code, {"error": err}, headers)

        # ----------------------------------------------------------- GET
        def do_GET(self):  # noqa: N802 - http.server API
            # keep-alive reuses the handler across requests: clear any
            # trace context left by an earlier POST on this connection
            self._trace_ctx = None
            # route on the path alone — /metrics?foo=1 is still /metrics
            # (matches the standalone PrometheusExporter's behavior)
            path, _, query = self.path.partition("?")
            if path == "/healthz":
                state = router.state()
                payload = {"status": state, "replicas": router.health()}
                cluster_stats = getattr(router, "cluster_stats", None)
                if cluster_stats is not None:
                    # a ServingCluster fronts the router: expose roles,
                    # prefix-index coverage, handoff/fallback counters
                    payload["cluster"] = cluster_stats()
                slo = get_telemetry().slo
                if slo is not None:
                    payload["slo"] = slo.health()
                    if state == "ready" and slo.breaching():
                        # still 200: the replica can serve, but tail
                        # latency is burning error budget — operators and
                        # balancers can deprioritize without ejecting it
                        payload["status"] = "degraded"
                agg = frontend.fleet_aggregator()
                if agg is not None:
                    # fleet-wide rollup: a breach anywhere in the fleet
                    # (another worker's SLO burn, a dead heartbeat, an open
                    # breaker) degrades THIS health page, so one probe sees
                    # cluster trouble without scraping every worker
                    fleet = agg.debug_payload()
                    payload["fleet"] = fleet["health"]
                    if (payload["status"] == "ready"
                            and fleet["health"]["value"] > 0):
                        payload["status"] = "degraded"
                self._send_json(503 if state == "draining" else 200, payload)
            elif path == "/metrics/fleet":
                agg = frontend.fleet_aggregator()
                if agg is None:
                    self._send_error_json(
                        404, "no fleet dir configured "
                        "(telemetry.configure(fleet={...}))")
                    return
                body = agg.render_prometheus().encode("utf-8")
                self._last_code = 200
                self.send_response(200)
                self.send_header("Content-Type",
                                 PrometheusExporter.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/debug/fleet":
                agg = frontend.fleet_aggregator()
                payload = ({"enabled": False} if agg is None
                           else agg.debug_payload())
                self._send_json(200, payload)
            elif path == "/debug/tenants":
                cm = get_telemetry().costmeter
                payload = ({"enabled": False} if cm is None
                           else cm.debug_payload())
                self._send_json(200, payload)
            elif path == "/metrics":
                router.refresh_metrics()
                tel = get_telemetry()
                if tel.slo is not None:
                    tel.slo.refresh_gauges()
                body = tel.registry.render_prometheus()
                body = body.encode("utf-8")
                self._last_code = 200
                self.send_response(200)
                self.send_header("Content-Type",
                                 PrometheusExporter.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/debug/trace":
                trace_id = (parse_qs(query).get("trace_id") or [None])[0]
                self._send_json(
                    200, get_telemetry().export_chrome_trace(trace_id))
            elif path == "/debug/memory":
                led = get_telemetry().memledger
                payload = ({"enabled": False} if led is None
                           else led.debug_payload())
                tiers = getattr(router, "tier_stats", None)
                if tiers is not None:
                    # per-replica KV tier rows (host/disk bytes, demotion/
                    # promotion/prefetch counters) ride along so operators
                    # see where off-device KV bytes live
                    t = tiers()
                    if t:
                        payload["kv_tiers"] = t
                self._send_json(200, payload)
            elif path == "/debug/profile":
                # bounded device-timeline capture over ~N engine-loop steps
                # (telemetry/devprof.py); one capture at a time per process
                qs = parse_qs(query)
                try:
                    steps = int((qs.get("steps") or ["8"])[0])
                    wait_s = float((qs.get("timeout_s") or ["5"])[0])
                except ValueError:
                    self._send_error_json(
                        400, "steps and timeout_s must be numeric")
                    return
                steps = max(1, min(256, steps))
                wait_s = max(0.1, min(30.0, wait_s))
                from deepspeed_tpu.telemetry.devprof import capture_serving

                loops, _ = router._snapshot()
                res = capture_serving(loops, steps=steps, max_wait_s=wait_s,
                                      telemetry=get_telemetry())
                if res is None:
                    self._send_error_json(
                        409, "a profiler capture is already in progress",
                        retry_after_s=wait_s)
                else:
                    self._send_json(200, res)
            else:
                self._send_error_json(404, f"no route for {path}")

        # ---------------------------------------------------------- POST
        def do_POST(self):  # noqa: N802 - http.server API
            path = self.path.partition("?")[0]
            if path != "/v1/completions":
                self._send_error_json(404, f"no route for {path}")
                return
            tracer = get_telemetry().tracer
            # root server span: pre-allocated so everything downstream
            # (router, engine loop, ragged engine) parents under it;
            # recorded retroactively once the response is on the wire
            ctx = tracer.extract(self.headers.get("traceparent"))
            self._trace_ctx = ctx
            self._last_code = 0
            t_req = time.perf_counter()
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError):
                self._send_error_json(400, "request body is not valid JSON")
                return
            try:
                req = CompletionRequest.from_json(body)
                req.trace_ctx = ctx
                req.t_submit = t_req
                stream = router.submit(req)
            except ProtocolError as e:
                self._send_error_json(400, str(e))
                return
            except Overloaded as e:
                self._send_error_json(
                    429, str(e),
                    headers={"Retry-After": f"{e.retry_after_s:g}"})
                return
            except Draining as e:
                self._send_error_json(503, str(e))
                return
            except DeadlineExceeded as e:
                self._send_error_json(504, str(e),
                                      headers={"Retry-After": "1"})
                return
            finally:
                if ctx is not None and self._last_code:
                    # submit was rejected: close the root span here (the
                    # success path closes it after the response is sent)
                    tracer.finish(ctx, "http/request", t_req,
                                  time.perf_counter(),
                                  status=self._last_code)
            try:
                if req.stream:
                    self._stream_response(req, stream)
                else:
                    self._full_response(req, stream)
            finally:
                router.release(req.request_id)
                if ctx is not None:
                    tracer.finish(ctx, "http/request", t_req,
                                  time.perf_counter(),
                                  status=self._last_code,
                                  request_id=req.request_id,
                                  stream=req.stream)

        # stream error_reasons that mean the replica (not the request) is at
        # fault: the request is replayable token-identically elsewhere
        _FAILOVER_REASONS = ("replica_died", "engine_crash")

        def _full_response(self, req, stream) -> None:
            try:
                while True:
                    try:
                        tokens, reason = stream.collect(
                            timeout=frontend.request_timeout_s)
                        break
                    except StreamError as e:
                        if stream.error_reason in self._FAILOVER_REASONS:
                            replay = router.resubmit(req)
                            if replay is not None:
                                stream = replay
                                continue
                        code = stream.error_code or 400
                        detail = {}
                        if stream.error_reason:
                            detail["reason"] = stream.error_reason
                        self._send_error_json(
                            code, str(e),
                            headers=({"Retry-After": "1"}
                                     if code in (503, 504) else None),
                            **detail)
                        return
            except TimeoutError as e:
                # the engine never finished inside the frontend's budget:
                # that is a gateway timeout, not a client error. Abort the
                # request (frees its KV blocks on the next engine step) and
                # tell the client when a retry is reasonable.
                router.cancel(req.request_id)
                self._send_error_json(
                    504,
                    f"request did not complete within "
                    f"{frontend.request_timeout_s:g}s: {e}",
                    headers={"Retry-After": "1"},
                    retry_after_s=1.0,
                    timeout_s=frontend.request_timeout_s)
                return
            resp = CompletionResponse(
                request_id=req.request_id, tokens=tokens,
                finish_reason=reason, prompt_tokens=len(req.prompt),
                trace_id=(req.trace_ctx.trace_id
                          if req.trace_ctx is not None else None),
                tenant=req.tenant, sla_class=req.sla_class)
            self._send_json(200, resp.to_json())

        def _stream_response(self, req, stream) -> None:
            self._last_code = 200
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            if req.trace_ctx is not None:
                self.send_header("traceparent",
                                 format_traceparent(req.trace_ctx))
            # no Content-Length for a live stream: HTTP/1.1 needs an
            # explicit close to delimit the body
            self.send_header("Connection", "close")
            self.end_headers()
            trace_id = (req.trace_ctx.trace_id
                        if req.trace_ctx is not None else None)
            tokens: list[int] = []
            try:
                while True:
                    resubmitted = False
                    # on failover the replacement stream replays from token
                    # 0 (deterministic per-request seeds); skip the prefix
                    # already on the wire and splice the tail seamlessly
                    skip, seen = len(tokens), 0
                    for kind, value in stream.events(
                            timeout=frontend.request_timeout_s):
                        if kind == "token":
                            seen += 1
                            if seen <= skip:
                                continue
                            frame = {"id": req.request_id, "token": value,
                                     "index": len(tokens)}
                            if trace_id:
                                frame["trace_id"] = trace_id
                            self.wfile.write(encode_sse(frame))
                            self.wfile.flush()
                            tokens.append(value)
                        elif kind == "error":
                            if (stream.error_reason
                                    in self._FAILOVER_REASONS):
                                replay = router.resubmit(req)
                                if replay is not None:
                                    stream = replay
                                    resubmitted = True
                                    break
                            self.wfile.write(encode_sse(
                                {"id": req.request_id, "error": value},
                                event="error"))
                            break
                        else:  # done
                            resp = CompletionResponse(
                                request_id=req.request_id, tokens=tokens,
                                finish_reason=value,
                                prompt_tokens=len(req.prompt),
                                trace_id=trace_id,
                                tenant=req.tenant, sla_class=req.sla_class)
                            self.wfile.write(encode_sse(resp.to_json()))
                            self.wfile.write(sse_done())
                    if not resubmitted:
                        break
                self.wfile.flush()
            except (BrokenPipeError, ConnectionError, TimeoutError, OSError):
                # client went away (or stalled past the deadline): abort the
                # request so its KV blocks free on the next engine step
                router.cancel(req.request_id)
                self.close_connection = True

    return Handler


def build_server(engines, host: str = "127.0.0.1", port: int = 0,
                 router_cfg=None, start: bool = True):
    """Convenience: EngineLoop-wrap ``engines``, route, bind, and start.

    Returns ``(frontend, router, loops)``; pass ``start=False`` to leave
    the loops and listener cold (tests use this for determinism).
    """
    from deepspeed_tpu.serving.engine_loop import EngineLoop

    loops = [EngineLoop(e, name=f"replica-{i}") for i, e in enumerate(engines)]
    router = ReplicaRouter(loops, router_cfg)
    frontend = ServingFrontend(router, host=host, port=port)
    if start:
        for lp in loops:
            lp.start()
        frontend.start()
    return frontend, router, loops
