"""Wire protocol for the serving frontend: request/response dataclasses,
validation, and SSE framing.

The HTTP surface is OpenAI-completions-shaped (``POST /v1/completions``,
non-streaming JSON or ``text/event-stream``), with one deliberate difference:
the stack has no tokenizer, so ``prompt`` is a list of token ids and
responses carry token ids — the serving tier is the engine-facing half of a
deployment (DeepSpeed-MII's role over the reference v2 engine), and
detokenization belongs to whatever owns the vocabulary.

Everything here is pure data + validation — no sockets, no threads — so the
router/frontend tests can exercise the math without binding a port.
"""

from __future__ import annotations

import json
import time
import uuid
from dataclasses import dataclass, field

# terminal states a completion can end in (finish_reason on the wire)
FINISH_STOP = "stop"          # hit eos_token_id
FINISH_LENGTH = "length"      # hit max_tokens
FINISH_CANCELLED = "cancelled"  # client disconnect / explicit cancel
FINISH_TIMEOUT = "timeout"    # per-request deadline expired


class ProtocolError(ValueError):
    """Invalid request payload (maps to HTTP 400)."""


# documented admission-priority range (lower = sooner); anything outside is
# a validation error, not a silent clamp — an out-of-range priority is
# almost always a units bug on the client side
PRIORITY_MIN = -32
PRIORITY_MAX = 32

# SLA classes a request may declare (docs/SERVING.md): "interactive" gets
# the tight ttft/decode objectives, "batch" the relaxed ones
SLA_CLASSES = ("interactive", "batch")

# bound on the tenant identifier so the label can't smuggle unbounded
# cardinality or junk into the metrics pipeline
TENANT_MAX_LEN = 64


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ProtocolError(msg)


@dataclass
class CompletionRequest:
    """One validated completion request.

    ``priority`` orders admission within a replica's inbox (lower = sooner);
    ``deadline_s`` bounds the request's whole lifetime including queue wait
    (expiry releases its KV blocks and returns finish_reason=timeout).
    """

    prompt: list[int]
    max_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stream: bool = False
    eos_token_id: int | None = None
    deadline_s: float | None = None
    priority: int = 0
    # pins the request's sampling stream: the same (prompt, seed, sampling
    # params) replays the same tokens on any replica, cold or prefix-cached
    seed: int | None = None
    # cost-attribution identity (docs/OBSERVABILITY.md "Cost attribution"):
    # tenant names the party billed for this request's capacity; sla_class
    # selects which latency objectives it is measured against
    tenant: str = "default"
    sla_class: str = "interactive"
    request_id: str = field(
        default_factory=lambda: "cmpl-" + uuid.uuid4().hex[:24])
    # not wire fields: the frontend attaches the sampled TraceContext here
    # (None = untraced) and stamps submit time so the engine loop can record
    # the inbox-wait span retroactively
    trace_ctx: object = field(default=None, repr=False, compare=False)
    t_submit: float = field(default=0.0, repr=False, compare=False)
    # not wire fields (disaggregated serving, serving/cluster.py): the
    # cluster marks the prefill-stage copy of a request with ``handoff`` so
    # the engine parks its KV for export instead of decoding, and the router
    # stamps the placement-time prefix-probe credit in ``cached_tokens_hint``
    # so admission can re-validate the splice (stale-probe fix)
    handoff: bool = field(default=False, repr=False, compare=False)
    cached_tokens_hint: int = field(default=0, repr=False, compare=False)

    def __post_init__(self):
        _require(isinstance(self.prompt, (list, tuple)) and len(self.prompt) > 0,
                 "prompt must be a non-empty list of token ids")
        try:
            self.prompt = [int(t) for t in self.prompt]
        except (TypeError, ValueError):
            raise ProtocolError("prompt must contain integers") from None
        _require(all(t >= 0 for t in self.prompt),
                 "prompt token ids must be non-negative")
        _require(int(self.max_tokens) >= 1, "max_tokens must be >= 1")
        self.max_tokens = int(self.max_tokens)
        _require(float(self.temperature) >= 0.0, "temperature must be >= 0")
        self.temperature = float(self.temperature)
        _require(int(self.top_k) >= 0, "top_k must be >= 0")
        self.top_k = int(self.top_k)
        _require(0.0 < float(self.top_p) <= 1.0, "top_p must be in (0, 1]")
        self.top_p = float(self.top_p)
        if self.deadline_s is not None:
            _require(float(self.deadline_s) > 0.0, "deadline_s must be > 0")
            self.deadline_s = float(self.deadline_s)
        if self.eos_token_id is not None:
            self.eos_token_id = int(self.eos_token_id)
        if self.seed is not None:
            _require(int(self.seed) >= 0, "seed must be >= 0")
            self.seed = int(self.seed)
        try:
            prio = int(self.priority)
        except (TypeError, ValueError):
            raise ProtocolError("priority must be an integer") from None
        _require(prio == self.priority,  # reject 1.5 — no silent truncation
                 "priority must be an integer")
        self.priority = prio
        _require(PRIORITY_MIN <= self.priority <= PRIORITY_MAX,
                 f"priority must be in [{PRIORITY_MIN}, {PRIORITY_MAX}], "
                 f"got {self.priority}")
        self.stream = bool(self.stream)
        _require(isinstance(self.request_id, str) and len(self.request_id) > 0,
                 "request_id must be a non-empty string")
        _require(isinstance(self.tenant, str)
                 and 0 < len(self.tenant) <= TENANT_MAX_LEN,
                 f"tenant must be a non-empty string of at most "
                 f"{TENANT_MAX_LEN} chars")
        _require(self.sla_class in SLA_CLASSES,
                 f"sla_class must be one of {list(SLA_CLASSES)}, "
                 f"got {self.sla_class!r}")

    @property
    def total_tokens(self) -> int:
        """Worst-case sequence length — the admission-control token budget."""
        return len(self.prompt) + self.max_tokens

    @classmethod
    def from_json(cls, body) -> "CompletionRequest":
        """Build + validate from a decoded JSON body (raises ProtocolError)."""
        _require(isinstance(body, dict), "request body must be a JSON object")
        known = {
            "prompt", "max_tokens", "temperature", "top_k", "top_p",
            "stream", "eos_token_id", "deadline_s", "priority", "request_id",
            "seed", "tenant", "sla_class",
        }
        unknown = set(body) - known
        _require(not unknown, f"unknown fields: {sorted(unknown)}")
        _require("prompt" in body, "missing required field: prompt")
        kwargs = {k: v for k, v in body.items() if v is not None}
        try:
            return cls(**kwargs)
        except ProtocolError:
            raise
        except (TypeError, ValueError) as e:
            raise ProtocolError(str(e)) from None


@dataclass
class CompletionResponse:
    """Terminal result of one request (the non-streaming response body; the
    streaming path sends the same shape as its final SSE frame)."""

    request_id: str
    tokens: list[int]
    finish_reason: str
    prompt_tokens: int
    created: float = field(default_factory=time.time)
    # trace id echoed to the client when the request was sampled
    trace_id: str | None = None
    # cost-attribution identity echoed back so clients can reconcile their
    # own accounting against the server-side ledger
    tenant: str | None = None
    sla_class: str | None = None

    def to_json(self) -> dict:
        out = {
            "id": self.request_id,
            "object": "completion",
            "created": self.created,
            "choices": [{
                "index": 0,
                "tokens": list(self.tokens),
                "finish_reason": self.finish_reason,
            }],
            "usage": {
                "prompt_tokens": self.prompt_tokens,
                "completion_tokens": len(self.tokens),
                "total_tokens": self.prompt_tokens + len(self.tokens),
            },
        }
        if self.trace_id:
            out["trace_id"] = self.trace_id
        if self.tenant is not None:
            out["tenant"] = self.tenant
            out["sla_class"] = self.sla_class
        return out


# ----------------------------------------------------------------- SSE
SSE_DONE_DATA = "[DONE]"


def encode_sse(data, event: str | None = None) -> bytes:
    """One server-sent-event frame. ``data`` is a JSON-serializable object
    (or the literal ``[DONE]`` terminator string); JSON encoding guarantees
    no raw newlines, so one ``data:`` line per frame is always valid SSE."""
    payload = data if isinstance(data, str) else json.dumps(data)
    head = f"event: {event}\n" if event else ""
    return (head + f"data: {payload}\n\n").encode("utf-8")


def sse_done() -> bytes:
    return encode_sse(SSE_DONE_DATA)


def decode_sse(payload: bytes) -> list:
    """Parse a byte stream of SSE frames back into the decoded ``data``
    values (dicts, or the ``[DONE]`` string). Multi-``data:``-line frames
    join with newlines per the SSE spec; comment/event lines are ignored."""
    out = []
    for block in payload.decode("utf-8").split("\n\n"):
        data_lines = [line[5:].lstrip() for line in block.splitlines()
                      if line.startswith("data:")]
        if not data_lines:
            continue
        data = "\n".join(data_lines)
        if data == SSE_DONE_DATA:
            out.append(data)
        else:
            try:
                out.append(json.loads(data))
            except json.JSONDecodeError:
                out.append(data)  # non-JSON data passes through verbatim
    return out
