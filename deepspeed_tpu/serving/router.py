"""Multi-replica request router: placement, admission control, backpressure.

The router is the MII-frontend role over our engine tier: it looks at each
replica's ``ReplicaStats`` snapshot and decides, per request, between

- **admit now** — some replica has enough unreserved KV blocks for the
  request's worst case (``ceil(total_tokens / block_size)`` on top of what
  its inbox already promised). Ties break to the replica with the fewest
  outstanding tokens (least-outstanding-tokens placement — outstanding
  tokens, not request count, is what predicts queueing delay under ragged
  batching).
- **queue** — no replica has free blocks, but some replica's bounded queue
  (``max_queue_tokens`` worth of outstanding work) still has room; place
  there and let the engine's own conservative admission pace it.
- **reject** — every live replica is past its queue bound. The caller gets
  ``Overloaded`` carrying a retry-after hint (HTTP 429 upstream). Shedding
  at the door beats timing out inside: an admitted request holds its KV
  reservation while it waits.

``plan_placement`` is a pure function of the stats snapshot so the admission
math is unit-testable without sockets or threads.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, replace

from deepspeed_tpu.serving.engine_loop import (
    EngineLoop,
    ReplicaDraining,
    ReplicaStats,
    TokenStream,
)
from deepspeed_tpu.serving.faults import POINT_SUBMIT, get_fault_injector
from deepspeed_tpu.serving.protocol import CompletionRequest, ProtocolError
from deepspeed_tpu.telemetry import get_telemetry


class Overloaded(RuntimeError):
    """Every replica is past its queue bound (maps to HTTP 429)."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class Draining(RuntimeError):
    """The whole router is draining (maps to HTTP 503)."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline expired before placement (maps to HTTP 504)."""


@dataclass(frozen=True)
class RouterConfig:
    # per-replica bound on outstanding (queued + inflight) tokens before the
    # router sheds load; sized so queue wait stays ~bounded at one replica's
    # worst-case step throughput
    max_queue_tokens: int = 4096
    # Retry-After hint handed to rejected clients
    retry_after_s: float = 1.0
    # --- circuit breaker (per replica, router→replica submit edge) ---
    # consecutive submit failures that trip the breaker open (quarantine)
    breaker_failures: int = 3
    # quarantine dwell before one half-open probe is allowed through
    breaker_reset_s: float = 5.0
    # failover re-placements allowed per request after its replica dies
    max_failovers: int = 1
    # --- tuned-profile loading (docs/AUTOTUNING.md) ---
    # when set, the router loads the persisted serve profile for
    # (autotune_fingerprint, current topology, autotune_workload) at
    # startup and exposes it via ``tuned_overrides()`` (the autoscaler's
    # template for new replicas) + the ``tuned_profile_loaded`` gauge.
    # Engine RaggedConfigs are filled via profiles.apply_serving_profile —
    # fields the operator wrote keep their values (config wins).
    autotune_profile_dir: str | None = None
    autotune_fingerprint: str = ""
    autotune_workload: str = "default"


class _ReplicaHealth:
    """Per-replica circuit breaker: closed → (failures) → open →
    (``breaker_reset_s`` dwell) → half_open → one probe decides. A probe
    failure while half-open re-opens immediately; a success closes."""

    __slots__ = ("failures", "breaker", "opened_at")

    def __init__(self):
        self.failures = 0
        self.breaker = "closed"
        self.opened_at = 0.0

    def note_success(self) -> None:
        self.failures = 0
        self.breaker = "closed"

    def note_failure(self, now: float, threshold: int) -> None:
        self.failures += 1
        if self.breaker == "half_open" or self.failures >= threshold:
            self.breaker = "open"
            self.opened_at = now

    def admissible(self, now: float, reset_s: float) -> bool:
        if self.breaker == "closed":
            return True
        if self.breaker == "open" and now - self.opened_at >= reset_s:
            self.breaker = "half_open"  # next submit is the probe
        return self.breaker == "half_open"


def plan_placement(
    stats: list[ReplicaStats], total_tokens: int, cfg: RouterConfig,
    cached_tokens: list[int] | None = None,
    roles: tuple = ("unified", "decode"),
    tenant_over_share: float = 0.0,
) -> tuple[int | None, str]:
    """Pure admission/placement decision over a stats snapshot.

    ``cached_tokens`` (optional, one entry per replica) is how much of the
    request's prompt each replica's prefix cache already holds: those
    full blocks are spliced (not allocated) on admission, so the worst-case
    block need and the queue-bound token footprint shrink by the cached
    amount — a replica holding the prefix admits requests a cold one must
    queue, and ties prefer the replica that reuses the most.

    ``roles`` restricts which replica roles may take the request. The
    default excludes "prefill": a dedicated prefill replica only ever runs
    handoff prompt stages the cluster places explicitly, so neither initial
    placement NOR failover resubmission can land a decode-bearing request
    on it (the never-fail-over-to-prefill invariant — resubmit() goes
    through this same function).

    ``tenant_over_share`` is the cost meter's fair-share signal: how far
    the requesting tenant's live-KV share exceeds its fair share (0.0 when
    metering is off, the tenant is at/under fair share, or only one tenant
    is active — those cases are byte-identical to the unmetered planner).
    A positive value shrinks the queue bound this request may ride, so a
    hog tenant hits backpressure (429 + retry-after) while the pool is
    contended instead of filling every replica queue — soft steering, never
    a hard quota.

    Returns ``(replica_index, verdict)`` where verdict is one of
    ``"admit"`` (free KV blocks now), ``"queue"`` (fits under the queue
    bound), ``"draining"`` / ``"overloaded"`` (index is None).
    """
    live = [(i, s) for i, s in enumerate(stats)
            if s.alive and not s.draining and s.role in roles]
    if not live:
        return None, "draining"

    def cached(i: int) -> int:
        if not cached_tokens:
            return 0
        return max(0, min(cached_tokens[i], total_tokens))

    def need(i: int, s: ReplicaStats) -> int:
        # cached full blocks are reused, not allocated; the tail still
        # needs ceil((total - block-aligned cached) / block_size)
        return s.worst_blocks(total_tokens
                              - (cached(i) // s.block_size) * s.block_size)

    def load(i: int, s: ReplicaStats) -> int:
        return s.outstanding_tokens + total_tokens - cached(i)

    def cap(s: ReplicaStats) -> int:
        # admit-now capacity: static free-block math, further capped by the
        # replica's measured free-byte headroom when the backend reports it
        # (headroom_blocks == -1 keeps the static path bit-identical)
        free = s.free_blocks - s.pending_blocks
        if s.headroom_blocks >= 0:
            free = min(free, s.headroom_blocks - s.pending_blocks)
        return free

    queue_bound = cfg.max_queue_tokens
    if tenant_over_share > 0.0:
        queue_bound = int(queue_bound / (1.0 + tenant_over_share))
    fits_now = [
        (i, s) for i, s in live
        if need(i, s) <= cap(s)
        and load(i, s) <= queue_bound
    ]
    if fits_now:
        i, _ = min(fits_now,
                   key=lambda t: (t[1].outstanding_tokens, -cached(t[0])))
        return i, "admit"
    can_queue = [
        (i, s) for i, s in live if load(i, s) <= queue_bound
    ]
    if can_queue:
        i, _ = min(can_queue,
                   key=lambda t: (t[1].outstanding_tokens, -cached(t[0])))
        return i, "queue"
    return None, "overloaded"


class ReplicaRouter:
    """Route requests across EngineLoop replicas; own drain + metrics."""

    def __init__(self, replicas: list[EngineLoop],
                 cfg: RouterConfig | None = None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self.cfg = cfg or RouterConfig()
        self._placements: dict[str, EngineLoop] = {}
        self._health = [_ReplicaHealth() for _ in self.replicas]
        self._failovers: dict[str, int] = {}
        self._faults = get_fault_injector()
        self._draining = False
        self.tuned_profile = self._load_tuned_profile()
        # guards the replicas/_health pair against autoscaler mutation;
        # every read path works on a _snapshot() so a concurrent
        # add/remove never shifts indices mid-decision
        self._replica_lock = threading.Lock()

    # ------------------------------------------------------ tuned profile
    def _load_tuned_profile(self) -> dict | None:
        """Load the persisted serve autotune profile at startup (None when
        not configured / no profile matches / the store is unreadable —
        the router must come up either way)."""
        if not self.cfg.autotune_profile_dir:
            return None
        try:
            from deepspeed_tpu.autotuning import profiles

            prof = profiles.load_profile(
                self.cfg.autotune_profile_dir, subsystem="serve",
                fingerprint=self.cfg.autotune_fingerprint,
                workload=self.cfg.autotune_workload)
        except Exception:
            return None
        if prof is not None:
            from deepspeed_tpu.utils.logging import log_dist

            log_dist(
                f"router: loaded serve autotune profile {prof['key']} "
                f"(workload {prof['workload']!r}): {prof['overrides']}",
                ranks=[0])
        return prof

    def tuned_overrides(self) -> dict:
        """RaggedConfig overrides from the loaded profile (empty when
        none); the template for autoscaler-built replicas."""
        return dict((self.tuned_profile or {}).get("overrides") or {})

    # ------------------------------------------- replica pool (autoscaler)
    def _snapshot(self) -> tuple[list[EngineLoop], list[_ReplicaHealth]]:
        with self._replica_lock:
            return list(self.replicas), list(self._health)

    def add_replica(self, replica: EngineLoop) -> None:
        """Grow the pool (autoscaler scale-up). The new replica starts with
        a fresh closed breaker and is placeable on the next submit."""
        with self._replica_lock:
            self.replicas.append(replica)
            self._health.append(_ReplicaHealth())
        if self._draining:
            replica.begin_drain()

    def remove_replica(self, replica: EngineLoop) -> bool:
        """Forget a replica (autoscaler scale-down, after its drain). The
        caller owns draining/joining the loop; in-flight snapshots keep
        working because breaker objects are identity-stable."""
        with self._replica_lock:
            try:
                i = self.replicas.index(replica)
            except ValueError:
                return False
            if len(self.replicas) == 1:
                return False  # never empty the pool
            del self.replicas[i]
            del self._health[i]
        return True

    # ------------------------------------------------------------- submit
    def submit(self, req: CompletionRequest) -> TokenStream:
        """Place + enqueue one request; returns its TokenStream. Raises
        Draining / Overloaded / ProtocolError (request can never fit)."""
        if self._draining:
            raise Draining("server is draining")
        if req.trace_ctx is not None:
            t0 = time.perf_counter()
            try:
                idx, verdict, stream = self._submit_placed(req)
            except Exception as e:
                get_telemetry().tracer.record(
                    req.trace_ctx, "router/submit", t0, time.perf_counter(),
                    verdict=type(e).__name__.lower())
                raise
            get_telemetry().tracer.record(
                req.trace_ctx, "router/submit", t0, time.perf_counter(),
                verdict=verdict, replica=idx)
            return stream
        return self._submit_placed(req)[2]

    def _submit_placed(self, req: CompletionRequest):
        tel = get_telemetry()
        if (req.deadline_s is not None and req.t_submit
                and time.perf_counter() - req.t_submit >= req.deadline_s):
            # already-expired queue entry: shed before placement rather
            # than dispatch doomed work that would hold KV blocks
            if tel.enabled:
                tel.counter(
                    "serving_requests_shed_total",
                    "expired-deadline requests shed pre-placement",
                ).inc(replica="router")
            raise DeadlineExceeded(
                f"request {req.request_id}: deadline_s={req.deadline_s} "
                "expired before placement")
        replicas, health = self._snapshot()
        stats = [r.stats() for r in replicas]
        cap_tokens = max(s.max_request_tokens for s in stats)
        cap_blocks = max(s.max_request_blocks for s in stats)
        if (req.total_tokens > cap_tokens
                or stats[0].worst_blocks(req.total_tokens) > cap_blocks):
            raise ProtocolError(
                f"prompt+max_tokens = {req.total_tokens} exceeds the "
                f"serveable maximum ({cap_tokens} tokens)")
        excluded: set[int] = set()
        while True:
            now = time.perf_counter()
            # mask replicas the breaker quarantines (or that already failed
            # this submit) so plan_placement stays a pure function of stats
            masked = [
                s if (i not in excluded
                      and health[i].admissible(
                          now, self.cfg.breaker_reset_s))
                else replace(s, alive=False)
                for i, s in enumerate(stats)
            ]
            cached = [r.cached_prefix_tokens(req.prompt)
                      for r in replicas]
            over = 0.0
            cm = tel.costmeter
            if cm is not None:
                # fair-share steering: how far this tenant's live-KV share
                # exceeds 1/active_tenants (exactly 0.0 single-tenant)
                share, fair = cm.outstanding_share(
                    getattr(req, "tenant", "default"))
                over = max(0.0, share - fair) * cm.fairness_weight
            idx, verdict = plan_placement(masked, req.total_tokens, self.cfg,
                                          cached_tokens=cached,
                                          tenant_over_share=over)
            if idx is None:
                if verdict == "draining":
                    # distinguish "every replica is gone/draining" (503)
                    # from "live replicas exist but are quarantined or just
                    # failed this submit" (429 + come back after the dwell)
                    if any(s.alive and not s.draining
                           and s.role != "prefill" for s in stats):
                        raise Overloaded(
                            "all live replicas quarantined by the circuit "
                            "breaker", retry_after_s=self.cfg.breaker_reset_s)
                    raise Draining("server is draining")
                if tel.enabled:
                    tel.counter("serving_requests_rejected_total").inc()
                raise Overloaded(
                    f"all {len(replicas)} replicas past "
                    f"max_queue_tokens={self.cfg.max_queue_tokens}",
                    retry_after_s=self.cfg.retry_after_s)
            replica = replicas[idx]
            # prefetch-on-admission: let the chosen replica's KV tier store
            # stage demoted prefix blocks disk→host while the request sits
            # in its inbox — by admission the restore either completed (tier
            # hit) or is abandoned; the splice is token-identical either way
            kick = getattr(replica, "prefetch_prefix", None)
            if kick is not None:
                kick(req.prompt)
            # record the placement-time prefix credit on the request so the
            # engine can re-validate the actual splice at admission (the
            # probe is advisory — LRU eviction between placement and
            # admission must cost a cold prefill, not over-credited reuse)
            req.cached_tokens_hint = cached[idx] if cached else 0
            try:
                if self._faults.enabled:
                    self._faults.fire(POINT_SUBMIT,
                                      request_id=req.request_id)
                stream = replica.submit(req)
            except ReplicaDraining:
                excluded.add(idx)
                stats[idx] = replica.stats()
                continue
            except Exception as e:  # noqa: BLE001 - breaker feeds on these
                health[idx].note_failure(time.perf_counter(),
                                         self.cfg.breaker_failures)
                if tel.enabled:
                    tel.counter(
                        "serving_submit_failures_total",
                        "router→replica submit failures",
                    ).inc(replica=replica.name, kind=type(e).__name__)
                excluded.add(idx)
                stats[idx] = replica.stats()
                continue
            health[idx].note_success()
            self._placements[req.request_id] = replica
            if tel.enabled:
                tel.counter("serving_requests_admitted_total").inc()
                if verdict == "queue":
                    tel.counter("serving_requests_queued_total").inc()
            return idx, verdict, stream

    def resubmit(self, req: CompletionRequest) -> TokenStream | None:
        """Failover: re-place an in-flight request after its replica died or
        its engine crashed. Deterministic per-request seeds make the replay
        token-identical on any replica, so the frontend can splice the new
        stream over the old one. Returns None when the per-request failover
        budget is spent or the router is draining (caller surfaces the
        original error)."""
        if self._draining:
            return None
        n = self._failovers.get(req.request_id, 0)
        if n >= self.cfg.max_failovers:
            return None
        self._failovers[req.request_id] = n + 1
        self._placements.pop(req.request_id, None)
        try:
            _, _, stream = self._submit_placed(req)
        except Exception:  # noqa: BLE001 - no surviving placement
            return None
        tel = get_telemetry()
        if tel.enabled:
            tel.counter(
                "serving_failovers_total",
                "in-flight requests re-placed on a surviving replica").inc()
        return stream

    def cancel(self, request_id: str) -> None:
        replica = self._placements.pop(request_id, None)
        self._failovers.pop(request_id, None)
        if replica is not None:
            replica.cancel(request_id)
            tel = get_telemetry()
            if tel.enabled:
                tel.counter("serving_requests_cancelled_total").inc()

    def release(self, request_id: str) -> None:
        """Forget a finished request's placement (frontend calls this after
        the terminal event so the map does not grow without bound)."""
        self._placements.pop(request_id, None)
        self._failovers.pop(request_id, None)

    # -------------------------------------------------------------- state
    def state(self) -> str:
        """Healthcheck verdict: ready | degraded | overloaded | draining.

        "degraded" = still serving, but some replica is off its full device
        path (engine ``degraded_mode`` > 0), quarantined by the breaker, or
        dead while others carry the load."""
        replicas, health = self._snapshot()
        if self._draining or not any(
                r.stats().alive and not r.draining for r in replicas):
            return "draining"
        stats = [r.stats() for r in replicas]
        idx, verdict = plan_placement(stats, 1, self.cfg)
        del idx
        if verdict == "overloaded":
            return "overloaded"
        if (any(s.degraded for s in stats)
                or any(not s.alive for s in stats)
                or any(h.breaker != "closed" for h in health)):
            return "degraded"
        return "ready"

    def health(self) -> list[dict]:
        """Per-replica health detail for /healthz: name, role, state
        (healthy | degraded | quarantined | dead), breaker phase, engine
        degradation rung, and containment counters."""
        out = []
        replicas, health = self._snapshot()
        for r, h in zip(replicas, health):
            s = r.stats()
            if not s.alive:
                state = "dead"
            elif h.breaker == "open":
                state = "quarantined"
            elif s.degraded or h.breaker == "half_open":
                state = "degraded"
            else:
                state = "healthy"
            out.append({
                "name": s.name, "role": s.role, "state": state,
                "breaker": h.breaker,
                "alive": s.alive, "draining": s.draining,
                "degraded_mode": s.degraded, "crashes": s.crashes,
                "respawns": s.respawns,
            })
        return out

    def tier_stats(self) -> dict:
        """Per-replica KV tier-store stats (counters, per-tier bytes/blocks)
        for /debug/memory. Replicas without tiering are omitted; empty dict
        when no replica has a tier store."""
        out = {}
        for r in self._snapshot()[0]:
            probe = getattr(r, "kv_tier_stats", None)
            if probe is None:
                continue
            s = probe()
            if s:
                out[r.name] = s
        return out

    def begin_drain(self) -> None:
        """Stop admitting everywhere; non-blocking and signal-safe — the
        frontend registers this as an immediate PreemptionHandler hook."""
        self._draining = True
        for r in self._snapshot()[0]:
            r.begin_drain()

    def drain(self, timeout: float | None = None) -> bool:
        """begin_drain + wait for every replica loop to finish inflight
        work and exit. True if all replicas stopped within the timeout."""
        self.begin_drain()
        ok = True
        for r in self._snapshot()[0]:
            ok = r.join(timeout) and ok
        return ok

    # ------------------------------------------------------------ metrics
    def refresh_metrics(self) -> None:
        """Write current serving gauges into the telemetry registry (called
        at /metrics scrape time; no-op while telemetry is disabled)."""
        tel = get_telemetry()
        if not tel.enabled:
            return
        replicas, health = self._snapshot()
        stats = [r.stats() for r in replicas]
        tel.gauge("serving_replicas").set(len(stats))
        for role in ("unified", "prefill", "decode"):
            n = sum(1 for s in stats if s.role == role)
            if n or role == "unified":
                tel.gauge(
                    "serving_replicas_by_role",
                    "pool size per replica role",
                ).set(n, role=role)
        tel.gauge("serving_replicas_live").set(
            sum(1 for s in stats if s.alive and not s.draining))
        tel.gauge("serving_queue_depth").set(sum(s.queued for s in stats))
        tel.gauge("serving_inflight").set(sum(s.inflight for s in stats))
        tel.gauge("serving_outstanding_tokens").set(
            sum(s.outstanding_tokens for s in stats))
        tel.gauge("serving_kv_free_blocks").set(
            sum(s.free_blocks for s in stats))
        tel.gauge("serving_kv_pending_blocks").set(
            sum(s.pending_blocks for s in stats))
        known = [s.headroom_blocks for s in stats if s.headroom_blocks >= 0]
        if known:
            tel.gauge(
                "serving_kv_headroom_blocks",
                "KV blocks fundable from measured free-byte headroom "
                "(replicas whose backend reports memory limits)",
            ).set(sum(known))
        tel.gauge("serving_draining").set(1.0 if self._draining else 0.0)
        if self.cfg.autotune_profile_dir:
            tel.gauge(
                "tuned_profile_loaded",
                "1 when a persisted autotune profile was applied at startup",
            ).set(1.0 if self.tuned_profile else 0.0, kind="serving")
        cm = tel.costmeter
        if cm is not None:
            for row in cm.ledger.rows():
                if row["outstanding_blocks"] or row["kv_block_seconds"]:
                    tel.gauge(
                        "tenant_outstanding_blocks",
                        "live KV blocks held per tenant (fair-share input)",
                    ).set(row["outstanding_blocks"],
                          tenant=cm.tenant_label(row["tenant"]))
        breaker_rank = {"closed": 0.0, "half_open": 1.0, "open": 2.0}
        for r, s, h in zip(replicas, stats, health):
            tel.gauge(
                "replica_breaker_state",
                "0 closed | 1 half-open | 2 open (quarantined)",
            ).set(breaker_rank[h.breaker], replica=r.name, role=s.role)
            tel.gauge(
                "replica_degraded_mode",
                "engine degradation rung (0 full device path)",
            ).set(float(s.degraded), replica=r.name, role=s.role)
