"""Multi-replica request router: placement, admission control, backpressure.

The router is the MII-frontend role over our engine tier: it looks at each
replica's ``ReplicaStats`` snapshot and decides, per request, between

- **admit now** — some replica has enough unreserved KV blocks for the
  request's worst case (``ceil(total_tokens / block_size)`` on top of what
  its inbox already promised). Ties break to the replica with the fewest
  outstanding tokens (least-outstanding-tokens placement — outstanding
  tokens, not request count, is what predicts queueing delay under ragged
  batching).
- **queue** — no replica has free blocks, but some replica's bounded queue
  (``max_queue_tokens`` worth of outstanding work) still has room; place
  there and let the engine's own conservative admission pace it.
- **reject** — every live replica is past its queue bound. The caller gets
  ``Overloaded`` carrying a retry-after hint (HTTP 429 upstream). Shedding
  at the door beats timing out inside: an admitted request holds its KV
  reservation while it waits.

``plan_placement`` is a pure function of the stats snapshot so the admission
math is unit-testable without sockets or threads.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from deepspeed_tpu.serving.engine_loop import (
    EngineLoop,
    ReplicaDraining,
    ReplicaStats,
    TokenStream,
)
from deepspeed_tpu.serving.protocol import CompletionRequest, ProtocolError
from deepspeed_tpu.telemetry import get_telemetry


class Overloaded(RuntimeError):
    """Every replica is past its queue bound (maps to HTTP 429)."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class Draining(RuntimeError):
    """The whole router is draining (maps to HTTP 503)."""


@dataclass(frozen=True)
class RouterConfig:
    # per-replica bound on outstanding (queued + inflight) tokens before the
    # router sheds load; sized so queue wait stays ~bounded at one replica's
    # worst-case step throughput
    max_queue_tokens: int = 4096
    # Retry-After hint handed to rejected clients
    retry_after_s: float = 1.0


def plan_placement(
    stats: list[ReplicaStats], total_tokens: int, cfg: RouterConfig,
    cached_tokens: list[int] | None = None,
) -> tuple[int | None, str]:
    """Pure admission/placement decision over a stats snapshot.

    ``cached_tokens`` (optional, one entry per replica) is how much of the
    request's prompt each replica's prefix cache already holds: those
    full blocks are spliced (not allocated) on admission, so the worst-case
    block need and the queue-bound token footprint shrink by the cached
    amount — a replica holding the prefix admits requests a cold one must
    queue, and ties prefer the replica that reuses the most.

    Returns ``(replica_index, verdict)`` where verdict is one of
    ``"admit"`` (free KV blocks now), ``"queue"`` (fits under the queue
    bound), ``"draining"`` / ``"overloaded"`` (index is None).
    """
    live = [(i, s) for i, s in enumerate(stats) if s.alive and not s.draining]
    if not live:
        return None, "draining"

    def cached(i: int) -> int:
        if not cached_tokens:
            return 0
        return max(0, min(cached_tokens[i], total_tokens))

    def need(i: int, s: ReplicaStats) -> int:
        # cached full blocks are reused, not allocated; the tail still
        # needs ceil((total - block-aligned cached) / block_size)
        return s.worst_blocks(total_tokens
                              - (cached(i) // s.block_size) * s.block_size)

    def load(i: int, s: ReplicaStats) -> int:
        return s.outstanding_tokens + total_tokens - cached(i)

    fits_now = [
        (i, s) for i, s in live
        if need(i, s) <= s.free_blocks - s.pending_blocks
        and load(i, s) <= cfg.max_queue_tokens
    ]
    if fits_now:
        i, _ = min(fits_now,
                   key=lambda t: (t[1].outstanding_tokens, -cached(t[0])))
        return i, "admit"
    can_queue = [
        (i, s) for i, s in live if load(i, s) <= cfg.max_queue_tokens
    ]
    if can_queue:
        i, _ = min(can_queue,
                   key=lambda t: (t[1].outstanding_tokens, -cached(t[0])))
        return i, "queue"
    return None, "overloaded"


class ReplicaRouter:
    """Route requests across EngineLoop replicas; own drain + metrics."""

    def __init__(self, replicas: list[EngineLoop],
                 cfg: RouterConfig | None = None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self.cfg = cfg or RouterConfig()
        self._placements: dict[str, EngineLoop] = {}
        self._draining = False

    # ------------------------------------------------------------- submit
    def submit(self, req: CompletionRequest) -> TokenStream:
        """Place + enqueue one request; returns its TokenStream. Raises
        Draining / Overloaded / ProtocolError (request can never fit)."""
        if self._draining:
            raise Draining("server is draining")
        if req.trace_ctx is not None:
            t0 = time.perf_counter()
            try:
                idx, verdict, stream = self._submit_placed(req)
            except Exception as e:
                get_telemetry().tracer.record(
                    req.trace_ctx, "router/submit", t0, time.perf_counter(),
                    verdict=type(e).__name__.lower())
                raise
            get_telemetry().tracer.record(
                req.trace_ctx, "router/submit", t0, time.perf_counter(),
                verdict=verdict, replica=idx)
            return stream
        return self._submit_placed(req)[2]

    def _submit_placed(self, req: CompletionRequest):
        stats = [r.stats() for r in self.replicas]
        cap_tokens = max(s.max_request_tokens for s in stats)
        cap_blocks = max(s.max_request_blocks for s in stats)
        if (req.total_tokens > cap_tokens
                or stats[0].worst_blocks(req.total_tokens) > cap_blocks):
            raise ProtocolError(
                f"prompt+max_tokens = {req.total_tokens} exceeds the "
                f"serveable maximum ({cap_tokens} tokens)")
        cached = [r.cached_prefix_tokens(req.prompt) for r in self.replicas]
        idx, verdict = plan_placement(stats, req.total_tokens, self.cfg,
                                      cached_tokens=cached)
        tel = get_telemetry()
        if idx is None:
            if verdict == "draining":
                raise Draining("server is draining")
            if tel.enabled:
                tel.counter("serving_requests_rejected_total").inc()
            raise Overloaded(
                f"all {len(self.replicas)} replicas past "
                f"max_queue_tokens={self.cfg.max_queue_tokens}",
                retry_after_s=self.cfg.retry_after_s)
        replica = self.replicas[idx]
        try:
            stream = replica.submit(req)
        except ReplicaDraining:
            raise Draining("server is draining") from None
        self._placements[req.request_id] = replica
        if tel.enabled:
            tel.counter("serving_requests_admitted_total").inc()
            if verdict == "queue":
                tel.counter("serving_requests_queued_total").inc()
        return idx, verdict, stream

    def cancel(self, request_id: str) -> None:
        replica = self._placements.pop(request_id, None)
        if replica is not None:
            replica.cancel(request_id)
            tel = get_telemetry()
            if tel.enabled:
                tel.counter("serving_requests_cancelled_total").inc()

    def release(self, request_id: str) -> None:
        """Forget a finished request's placement (frontend calls this after
        the terminal event so the map does not grow without bound)."""
        self._placements.pop(request_id, None)

    # -------------------------------------------------------------- state
    def state(self) -> str:
        """Healthcheck verdict: ready | overloaded | draining."""
        if self._draining or not any(
                r.stats().alive and not r.draining for r in self.replicas):
            return "draining"
        stats = [r.stats() for r in self.replicas]
        idx, verdict = plan_placement(stats, 1, self.cfg)
        del idx
        return "overloaded" if verdict == "overloaded" else "ready"

    def begin_drain(self) -> None:
        """Stop admitting everywhere; non-blocking and signal-safe — the
        frontend registers this as an immediate PreemptionHandler hook."""
        self._draining = True
        for r in self.replicas:
            r.begin_drain()

    def drain(self, timeout: float | None = None) -> bool:
        """begin_drain + wait for every replica loop to finish inflight
        work and exit. True if all replicas stopped within the timeout."""
        self.begin_drain()
        ok = True
        for r in self.replicas:
            ok = r.join(timeout) and ok
        return ok

    # ------------------------------------------------------------ metrics
    def refresh_metrics(self) -> None:
        """Write current serving gauges into the telemetry registry (called
        at /metrics scrape time; no-op while telemetry is disabled)."""
        tel = get_telemetry()
        if not tel.enabled:
            return
        stats = [r.stats() for r in self.replicas]
        tel.gauge("serving_replicas").set(len(stats))
        tel.gauge("serving_replicas_live").set(
            sum(1 for s in stats if s.alive and not s.draining))
        tel.gauge("serving_queue_depth").set(sum(s.queued for s in stats))
        tel.gauge("serving_inflight").set(sum(s.inflight for s in stats))
        tel.gauge("serving_outstanding_tokens").set(
            sum(s.outstanding_tokens for s in stats))
        tel.gauge("serving_kv_free_blocks").set(
            sum(s.free_blocks for s in stats))
        tel.gauge("serving_kv_pending_blocks").set(
            sum(s.pending_blocks for s in stats))
        tel.gauge("serving_draining").set(1.0 if self._draining else 0.0)
