"""Disaggregated prefill/decode serving over the replica tier.

The single-replica serving path (router → EngineLoop → ragged engine) keeps
prefill and decode time-sliced inside one engine: a long prompt admitted
mid-stream steals whole SplitFuse budgets from every decoding request on the
same replica. This module splits the two phases across *role-tagged*
replicas — the DistServe/Splitwise shape, built from pieces the stack
already has:

- **Prefill replicas** run only the prompt (plus the first token, so the
  handoff is resumable at a real sampling boundary). The engine parks the
  finished request's KV blocks (``put(handoff=True)``) and
  ``export_handoff()`` turns them into a :class:`~deepspeed_tpu.inference.
  ragged.KVHandoff` record — block payloads plus the PR-4 device-row
  snapshot, so the decode side restores scheduler state with the same
  donated row-writer admission uses.
- **Decode replicas** ``adopt()`` the record: fresh blocks, one scatter,
  token-identical resume (per-request sampling keys depend only on
  ``(seed, gen_idx)``, never on which engine holds the sequence).
- A **cluster-wide prefix index** mirrors every replica's hash-chained
  prefix-cache keys (allocator publish/evict listeners), so the cluster
  sees prompt reuse on *any* replica. When the chosen prefill replica is
  cold but another replica holds the prefix, the cluster either routes the
  prompt stage to the holder (free, when the holder can take it) or ships
  the published blocks over the transfer channel — taken when the wire
  time beats re-prefilling the covered tokens
  (``tokens * bytes_per_token * 8 / gbps*1e9  <  tokens / prefill_tok_s``).
- A **decode-pool autoscaler** grows/shrinks between ``min``/``max``
  replicas on the PR-5 SLO burn-rate gauges, draining via the same
  ``begin_drain`` stop-hook elasticity uses for SIGTERM.

First cut is N replicas in one process: threaded EngineLoops sharing model
params, an in-memory transfer channel. The handoff record and the index
are deliberately transport-agnostic (numpy payloads, primitive metadata,
name-keyed holders) so a real RDMA/ICI channel can replace
:class:`InMemoryTransferChannel` without touching the engines.

The :class:`ServingCluster` duck-types the ``ReplicaRouter`` surface the
HTTP frontend consumes (submit/cancel/state/health/drain/metrics), so
``ServingFrontend(cluster)`` serves a disaggregated pool unchanged.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, replace

from deepspeed_tpu.serving.engine_loop import (
    EngineLoop,
    ReplicaDraining,
    TokenStream,
)
from deepspeed_tpu.serving.protocol import (
    FINISH_CANCELLED,
    CompletionRequest,
)
from deepspeed_tpu.serving.router import (
    Draining,
    Overloaded,
    ReplicaRouter,
    RouterConfig,
    plan_placement,
)
from deepspeed_tpu.telemetry import get_telemetry
from deepspeed_tpu.utils.logging import log_dist


@dataclass(frozen=True)
class ClusterConfig:
    """Knobs for the disaggregated serving tier (docs/SERVING.md)."""

    # decode-pool bounds the autoscaler moves between
    min_decode_replicas: int = 1
    max_decode_replicas: int = 4
    # SLO objectives whose burn rate drives scaling (max over them)
    autoscale_objectives: tuple = ("ttft", "decode_latency")
    # burn >= scale_up_burn grows the pool; burn <= scale_down_burn with
    # headroom shrinks it. 1.0 = exactly consuming the error budget.
    scale_up_burn: float = 1.0
    scale_down_burn: float = 0.25
    # dwell between autoscale actions (either direction)
    autoscale_cooldown_s: float = 30.0
    # --- transfer-vs-prefill cost model ---
    # modeled channel bandwidth (the in-memory channel is effectively
    # infinite; this models the real transport the record is designed for)
    transfer_gbps: float = 10.0
    # modeled prefill throughput of one replica, tokens/s
    prefill_tokens_per_s: float = 50000.0
    # allow shipping published prefix blocks between replicas at all
    enable_prefix_transfer: bool = True
    # per-stage wait bound (prefill collect / decode event gaps)
    stage_timeout_s: float = 300.0


def transfer_beats_prefill(tokens: int, bytes_per_token: int,
                           cfg: ClusterConfig) -> bool:
    """The bytes-vs-prefill-flops estimate: ship ``tokens`` worth of KV
    (``tokens * bytes_per_token`` bytes over the modeled channel) iff the
    wire time undercuts re-running prefill for those tokens. Conservative
    on unknowns: an unreported bandwidth or prefill rate (-1/0) must never
    transfer — a negative divisor would flip the inequality and claim a
    free wire.

    ``bytes_per_token`` comes from the holder engine's
    ``kv_bytes_per_token()``, measured over its actual cache pytree — with
    low-bit KV (``RaggedConfig.quant``, inference/kvquant.py) that is the
    quantized payload + scale bytes, so a ~2x smaller wire cost shifts this
    inequality toward transferring exactly as it should (and codec-matched
    import is enforced at the importer, not here)."""
    if tokens <= 0 or cfg.transfer_gbps <= 0 or cfg.prefill_tokens_per_s <= 0:
        return False
    wire_s = tokens * bytes_per_token * 8.0 / (cfg.transfer_gbps * 1e9)
    prefill_s = tokens / cfg.prefill_tokens_per_s
    return wire_s < prefill_s


class InMemoryTransferChannel:
    """Identity transfer with byte accounting — the single-process stand-in
    for a real KV transport. ``transfer()`` is called off the engine
    threads with a fully host-resident record, which is exactly the
    contract a remote channel needs (serialize, ship, deserialize)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.transfers = 0
        self.bytes_moved = 0
        self.seconds = 0.0

    def transfer(self, record):
        t0 = time.perf_counter()
        nbytes = int(getattr(record, "nbytes", 0))
        dt = time.perf_counter() - t0
        with self._lock:
            self.transfers += 1
            self.bytes_moved += nbytes
            self.seconds += dt
        tel = get_telemetry()
        if tel.enabled:
            tel.counter("kv_transfer_bytes_total",
                        "KV payload bytes moved between replicas"
                        ).inc(nbytes)
            tel.histogram("kv_transfer_seconds",
                          "per-record transfer channel latency").observe(dt)
        return record


class _IndexListener:
    """Bridges one engine's allocator publish/evict stream (engine thread)
    into the cluster index. Installed via ``engine.set_prefix_listener``;
    survives ``reset_state`` (the engine re-installs it and calls
    ``on_reset`` so the index drops this replica's stale keys)."""

    __slots__ = ("_index", "_name")

    def __init__(self, index: "ClusterPrefixIndex", name: str):
        self._index = index
        self._name = name

    def on_publish(self, key) -> None:
        self._index.publish(self._name, key)

    def on_evict(self, key) -> None:
        self._index.evict(self._name, key)

    def on_demote(self, key) -> None:
        # tiered engines: the key left HBM but stays restorable from the
        # replica's host/disk tiers — the index keeps the holder, marked
        # demoted, instead of dropping the entry (fired BEFORE the block id
        # is reusable, so the index never promises payload-less HBM blocks)
        self._index.demote(self._name, key)

    def on_reset(self) -> None:
        self._index.drop_replica(self._name)


class ClusterPrefixIndex:
    """Cluster-wide view of every replica's prefix cache.

    Same hash-chained keying as the per-replica index — keys are
    ``(parent_key, tuple(block_tokens))`` exact-token tuples, fed verbatim
    from allocator listeners — mapped to the replicas holding each chain
    link, each tagged with the TIER the holder keeps it in (0 = HBM,
    1 = demoted to the replica's host/disk tiers but restorable).
    ``best_holder`` walks a prompt's chain and returns the replica with the
    longest contiguous-from-root coverage — the only kind of coverage a
    splice can use — tie-broken toward the holder whose chain sits lowest
    in the hierarchy (HBM beats demoted: no restore cost on arrival)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._holders: dict = {}      # chain key -> {replica name: tier}
        self.hits = 0                 # lookups that found a holder
        self.misses = 0
        self.invalidations = 0        # key-holder pairs dropped by eviction
        self.demotions = 0            # key-holder pairs marked demoted

    # ----------------------------------------------- listener-facing edges
    def publish(self, name: str, key) -> None:
        # also the promotion edge: a demoted key restored to HBM republishes
        # through the allocator, which resets the holder's tier tag to 0
        with self._lock:
            self._holders.setdefault(key, {})[name] = 0

    def evict(self, name: str, key) -> None:
        with self._lock:
            hs = self._holders.get(key)
            if hs is None or name not in hs:
                return
            del hs[name]
            if not hs:
                del self._holders[key]
            self.invalidations += 1

    def demote(self, name: str, key) -> None:
        """The key left ``name``'s HBM for a lower tier: keep the holder —
        routing a request there still reuses the prefix (the replica
        restores it at admission) — but tag it so ties prefer HBM."""
        with self._lock:
            self._holders.setdefault(key, {})[name] = 1
            self.demotions += 1

    def drop_replica(self, name: str) -> int:
        """Forget every key ``name`` holds (replica reset/removed)."""
        dropped = 0
        with self._lock:
            for key in list(self._holders):
                hs = self._holders[key]
                if name in hs:
                    del hs[name]
                    dropped += 1
                    if not hs:
                        del self._holders[key]
            self.invalidations += dropped
        return dropped

    def listener_for(self, name: str) -> _IndexListener:
        return _IndexListener(self, name)

    # ------------------------------------------------------------- queries
    def best_holder(self, prompt, block_size: int,
                    exclude: frozenset = frozenset()) -> tuple[int, str | None]:
        """``(cached_tokens, holder)`` for the longest contiguous-from-root
        chain any single replica (outside ``exclude``) holds for ``prompt``.
        Capped one block short of the prompt like the engine's own match,
        so a full splice still leaves a real first-token forward."""
        prompt = [int(t) for t in prompt]
        n = max(0, (len(prompt) - 1) // block_size)
        best_n, best = 0, None
        cur: set | None = None
        cost: dict = {}  # replica -> total tier depth along its chain
        key = None
        with self._lock:
            for i in range(n):
                key = (key, tuple(prompt[i * block_size:(i + 1) * block_size]))
                hs = self._holders.get(key)
                if not hs:
                    break
                live = (set(hs) if cur is None else cur & set(hs)) - exclude
                if not live:
                    break
                cur = live
                for nm in live:
                    cost[nm] = cost.get(nm, 0) + hs[nm]
                # coverage first, then the cheapest chain (fewest demoted
                # links = least restore work on arrival), then name for
                # determinism
                best_n = i + 1
                best = min(live, key=lambda nm: (cost.get(nm, 0), nm))
        if best_n:
            self.hits += 1
        else:
            self.misses += 1
        return best_n * block_size, best

    def stats(self) -> dict:
        with self._lock:
            entries = len(self._holders)
            demoted = sum(1 for hs in self._holders.values()
                          for t in hs.values() if t > 0)
        return {"entries": entries, "demoted_entries": demoted,
                "hits": self.hits, "misses": self.misses,
                "invalidations": self.invalidations,
                "demotions": self.demotions}


@dataclass
class _Stage:
    """Per-request live-stage pointer so cancel() reaches the right loop."""

    loop: EngineLoop | None = None
    cancelled: bool = False
    via_router: bool = False  # cold path: router owns the placement


class ServingCluster:
    """Role-aware serving pool: the frontend-facing router surface over
    prefill replicas, decode replicas, a cluster prefix index, a KV
    transfer channel, and (optionally) a decode autoscaler.

    Duck-types ``ReplicaRouter`` for ``ServingFrontend``: ``submit`` runs
    the disaggregated two-stage flow (prefill → handoff → decode) when a
    live prefill replica exists and falls back to the plain single-replica
    path otherwise — and *mid-request* on any stage failure, relying on
    deterministic seeds to replay token-identically.
    """

    def __init__(self, prefill_loops: list[EngineLoop],
                 decode_loops: list[EngineLoop],
                 cfg: ClusterConfig | None = None,
                 router_cfg: RouterConfig | None = None,
                 channel=None):
        self.cfg = cfg or ClusterConfig()
        self.channel = channel or InMemoryTransferChannel()
        self.index = ClusterPrefixIndex()
        for lp in (*prefill_loops, *decode_loops):
            self._attach_index(lp)
        # one router over the WHOLE pool: its role-aware plan_placement
        # keeps whole requests (and failover resubmission) off prefill
        # replicas, while the cluster places prompt stages explicitly
        self.router = ReplicaRouter([*prefill_loops, *decode_loops],
                                    router_cfg)
        self._stages: dict[str, _Stage] = {}
        self._stage_lock = threading.Lock()
        # plain-int counters readable with telemetry off (bench pattern)
        self.disagg_requests = 0
        self.handoffs_ok = 0
        self.handoffs_failed = 0
        self.handoff_seconds = 0.0
        self.prefix_transfers = 0
        self.prefix_transfer_tokens = 0
        self.fallbacks: dict[str, int] = {}
        self.autoscale_events: list[dict] = []

    # --------------------------------------------------------- pool access
    def _attach_index(self, loop: EngineLoop) -> None:
        eng = loop._engine
        if hasattr(eng, "set_prefix_listener"):
            if loop._thread.ident is None:
                eng.set_prefix_listener(self.index.listener_for(loop.name))
            else:
                loop.call(lambda e: e.set_prefix_listener(
                    self.index.listener_for(loop.name)))

    def _pool(self, *roles) -> list[EngineLoop]:
        return [r for r in self.router._snapshot()[0] if r.role in roles]

    def _fallback(self, reason: str) -> None:
        self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1
        tel = get_telemetry()
        if tel.enabled:
            tel.counter(
                "cluster_fallbacks_total",
                "disaggregated requests rerouted to the cold path",
            ).inc(reason=reason)

    # ------------------------------------------------------------- submit
    def submit(self, req: CompletionRequest) -> TokenStream:
        """Frontend entry point. Admission control happens HERE (so 429/503
        raise synchronously like the plain router); the two-stage flow then
        runs on a worker thread feeding the returned stream."""
        prefill = [r for r in self._pool("prefill")
                   if r.stats().alive and not r.draining]
        if not prefill:
            # no dedicated prefill tier (or it drained away): plain path
            return self.router.submit(req)
        # decode-pool admission probe — same verdicts/raises as the router,
        # evaluated over the replicas that will own the decode phase
        stats = [r.stats() for r in self.router._snapshot()[0]]
        idx, verdict = plan_placement(stats, req.total_tokens,
                                      self.router.cfg)
        if idx is None:
            if verdict == "draining":
                raise Draining("no live decode replicas")
            tel = get_telemetry()
            if tel.enabled:
                tel.counter("serving_requests_rejected_total").inc()
            raise Overloaded(
                "decode pool past max_queue_tokens="
                f"{self.router.cfg.max_queue_tokens}",
                retry_after_s=self.router.cfg.retry_after_s)
        if not req.t_submit:
            req.t_submit = time.perf_counter()
        out = TokenStream(req.request_id)
        with self._stage_lock:
            self._stages[req.request_id] = _Stage()
        self.disagg_requests += 1
        tel = get_telemetry()
        if tel.enabled:
            tel.counter("cluster_disagg_requests_total",
                        "requests served via prefill/decode handoff").inc()
        worker = threading.Thread(
            target=self._serve_disagg, args=(req, out),
            name=f"cluster-{req.request_id[:12]}", daemon=True)
        worker.start()
        return out

    # ----------------------------------------------- disaggregated pipeline
    def _pick_prefill(self, req: CompletionRequest,
                      exclude: frozenset = frozenset()):
        """Least-outstanding placement over live prefill replicas, holder
        preference via each replica's local prefix probe (mirrors
        plan_placement's tie-break)."""
        pool = [r for r in self._pool("prefill")
                if r.name not in exclude]
        scored = []
        for r in pool:
            s = r.stats()
            if not s.alive or s.draining:
                continue
            scored.append((s.outstanding_tokens,
                           -r.cached_prefix_tokens(req.prompt), r.name, r))
        if not scored:
            return None
        return min(scored)[3]

    def _prefix_plan(self, req: CompletionRequest, chosen: EngineLoop,
                     exclude: frozenset = frozenset()):
        """Cluster-index consult for the prompt stage: route to the holder
        when a better-covered prefill replica exists (free), else ship the
        holder's published blocks to ``chosen`` when the wire beats
        re-prefilling the delta. Returns the (possibly re-routed) loop."""
        bs = chosen._block_size
        local = chosen.cached_prefix_tokens(req.prompt)
        matched, holder = self.index.best_holder(
            req.prompt, bs, exclude=exclude | frozenset((chosen.name,)))
        tel = get_telemetry()
        if tel.enabled:
            tel.counter(
                "cluster_prefix_hits_total" if matched
                else "cluster_prefix_misses_total",
                "prompt-stage lookups against the cluster prefix index",
            ).inc()
        if matched <= local or holder is None:
            return chosen
        by_name = {r.name: r for r in self.router._snapshot()[0]}
        holder_loop = by_name.get(holder)
        if holder_loop is None:
            return chosen
        hs = holder_loop.stats()
        if (holder_loop.role == "prefill" and hs.alive and not hs.draining):
            # routing is free: run the prompt stage where the blocks live
            return holder_loop
        # holder can't take prompt stages (decode role, or draining):
        # ship the blocks if the modeled wire time wins
        delta = matched - local
        if not self.cfg.enable_prefix_transfer:
            return chosen
        try:
            bpt = holder_loop.call(lambda e: e.kv_bytes_per_token())
            if not transfer_beats_prefill(delta, bpt, self.cfg):
                return chosen
            payload = holder_loop.call(
                lambda e: e.export_prefix(req.prompt,
                                          trace=req.trace_ctx))
            if payload is None:
                return chosen
            self.channel.transfer(payload)
            moved = chosen.call(lambda e: e.import_prefix(payload))
        except Exception as e:  # noqa: BLE001 - transfer is best-effort
            log_dist(f"cluster prefix transfer failed: {e}", ranks=[0])
            return chosen
        if moved:
            self.prefix_transfers += 1
            self.prefix_transfer_tokens += moved
            if tel.enabled:
                tel.counter(
                    "cluster_prefix_transfers_total",
                    "prefix-block payloads shipped between replicas",
                ).inc()
        return chosen

    def _serve_disagg(self, req: CompletionRequest, out: TokenStream) -> None:
        try:
            self._serve_disagg_inner(req, out)
        except Exception as e:  # noqa: BLE001 - the stream is the error path
            if out.finish_reason is None and out.error is None:
                out._fail(f"cluster pipeline failed: {e}", code=500,
                          reason="cluster_error")

    def _serve_disagg_inner(self, req: CompletionRequest,
                            out: TokenStream) -> None:
        rid = req.request_id
        stage = self._stages.get(rid) or _Stage()
        timeout = self.cfg.stage_timeout_s
        tel = get_telemetry()

        # ---- stage 1: prompt on a prefill replica -----------------------
        tried: set[str] = set()
        record = None
        while record is None:
            if stage.cancelled:
                out._finish(FINISH_CANCELLED)
                return
            chosen = self._pick_prefill(req, exclude=frozenset(tried))
            if chosen is None:
                self._fallback("no_prefill_replica")
                return self._serve_cold(req, out, skip=0)
            chosen = self._prefix_plan(req, chosen,
                                       exclude=frozenset(tried))
            tried.add(chosen.name)
            pre = replace(req)
            pre.handoff = True
            pre.stream = False
            pre.trace_ctx = req.trace_ctx
            pre.t_submit = req.t_submit
            pre.cached_tokens_hint = chosen.cached_prefix_tokens(req.prompt)
            try:
                pstream = chosen.submit(pre)
            except ReplicaDraining:
                continue
            stage.loop = chosen
            t_h0 = time.perf_counter()
            try:
                _, reason = pstream.collect(timeout=timeout)
            except Exception:  # noqa: BLE001 - structured detail on stream
                if pstream.error_reason in ("replica_died", "engine_crash"):
                    # mid-handoff replica death: nothing reached the client
                    # yet, so a fresh prefill replica (or the cold path)
                    # replays token-identically
                    continue
                self._fallback("prefill_failed")
                return self._serve_cold(req, out, skip=0)
            if reason not in ("length", "stop"):
                # cancelled/timeout during the prompt: the stage is the
                # request's terminal state (handoff parking only happens on
                # a finished prefill). "length" is the normal single-token
                # prefill finish; "stop" means the first token WAS eos (the
                # decode side will retire the import immediately).
                out._finish(reason)
                return
            try:
                record = chosen.call(lambda e: e.export_handoff(rid))
            except Exception:  # noqa: BLE001 - loop died around the call
                continue
            if record is None:
                # parked state vanished (cancel raced the finish)
                out._finish(FINISH_CANCELLED if stage.cancelled
                            else "cancelled")
                return
            dt = time.perf_counter() - t_h0
            self.handoff_seconds += dt
            if tel.enabled:
                tel.histogram(
                    "kv_handoff_seconds",
                    "prompt submit → exported handoff record").observe(dt)

        self.channel.transfer(record)

        # ---- stage 2: adopt on a decode replica -------------------------
        excluded: set[str] = set()
        while True:
            if stage.cancelled:
                out._finish(FINISH_CANCELLED)
                return
            pool = [(r, r.stats()) for r in self._pool("decode", "unified")]
            pool = [(r, s) for r, s in pool
                    if s.alive and not s.draining and r.name not in excluded]
            if not pool:
                self.handoffs_failed += 1
                if tel.enabled:
                    tel.counter("kv_handoffs_total",
                                "prefill→decode handoffs by result"
                                ).inc(result="no_decode_replica")
                self._fallback("no_decode_replica")
                return self._serve_cold(req, out, skip=0)
            idx, _ = plan_placement([s for _, s in pool], req.total_tokens,
                                    self.router.cfg,
                                    roles=("decode", "unified"))
            if idx is not None:
                dloop = pool[idx][0]
            else:
                # pool is past the queue bound: adopt on the least-loaded
                # anyway — the import itself gates on real block capacity
                dloop = min(pool, key=lambda t: t[1].outstanding_tokens)[0]
            try:
                dstream = dloop.adopt(req, record)
            except ReplicaDraining:
                excluded.add(dloop.name)
                continue
            stage.loop = dloop
            ok, delivered = self._pipe(dstream, out, req, skip=0)
            if ok:
                self.handoffs_ok += 1
                if tel.enabled:
                    tel.counter("kv_handoffs_total",
                                "prefill→decode handoffs by result"
                                ).inc(result="ok")
                return
            if dstream.error_reason == "import_rejected" and delivered == 0:
                excluded.add(dloop.name)
                continue
            # decode replica died mid-stream: deterministic seeds make a
            # cold replay token-identical; skip what was already delivered
            self.handoffs_failed += 1
            if tel.enabled:
                tel.counter("kv_handoffs_total",
                            "prefill→decode handoffs by result"
                            ).inc(result="failed")
            self._fallback("decode_died")
            return self._serve_cold(req, out, skip=delivered)

    def _pipe(self, src: TokenStream, out: TokenStream,
              req: CompletionRequest, skip: int) -> tuple[bool, int]:
        """Forward ``src`` events into ``out``, skipping the first ``skip``
        tokens (already on the wire before a failover). Returns
        ``(finished_cleanly, tokens_delivered_to_out)``."""
        delivered = 0
        seen = 0
        try:
            for kind, value in src.events(timeout=self.cfg.stage_timeout_s):
                if kind == "token":
                    seen += 1
                    if seen <= skip:
                        continue
                    out._push(value)
                    delivered += 1
                elif kind == "done":
                    out._finish(value)
                    return True, delivered
                else:
                    return False, delivered
        except TimeoutError:
            self.router.cancel(req.request_id)
            out._fail(
                f"request {req.request_id}: no decode progress within "
                f"{self.cfg.stage_timeout_s:g}s", code=504, reason="timeout")
            return True, delivered  # terminal: don't fall back again
        return False, delivered

    def _serve_cold(self, req: CompletionRequest, out: TokenStream,
                    skip: int) -> None:
        """Cold fallback: the plain router path (decode/unified pool),
        splicing over anything already delivered."""
        stage = self._stages.get(req.request_id) or _Stage()
        stage.via_router = True
        stage.loop = None
        try:
            stream = self.router.submit(req)
        except Overloaded as e:
            out._fail(str(e), code=429, reason="overloaded")
            return
        except Exception as e:  # noqa: BLE001 - draining, protocol, ...
            out._fail(str(e), code=503, reason="fallback_failed")
            return
        while True:
            ok, n = self._pipe(stream, out, req, skip=skip)
            if ok:
                return
            skip += n
            if stream.error_reason in ("replica_died", "engine_crash"):
                replay = self.router.resubmit(req)
                if replay is not None:
                    stream = replay
                    continue
            out._fail(stream.error or "fallback stream failed",
                      code=stream.error_code or 500,
                      reason=stream.error_reason or "fallback_failed")
            return

    # ------------------------------------------- router-compatible surface
    def resubmit(self, req: CompletionRequest):
        return self.router.resubmit(req)

    def cancel(self, request_id: str) -> None:
        with self._stage_lock:
            stage = self._stages.get(request_id)
        if stage is not None:
            stage.cancelled = True
            if stage.loop is not None:
                stage.loop.cancel(request_id)
            if stage.via_router:
                self.router.cancel(request_id)
        else:
            self.router.cancel(request_id)

    def release(self, request_id: str) -> None:
        with self._stage_lock:
            self._stages.pop(request_id, None)
        self.router.release(request_id)

    def state(self) -> str:
        return self.router.state()

    def health(self) -> list[dict]:
        return self.router.health()

    def begin_drain(self) -> None:
        self.router.begin_drain()

    def drain(self, timeout: float | None = None) -> bool:
        return self.router.drain(timeout)

    def tier_stats(self) -> dict:
        return self.router.tier_stats()

    def refresh_metrics(self) -> None:
        self.router.refresh_metrics()
        tel = get_telemetry()
        if not tel.enabled:
            return
        idx = self.index.stats()
        tel.gauge("cluster_prefix_index_entries",
                  "chain keys tracked by the cluster prefix index"
                  ).set(idx["entries"])
        tel.gauge("cluster_prefix_invalidations",
                  "key-holder pairs dropped by eviction/reset"
                  ).set(idx["invalidations"])

    # ------------------------------------------------------------- summary
    def cluster_stats(self) -> dict:
        """Cluster-level observability block (embedded in /healthz and the
        disagg bench JSON)."""
        roles: dict[str, int] = {}
        spec_p = spec_a = 0
        for r in self.router._snapshot()[0]:
            roles[r.role] = roles.get(r.role, 0) + 1
            spec_p += int(getattr(r._engine, "spec_proposed", 0))
            spec_a += int(getattr(r._engine, "spec_accepted", 0))
        return {
            "roles": roles,
            # self-speculative decode economy pooled across replicas (draft
            # history itself is NOT part of the handoff record: the decode
            # side rebuilds it from prompt+generated on adoption)
            "speculation": {"proposed": spec_p, "accepted": spec_a,
                            "acceptance_rate": spec_a / max(spec_p, 1)},
            "prefix_index": self.index.stats(),
            "disagg_requests": self.disagg_requests,
            "handoffs": {"ok": self.handoffs_ok,
                         "failed": self.handoffs_failed,
                         "seconds": self.handoff_seconds},
            "prefix_transfers": self.prefix_transfers,
            "prefix_transfer_tokens": self.prefix_transfer_tokens,
            "kv_transfer": {"transfers": self.channel.transfers,
                            "bytes": self.channel.bytes_moved,
                            "seconds": self.channel.seconds},
            "fallbacks": dict(self.fallbacks),
            "autoscale_events": list(self.autoscale_events),
        }


class DecodeAutoscaler:
    """Grow/shrink the decode pool on SLO burn rate (PR-5 gauges).

    ``tick()`` is the whole policy — call it from a cron, the bench loop,
    or ``start()``'s background thread. Scale-up spawns a replica via the
    factory and splices it into the router + cluster index; scale-down
    drains the least-loaded decode replica through the elasticity
    stop-hook path (``begin_drain`` → join → remove) so in-flight decodes
    finish untouched."""

    def __init__(self, cluster: ServingCluster, factory,
                 cfg: ClusterConfig | None = None, burn_fn=None):
        self.cluster = cluster
        self.factory = factory          # name -> EngineLoop(role="decode")
        self.cfg = cfg or cluster.cfg
        self._burn_fn = burn_fn
        self._last_action = 0.0
        self._spawned = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._waiters: list[threading.Thread] = []

    # --------------------------------------------------------------- input
    def _burn(self) -> float | None:
        """Max burn rate across the configured objectives; None when no
        objective has enough samples to act on."""
        if self._burn_fn is not None:
            return self._burn_fn()
        slo = get_telemetry().slo
        if slo is None:
            return None
        from deepspeed_tpu.telemetry.slo import MIN_SAMPLES
        burns = []
        for name in self.cfg.autoscale_objectives:
            try:
                s = slo.stats(name)
            except Exception:  # noqa: BLE001 - unknown objective
                continue
            if s and s.get("count", 0) >= MIN_SAMPLES:
                burns.append(float(s.get("burn_rate", 0.0)))
        return max(burns) if burns else None

    def _decode_pool(self) -> list[EngineLoop]:
        return [r for r in self.cluster.router._snapshot()[0]
                if r.role == "decode" and not r.draining]

    # -------------------------------------------------------------- policy
    def tick(self, now: float | None = None) -> int:
        """One policy evaluation: returns +1 (scaled up), -1 (scaled
        down), or 0. Honors min/max bounds and the cooldown dwell."""
        now = time.perf_counter() if now is None else now
        if now - self._last_action < self.cfg.autoscale_cooldown_s:
            return 0
        burn = self._burn()
        if burn is None:
            return 0
        pool = self._decode_pool()
        if (burn >= self.cfg.scale_up_burn
                and len(pool) < self.cfg.max_decode_replicas):
            self._scale_up(now, burn)
            return 1
        if (burn <= self.cfg.scale_down_burn
                and len(pool) > self.cfg.min_decode_replicas):
            self._scale_down(now, burn, pool)
            return -1
        return 0

    def _record(self, direction: str, burn: float, replica: str) -> None:
        self.cluster.autoscale_events.append(
            {"direction": direction, "burn": round(burn, 4),
             "replica": replica})
        tel = get_telemetry()
        if tel.enabled:
            tel.counter("cluster_autoscale_events_total",
                        "decode-pool scale actions").inc(direction=direction)
            tel.gauge("cluster_replicas", "pool size by role").set(
                len(self._decode_pool()), role="decode")

    def _scale_up(self, now: float, burn: float) -> None:
        self._spawned += 1
        name = f"decode-auto-{self._spawned}"
        loop = self.factory(name)
        if loop._thread.ident is None:
            loop.start()
        self.cluster._attach_index(loop)
        self.cluster.router.add_replica(loop)
        self._last_action = now
        self.scale_ups += 1
        self._record("up", burn, name)
        log_dist(f"autoscaler: +{name} (burn {burn:.2f})", ranks=[0])

    def _scale_down(self, now: float, burn: float,
                    pool: list[EngineLoop]) -> None:
        victim = min(pool, key=lambda r: r.stats().outstanding_tokens)
        victim.begin_drain()  # the elasticity stop-hook drain path
        self.cluster.router.remove_replica(victim)
        self._last_action = now
        self.scale_downs += 1
        self._record("down", burn, victim.name)
        log_dist(f"autoscaler: draining {victim.name} (burn {burn:.2f})",
                 ranks=[0])

        def _reap():
            victim.join(timeout=self.cfg.stage_timeout_s)
            self.cluster.index.drop_replica(victim.name)

        t = threading.Thread(target=_reap, name=f"reap-{victim.name}",
                             daemon=True)
        t.start()
        self._waiters.append(t)

    # ---------------------------------------------------------- background
    def start(self, interval_s: float = 5.0) -> "DecodeAutoscaler":
        self._thread = threading.Thread(
            target=self._run, args=(float(interval_s),),
            name="decode-autoscaler", daemon=True)
        self._thread.start()
        return self

    def _run(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 - keep the loop alive
                log_dist(f"autoscaler tick failed: {e}", ranks=[0])

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        for t in self._waiters:
            t.join(timeout=10.0)


def build_cluster_server(prefill_engines, decode_engines,
                         host: str = "127.0.0.1", port: int = 0,
                         cluster_cfg: ClusterConfig | None = None,
                         router_cfg: RouterConfig | None = None,
                         start: bool = True, fleet_dir: str | None = None):
    """Convenience mirror of ``frontend.build_server`` for a disaggregated
    pool: wrap engines in role-tagged loops, build the cluster, bind the
    HTTP frontend on it. Returns ``(frontend, cluster, loops)``.
    ``fleet_dir`` additionally serves the federated ``/metrics/fleet`` +
    ``/debug/fleet`` rollup over that snapshot directory."""
    from deepspeed_tpu.serving.frontend import ServingFrontend

    pre = [EngineLoop(e, name=f"prefill-{i}", role="prefill")
           for i, e in enumerate(prefill_engines)]
    dec = [EngineLoop(e, name=f"decode-{i}", role="decode")
           for i, e in enumerate(decode_engines)]
    cluster = ServingCluster(pre, dec, cfg=cluster_cfg,
                             router_cfg=router_cfg)
    frontend = ServingFrontend(cluster, host=host, port=port,
                               fleet_dir=fleet_dir)
    if start:
        for lp in (*pre, *dec):
            lp.start()
        frontend.start()
    return frontend, cluster, (*pre, *dec)
