"""Per-replica step-loop driver: the thread that owns a RaggedInferenceEngine.

The ragged engine (``inference/ragged.py``) is a pull-driven scheduler —
someone must pump ``put()``/``step()`` — and it is not thread-safe. The
``EngineLoop`` makes it servable: one background thread owns the engine
outright, requests arrive through a bounded priority inbox, emitted tokens
are delivered to per-request ``TokenStream`` queues as each step completes,
and graceful drain (stop admitting, finish inflight, exit) hooks into the
same SIGTERM path as ``elasticity.PreemptionHandler``.

Cross-thread surface, by design minimal:

- ``submit()``/``cancel()`` mutate only the inbox under its lock and set a
  wake event; the loop thread does every ``engine.*`` call.
- ``stats()`` combines the loop thread's last published engine snapshot
  (an immutable tuple swap — no lock on the hot path) with the live inbox
  counters, giving the router a conservative view for placement/admission.
"""

from __future__ import annotations

import heapq
import itertools
import queue
import threading
import time
from dataclasses import dataclass

from deepspeed_tpu.serving.faults import POINT_LOOP, get_fault_injector
from deepspeed_tpu.serving.protocol import (
    FINISH_CANCELLED,
    FINISH_LENGTH,
    FINISH_STOP,
    CompletionRequest,
)
from deepspeed_tpu.telemetry import get_telemetry
from deepspeed_tpu.utils.logging import log_dist


class StreamError(RuntimeError):
    """The request failed server-side (validation or engine error)."""


class ReplicaDraining(RuntimeError):
    """submit() after begin_drain(): the replica no longer admits work."""


class TokenStream:
    """Consumer handle for one request's token stream.

    The loop thread pushes ``("token", id)`` events and exactly one terminal
    ``("done", finish_reason)`` or ``("error", message)``; consumers iterate
    ``events()`` (SSE path) or block on ``collect()`` (non-streaming path).
    """

    def __init__(self, request_id: str):
        self.request_id = request_id
        self.finish_reason: str | None = None
        self.error: str | None = None
        # structured failure detail: an HTTP-equivalent status and a
        # machine-readable reason ("replica_died", "engine_crash",
        # "deadline", ...) so the frontend can map the error to the right
        # response and the router can decide whether failover is sound
        self.error_code: int | None = None
        self.error_reason: str | None = None
        self._q: queue.SimpleQueue = queue.SimpleQueue()

    # ---------------------------------------------- producer (loop thread)
    def _push(self, token: int) -> None:
        self._q.put(("token", int(token)))

    def _finish(self, reason: str) -> None:
        self.finish_reason = reason
        self._q.put(("done", reason))

    def _fail(self, message: str, code: int | None = None,
              reason: str | None = None) -> None:
        self.error = message
        self.error_code = code
        self.error_reason = reason
        self._q.put(("error", message))

    # ---------------------------------------------------------- consumer
    def events(self, timeout: float | None = None):
        """Yield ``("token", id)`` events until the terminal ``("done", _)``
        / ``("error", _)`` event, which is yielded last. ``timeout`` bounds
        the wait for EACH event (TimeoutError past it)."""
        while True:
            try:
                kind, value = self._q.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"request {self.request_id}: no event within {timeout}s"
                ) from None
            yield kind, value
            if kind in ("done", "error"):
                return

    def collect(self, timeout: float | None = None) -> tuple[list[int], str]:
        """Block until terminal; returns ``(tokens, finish_reason)`` or
        raises StreamError / TimeoutError."""
        tokens: list[int] = []
        for kind, value in self.events(timeout=timeout):
            if kind == "token":
                tokens.append(value)
            elif kind == "error":
                raise StreamError(value)
            else:
                return tokens, value
        raise StreamError(f"request {self.request_id}: stream ended abruptly")


@dataclass(frozen=True)
class ReplicaStats:
    """Router-facing snapshot of one replica (conservative: inbox work not
    yet visible to the engine counts as queued/pending)."""

    name: str
    alive: bool
    draining: bool
    queued: int               # engine queue + undrained inbox
    inflight: int             # admitted (running) sequences
    outstanding_tokens: int   # remaining prompt+decode tokens across all work
    free_blocks: int          # unreserved free KV blocks in the engine pool
    pending_blocks: int       # worst-case blocks promised to inbox requests
    block_size: int
    usable_blocks: int        # pool size minus the scratch block
    max_request_blocks: int   # per-request block ceiling (put() rejects past it)
    max_request_tokens: int   # engine max_seq_len
    degraded: int = 0         # engine degraded_mode rung (0 = full path)
    crashes: int = 0          # step exceptions contained by the loop
    respawns: int = 0         # loop-thread deaths survived by respawn
    # disaggregated serving: "prefill" replicas only run prompt stages
    # (handoff exports), "decode" replicas adopt handoffs; "unified" does
    # everything. plan_placement() filters on this.
    role: str = "unified"
    # multi-step scheduled decode: dispatches per emitted token (1.0 for a
    # per-token engine; the device-side scheduler drives it toward 1/K) and
    # the self-speculation draft economy, for cluster-level observability
    dispatches_per_token: float = 1.0
    spec_proposed: int = 0
    spec_accepted: int = 0
    # measured free-byte headroom expressed in KV blocks (-1 = backend does
    # not report memory limits; routers fall back to the static block math)
    headroom_blocks: int = -1

    def worst_blocks(self, total_tokens: int) -> int:
        return -(-total_tokens // self.block_size)


class _Open:
    """Loop-thread bookkeeping for one in-engine request."""

    __slots__ = ("stream", "delivered")

    def __init__(self, stream: TokenStream):
        self.stream = stream
        self.delivered = 0


class EngineLoop:
    """Background driver for one RaggedInferenceEngine replica."""

    def __init__(self, engine, name: str = "replica-0",
                 idle_wait_s: float = 0.002, max_respawns: int = 3,
                 role: str = "unified"):
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r}")
        self._engine = engine
        self.name = name
        self.role = role
        self._idle_wait_s = float(idle_wait_s)
        self._max_respawns = int(max_respawns)
        self._faults = get_fault_injector()
        # fault-tolerance counters: crash_count = step exceptions contained
        # in-place (affected requests failed, engine state rebuilt, loop
        # keeps running); respawn_count = loop-thread deaths survived by
        # starting a replacement thread
        self.crash_count = 0
        self.respawn_count = 0
        # monotonically increasing count of successful engine steps; polled
        # by devprof.capture_serving to bound /debug/profile windows in
        # steps rather than wall time
        self.steps = 0
        self._consec_crashes = 0
        self._lock = threading.Lock()
        self._inbox: list = []       # heap of (priority, seqno, req, stream)
        self._seqno = itertools.count()
        self._cancel_ids: set[str] = set()
        self._pending_blocks = 0
        self._pending_tokens = 0
        self._open: dict[str, _Open] = {}
        # cross-thread engine calls (cluster KV export/import): the loop
        # thread runs each entry's first element against the engine; the
        # second is the drop handler invoked if the loop dies first
        self._pending_calls: list = []
        self._wake = threading.Event()
        self._draining = threading.Event()
        self._stopped = threading.Event()
        # alive = "has not died": true from construction so a cold (not yet
        # started) loop can accumulate queued work, false once _run exits
        self._alive = True
        self.error: str | None = None
        self._thread = threading.Thread(
            target=self._run, name=f"engine-loop-{name}", daemon=True)
        cfg = engine.cfg
        self._block_size = cfg.block_size
        self._usable_blocks = cfg.num_blocks - 1
        self._max_request_blocks = min(cfg.num_blocks - 1,
                                       cfg.max_blocks_per_seq)
        self._max_request_tokens = cfg.max_seq_len
        # (queued, inflight, outstanding_tokens, free_unreserved_blocks):
        # published by the loop thread as an atomic tuple swap
        self._engine_stats = (0, 0, 0, engine.allocator.free_blocks)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "EngineLoop":
        self._thread.start()
        return self

    def begin_drain(self) -> None:
        """Stop admitting; the loop finishes inflight work then exits.
        Non-blocking and signal-safe (flag flips only) — registrable as an
        ``immediate`` PreemptionHandler callback."""
        self._draining.set()
        self._wake.set()

    def join(self, timeout: float | None = None) -> bool:
        """Wait for the loop to exit (after ``begin_drain``). Waits on the
        ``_stopped`` event, not the thread handle: a respawn swaps
        ``self._thread`` for a replacement, and only final death (or clean
        drain) sets ``_stopped``."""
        if self._stopped.is_set():
            return True
        if self._thread.ident is None:  # never started: nothing will run
            return True
        return self._stopped.wait(timeout)

    def close(self, timeout: float | None = 30.0) -> bool:
        self.begin_drain()
        return self.join(timeout)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    # -------------------------------------------------------------- submit
    def _worst_blocks(self, req: CompletionRequest) -> int:
        return -(-req.total_tokens // self._block_size)

    def submit(self, req: CompletionRequest) -> TokenStream:
        """Enqueue a request; returns its TokenStream immediately. The
        actual ``engine.put()`` happens on the loop thread (priority order,
        lower first). Raises ReplicaDraining after ``begin_drain``."""
        if self._draining.is_set():
            raise ReplicaDraining(f"{self.name} is draining")
        if not req.t_submit:
            # stamp here (not only in the frontend) so deadline-aware inbox
            # shedding measures queue wait for direct submitters too
            req.t_submit = time.perf_counter()
        stream = TokenStream(req.request_id)
        with self._lock:
            heapq.heappush(
                self._inbox, (req.priority, next(self._seqno), req, stream))
            self._pending_blocks += self._worst_blocks(req)
            self._pending_tokens += req.total_tokens
        self._wake.set()
        return stream

    def cancel(self, request_id: str) -> None:
        """Abort a request wherever it is (inbox, queued, or running); its
        stream terminates with finish_reason=cancelled and KV blocks free on
        the loop's next step."""
        with self._lock:
            self._cancel_ids.add(request_id)
        self._wake.set()

    def cached_prefix_tokens(self, prompt_tokens) -> int:
        """Tokens of ``prompt_tokens`` the engine's prefix cache could serve
        right now. An ADVISORY cross-thread probe: it reads the engine's
        prefix index without locking (dict reads are atomic in CPython, and
        the router only uses the answer to bias placement/admission — a
        stale answer costs one conservative decision, never correctness).
        Engines without a prefix cache (or with it disabled) report 0."""
        probe = getattr(self._engine, "cached_prefix_len", None)
        if probe is None:
            return 0
        try:
            return int(probe(prompt_tokens))
        except Exception:  # noqa: BLE001 - advisory: racing a mutation is fine
            return 0

    def prefetch_prefix(self, prompt_tokens) -> None:
        """Advisory tier-prefetch kick: ask the engine's KV tier store to
        stage any demoted blocks of ``prompt_tokens`` disk→host while the
        request waits in the queue. Fire-and-forget from the router thread —
        the method is thread-safe on the engine side (it only touches the
        tier store's own lock plus racy-safe dict probes), and a missed or
        stale prefetch costs latency, never correctness."""
        kick = getattr(self._engine, "tier_prefetch_async", None)
        if kick is None:
            return
        try:
            kick(prompt_tokens)
        except Exception:  # noqa: BLE001 - advisory: never fail a submit
            pass

    def kv_tier_stats(self):
        """Tier-store counters/bytes for this replica, or None when tiering
        is off. Advisory cross-thread read (plain ints + dict builds)."""
        probe = getattr(self._engine, "kv_tier_stats", None)
        if probe is None:
            return None
        try:
            return probe()
        except Exception:  # noqa: BLE001 - advisory
            return None

    # --------------------------------- cross-thread engine calls (cluster)
    def call(self, fn, timeout: float | None = 30.0):
        """Run ``fn(engine)`` on the loop thread and return its result.

        The engine is single-owner (the loop thread does every ``engine.*``
        call), so the cluster's KV handoff/prefix transfers go through here
        instead of touching the engine directly. On a loop whose thread was
        never started the call runs inline (the caller is the only owner —
        the unit-test convenience). Raises ``fn``'s exception, TimeoutError
        past ``timeout``, or RuntimeError if the loop dies/exits before
        servicing the call."""
        if self._stopped.is_set():
            raise RuntimeError(f"{self.name}: loop is stopped")
        if self._thread.ident is None:
            return fn(self._engine)
        box: dict = {}
        done = threading.Event()

        def run(eng):
            try:
                box["value"] = fn(eng)
            except BaseException as e:  # noqa: BLE001 - re-raised at caller
                box["exc"] = e
            finally:
                done.set()

        def drop(msg: str):
            box["exc"] = RuntimeError(msg)
            done.set()

        with self._lock:
            self._pending_calls.append((run, drop))
        self._wake.set()
        if not done.wait(timeout):
            raise TimeoutError(
                f"{self.name}: engine call not serviced within {timeout}s")
        if "exc" in box:
            raise box["exc"]
        return box.get("value")

    def adopt(self, req: CompletionRequest, handoff) -> TokenStream:
        """Adopt a prefill replica's handoff record as a live request.

        The loop thread imports the KV payload (``engine.import_handoff``);
        the returned stream then carries the WHOLE generation — the prefill
        stage's first token included, since delivery starts at generated
        index 0 and the record's ``generated`` seeds it. On rejection (no
        slot/blocks right now, or a record this engine can never fit) the
        stream fails with ``reason="import_rejected"`` so the cluster can
        fall back to a cold submit."""
        if self._draining.is_set():
            raise ReplicaDraining(f"{self.name} is draining")
        stream = TokenStream(req.request_id)
        rid = req.request_id

        def _do(eng):
            if self._draining.is_set():
                stream._fail(f"{self.name} is draining", code=503,
                             reason="import_rejected")
                return
            try:
                ok = eng.import_handoff(handoff)
            except Exception as e:  # noqa: BLE001 - structurally unservable
                stream._fail(f"handoff import failed on {self.name}: {e}",
                             code=503, reason="import_rejected")
                return
            if not ok:
                stream._fail(
                    f"{self.name}: no slot/blocks to adopt handoff {rid}",
                    code=503, reason="import_rejected")
                return
            self._open[rid] = _Open(stream)

        def drop(msg: str):
            stream._fail(msg, code=503, reason="replica_died")

        if self._stopped.is_set():
            drop(f"{self.name}: loop is stopped")
            return stream
        if self._thread.ident is None:
            _do(self._engine)
            return stream
        with self._lock:
            self._pending_calls.append((_do, drop))
        self._wake.set()
        return stream

    # --------------------------------------------------------------- stats
    def stats(self) -> ReplicaStats:
        queued, inflight, outstanding, free = self._engine_stats
        with self._lock:
            n_inbox = len(self._inbox)
            pending_blocks = self._pending_blocks
            pending_tokens = self._pending_tokens
        return ReplicaStats(
            name=self.name, alive=self._alive,
            draining=self._draining.is_set(),
            queued=queued + n_inbox, inflight=inflight,
            outstanding_tokens=outstanding + pending_tokens,
            free_blocks=free, pending_blocks=pending_blocks,
            block_size=self._block_size, usable_blocks=self._usable_blocks,
            max_request_blocks=self._max_request_blocks,
            max_request_tokens=self._max_request_tokens,
            degraded=int(getattr(self._engine, "degraded_mode", 0)),
            crashes=self.crash_count, respawns=self.respawn_count,
            role=self.role,
            dispatches_per_token=(
                getattr(self._engine, "dispatch_count", 0)
                / max(getattr(self._engine, "tokens_emitted", 0), 1)),
            spec_proposed=int(getattr(self._engine, "spec_proposed", 0)),
            spec_accepted=int(getattr(self._engine, "spec_accepted", 0)),
            headroom_blocks=int(getattr(
                self._engine, "admission_headroom_blocks", lambda: -1)()))

    # ------------------------------------------------------- loop internals
    def _drain_inbox(self) -> None:
        eng = self._engine
        with self._lock:
            items = [heapq.heappop(self._inbox) for _ in range(len(self._inbox))]
            cancels = self._cancel_ids
            self._cancel_ids = set()
        for _, _, req, stream in items:
            rid = req.request_id
            if rid in cancels:
                cancels.discard(rid)
                stream._finish(FINISH_CANCELLED)
            elif (req.deadline_s is not None and req.t_submit
                  and time.perf_counter() - req.t_submit >= req.deadline_s):
                # deadline already burned in the inbox: shed instead of
                # dispatching doomed work (504-equivalent structured error)
                stream._fail(
                    f"request {rid}: deadline_s={req.deadline_s} expired "
                    f"before placement on {self.name}",
                    code=504, reason="deadline")
                tel = get_telemetry()
                if tel.enabled:
                    tel.counter(
                        "serving_requests_shed_total",
                        "expired-deadline requests shed pre-placement",
                    ).inc(replica=self.name)
            else:
                if req.trace_ctx is not None and req.t_submit:
                    # frontend submit → loop-thread pickup: the cross-thread
                    # inbox wait, recorded retroactively from the stamp
                    get_telemetry().tracer.record(
                        req.trace_ctx, "loop/inbox_wait", req.t_submit,
                        time.perf_counter(), replica=self.name,
                        priority=req.priority)
                try:
                    eng.put(rid, req.prompt, max_new_tokens=req.max_tokens,
                            eos_token_id=req.eos_token_id,
                            temperature=req.temperature, top_k=req.top_k,
                            top_p=req.top_p, deadline_s=req.deadline_s,
                            seed=req.seed, trace=req.trace_ctx,
                            handoff=getattr(req, "handoff", False),
                            expected_cached_tokens=getattr(
                                req, "cached_tokens_hint", 0),
                            tenant=getattr(req, "tenant", "default"),
                            sla_class=getattr(
                                req, "sla_class", "interactive"))
                    self._open[rid] = _Open(stream)
                except ValueError as e:
                    stream._fail(str(e))
            with self._lock:
                self._pending_blocks -= self._worst_blocks(req)
                self._pending_tokens -= req.total_tokens
        for rid in cancels:
            eng.cancel(rid)  # unknown/already-retired ids are a no-op

    def _finish_reason(self, seq) -> str:
        if seq.status != "finished":
            return seq.status  # cancelled | timeout
        if (seq.eos_token_id is not None and seq.generated
                and seq.generated[-1] == seq.eos_token_id):
            return FINISH_STOP
        return FINISH_LENGTH

    def _deliver(self) -> None:
        eng = self._engine
        for rid in list(self._open):
            op = self._open[rid]
            seq = eng.get_request(rid)
            if seq is None:  # pragma: no cover - put() succeeded, must exist
                op.stream._fail(f"request {rid} lost by engine")
                del self._open[rid]
                continue
            gen = seq.generated
            while op.delivered < len(gen):
                op.stream._push(gen[op.delivered])
                op.delivered += 1
            if rid in eng._results:
                op.stream._finish(self._finish_reason(seq))
                del self._open[rid]

    def _publish_stats(self) -> None:
        eng = self._engine
        outstanding = 0
        for s in eng._queued:
            outstanding += len(s.prompt) + s.max_new_tokens
        for s in eng._running.values():
            # Under async readback (device-resident dispatch, fused pipeline)
            # s.pos runs ahead of len(s.generated) by the in-flight window;
            # tokens already scheduled on device are progress, not load the
            # admission controller should throttle on.
            progress = max(len(s.generated), s.pos - len(s.prompt))
            outstanding += max(0, len(s.prompt) - s.pos) + \
                max(0, s.max_new_tokens - progress)
        self._engine_stats = (
            len(eng._queued), len(eng._running), outstanding,
            eng.allocator.free_blocks - eng._reserved)
        tel = get_telemetry()
        if tel.enabled:
            # per-priority inbox depth (docs/OBSERVABILITY.md): the default
            # priority-0 row always publishes (so an empty inbox scrapes as
            # an explicit 0, not an absent series), other priorities appear
            # on first use and are zeroed — not left frozen — when they
            # empty out
            with self._lock:
                depths: dict[int, int] = {}
                for prio, _, _, _ in self._inbox:
                    depths[prio] = depths.get(prio, 0) + 1
            last = getattr(self, "_last_inbox_depths", None)
            g = tel.gauge("serving_inbox_depth",
                          "requests waiting in the loop inbox, "
                          "by priority")
            for prio in (set(depths) | set(last or ()) | {0}):
                g.set(depths.get(prio, 0),
                      replica=self.name, priority=str(prio))
            self._last_inbox_depths = depths

    def _contain(self, exc: Exception) -> None:
        """Crash containment for one failed ``engine.step()``: fail only the
        affected requests with a structured error, rebuild the poisoned
        engine state, and keep the loop running. Repeated back-to-back
        crashes escalate to loop death (handled by ``_run``'s respawn)."""
        self.crash_count += 1
        self._consec_crashes += 1
        if self._consec_crashes > self._max_respawns:
            raise exc  # containment is not converging — escalate
        msg = (f"engine step crashed on {self.name}: "
               f"{type(exc).__name__}: {exc}")
        log_dist(f"{msg} (contained; rebuilding engine state)", ranks=[0])
        tel = get_telemetry()
        if tel.enabled:
            tel.counter(
                "engine_loop_crashes_total",
                "engine.step() exceptions contained by the loop",
            ).inc(replica=self.name)
        try:
            self._deliver()  # flush tokens/finishes that predate the crash
        except Exception:  # noqa: BLE001 - engine state may be poisoned
            pass
        for op in self._open.values():
            op.stream._fail(msg, code=500, reason="engine_crash")
        self._open.clear()
        self._engine.reset_state()
        self._publish_stats()

    def _drain_calls(self) -> None:
        with self._lock:
            calls, self._pending_calls = self._pending_calls, []
        for run, _ in calls:
            run(self._engine)  # run() boxes fn's exceptions for the caller

    def _drop_calls(self, msg: str) -> None:
        with self._lock:
            calls, self._pending_calls = self._pending_calls, []
        for _, drop in calls:
            drop(msg)

    def _run_loop(self) -> None:
        eng = self._engine
        while True:
            self._drain_inbox()
            self._drain_calls()
            if eng.has_work:
                if self._faults.enabled:
                    # outside the try: an injected loop fault kills the
                    # thread (exercising respawn), engine faults exercise
                    # containment. Idle replicas never reach this point,
                    # which keeps chaos schedules deterministic.
                    self._faults.fire(POINT_LOOP)
                try:
                    eng.step()
                except Exception as e:  # noqa: BLE001 - contain, don't die
                    self._contain(e)
                else:
                    self._consec_crashes = 0
                    self.steps += 1
                self._deliver()
                self._publish_stats()
                continue
            self._deliver()
            self._publish_stats()
            with self._lock:
                idle = (not self._inbox and not self._cancel_ids
                        and not self._pending_calls)
            if idle and self._draining.is_set():
                return
            self._wake.wait(self._idle_wait_s)
            self._wake.clear()

    def _fail_all(self, msg: str, code: int, reason: str) -> None:
        for op in self._open.values():
            op.stream._fail(msg, code=code, reason=reason)
        self._open.clear()
        with self._lock:
            items, self._inbox = self._inbox, []
            self._pending_blocks = self._pending_tokens = 0
        for _, _, _, stream in items:
            stream._fail(msg, code=code, reason=reason)
        self._drop_calls(msg)

    def _run(self) -> None:
        try:
            self._run_loop()
        except Exception as e:  # noqa: BLE001 - the loop IS the failure domain
            self.error = f"{type(e).__name__}: {e}"
            log_dist(f"engine loop {self.name} died: {self.error}", ranks=[0])
            self._fail_all(self.error, code=503, reason="replica_died")
            if (not self._draining.is_set()
                    and self.respawn_count < self._max_respawns):
                # respawn rather than silently dying: rebuild the engine,
                # start a replacement thread, and leave _alive/_stopped
                # untouched so the replica stays routable
                try:
                    self._engine.reset_state()
                except Exception as re:  # noqa: BLE001 - rebuild failed
                    log_dist(f"engine loop {self.name}: state rebuild after "
                             f"death failed ({re}); staying down", ranks=[0])
                else:
                    self.respawn_count += 1
                    self._consec_crashes = 0
                    tel = get_telemetry()
                    if tel.enabled:
                        tel.counter(
                            "engine_loop_respawns_total",
                            "engine-loop threads respawned after death",
                        ).inc(replica=self.name)
                    log_dist(f"engine loop {self.name}: respawning thread "
                             f"({self.respawn_count}/{self._max_respawns})",
                             ranks=[0])
                    self._thread = threading.Thread(
                        target=self._run,
                        name=f"engine-loop-{self.name}-r{self.respawn_count}",
                        daemon=True)
                    self._thread.start()
                    return  # replacement owns the engine now
        # clean drain exit, or final death (respawn budget spent / rebuild
        # failed / draining)
        self._alive = False
        self._draining.set()  # a dead replica must not admit
        self._stopped.set()
        self._drop_calls(f"{self.name}: loop exited")
