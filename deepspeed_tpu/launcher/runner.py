"""``dstpu`` launcher: hostfile-driven multi-host job launch.

Role parity with the reference ``launcher/runner.py:436`` (the ``deepspeed``
command: hostfile parse ``fetch_hostfile:230``, ``--include/--exclude``
filtering, env propagation via ``.deepspeed_env``, SSH/PDSH fan-out to
``launch.py`` per node).

TPU-native difference: JAX runs ONE process per host (not one per chip), and
rendezvous is ``jax.distributed.initialize`` via a coordinator address — so the
per-node spawner sets ``DSTPU_COORDINATOR`` / ``DSTPU_NUM_PROCESSES`` /
``DSTPU_PROCESS_ID`` instead of RANK/LOCAL_RANK per accelerator. On Cloud TPU
pods the runtime discovers peers itself and the launcher degenerates to "run
the script on every host".
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from deepspeed_tpu.utils.logging import logger

ENV_FILE = ".dstpu_env"


def fetch_hostfile(path: str) -> dict[str, int]:
    """Parse ``host slots=N`` lines (reference ``fetch_hostfile:230``)."""
    hosts: dict[str, int] = {}
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=", 1)[1])
            if host in hosts:
                raise ValueError(f"duplicate host {host} in hostfile")
            hosts[host] = slots
    if not hosts:
        raise ValueError(f"no hosts found in {path}")
    return hosts


def filter_hosts(hosts: dict[str, int], include: str = "", exclude: str = "") -> dict[str, int]:
    """``--include host1@host2`` / ``--exclude`` filtering (reference ``:310``)."""
    selected = dict(hosts)
    if include:
        names = include.split("@")
        unknown = [n for n in names if n not in hosts]
        if unknown:
            raise ValueError(f"--include hosts not in hostfile: {unknown}")
        selected = {h: hosts[h] for h in names}
    if exclude:
        for name in exclude.split("@"):
            selected.pop(name, None)
    if not selected:
        raise ValueError("host filtering removed every host")
    return selected


def propagate_env() -> dict[str, str]:
    """Read ``.dstpu_env`` (KEY=VALUE lines) for cross-node env propagation
    (reference ``.deepspeed_env`` handling)."""
    env = {}
    for base in (os.path.expanduser("~"), os.getcwd()):
        path = os.path.join(base, ENV_FILE)
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line and "=" in line and not line.startswith("#"):
                        k, v = line.split("=", 1)
                        env[k] = v
    return env


def build_runner(args, extra_env: dict[str, str]):
    """Map parsed CLI args to a MultiNodeRunner (reference ``runner.py``'s
    PDSH/Slurm/MPI selection, TPU-idiomatic backends)."""
    from deepspeed_tpu.launcher.multinode_runner import (
        GcloudTPURunner,
        GKERunner,
        SlurmRunner,
        SSHRunner,
    )

    if args.launcher == "slurm":
        if not args.num_nodes and not args.hostfile:
            raise ValueError("--launcher slurm needs --num_nodes or --hostfile")
        if args.hostfile:
            hosts = filter_hosts(fetch_hostfile(args.hostfile),
                                 args.include, args.exclude)
            names = list(hosts)
            nodelist = ",".join(names)
            n = len(names)
        else:
            nodelist, n = "", args.num_nodes
        coord_host = args.master_addr or (nodelist.split(",")[0] if nodelist
                                          else None)
        if coord_host is None:
            # a per-task shell fallback like $SLURMD_NODENAME cannot work:
            # the env export is quoted (no expansion), and even expanded each
            # rank would name ITSELF rather than one common coordinator
            raise ValueError(
                "--launcher slurm with --num_nodes needs --master_addr "
                "(or a --hostfile to take the first host from)")
        return SlurmRunner(
            args.script, args.script_args, num_nodes=n,
            coordinator=f"{coord_host}:{args.master_port}",
            nodelist=nodelist, partition=args.partition,
            account=args.account, extra_env=extra_env)
    if args.launcher == "gcloud":
        if not args.tpu_name or not args.zone:
            raise ValueError("--launcher gcloud needs --tpu_name and --zone")
        return GcloudTPURunner(
            args.script, args.script_args, tpu_name=args.tpu_name,
            zone=args.zone, project=args.project, extra_env=extra_env)
    if args.launcher == "gke":
        if not args.num_nodes or not args.image:
            raise ValueError("--launcher gke needs --num_nodes and --image")
        return GKERunner(
            args.script, args.script_args, job_name=args.job_name,
            num_nodes=args.num_nodes, image=args.image,
            tpu_topology=args.tpu_topology, accelerator=args.accelerator,
            extra_env=extra_env)
    # default: raw SSH over the hostfile
    hosts = filter_hosts(fetch_hostfile(args.hostfile), args.include, args.exclude)
    names = list(hosts)
    coordinator = f"{args.master_addr or names[0]}:{args.master_port}"
    return SSHRunner(args.script, args.script_args, hosts=names,
                     coordinator=coordinator, ssh_port=args.ssh_port,
                     extra_env=extra_env)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="dstpu", description="deepspeed_tpu multi-host launcher"
    )
    parser.add_argument("--hostfile", default=None)
    parser.add_argument("--include", default="")
    parser.add_argument("--exclude", default="")
    parser.add_argument("--master_addr", default=None,
                        help="coordinator host (default: first host)")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--ssh_port", type=int, default=22)
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--launcher", default="ssh",
                        choices=("ssh", "slurm", "gcloud", "gke"),
                        help="multinode fan-out backend")
    # slurm
    parser.add_argument("--num_nodes", type=int, default=0)
    parser.add_argument("--partition", default="")
    parser.add_argument("--account", default="")
    # gcloud tpu-vm
    parser.add_argument("--tpu_name", default="")
    parser.add_argument("--zone", default="")
    parser.add_argument("--project", default="")
    # gke
    parser.add_argument("--image", default="")
    parser.add_argument("--job_name", default="dstpu-job")
    parser.add_argument("--tpu_topology", default="")
    parser.add_argument("--accelerator", default="")
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    extra_env = propagate_env()

    if args.hostfile is None and args.launcher == "ssh":
        # single-host: exec in place, jax discovers local devices itself
        cmd = [sys.executable, args.script] + args.script_args
        logger.info(f"dstpu single-host: {' '.join(cmd)}")
        return subprocess.call(cmd, env={**os.environ, **extra_env})

    runner = build_runner(args, extra_env)
    if not runner.backend_exists():
        logger.warning(f"launcher backend {runner.name!r} tooling not found "
                       "on PATH; the generated commands may fail")
    logger.info(f"dstpu launching via {runner.name}")
    return runner.launch()


if __name__ == "__main__":
    sys.exit(main())
