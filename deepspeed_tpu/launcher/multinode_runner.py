"""Multinode runners: pluggable fan-out backends for the ``dstpu`` launcher.

Role parity with the reference ``launcher/multinode_runner.py`` (PDSHRunner,
OpenMPIRunner, MVAPICHRunner, SlurmRunner, IMPIRunner — each wrapping a
cluster's native process launcher behind ``backend_exists()``/``get_cmd()``).

TPU-idiomatic backends instead of MPI flavors:
- ``ssh``   : raw SSH per host (the PDSH analog; default with a hostfile)
- ``slurm`` : ``srun`` one task per node, process id from ``SLURM_PROCID``
- ``gcloud``: ``gcloud compute tpus tpu-vm ssh --worker=all`` (Cloud TPU pods;
  the TPU runtime discovers peers itself, no coordinator env needed)
- ``gke``   : renders a JobSet-style Kubernetes manifest for
  ``kubectl apply`` (GKE TPU slices / queued-resources provisioning)

Each runner exposes ``get_cmd()`` returning the exact argv/manifest it would
execute — unit-testable with no cluster attached (reference test style:
command generation only).
"""

from __future__ import annotations

import json
import os
import shlex
import shutil
import sys
from abc import ABC, abstractmethod


def _export_prefix(env: dict[str, str]) -> str:
    return " ".join(f"export {k}={shlex.quote(v)};" for k, v in env.items())


class MultiNodeRunner(ABC):
    """One fan-out backend (reference ``multinode_runner.py`` ABC)."""

    name: str = "abstract"

    def __init__(self, script: str, script_args: list[str],
                 extra_env: dict[str, str] | None = None,
                 python: str | None = None):
        self.script = script
        self.script_args = list(script_args)
        self.extra_env = dict(extra_env or {})
        self.python = python or sys.executable

    @abstractmethod
    def backend_exists(self) -> bool:
        """Is this backend usable on the current machine?"""

    @abstractmethod
    def get_cmd(self) -> list[list[str]]:
        """The argv list(s) this runner would execute, in order."""

    def launch(self) -> int:
        import subprocess

        rc = 0
        procs = [subprocess.Popen(cmd) for cmd in self.get_cmd()]
        for p in procs:
            rc = p.wait() or rc
        return rc

    def _node_shell_cmd(self, env: dict[str, str]) -> str:
        args = " ".join(shlex.quote(a) for a in self.script_args)
        return (f"{_export_prefix({**env, **self.extra_env})} "
                f"cd {shlex.quote(os.getcwd())}; "
                f"{self.python} {shlex.quote(self.script)} {args}").strip()


class SSHRunner(MultiNodeRunner):
    """Raw-SSH fan-out, one process per host (the reference PDSH analog)."""

    name = "ssh"

    def __init__(self, script, script_args, hosts: list[str],
                 coordinator: str, ssh_port: int = 22, **kw):
        super().__init__(script, script_args, **kw)
        self.hosts = list(hosts)
        self.coordinator = coordinator
        self.ssh_port = ssh_port

    def backend_exists(self) -> bool:
        return shutil.which("ssh") is not None

    def get_cmd(self) -> list[list[str]]:
        cmds = []
        for pid, host in enumerate(self.hosts):
            env = {
                "DSTPU_COORDINATOR": self.coordinator,
                "DSTPU_NUM_PROCESSES": str(len(self.hosts)),
                "DSTPU_PROCESS_ID": str(pid),
            }
            cmds.append(["ssh", "-p", str(self.ssh_port), host,
                         self._node_shell_cmd(env)])
        return cmds


class SlurmRunner(MultiNodeRunner):
    """``srun`` launch: one task per node; the per-process id comes from
    ``SLURM_PROCID`` at runtime (reference SlurmRunner, ``multinode_runner.py``
    — ``srun`` replaces its mpirun-style rank wiring)."""

    name = "slurm"

    def __init__(self, script, script_args, num_nodes: int, coordinator: str,
                 nodelist: str = "", partition: str = "", account: str = "",
                 **kw):
        super().__init__(script, script_args, **kw)
        self.num_nodes = num_nodes
        self.coordinator = coordinator
        self.nodelist = nodelist
        self.partition = partition
        self.account = account

    def backend_exists(self) -> bool:
        return shutil.which("srun") is not None

    def get_cmd(self) -> list[list[str]]:
        srun = ["srun", "--nodes", str(self.num_nodes),
                "--ntasks", str(self.num_nodes), "--ntasks-per-node", "1"]
        if self.nodelist:
            srun += ["--nodelist", self.nodelist]
        if self.partition:
            srun += ["--partition", self.partition]
        if self.account:
            srun += ["--account", self.account]
        env = {
            "DSTPU_COORDINATOR": self.coordinator,
            "DSTPU_NUM_PROCESSES": str(self.num_nodes),
        }
        # process id resolves per task on the allocation, not at submit time
        node = (f"{_export_prefix({**env, **self.extra_env})} "
                f"export DSTPU_PROCESS_ID=$SLURM_PROCID; "
                f"cd {shlex.quote(os.getcwd())}; "
                f"{self.python} {shlex.quote(self.script)} "
                + " ".join(shlex.quote(a) for a in self.script_args)).strip()
        return [srun + ["bash", "-c", node]]


class GcloudTPURunner(MultiNodeRunner):
    """Cloud TPU pod launch: ``gcloud compute tpus tpu-vm ssh --worker=all``
    runs the script on every host of the slice; the TPU runtime provides the
    coordinator/rank wiring itself (``jax.distributed.initialize()`` with no
    args), so no DSTPU_* env is injected."""

    name = "gcloud"

    def __init__(self, script, script_args, tpu_name: str, zone: str,
                 project: str = "", **kw):
        super().__init__(script, script_args, **kw)
        self.tpu_name = tpu_name
        self.zone = zone
        self.project = project

    def backend_exists(self) -> bool:
        return shutil.which("gcloud") is not None

    def get_cmd(self) -> list[list[str]]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", self.tpu_name,
               "--zone", self.zone, "--worker=all"]
        if self.project:
            cmd += ["--project", self.project]
        node = self._node_shell_cmd({})
        return [cmd + ["--command", node]]


class GKERunner(MultiNodeRunner):
    """GKE TPU-slice launch: renders a JobSet-style manifest (the idiom for
    multi-host TPU on GKE / queued-resources-provisioned node pools) and
    applies it with kubectl. ``get_cmd()`` returns the kubectl argv;
    ``get_manifest()`` the YAML, so both are testable without a cluster."""

    name = "gke"

    def __init__(self, script, script_args, job_name: str, num_nodes: int,
                 image: str, tpu_topology: str = "", accelerator: str = "",
                 chips_per_node: int = 0, **kw):
        super().__init__(script, script_args, python="python", **kw)
        self.job_name = job_name
        self.num_nodes = num_nodes
        self.image = image
        self.tpu_topology = tpu_topology
        self.accelerator = accelerator
        self.chips_per_node = chips_per_node

    def backend_exists(self) -> bool:
        return shutil.which("kubectl") is not None

    def _chips_per_node(self) -> int:
        """Per-node TPU chip request: explicit override, else derived from the
        slice topology (product of dims / nodes), else the 4-chip-host default."""
        if self.chips_per_node:
            return int(self.chips_per_node)
        if self.tpu_topology:
            try:
                total = 1
                for d in self.tpu_topology.lower().split("x"):
                    total *= int(d)
                per = total // max(self.num_nodes, 1)
                if per >= 1 and per * self.num_nodes == total:
                    return per
            except ValueError:
                pass
        return 4

    def get_manifest(self) -> str:
        # json.dumps per scalar: JSON is a YAML subset, so every value —
        # quotes, backslashes, newlines — lands in the manifest intact.
        q = json.dumps
        args = " ".join(shlex.quote(a) for a in self.script_args)
        env_lines = "".join(
            f"\n            - name: {q(str(k))}\n              value: {q(str(v))}"
            for k, v in self.extra_env.items())
        selectors = ""
        if self.accelerator:
            selectors += (f"\n            cloud.google.com/gke-tpu-accelerator: "
                          f"{q(self.accelerator)}")
        if self.tpu_topology:
            selectors += (f"\n            cloud.google.com/gke-tpu-topology: "
                          f"{q(self.tpu_topology)}")
        shell_cmd = f"{self.python} {self.script} {args}".strip()
        return f"""apiVersion: jobset.x-k8s.io/v1alpha2
kind: JobSet
metadata:
  name: {q(self.job_name)}
spec:
  replicatedJobs:
  - name: workers
    template:
      spec:
        parallelism: {self.num_nodes}
        completions: {self.num_nodes}
        backoffLimit: 0
        template:
          spec:
            restartPolicy: Never
            nodeSelector:{selectors if selectors else " {}"}
            containers:
            - name: worker
              image: {q(self.image)}
              command: ["bash", "-c"]
              args: [{q(shell_cmd)}]
              env:{env_lines if env_lines else " []"}
              resources:
                limits:
                  google.com/tpu: {q(str(self._chips_per_node()))}
"""

    def get_cmd(self) -> list[list[str]]:
        return [["kubectl", "apply", "-f", "-"]]

    def launch(self) -> int:
        import subprocess

        proc = subprocess.run(self.get_cmd()[0], input=self.get_manifest(),
                              text=True)
        return proc.returncode


RUNNERS = {r.name: r for r in
           (SSHRunner, SlurmRunner, GcloudTPURunner, GKERunner)}
