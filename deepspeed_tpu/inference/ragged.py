"""Ragged / continuous-batching inference engine (FastGen v2 analog).

Role parity with the reference second inference engine:
``inference/v2/engine_v2.py:30 InferenceEngineV2`` (``put()`` scheduling),
``inference/v2/ragged/ragged_manager.py:19 DSStateManager`` (per-sequence
state + host descriptors), ``inference/v2/ragged/blocked_allocator.py``
(KV block free list), and the SplitFuse token-budget policy from the FastGen
blog (``blogs/deepspeed-fastgen``): every engine step processes a fixed
budget of tokens that freely mixes ongoing decodes (1 token/seq, scheduled
first for latency) with prompt-prefill *chunks*, so long prompts never stall
running generations and short ones never wait for a batch to drain.

TPU-native shape: instead of the reference's ragged CUDA kernel set
(``inference/v2/kernels/ragged_ops``), the whole mixed step is ONE
static-shape jitted XLA program over a flat ``[T]`` token batch — each token
carries (slot, position), new KV is scattered into a paged block pool before
attention, and each token attends over its sequence's gathered blocks under a
position mask. Static shapes mean exactly one compile, ever, per engine; the
scheduler pads the tail of the token batch onto a scratch block (block 0).

The paged-attention gather is pure XLA (correct everywhere, including the
CPU test mesh); a Pallas flash-decode kernel over the same block pool is the
drop-in optimization point.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.api import ModelSpec, ShardCtx
from deepspeed_tpu.utils.logging import log_dist


class BlockedAllocator:
    """Free-list allocator over the KV block pool
    (reference ``inference/v2/ragged/blocked_allocator.py``).

    Block 0 is reserved as the scratch block that padding tokens write into;
    it is never handed out.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (one is the scratch block)")
        self._free = list(range(num_blocks - 1, 0, -1))  # pop() -> lowest first
        self.num_blocks = num_blocks

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> list[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted: need {n} blocks, {len(self._free)} free"
            )
        return [self._free.pop() for _ in range(n)]

    def free(self, blocks: list[int]) -> None:
        for b in blocks:
            if b == 0 or b >= self.num_blocks:
                raise ValueError(f"bad block id {b}")
            if b in self._free:
                raise ValueError(f"double free of block {b}")
            self._free.append(b)


@dataclass
class RaggedConfig:
    """Engine sizing. ``max_tokens_per_step`` is the SplitFuse token budget."""

    max_tokens_per_step: int = 256
    max_seqs: int = 8
    block_size: int = 16
    num_blocks: int = 257  # 256 usable + scratch
    max_blocks_per_seq: int = 32
    # decode run-ahead: when the scheduler has no prefill or admission work,
    # run up to this many decode steps inside ONE jitted lax.scan (greedy
    # next-token fed back on device) instead of one dispatch per token —
    # the multi-step-scheduling idiom of continuous-batching engines, and
    # the difference between dispatch-latency-bound and compute-bound decode
    # on remote/tunneled accelerators. 0 disables.
    decode_run_ahead: int = 0
    # tiled prefill: lay prefill chunks at tile-aligned offsets so the tiled
    # Pallas kernel fetches each KV block once per TILE instead of once per
    # token (ops/pallas ragged_prefill_attention — the SplitFuse blocked
    # flash attention). 0 disables (per-token kernel for everything).
    prefill_tile: int = 0
    # with arrivals queued but UNADMITTABLE (a free slot exists yet the KV
    # pool can't cover the reservation), run-ahead still fuses up to this
    # many decode steps per dispatch — decode progress is exactly what frees
    # blocks; admittable requests are admitted before run-ahead is even
    # considered. Only active when decode_run_ahead is set.
    run_ahead_admission_cap: int = 8

    @property
    def max_seq_len(self) -> int:
        return self.block_size * self.max_blocks_per_seq


@dataclass
class _SeqState:
    """Host descriptor of one request (reference DSStateManager sequence)."""

    uid: Any
    prompt: list[int]
    max_new_tokens: int
    eos_token_id: int | None = None
    slot: int = -1
    pos: int = 0  # tokens whose KV has been scheduled into the cache
    generated: list[int] = field(default_factory=list)
    blocks: list[int] = field(default_factory=list)
    reserved_remaining: int = 0  # worst-case blocks reserved but not yet held
    done: bool = False

    def token_at(self, p: int) -> int:
        if p < len(self.prompt):
            return self.prompt[p]
        return self.generated[p - len(self.prompt)]

    @property
    def in_decode(self) -> bool:
        return self.pos >= len(self.prompt)

    @property
    def finished(self) -> bool:
        if self.done:
            return True
        if len(self.generated) >= self.max_new_tokens:
            return True
        return bool(self.generated) and self.generated[-1] == self.eos_token_id


class RaggedInferenceEngine:
    """Continuous-batching engine over a ``ModelSpec`` with ragged hooks.

    ``put()`` requests at any time; ``step()`` advances every admitted request
    by up to one token (decodes) and/or one prompt chunk (prefills) inside one
    XLA call; finished sequences free their blocks and their slot is reused
    immediately (reference ``engine_v2.put`` + ``DSStateManager`` lifecycle).
    """

    def __init__(self, model, ragged_config: RaggedConfig | None = None,
                 dtype=jnp.bfloat16, params: Any = None, seed: int = 0,
                 eos_token_id: int | None = None, quantize_bits: int = 0):
        self.cfg = ragged_config or RaggedConfig()
        self.ctx = ShardCtx()
        self.spec: ModelSpec = model(self.ctx) if callable(model) else model
        if self.spec.ragged_forward_fn is None or self.spec.init_paged_cache_fn is None:
            raise ValueError(f"model {self.spec.name} has no ragged/paged support")
        self.dtype = dtype
        self.eos_token_id = eos_token_id

        if params is None:
            params = self.spec.init_fn(jax.random.PRNGKey(seed))
        self.params = jax.tree_util.tree_map(
            lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
            params,
        )
        if quantize_bits:
            # weight-only quantization over the paged-KV engine (reference
            # inference/quantization WOQ composed with the v2 ragged engine)
            from deepspeed_tpu.ops.quantizer import quantize_params

            self.params = jax.jit(
                lambda p: quantize_params(p, bits=int(quantize_bits),
                                          skip=tuple(self.spec.woq_skip))
            )(self.params)
        self.cache = self.spec.init_paged_cache_fn(
            self.cfg.num_blocks, self.cfg.block_size, dtype
        )
        self.allocator = BlockedAllocator(self.cfg.num_blocks)
        # row max_seqs is the all-zeros padding row -> scratch block 0
        self.block_tables = np.zeros(
            (self.cfg.max_seqs + 1, self.cfg.max_blocks_per_seq), np.int32
        )
        self._free_slots = list(range(self.cfg.max_seqs - 1, -1, -1))
        # blocks promised to admitted sequences but not yet allocated;
        # admission reserves worst case (prompt + max_new) so an admitted
        # sequence can always finish (reference conservative admission)
        self._reserved = 0
        self._queued: list[_SeqState] = []
        self._running: dict[int, _SeqState] = {}  # slot -> seq
        self._results: dict[Any, _SeqState] = {}
        # token-batch size buckets: decode-heavy steps run a small compiled
        # size instead of padding to the full SplitFuse budget (the static-
        # shape analog of the reference's truly-ragged kernel batches); jit
        # specializes once per bucket shape, so at most log2 programs compile
        b = 4
        self._buckets = []
        while b < self.cfg.max_tokens_per_step:
            self._buckets.append(b)
            b *= 2
        self._buckets.append(self.cfg.max_tokens_per_step)
        self._step_jit = self._build_step()
        self._chunk_jit = None  # decode run-ahead program (lazy)
        self._use_tiles = self.cfg.prefill_tile > 0
        if self._use_tiles and not self.spec.supports_prefill_tiles:
            raise ValueError(
                f"prefill_tile={self.cfg.prefill_tile} but model "
                f"{self.spec.name} does not accept prefill_tiles (its "
                "ragged_forward has no tiled path); it would silently no-op")
        if self._use_tiles and self.cfg.prefill_tile > self.cfg.max_tokens_per_step:
            raise ValueError("prefill_tile exceeds max_tokens_per_step")
        self._tiled_jits: dict = {}
        # decode-region buckets for the tiled path (decodes <= max_seqs)
        self._dec_buckets = []
        b = 4
        while b < self.cfg.max_seqs:
            self._dec_buckets.append(b)
            b *= 2
        self._dec_buckets.append(self.cfg.max_seqs)
        # scheduling efficiency telemetry (padding fraction; comparable to the
        # dense engine's pad-to-max waste)
        self.tokens_scheduled = 0
        self.tokens_padded = 0
        log_dist(
            f"RaggedInferenceEngine: model={self.spec.name} "
            f"budget={self.cfg.max_tokens_per_step} max_seqs={self.cfg.max_seqs} "
            f"blocks={self.cfg.num_blocks}x{self.cfg.block_size}", ranks=[0],
        )

    # ------------------------------------------------------------------ put
    def put(self, uid, prompt_tokens, max_new_tokens: int = 64,
            eos_token_id: int | None = None) -> None:
        """Enqueue a request (reference ``engine_v2.py put()``). Admission into
        the running batch happens inside ``step()`` as slots/budget free up."""
        prompt = [int(t) for t in np.asarray(prompt_tokens).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(prompt) + max_new_tokens
        if total > self.cfg.max_seq_len:
            raise ValueError(
                f"request length {total} exceeds engine max_seq_len "
                f"{self.cfg.max_seq_len}"
            )
        worst = -(-total // self.cfg.block_size)
        if worst > min(self.cfg.num_blocks - 1, self.cfg.max_blocks_per_seq):
            raise ValueError(
                f"request needs {worst} KV blocks but at most "
                f"{min(self.cfg.num_blocks - 1, self.cfg.max_blocks_per_seq)} "
                "are available per sequence — it could never be admitted"
            )
        self._queued.append(_SeqState(
            uid=uid, prompt=prompt, max_new_tokens=max_new_tokens,
            eos_token_id=eos_token_id if eos_token_id is not None else self.eos_token_id,
        ))

    @property
    def has_work(self) -> bool:
        return bool(self._queued or self._running)

    @property
    def finished_uids(self):
        """UIDs of completed requests (public completion signal; the full
        token lists come from ``generate_all`` / the per-uid state)."""
        return set(self._results)

    # ------------------------------------------------------------------ step
    def _worst_case_blocks(self, seq: _SeqState) -> int:
        total = len(seq.prompt) + seq.max_new_tokens
        return -(-total // self.cfg.block_size)

    def _ensure_capacity(self, seq: _SeqState, upto: int) -> bool:
        """Grow seq's block table to cover positions [0, upto); False if the
        pool can't satisfy it right now. Admitted sequences draw from their
        admission-time reservation, so this cannot fail for them."""
        need = -(-upto // self.cfg.block_size) - len(seq.blocks)
        if need <= 0:
            return True
        if need > self.allocator.free_blocks:
            return False
        if len(seq.blocks) + need > self.cfg.max_blocks_per_seq:
            return False
        new = self.allocator.allocate(need)
        start = len(seq.blocks)
        seq.blocks.extend(new)
        drawn = min(seq.reserved_remaining, len(new))
        seq.reserved_remaining -= drawn
        self._reserved -= drawn
        self.block_tables[seq.slot, start:start + len(new)] = new
        return True

    def _release(self, seq: _SeqState) -> None:
        self._reserved -= seq.reserved_remaining  # return unused reservation
        seq.reserved_remaining = 0
        self.allocator.free(seq.blocks)
        seq.blocks = []
        self.block_tables[seq.slot, :] = 0
        self._free_slots.append(seq.slot)
        del self._running[seq.slot]
        seq.slot = -1
        self._results[seq.uid] = seq

    def _build_step(self) -> Callable:
        fwd = self.spec.ragged_forward_fn

        def step_fn(params, cache, tokens, slots, positions, block_tables):
            return fwd(params, tokens, slots, positions, block_tables, cache)

        return jax.jit(step_fn, donate_argnums=(1,))

    def _build_decode_chunk(self) -> Callable:
        """K fused greedy decode steps over the paged cache: one dispatch,
        next-token argmax fed back on device, KV scattered per step. ``K`` is
        static (jit specializes per (K, batch) pair)."""
        fwd = self.spec.ragged_forward_fn
        from functools import partial

        @partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
        def chunk_fn(k, params, cache, tokens, slots, positions, block_tables):
            def one(carry, _):
                cache, toks, pos = carry
                logits, cache = fwd(params, toks, slots, pos, block_tables, cache)
                nxt = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
                return (cache, nxt, pos + 1), nxt

            (cache, _, _), out = jax.lax.scan(
                one, (cache, tokens, positions), None, length=k)
            return out, cache  # out: [K, T] generated tokens

        return chunk_fn

    def _try_decode_run_ahead(self) -> dict | None:
        """Fused multi-step decode when the scheduler is quiescent: every
        running sequence is decoding and no admission can happen (queue empty
        or no free slot). Returns the emit dict, or None to fall back to the
        single SplitFuse step."""
        k_max = self.cfg.decode_run_ahead
        seqs = list(self._running.values())
        if k_max < 2 or not seqs or any(not s.in_decode for s in seqs):
            return None
        if self._queued and self._free_slots:
            # a queued request has a slot but the pool can't cover its
            # reservation (step() already admitted everything admittable):
            # fuse a BOUNDED chunk — decode progress is what frees blocks
            k_max = min(k_max, self.cfg.run_ahead_admission_cap)
            if k_max < 2:
                return None
        k = min(k_max, min(s.max_new_tokens - len(s.generated) for s in seqs))
        while k >= 2 and not all(self._ensure_capacity(s, s.pos + k)
                                 for s in seqs):
            k -= 1  # pool pressure: partial growth is kept, retry smaller
        if k < 2:
            return None
        # round k DOWN to a power of two: jit specializes per (k, batch), and
        # arbitrary residuals (47, 45, 31, ...) would each compile a fresh
        # K-step scan — the bucketing discipline every other dimension uses
        k = 1 << (k.bit_length() - 1)
        t = len(seqs)
        bucket = next(b for b in self._buckets if b >= t)
        tokens = np.zeros(bucket, np.int32)
        slots = np.full(bucket, self.cfg.max_seqs, np.int32)
        positions = np.zeros(bucket, np.int32)
        for j, s in enumerate(seqs):
            tokens[j] = s.token_at(s.pos)
            slots[j] = s.slot
            positions[j] = s.pos
        if self._chunk_jit is None:
            self._chunk_jit = self._build_decode_chunk()
        out, self.cache = self._chunk_jit(
            k, self.params, self.cache,
            jnp.asarray(tokens), jnp.asarray(slots), jnp.asarray(positions),
            jnp.asarray(self.block_tables),
        )
        out = np.asarray(out)  # [K, bucket]
        self.tokens_scheduled += k * t
        self.tokens_padded += k * (bucket - t)
        emit: dict = {}
        for j, s in enumerate(seqs):
            for i in range(k):
                tok = int(out[i, j])
                s.generated.append(tok)
                s.pos += 1
                emit[s.uid] = tok
                if s.finished:
                    break  # tokens past EOS stay in the pool; freed on release
            if s.finished:
                self._release(s)
        return emit

    def _schedule_decodes(self, budget: int, tokens, slots, positions,
                          emit) -> int:
        """Pass 1: ongoing decodes first (latency priority, FastGen policy).
        Writes into the arrays from index 0, returns the count."""
        n = 0
        for seq in list(self._running.values()):
            if not seq.in_decode or n >= budget:
                continue
            if not self._ensure_capacity(seq, seq.pos + 1):
                continue  # pool pressure: this seq stalls one step
            tokens[n] = seq.token_at(seq.pos)
            slots[n] = seq.slot
            positions[n] = seq.pos
            emit.append((n, seq))
            seq.pos += 1
            n += 1
        return n

    def _admit_queued(self) -> None:
        """Pass 2: admit queued requests while slots remain (their prompt
        chunks are scheduled by pass 3); admission reserves the request's
        worst-case block count so admitted work always finishes."""
        while self._queued and self._free_slots:
            seq = self._queued[0]
            worst = self._worst_case_blocks(seq)
            if worst > self.allocator.free_blocks - self._reserved:
                break  # pool pressure: retry admission as blocks free up
            self._queued.pop(0)
            seq.slot = self._free_slots.pop()
            seq.reserved_remaining = worst
            self._reserved += worst
            self._running[seq.slot] = seq

    def _emit_tokens(self, logits, emit) -> dict:
        """Shared step epilogue: greedy-pick at the emit indices, extend the
        sequences, release finished ones."""
        out: dict = {}
        if emit:
            idx = np.asarray([i for i, _ in emit])
            picked = np.asarray(jnp.argmax(logits[idx].astype(jnp.float32), axis=-1))
            for (_, seq), tok in zip(emit, picked):
                seq.generated.append(int(tok))
                out[seq.uid] = int(tok)
                if seq.finished:
                    self._release(seq)
        return out

    def _deadlock_guard(self, n: int) -> None:
        if n == 0:
            # has_work but nothing schedulable: every sequence is stalled on
            # KV-pool capacity and nothing can ever free a block — a silent
            # livelock without this guard. (The reference avoids this state
            # with conservative admission; we surface it instead.)
            raise RuntimeError(
                "KV pool deadlock: all sequences stalled waiting for blocks "
                f"({self.allocator.free_blocks} free of "
                f"{self.cfg.num_blocks - 1} usable); enlarge num_blocks or "
                "lower max_seqs/max_new_tokens"
            )

    def step(self) -> dict:
        """One SplitFuse step. Returns {uid: token} for sequences that emitted
        a token this step (under decode run-ahead: the LAST token of each
        sequence's chunk; the full stream is in the per-sequence state)."""
        if not self.has_work:
            return {}
        # admission FIRST: a newly admitted sequence is in prefill, which
        # disables run-ahead for this step — so queued requests are admitted
        # within one step whenever a slot + pool reservation exist, and the
        # admission-capped run-ahead below only governs the pool-blocked case
        # (without this order, capped chunks re-fire back-to-back and starve
        # admission for up to a whole generation)
        self._admit_queued()
        ahead = self._try_decode_run_ahead()
        if ahead is not None:
            return ahead
        if self._use_tiles:
            return self._step_tiled()
        budget = self.cfg.max_tokens_per_step
        tokens = np.zeros(budget, np.int32)
        slots = np.full(budget, self.cfg.max_seqs, np.int32)  # padding row
        positions = np.zeros(budget, np.int32)
        emit: list[tuple[int, _SeqState]] = []
        n = self._schedule_decodes(budget, tokens, slots, positions, emit)

        # 3) prefill chunks for running prompts within the remaining budget
        for seq in list(self._running.values()):
            if seq.in_decode or n >= budget:
                continue
            take = min(budget - n, len(seq.prompt) - seq.pos)
            while take and not self._ensure_capacity(seq, seq.pos + take):
                take -= 1  # partial chunk under pool pressure
            if take <= 0:
                continue
            sl = slice(n, n + take)
            tokens[sl] = seq.prompt[seq.pos:seq.pos + take]
            slots[sl] = seq.slot
            positions[sl] = np.arange(seq.pos, seq.pos + take, dtype=np.int32)
            seq.pos += take
            n += take
            if seq.pos == len(seq.prompt):
                emit.append((n - 1, seq))  # last prompt token -> first new token

        self._deadlock_guard(n)
        bucket = next(b for b in self._buckets if b >= n)
        self.tokens_scheduled += n
        self.tokens_padded += bucket - n

        logits, self.cache = self._step_jit(
            self.params, self.cache,
            jnp.asarray(tokens[:bucket]), jnp.asarray(slots[:bucket]),
            jnp.asarray(positions[:bucket]),
            jnp.asarray(self.block_tables),
        )
        return self._emit_tokens(logits, emit)

    def _get_tiled_step(self, nd: int, nt: int):
        """Jitted step with a static (decode-count, tile-count) split; one
        program per bucket pair."""
        key = (nd, nt)
        if key not in self._tiled_jits:
            fwd = self.spec.ragged_forward_fn
            ct = self.cfg.prefill_tile

            def step_fn(params, cache, tokens, slots, positions, ts, tp, tv, bt):
                return fwd(params, tokens, slots, positions, bt, cache,
                           prefill_tiles=(nd, ts, tp, tv, ct))

            self._tiled_jits[key] = jax.jit(step_fn, donate_argnums=(1,))
        return self._tiled_jits[key]

    def _step_tiled(self) -> dict:
        """One SplitFuse step with tile-aligned prefill layout: tokens
        [0, ND) are decodes (bucketed), the rest are prefill chunks laid at
        tile boundaries so the tiled kernel fetches each KV block once per
        tile (see RaggedConfig.prefill_tile)."""
        ct = self.cfg.prefill_tile
        budget = self.cfg.max_tokens_per_step
        tokens = np.zeros(budget + ct, np.int32)
        slots = np.full(budget + ct, self.cfg.max_seqs, np.int32)
        positions = np.zeros(budget + ct, np.int32)
        emit: list[tuple[int, _SeqState]] = []
        n_dec = self._schedule_decodes(min(budget, self.cfg.max_seqs),
                                       tokens, slots, positions, emit)
        self._admit_queued()
        nd = 0 if n_dec == 0 else next(b for b in self._dec_buckets
                                       if b >= n_dec)

        # prefill chunks at tile-aligned offsets after the decode region
        ntiles_cap = max(0, (budget - nd) // ct)
        chunks: list[tuple[_SeqState, int, int]] = []  # (seq, rel_tile0, take)
        tiles_used = 0
        sched = 0
        for seq in list(self._running.values()):
            if seq.in_decode or tiles_used >= ntiles_cap:
                continue
            avail = (ntiles_cap - tiles_used) * ct
            take = min(avail, len(seq.prompt) - seq.pos)
            while take and not self._ensure_capacity(seq, seq.pos + take):
                take -= 1  # partial chunk under pool pressure
            if take <= 0:
                continue
            start = nd + tiles_used * ct
            tokens[start:start + take] = seq.prompt[seq.pos:seq.pos + take]
            slots[start:start + take] = seq.slot
            positions[start:start + take] = np.arange(
                seq.pos, seq.pos + take, dtype=np.int32)
            chunks.append((seq, tiles_used, take))
            seq.pos += take
            sched += take
            tiles_used += -(-take // ct)
            if seq.pos == len(seq.prompt):
                emit.append((start + take - 1, seq))
        self._deadlock_guard(n_dec + sched)

        if tiles_used == 0:
            nt = 0
        else:
            nt = 1
            while nt < tiles_used:
                nt *= 2
            nt = min(nt, max(1, ntiles_cap))
            if nt < tiles_used:  # cap can be non-power-of-2
                nt = tiles_used
        total = nd + nt * ct
        # per-tile metadata (pad tiles: scratch row, valid=0)
        ts = np.full(max(nt, 1), self.cfg.max_seqs, np.int32)
        tp = np.zeros(max(nt, 1), np.int32)
        tv = np.zeros(max(nt, 1), np.int32)
        for seq, tile0, take in chunks:
            pos0 = positions[nd + tile0 * ct]
            for t in range(-(-take // ct)):
                ts[tile0 + t] = seq.slot
                tp[tile0 + t] = pos0 + t * ct
                tv[tile0 + t] = min(ct, take - t * ct)

        self.tokens_scheduled += n_dec + sched
        self.tokens_padded += total - n_dec - sched

        step_fn = self._get_tiled_step(nd, nt)
        logits, self.cache = step_fn(
            self.params, self.cache,
            jnp.asarray(tokens[:total]), jnp.asarray(slots[:total]),
            jnp.asarray(positions[:total]),
            jnp.asarray(ts[:max(nt, 1)]), jnp.asarray(tp[:max(nt, 1)]),
            jnp.asarray(tv[:max(nt, 1)]),
            jnp.asarray(self.block_tables),
        )
        return self._emit_tokens(logits, emit)

    # ------------------------------------------------------------------ convenience
    def generate_all(self, max_steps: int = 10_000) -> dict:
        """Drive ``step()`` until all queued/admitted work finishes; returns
        {uid: generated token list}."""
        for _ in range(max_steps):
            if not self.has_work:
                break
            self.step()
        if self.has_work:
            raise RuntimeError(f"work left after {max_steps} steps")
        return {uid: list(seq.generated) for uid, seq in self._results.items()}
