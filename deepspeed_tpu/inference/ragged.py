"""Ragged / continuous-batching inference engine (FastGen v2 analog).

Role parity with the reference second inference engine:
``inference/v2/engine_v2.py:30 InferenceEngineV2`` (``put()`` scheduling),
``inference/v2/ragged/ragged_manager.py:19 DSStateManager`` (per-sequence
state + host descriptors), ``inference/v2/ragged/blocked_allocator.py``
(KV block free list), and the SplitFuse token-budget policy from the FastGen
blog (``blogs/deepspeed-fastgen``): every engine step processes a fixed
budget of tokens that freely mixes ongoing decodes (1 token/seq, scheduled
first for latency) with prompt-prefill *chunks*, so long prompts never stall
running generations and short ones never wait for a batch to drain.

TPU-native shape: instead of the reference's ragged CUDA kernel set
(``inference/v2/kernels/ragged_ops``), the whole mixed step is ONE
static-shape jitted XLA program over a flat ``[T]`` token batch — each token
carries (slot, position), new KV is scattered into a paged block pool before
attention, and each token attends over its sequence's gathered blocks under a
position mask. Static shapes mean exactly one compile, ever, per engine; the
scheduler pads the tail of the token batch onto a scratch block (block 0).

The paged-attention gather is pure XLA (correct everywhere, including the
CPU test mesh); a Pallas flash-decode kernel over the same block pool is the
drop-in optimization point.
"""

from __future__ import annotations

import pickle
import random
import time
import weakref
from dataclasses import dataclass, field, fields
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference import kvquant
from deepspeed_tpu.models.api import ModelSpec, ShardCtx
from deepspeed_tpu.serving.faults import (
    POINT_ALLOC,
    POINT_DISPATCH,
    POINT_H2D,
    POINT_READBACK,
    classify_transient,
    get_fault_injector,
)
from deepspeed_tpu.telemetry import get_telemetry
from deepspeed_tpu.telemetry.memledger import is_resource_exhausted, record_oom
from deepspeed_tpu.telemetry.tracing import format_traceparent
from deepspeed_tpu.utils.logging import log_dist


class BlockedAllocator:
    """Ref-counted free-list allocator over the KV block pool
    (reference ``inference/v2/ragged/blocked_allocator.py``, grown the
    SGLang/vLLM prefix-cache direction: blocks carry refcounts so several
    sequences can share one prefix block, and retired prompt blocks can be
    *published* into a hash-chained prefix index instead of freed).

    Block 0 is reserved as the scratch block that padding tokens write into;
    it is never handed out. Published blocks with refcount 0 sit in an LRU
    and are evicted on demand when ``allocate`` finds the free list dry —
    the prefix cache is strictly free-memory-funded: ``free_blocks`` counts
    evictable cached blocks as allocatable, so admission reservations see
    the same capacity they would without caching and can never deadlock on
    retained blocks.

    Prefix keys are exact hash-chains: ``key = (parent_key, block_tokens)``
    per full block (structural sharing keeps them cheap); exact tuples
    rather than digests so a hash collision can never splice wrong KV.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (one is the scratch block)")
        self._free = list(range(num_blocks - 1, 0, -1))  # pop() -> lowest first
        self.num_blocks = num_blocks
        self._refs = [0] * num_blocks
        # prefix cache state (inert until publish() is first called)
        self._index: dict = {}   # chain key -> block id
        self._keys: dict[int, Any] = {}  # block id -> its chain key
        self._lru: dict[int, None] = {}  # refcount-0 published blocks, LRU->MRU
        self.evictions = 0  # cumulative cached blocks reclaimed under pressure
        self.allocated_total = 0  # cumulative blocks handed out (all paths)
        # optional publish/evict listener (serving cluster prefix index):
        # an object with on_publish(key) / on_evict(key), called on the
        # engine thread as keys enter/leave the index. None = standalone.
        self.listener = None
        # optional tiering hook: demote_hook(block, key) -> bool is called
        # as an LRU eviction reclaims a published block, WHILE the payload
        # is still intact — True means the block was captured into a lower
        # tier (inference/kvtier.py) rather than dropped. None = untiered
        # (the eviction path is bit-identical to the pre-tiering engine).
        self.demote_hook = None

    @property
    def free_blocks(self) -> int:
        """Allocatable blocks: truly free + evictable (refcount-0 cached)."""
        return len(self._free) + len(self._lru)

    @property
    def busy_blocks(self) -> int:
        """Blocks holding live or retained KV right now: everything except
        the scratch block and the truly-free list. The cost meter's pool
        occupancy integral sums this over time (retained cached blocks ARE
        occupancy — they are the prefix cache's rent)."""
        return self.num_blocks - 1 - len(self._free)

    @property
    def cached_blocks(self) -> int:
        """Blocks currently published in the prefix index (any refcount)."""
        return len(self._keys)

    @property
    def retained_blocks(self) -> int:
        """Refcount-0 cached blocks held back from the free list (the
        memory the prefix cache is actually occupying right now)."""
        return len(self._lru)

    def allocate(self, n: int) -> list[int]:
        if n > self.free_blocks:
            raise RuntimeError(
                f"KV pool exhausted: need {n} blocks, {self.free_blocks} free"
            )
        while len(self._free) < n:
            self._evict_lru()
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        self.allocated_total += n
        return out

    def _evict_lru(self) -> None:
        b = next(iter(self._lru))  # oldest entry (LRU order)
        del self._lru[b]
        key = self._keys.pop(b)
        del self._index[key]
        demoted = False
        if self.demote_hook is not None:
            # tiering: capture the payload device->host NOW — once the id
            # is back on the free list the next allocation may rewrite it
            try:
                demoted = bool(self.demote_hook(b, key))
            except Exception:  # noqa: BLE001 - demotion is best-effort
                demoted = False
        self.evictions += 1
        if self.listener is not None:
            # notify BEFORE the id returns to the free list: a cluster-index
            # entry must never promise a block its replica could already be
            # rewriting. A captured block demotes (the key stays servable
            # from a lower tier); an uncaptured one is a plain eviction.
            on_demote = getattr(self.listener, "on_demote", None)
            if demoted and on_demote is not None:
                on_demote(key)
            else:
                self.listener.on_evict(key)
        self._free.append(b)

    def shrink_retained(self, budget: int) -> int:
        """Evict LRU cached blocks until at most ``budget`` refcount-0
        blocks stay retained (headroom-driven cache budget: when measured
        free-byte headroom is scarce, retention shrinks before admission
        starves). Returns how many blocks were evicted; a budget at or
        above the current retention is a no-op — the ample-headroom case
        stays bit-identical to the unbudgeted LRU."""
        n = 0
        while len(self._lru) > max(0, budget):
            self._evict_lru()
            n += 1
        return n

    def free(self, blocks: list[int]) -> None:
        """Drop one reference per block; a block reaching refcount 0 returns
        to the free list, or to the evictable LRU if it is published."""
        for b in blocks:
            if b == 0 or b >= self.num_blocks:
                raise ValueError(f"bad block id {b}")
            if self._refs[b] <= 0:
                raise ValueError(f"double free of block {b}")
            self._refs[b] -= 1
            if self._refs[b] == 0:
                if b in self._keys:
                    self._lru[b] = None  # dict preserves insertion = MRU last
                else:
                    self._free.append(b)

    # ------------------------------------------------------- prefix cache
    def lookup(self, key) -> int | None:
        """Block id published under ``key``, or None. Read-only (no LRU
        touch) so the serving router can probe concurrently."""
        return self._index.get(key)

    def acquire(self, blocks: list[int]) -> None:
        """Take a reference on cached blocks (a prefix hit splicing them
        into a sequence's block table). A refcount-0 block leaves the
        evictable LRU."""
        for b in blocks:
            if self._refs[b] == 0:
                del self._lru[b]
            self._refs[b] += 1

    def publish(self, block: int, key) -> bool:
        """Register ``block``'s content under its chain key (called at
        sequence release, BEFORE ``free``). Returns False when the key is
        already cached (dedupe: the existing block stays authoritative)."""
        if key in self._index:
            return False
        self._index[key] = block
        self._keys[block] = key
        if self.listener is not None:
            self.listener.on_publish(key)
        return True


@dataclass
class RaggedConfig:
    """Engine sizing. ``max_tokens_per_step`` is the SplitFuse token budget."""

    max_tokens_per_step: int = 256
    max_seqs: int = 8
    block_size: int = 16
    num_blocks: int = 257  # 256 usable + scratch
    max_blocks_per_seq: int = 32
    # decode run-ahead: when the scheduler has no prefill or admission work,
    # run up to this many decode steps inside ONE jitted lax.scan (greedy
    # next-token fed back on device) instead of one dispatch per token —
    # the multi-step-scheduling idiom of continuous-batching engines, and
    # the difference between dispatch-latency-bound and compute-bound decode
    # on remote/tunneled accelerators. 0 disables.
    decode_run_ahead: int = 0
    # tiled prefill: lay prefill chunks at tile-aligned offsets so the tiled
    # Pallas kernel fetches each KV block once per TILE instead of once per
    # token (ops/pallas ragged_prefill_attention — the SplitFuse blocked
    # flash attention). 0 disables (per-token kernel for everything).
    prefill_tile: int = 0
    # with arrivals queued but UNADMITTABLE (a free slot exists yet the KV
    # pool can't cover the reservation), run-ahead still fuses up to this
    # many decode steps per dispatch — decode progress is exactly what frees
    # blocks; admittable requests are admitted before run-ahead is even
    # considered. Only active when decode_run_ahead is set.
    run_ahead_admission_cap: int = 8
    # fused mixed chunks (>= 2 enables): EVERY dispatch is one program that
    # runs the mixed SplitFuse step (decodes + prefill chunks) and then
    # fused_chunk-1 further decode steps for the decode rows, next tokens
    # fed back on device. Unlike decode_run_ahead (which only engages when
    # every running sequence decodes), arrivals never break the fusion —
    # the high-RTT-transport fix the round-4 bench demanded.
    fused_chunk: int = 0
    # how many fused chunks may be in flight undispatched-results-wise:
    # chunk t+1 is dispatched before chunk t's tokens are read back, the
    # next-token feed riding a device-resident per-slot buffer (bounded
    # speculation; EOS reconciled on readback)
    pipeline_depth: int = 2
    # device-resident scheduler state (the steady-state decode fix): slot
    # rows (last token / position / seed / prompt length / sampling params)
    # live in persistent device arrays updated in place by donated jitted
    # updaters at admission, and the block table is device-resident with a
    # dirty-row delta upload — so a steady decode step stages NO per-row
    # host arrays (the packed staging buffer byte-compares equal and is
    # reused) and token readback for dispatch t overlaps dispatch t+1.
    # False restores the legacy host-staged dispatch path (token-identical;
    # kept as the parity baseline and an escape hatch).
    device_state: bool = True
    # device-side multi-step decode scheduler (>= 2 enables): when every
    # running sequence is decoding, ONE jitted program runs up to
    # sched_steps decode steps and retires slots on EOS/length INSIDE the
    # program (a lax.while_loop that masks retired rows to the scratch
    # slot and early-exits when all rows retire), returning per-slot
    # steps_taken so the host only reconciles — no per-token dispatch and
    # no post-EOS wasted compute. Requires device_state (silently inert
    # under the host-staged kill switch, which stays token-identical).
    sched_steps: int = 0
    # self-speculative decoding depth (> 0 enables; requires
    # sched_steps >= 2): each scheduler iteration proposes up to
    # spec_draft tokens per slot from a device-resident n-gram /
    # prompt-lookup draft (suffix match over the slot's own token
    # history — no second model), verifies all of them in ONE batched
    # forward, and surfaces the accepted prefix plus the target's bonus
    # pick. Verification is exact-match against the target's own
    # deterministic picks, so output is BIT-identical to plain decoding
    # for greedy AND seeded sampling (per_request_keys makes each draw a
    # function of (seed, gen_idx) only).
    spec_draft: int = 0
    # suffix-match length for the prompt-lookup draft source
    spec_ngram: int = 3
    # ---- dispatch watchdog (docs/FAULT_TOLERANCE.md) ----
    # wall-clock budget for one step(); a step exceeding it counts toward
    # the degradation ladder like a transient failure (the device path is
    # limping even though it completed). 0 disables the deadline check.
    step_deadline_s: float = 0.0
    # transient step failures retried in place (with backoff) before the
    # error escalates out of step(); fatal errors never retry
    dispatch_retries: int = 2
    retry_backoff_s: float = 0.05
    retry_backoff_max_s: float = 2.0
    # multiplicative jitter on each backoff sleep, drawn from an
    # engine-seeded RNG so a replayed run backs off identically
    retry_jitter: float = 0.25
    # consecutive device-path failures that trigger automatic degradation:
    # device-resident state -> host-staged kill-switch path -> plain-step
    # fallback (token-identical rungs). 0 disables degradation.
    degrade_after: int = 3
    # block-level prefix caching (SGLang/vLLM-style): retired sequences
    # publish their full prompt blocks into a hash-chained index; admission
    # splices the longest cached full-block prefix into a new sequence's
    # block table (refcounts bumped) and prefills only the tail. Cached
    # blocks with no referents stay evictable (LRU) so the cache is funded
    # purely by free memory. Off by default: disabled, scheduling behavior
    # is bit-identical to an uncached engine.
    enable_prefix_cache: bool = False
    # headroom-driven admission (telemetry/memledger.py): cap admission by
    # MEASURED free-byte headroom alongside the static block count. The KV
    # pool is preallocated at init, so its free blocks are credited as
    # already-funded bytes — the gate only bites when OTHER owners
    # (checkpoint staging, compile temps, co-located jobs) have eaten the
    # device's guard band beyond what the pool itself could fund. Opt-in:
    # admission from a preallocated pool allocates no new device bytes, so
    # most deployments want the static path; a backend that reports no
    # bytes_limit (the CPU test accelerator) yields "unknown" headroom and
    # the static path verbatim either way.
    headroom_admission: bool = False
    # fraction of bytes_limit held back from the measured free bytes before
    # converting headroom to KV blocks (allocator slack + fragmentation)
    headroom_guard_fraction: float = 0.05
    # consecutive zero-progress scheduler ticks spent headroom-pinned before
    # the stall alarm raises (a headroom wait must never be a silent forever
    # hang — external pressure is expected to lift, and when it doesn't the
    # operator needs a loud failure, not an idle loop). 0 disables the alarm.
    headroom_stall_alarm_ticks: int = 1000
    # ---- hierarchical KV-cache tiering (inference/kvtier.py) ----
    # three-tier prefix cache: HBM (tier 0, the pool above) -> bounded
    # host-RAM arena (tier 1) -> disk spill directory (tier 2). LRU eviction
    # becomes *demotion* (the evicted block's payload is gathered to host
    # before the id is reused) and admission *promotes* demoted chain links
    # back through the standard allocate->scatter->publish path when the
    # restore_beats_prefill cost model favors it — token-identical either
    # way. Requires enable_prefix_cache. Off by default: eviction drops
    # payloads exactly as before, bit-identical to the untiered engine.
    kv_tier: bool = False
    # tier-1 budget in KV blocks (must be > 0 when kv_tier is on)
    kv_tier_host_blocks: int = 64
    # tier-2 budget in records; 0 disables the disk tier (host overflow is
    # then dropped, which is exactly the old eviction for those blocks)
    kv_tier_disk_blocks: int = 0
    # spill directory; swept for torn temp files at engine startup
    kv_tier_dir: str = "runs/kvtier"
    # modeled tier-crossing bandwidths for the promotion cost model
    # (host<->device link, and disk read). <= 0 = unknown, which
    # conservatively never restores from that tier.
    kv_tier_host_gbps: float = 100.0
    kv_tier_disk_gbps: float = 8.0
    # modeled prefill throughput the restore competes against (the same
    # constant ClusterConfig.prefill_tokens_per_s models for wire transfers)
    kv_tier_prefill_tokens_per_s: float = 50000.0
    # router-kicked async prefetch: stage disk records up to the host arena
    # while the request rides the queue, so the admission-time restore only
    # pays the host->device hop
    kv_tier_prefetch: bool = True
    # ---- low-bit serving (inference/kvquant.py) ----
    # ONE config surface for the full low-bit path, grammar
    # "off" | "int8" | "fp8" | "woq8" | "woq4" | "qcol" joined with "+"
    # (e.g. "int8+woq8"). The KV codec makes the *block* the unit of
    # quantization everywhere a block lives — HBM pool, host/disk tiers,
    # prefix-cache retained set, KVHandoff wire — quantized at write time,
    # dequant fused into the jitted gather; ~2x resident blocks per HBM
    # byte under a measured drift budget (kvquant.DRIFT_BUDGET). "woqN"
    # is the weight-only path (same as the quantize_bits ctor arg);
    # "qcol" quantizes the TP inference collectives (needs a mesh — the
    # GSPMD-sharded InferenceEngine; inert on this single-host engine).
    # Off by default: the default path is bit-identical to an engine
    # that predates this knob (pinned by test).
    quant: str = "off"

    @property
    def max_seq_len(self) -> int:
        return self.block_size * self.max_blocks_per_seq


@dataclass
class _SeqState:
    """Host descriptor of one request (reference DSStateManager sequence)."""

    uid: Any
    prompt: list[int]
    max_new_tokens: int
    eos_token_id: int | None = None
    slot: int = -1
    pos: int = 0  # tokens whose KV has been scheduled into the cache
    generated: list[int] = field(default_factory=list)
    blocks: list[int] = field(default_factory=list)
    reserved_remaining: int = 0  # worst-case blocks reserved but not yet held
    done: bool = False
    # prompt tokens whose KV came from the prefix cache (block-aligned; the
    # leading cached_prefix // block_size entries of ``blocks`` are SHARED
    # blocks this sequence must never write — pos starts past them)
    cached_prefix: int = 0
    # sampling controls (reference generate kwargs; 0-temperature = greedy)
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    # per-request sampling seed: token g of this request draws from
    # fold_in(fold_in(SAMPLE_ROOT, seed), g) — independent of batch
    # composition and dispatch history, so a sampled generation is
    # reproducible on any engine (cache hit == cold, fused == plain)
    seed: int = 0
    # fused-pipeline bookkeeping: chunks dispatched but not yet reconciled
    # that reference this sequence (release deferred until it drains)
    refs: int = 0
    # request-lifecycle telemetry (perf_counter stamps; 0.0 = not recorded):
    # enqueue -> admit is queue wait, enqueue -> first token is TTFT
    t_enqueue: float = 0.0
    t_admit: float = 0.0
    t_first_token: float = 0.0
    t_last_token: float = 0.0
    # decode steps where this sequence stalled on KV-pool pressure
    preemptions: int = 0
    # abort path (serving tier): absolute perf_counter deadline (0.0 = none)
    # and terminal status — "finished" until cancel()/deadline expiry flips it
    # to "cancelled"/"timeout", which makes ``finished`` true so every
    # dispatch mode's release machinery retires the sequence on the next step
    deadline: float = 0.0
    status: str = "finished"
    # request-trace context (telemetry.tracing.TraceContext). Only ever
    # non-None while the tracer is enabled AND this request was sampled, so
    # ``seq.trace is not None`` is the complete hot-path guard
    trace: Any = None
    # disaggregated serving (serving/cluster.py): a prefill-stage request.
    # The engine runs the prompt plus the FIRST token only, then parks the
    # sequence (KV blocks held, slot freed) until export_handoff() gathers
    # the blocks into a KVHandoff record for a decode replica to import.
    # ``handoff_budget`` carries the request's FULL max_new_tokens through
    # to the record (the prefill stage itself runs with max_new_tokens=1).
    handoff: bool = False
    handoff_budget: int = 0
    # the cached-prefix token count the router credited at placement time
    # (advisory probe); admission re-validates the actual splice against it
    # and counts the shortfall instead of over-crediting (stale-probe fix)
    expected_cached: int = 0
    # cost attribution (telemetry/costmeter.py): billing identity plus the
    # per-request RequestCost record. ``cost`` is only ever non-None while
    # a cost meter is configured, so ``seq.cost is not None`` is the
    # complete hot-path guard at every charging seam.
    tenant: str = "default"
    sla_class: str = "interactive"
    cost: Any = None

    def token_at(self, p: int) -> int:
        if p < len(self.prompt):
            return self.prompt[p]
        return self.generated[p - len(self.prompt)]

    @property
    def in_decode(self) -> bool:
        return self.pos >= len(self.prompt)

    @property
    def finished(self) -> bool:
        if self.status != "finished":
            return True
        if self.done:
            return True
        if len(self.generated) >= self.max_new_tokens:
            return True
        return bool(self.generated) and self.generated[-1] == self.eos_token_id


@dataclass
class KVHandoff:
    """Compact prefill→decode handoff record for disaggregated serving.

    Produced by ``export_handoff`` on a prefill replica after the prompt
    (plus the first generated token) has run; consumed by ``import_handoff``
    on a decode replica, which allocates fresh blocks, scatters the payloads,
    and resumes decode token-identically (per-request sampling keys depend
    only on (seed, gen_idx), never on the engine).

    The record is deliberately transport-agnostic: plain numpy payloads, the
    device-row snapshot in the PR-4 slot-row format (``row_iv``/``row_fv``
    mirror ``_write_slot_row``'s packed int/float planes), and primitive
    request metadata — an RDMA/ICI channel can serialize it without touching
    engine internals. The in-memory channel just passes the object through.
    """

    uid: Any
    prompt: list[int]
    generated: list[int]        # tokens emitted by the prefill stage (>= 1)
    pos: int                    # KV scheduled for positions [0, pos)
    max_new_tokens: int         # the DECODE side's budget (full request)
    eos_token_id: int | None
    temperature: float
    top_k: int
    top_p: float
    seed: int                   # effective per-request sampling seed
    deadline_remaining_s: float  # seconds of deadline left at export (0 = none)
    # KV payload covering ceil(pos / block_size) blocks: a pytree mirroring
    # the engine's paged cache with each leaf sliced to the exported blocks
    # along axis 1 ([num_layers, n_blocks, block_size, ...] per leaf), as
    # host numpy arrays
    block_payload: Any = None
    # device-row snapshot (PR-4 dirty-row format): int plane
    # (tok, pos, seed, prompt_len, top_k) + float plane (temperature, top_p)
    row_iv: np.ndarray = None
    row_fv: np.ndarray = None
    # W3C trace context of the originating request, so the decode replica
    # parents its spans under the same trace_id (fleet trace stitching)
    traceparent: str | None = None
    # KV codec of block_payload ("off" = fp payload). A decode replica
    # running a DIFFERENT codec config must reject the record
    # (import_handoff raises; the cluster falls back to a cold submit)
    # instead of scattering bytes it would dequantize wrong.
    codec: str = "off"
    # billing identity carried across the prefill->decode seam so the decode
    # replica's cost meter attributes the adopted request to the same tenant
    # (defaulted: records pickled by older peers import as tenant "default")
    tenant: str = "default"
    sla_class: str = "interactive"

    @property
    def n_blocks(self) -> int:
        leaves = jax.tree_util.tree_leaves(self.block_payload)
        return int(leaves[0].shape[1]) if leaves else 0

    @property
    def nbytes(self) -> int:
        n = sum(int(a.nbytes)
                for a in jax.tree_util.tree_leaves(self.block_payload))
        for a in (self.row_iv, self.row_fv):
            if a is not None:
                n += a.nbytes
        return n

    def to_bytes(self) -> bytes:
        """Serialize the record with length+sha256 framing
        (``kvtier.frame_bytes``) so the disk spill tier and any cross-host
        transport share one end-to-end integrity check — a torn or
        bit-flipped buffer fails loudly in ``from_bytes`` instead of
        splicing corrupt KV."""
        from deepspeed_tpu.inference.kvtier import HANDOFF_MAGIC, frame_bytes

        body = pickle.dumps({f.name: getattr(self, f.name)
                             for f in fields(self)}, protocol=4)
        return HANDOFF_MAGIC + frame_bytes(body)

    @classmethod
    def from_bytes(cls, buf: bytes) -> "KVHandoff":
        """Inverse of ``to_bytes``. Raises ValueError for anything short of
        a byte-exact record (bad magic, torn frame, digest mismatch,
        trailing garbage)."""
        from deepspeed_tpu.inference.kvtier import (
            HANDOFF_MAGIC,
            unframe_bytes,
        )

        buf = bytes(buf)
        if not buf.startswith(HANDOFF_MAGIC):
            raise ValueError("not a KVHandoff record (bad magic)")
        body, end = unframe_bytes(buf, len(HANDOFF_MAGIC))
        if end != len(buf):
            raise ValueError("trailing bytes after KVHandoff frame")
        return cls(**pickle.loads(body))


@dataclass
class PrefixPayload:
    """Published prefix-cache blocks in transferable form: the prompt slice
    they cover plus their KV payloads. ``import_prefix`` re-derives the hash
    chain from the tokens (exact tuples, same keying as the local index) so
    a transferred block can never splice under the wrong key."""

    tokens: list[int]        # the covered block-aligned prompt prefix
    block_payload: Any = None  # cache pytree, leaves [L, n_blocks, bs, ...]
    # trace context of the exporting request (cross-replica span links)
    traceparent: str | None = None
    # KV codec of block_payload; a mismatched importer declines the splice
    # (prefix reuse is an optimization — a miss, not an error)
    codec: str = "off"

    @property
    def n_blocks(self) -> int:
        leaves = jax.tree_util.tree_leaves(self.block_payload)
        return int(leaves[0].shape[1]) if leaves else 0

    @property
    def nbytes(self) -> int:
        return sum(int(a.nbytes)
                   for a in jax.tree_util.tree_leaves(self.block_payload))


class RaggedInferenceEngine:
    """Continuous-batching engine over a ``ModelSpec`` with ragged hooks.

    ``put()`` requests at any time; ``step()`` advances every admitted request
    by up to one token (decodes) and/or one prompt chunk (prefills) inside one
    XLA call; finished sequences free their blocks and their slot is reused
    immediately (reference ``engine_v2.put`` + ``DSStateManager`` lifecycle).
    """

    def __init__(self, model, ragged_config: RaggedConfig | None = None,
                 dtype=jnp.bfloat16, params: Any = None, seed: int = 0,
                 eos_token_id: int | None = None, quantize_bits: int = 0):
        self.cfg = ragged_config or RaggedConfig()
        self.ctx = ShardCtx()
        self.spec: ModelSpec = model(self.ctx) if callable(model) else model
        if self.spec.ragged_forward_fn is None or self.spec.init_paged_cache_fn is None:
            raise ValueError(f"model {self.spec.name} has no ragged/paged support")
        self.dtype = dtype
        self.eos_token_id = eos_token_id

        if params is None:
            params = self.spec.init_fn(jax.random.PRNGKey(seed))
        self.params = jax.tree_util.tree_map(
            lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
            params,
        )
        # ---- low-bit serving (inference/kvquant.py) ----
        # ONE config surface: cfg.quant carries the KV codec, the woq bits
        # and the collective flag; the quantize_bits ctor arg stays as the
        # back-compat spelling of the woq component.
        parsed = kvquant.parse_quant(self.cfg.quant)
        self._kvq = parsed.kv
        self._kvq_name = parsed.kv.name if parsed.kv else "off"
        if parsed.qcol:
            # the quantized TP logits collective needs a mesh; this engine
            # is GSPMD-free single-host — accepted so one quant string works
            # across both engines, but inert here
            log_dist("ragged engine: quant '+qcol' has no mesh here; "
                     "ignored (see inference/engine.py)", ranks=[0])
        woq_bits = int(quantize_bits) or parsed.woq_bits
        if woq_bits:
            # weight-only quantization over the paged-KV engine (reference
            # inference/quantization WOQ composed with the v2 ragged engine)
            from deepspeed_tpu.ops.quantizer import quantize_params

            self.params = jax.jit(
                lambda p: quantize_params(p, bits=woq_bits,
                                          skip=tuple(self.spec.woq_skip))
            )(self.params)
        self.quantize_bits = woq_bits
        self.cache = self._build_cache()
        # bytes one block would cost unquantized at the engine dtype / at
        # fp16: the baselines for kvquant_bytes_saved_total and the
        # resident-block multiplier the bench gates on. The blocks base
        # accumulates allocated_total of allocators retired by reset_state
        # so the saved-bytes counter stays monotonic across containment.
        self._kvq_blocks_allocated = 0
        self._kvquant_saved_seen = 0
        if self._kvq is not None:
            self._fp_block_bytes = kvquant.paged_block_bytes(
                self.spec.init_paged_cache_fn, self.cfg.num_blocks,
                self.cfg.block_size, dtype)
            self._fp16_block_bytes = kvquant.paged_block_bytes(
                self.spec.init_paged_cache_fn, self.cfg.num_blocks,
                self.cfg.block_size, jnp.float16)
        self.allocator = BlockedAllocator(self.cfg.num_blocks)
        # ---- hierarchical KV tiering (inference/kvtier.py) ----
        # tier store + allocator demote hook; None with kv_tier off, and
        # the allocator's eviction path is then bit-identical to before
        self._kvtier = None
        self._kvtier_seen: dict[str, int] = {}
        if self.cfg.kv_tier:
            if not self.cfg.enable_prefix_cache:
                raise ValueError("kv_tier requires enable_prefix_cache "
                                 "(the tiers hold demoted prefix blocks)")
            if self.cfg.kv_tier_host_blocks <= 0:
                raise ValueError("kv_tier needs kv_tier_host_blocks > 0")
            from deepspeed_tpu.inference.kvtier import KVTierStore

            self._kvtier = KVTierStore(
                host_blocks=self.cfg.kv_tier_host_blocks,
                disk_blocks=self.cfg.kv_tier_disk_blocks,
                directory=self.cfg.kv_tier_dir,
                host_gbps=self.cfg.kv_tier_host_gbps,
                disk_gbps=self.cfg.kv_tier_disk_gbps,
                prefill_tokens_per_s=self.cfg.kv_tier_prefill_tokens_per_s,
                bytes_per_token=self.kv_bytes_per_token(),
                codec=self._kvq_name,
            )
            self.allocator.demote_hook = self._demote_block
        # row max_seqs is the all-zeros padding row -> scratch block 0
        self.block_tables = np.zeros(
            (self.cfg.max_seqs + 1, self.cfg.max_blocks_per_seq), np.int32
        )
        self._free_slots = list(range(self.cfg.max_seqs - 1, -1, -1))
        # blocks promised to admitted sequences but not yet allocated;
        # admission reserves worst case (prompt + max_new) so an admitted
        # sequence can always finish (reference conservative admission)
        self._reserved = 0
        self._queued: list[_SeqState] = []
        self._running: dict[int, _SeqState] = {}  # slot -> seq
        self._results: dict[Any, _SeqState] = {}
        # token-batch size buckets: decode-heavy steps run a small compiled
        # size instead of padding to the full SplitFuse budget (the static-
        # shape analog of the reference's truly-ragged kernel batches); jit
        # specializes once per bucket shape, so at most log2 programs compile
        b = 4
        self._buckets = []
        while b < self.cfg.max_tokens_per_step:
            self._buckets.append(b)
            b *= 2
        self._buckets.append(self.cfg.max_tokens_per_step)
        self._step_jit = self._build_step()
        self._chunk_jit = None  # decode run-ahead program (lazy)
        self._use_tiles = self.cfg.prefill_tile > 0
        if self._use_tiles and not self.spec.supports_prefill_tiles:
            raise ValueError(
                f"prefill_tile={self.cfg.prefill_tile} but model "
                f"{self.spec.name} does not accept prefill_tiles (its "
                "ragged_forward has no tiled path); it would silently no-op")
        if self._use_tiles and self.cfg.prefill_tile > self.cfg.max_tokens_per_step:
            raise ValueError("prefill_tile exceeds max_tokens_per_step")
        self._tiled_jits: dict = {}
        # decode-region buckets for the tiled path (decodes <= max_seqs)
        self._dec_buckets = []
        b = 4
        while b < self.cfg.max_seqs:
            self._dec_buckets.append(b)
            b *= 2
        self._dec_buckets.append(self.cfg.max_seqs)
        # fused mixed-chunk pipeline (see RaggedConfig.fused_chunk)
        self._fused_jits: dict = {}
        self._inflight_chunks: list = []
        # per-slot device buffer of the latest emitted token (+1 scratch row):
        # the next chunk's decode feed reads it ON DEVICE, so chunk t+1 can
        # dispatch before chunk t's tokens ever reach the host
        self._slot_toks = jnp.zeros(self.cfg.max_seqs + 1, jnp.int32)
        # host mirror of which slots have a valid device-side next token
        self._slot_feed = np.zeros(self.cfg.max_seqs + 1, bool)
        # ---- device-resident scheduler state (cfg.device_state) ----
        # per-slot persistent rows (+1 scratch row at index max_seqs):
        # (last_token, next_position, seed, prompt_len, temp, top_k, top_p).
        # Written in place by a donated single-row updater at admission and
        # by the dispatch programs themselves (picked token / advanced
        # position), so a steady decode dispatch reads everything per-row
        # from device memory instead of re-packed host arrays.
        s1 = self.cfg.max_seqs + 1
        self._dev_state = (
            jnp.zeros(s1, jnp.int32), jnp.zeros(s1, jnp.int32),
            jnp.zeros(s1, jnp.int32), jnp.zeros(s1, jnp.int32),
            jnp.zeros(s1, jnp.float32), jnp.zeros(s1, jnp.int32),
            jnp.ones(s1, jnp.float32),
        )
        self._slot_row_jit = jax.jit(
            lambda st, row, iv, fv: (
                st[0].at[row].set(iv[0]), st[1].at[row].set(iv[1]),
                st[2].at[row].set(iv[2]), st[3].at[row].set(iv[3]),
                st[4].at[row].set(fv[0]), st[5].at[row].set(iv[4]),
                st[6].at[row].set(fv[1])),
            donate_argnums=(0,))
        # device-resident block table: host self.block_tables stays ground
        # truth; rows dirtied by allocation/splice/release are delta-uploaded
        # (pow2-bucketed row count) before the next dispatch instead of
        # re-shipping a fresh _table_view slice every step
        self._bt_dev = jnp.asarray(self.block_tables)
        self._bt_dirty: set[int] = set()
        self._bt_row_jit = jax.jit(
            lambda bt, idx, vals: bt.at[idx].set(vals), donate_argnums=(0,))
        # packed staging buffer cache: one flat int32 upload per dispatch,
        # and ZERO uploads when the bytes match the previous dispatch at the
        # same size (the steady-decode case)
        self._staging_cache: dict[int, tuple[bytes, Any]] = {}
        # double-buffered readback for the non-fused modes: dispatched steps
        # whose tokens have not been read back yet (depth 1: readback of
        # step t overlaps the device executing step t+1)
        self._pending: list[dict] = []
        self._dev_step_jits: dict = {}
        self._dev_chunk_jits: dict = {}
        self._dev_fused_jits: dict = {}
        # device-side multi-step scheduler (cfg.sched_steps) + self-
        # speculative decoding (cfg.spec_draft) program cache
        self._dev_sched_jits: dict = {}
        # self-speculative draft state: per-slot token-history rows (prompt +
        # generated, by context position) the n-gram draft suffix-matches on
        # device. The scheduler program appends what it emits; any OTHER
        # path that moves a slot (admission, handoff import, recovery,
        # non-sched dispatches) flips the host-side stale flag so the row is
        # re-uploaded from prompt+generated before the slot's next sched
        # dispatch.
        self._hist_dev = (jnp.zeros((s1, self.cfg.max_seq_len), jnp.int32)
                          if self.cfg.spec_draft else None)
        self._hist_stale = np.ones(s1, bool)
        self._hist_row_jit = jax.jit(
            lambda h, row, vals: h.at[row].set(vals), donate_argnums=(0,))
        # set when a sched dispatch declined because stale history rows
        # cannot sync yet (outstanding refs): the turn loop must reconcile
        # instead of falling through to per-step dispatch
        self._sched_wait = False
        self.spec_proposed = 0
        self.spec_accepted = 0
        # dispatch-overhead accounting (plain ints so the bench reads them
        # with telemetry off; telemetry mirrors them when enabled)
        self.host_stage_ns = 0
        self.readback_ns = 0
        self.h2d_bytes = 0
        self._h2d_seen = 0
        # per-request sampling: token g of a request with effective seed s
        # draws from fold_in(fold_in(_sample_root, s), g). The root is a
        # FIXED constant (not engine-seeded) so an explicitly seeded request
        # reproduces on any engine; auto-assigned seeds mix the engine seed
        # + put order in instead (legacy whole-engine determinism).
        self._sample_root = jax.random.PRNGKey(0x5A3D1E)
        self._engine_seed = int(seed)
        self._put_counter = 0
        # prefix-cache accounting (plain ints so the bench can read them
        # with telemetry off; telemetry mirrors them when enabled)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_reused = 0
        self.prefix_stale_probes = 0  # admissions whose splice came up short
        self._evictions_seen = 0  # high-water for the eviction counter delta
        # ---- disaggregated serving (serving/cluster.py) ----
        # finished prefill-stage sequences whose KV blocks are parked for
        # export_handoff(); cluster prefix-index listener survives
        # reset_state() by being reinstalled on the fresh allocator
        self._handoffs: dict[Any, _SeqState] = {}
        self._prefix_listener = None
        self._kv_gather_jits: dict[int, Any] = {}
        self._kv_scatter_jits: dict[int, Any] = {}
        self.kv_blocks_exported = 0
        self.kv_blocks_imported = 0
        if self.cfg.fused_chunk == 1 or self.cfg.fused_chunk < 0:
            raise ValueError("fused_chunk must be 0 (off) or >= 2")
        if self.cfg.fused_chunk and self.cfg.pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        if self.cfg.sched_steps == 1 or self.cfg.sched_steps < 0:
            raise ValueError("sched_steps must be 0 (off) or >= 2")
        if self.cfg.spec_draft:
            if self.cfg.sched_steps < 2:
                raise ValueError("spec_draft requires sched_steps >= 2")
            if self.cfg.spec_ngram < 1:
                raise ValueError("spec_ngram must be >= 1")
        # scheduling efficiency telemetry (padding fraction; comparable to the
        # dense engine's pad-to-max waste) + dispatch accounting (on a
        # high-RTT transport, dispatches per token is the serving cost)
        self.tokens_scheduled = 0
        self.tokens_padded = 0
        self.dispatch_count = 0
        self.tokens_emitted = 0
        self.preemptions = 0
        # structured telemetry bus: request spans (queue wait / TTFT /
        # per-token decode latency / preemptions) + KV-occupancy gauges; every
        # emit is behind the singleton's enabled flag
        self.telemetry = get_telemetry()
        # request tracer: the object reference is stable for the process
        # lifetime (only its enabled flag toggles), so dispatch paths guard
        # on one attribute read and allocate nothing while tracing is off
        self._tracer = self.telemetry.tracer
        # ---- cost attribution (telemetry/costmeter.py) ----
        # the meter is read live off the bus at each seam (reconfiguration
        # mid-flight picks it up); per-seq charges guard on seq.cost, and
        # with no meter configured none of this state is ever touched.
        # _block_tenant maps published block id -> publishing tenant so the
        # retained-prefix carveout and cross-tenant splice credit/debit know
        # who to bill (bounded by num_blocks; overwritten on republish).
        self._block_tenant: dict[int, str] = {}
        self._cost_last_tick = 0.0
        self._flops_per_token: float | None = None
        # compile observability: every dispatch notes whether its jitted
        # program already existed (warm) or was created now (cold = a jit
        # cache miss at serve time); warmup() flips _warmed so coverage
        # distinguishes expected first-compiles from shape-busting traffic
        self.program_dispatches = 0
        self.program_cold_dispatches = 0
        self._warmed = False
        # specialization keys already dispatched for the paths whose jit
        # cache is internal to jax (no explicit program dict to probe)
        self._chunk_keys: set = set()
        self._step_keys: set = set()
        # ---- dispatch watchdog (docs/FAULT_TOLERANCE.md) ----
        # degraded_mode: 0 = full configured path, 1 = host-staged fallback
        # (device_state flipped off), 2 = plain-step fallback (fused/run-
        # ahead/tiles disabled). Every rung is token-identical; the ladder
        # trades dispatch efficiency for a smaller failure surface.
        self._faults = get_fault_injector()
        self._retry_rng = random.Random(self._engine_seed ^ 0x5EED)
        self.degraded_mode = 0
        self.degraded_reason: str | None = None
        self.step_failures = 0   # transient device-path failures observed
        self.step_retries = 0    # in-place retries the watchdog issued
        self._consec_failures = 0
        # ---- memory ledger (telemetry/memledger.py) ----
        # per-owner byte attribution: fixed allocations (KV pool, device
        # scheduler rows, spec history) register handles; derived owners
        # (prefix LRU, parked handoffs, staging cache) register weakref'd
        # providers. All of it only exists when the ledger is configured —
        # with it off this is one attribute read and two None stores.
        self._kv_block_bytes: int | None = None
        self._mem_stats_fn: Callable | None = None  # test hook: fake stats
        self._memledger_handles: dict | None = None
        self._headroom_wait = False  # admission pinned by measured headroom
        self._headroom_stall_ticks = 0  # consecutive zero-progress waits
        self.last_oom_report: str | None = None
        self._register_memory_owners()
        log_dist(
            f"RaggedInferenceEngine: model={self.spec.name} "
            f"budget={self.cfg.max_tokens_per_step} max_seqs={self.cfg.max_seqs} "
            f"blocks={self.cfg.num_blocks}x{self.cfg.block_size}", ranks=[0],
        )

    # ------------------------------------------------------------------ put
    def put(self, uid, prompt_tokens, max_new_tokens: int = 64,
            eos_token_id: int | None = None, temperature: float = 0.0,
            top_k: int = 0, top_p: float = 1.0,
            deadline_s: float | None = None,
            seed: int | None = None, trace=None,
            handoff: bool = False,
            expected_cached_tokens: int = 0,
            tenant: str = "default",
            sla_class: str = "interactive") -> None:
        """Enqueue a request (reference ``engine_v2.py put()``). Admission into
        the running batch happens inside ``step()`` as slots/budget free up.
        ``temperature``/``top_k``/``top_p`` select per-request sampling
        (0-temperature = greedy), applied inside the compiled step — sampled
        decode works under run-ahead and the fused pipeline with no host
        round trip (``inference/sampling.py``). ``seed`` pins the request's
        sampling stream: token g draws from a key derived only from
        (seed, g), so the same seeded request yields identical tokens on any
        engine regardless of batch composition, dispatch mode, or prefix-
        cache hits; None assigns an engine-seed + arrival-order seed (same
        engine seed + same put order still reproduces). ``deadline_s``
        bounds the request's whole lifetime (queue wait included): past it
        the sequence is released on the next ``step()`` with span
        status=timeout. ``trace`` threads a serving-side trace context
        (``telemetry.tracing.TraceContext``) so the request's engine spans
        parent under the HTTP root; with the tracer enabled and no context
        given, the engine head-samples a fresh trace per request."""
        prompt = [int(t) for t in np.asarray(prompt_tokens).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # a prefill-stage (handoff) request runs prompt + ONE token here;
        # the decode replica that imports the record owns the full budget
        # (and re-validates it against its own caps at import)
        eff_new = 1 if handoff else max_new_tokens
        total = len(prompt) + eff_new
        if total > self.cfg.max_seq_len:
            raise ValueError(
                f"request length {total} exceeds engine max_seq_len "
                f"{self.cfg.max_seq_len}"
            )
        worst = -(-total // self.cfg.block_size)
        if worst > min(self.cfg.num_blocks - 1, self.cfg.max_blocks_per_seq):
            raise ValueError(
                f"request needs {worst} KV blocks but at most "
                f"{min(self.cfg.num_blocks - 1, self.cfg.max_blocks_per_seq)} "
                "are available per sequence — it could never be admitted"
            )
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be positive")
        if seed is None:
            eff_seed = (self._engine_seed * 1000003
                        + self._put_counter) & 0x7FFFFFFF
        else:
            eff_seed = int(seed) & 0x7FFFFFFF
        self._put_counter += 1
        # re-putting a retired uid supersedes its old record (idempotent
        # failover resubmission: the router replays a request that died with
        # its replica; get_request/_results must reflect the live attempt,
        # not the stale error)
        self._results.pop(uid, None)
        if self._tracer.enabled:
            # seq.trace is the request's umbrella "engine/request" span:
            # a child of the serving root when one was threaded in, or a
            # fresh head-sampled root for direct engine use. The span id is
            # allocated now so queue/admission/dispatch/readback children
            # can parent to it; the span itself is recorded at release.
            trace_ctx = (self._tracer.begin(trace) if trace is not None
                         else self._tracer.extract(None))
        else:
            trace_ctx = None
        seq = _SeqState(
            uid=uid, prompt=prompt, max_new_tokens=eff_new,
            eos_token_id=eos_token_id if eos_token_id is not None else self.eos_token_id,
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p), seed=eff_seed,
            deadline=(time.perf_counter() + deadline_s) if deadline_s else 0.0,
            t_enqueue=time.perf_counter() if self.telemetry.enabled else 0.0,
            trace=trace_ctx,
            handoff=bool(handoff), handoff_budget=int(max_new_tokens),
            expected_cached=max(0, int(expected_cached_tokens)),
            tenant=str(tenant), sla_class=str(sla_class),
        )
        cm = self.telemetry.costmeter
        if cm is not None:
            seq.cost = cm.start(seq.tenant, seq.sla_class)
        self._queued.append(seq)
        if self.telemetry.enabled:
            self.telemetry.counter(
                "inference_requests_queued_total", "requests accepted").inc()

    @property
    def has_work(self) -> bool:
        return bool(self._queued or self._running or self._inflight_chunks
                    or self._pending)

    @property
    def finished_uids(self):
        """UIDs of completed requests (public completion signal; the full
        token lists come from ``generate_all`` / the per-uid state)."""
        return set(self._results)

    def get_request(self, uid):
        """Host descriptor of a request at any lifecycle stage (queued,
        running, or retired), or None if the uid is unknown. The serving
        tier's token-delivery loop reads ``generated``/``status`` off it."""
        seq = self._results.get(uid)
        if seq is not None:
            return seq
        for seq in self._running.values():
            if seq.uid == uid:
                return seq
        for seq in self._queued:
            if seq.uid == uid:
                return seq
        return None

    def cancel(self, uid) -> bool:
        """Abort a request. The reference engine has no abort path (only a
        full drain); a serving frontend needs one or a hung client leaks KV
        pages forever. A queued request is dropped and a running one releases
        its KV blocks on the next ``step()`` (``_release`` via the normal
        retirement machinery — under the fused pipeline the release defers
        until in-flight chunks referencing the sequence reconcile). The
        request span is emitted with ``status=cancelled``. Returns False if
        the uid is unknown or already retired."""
        for seq in self._queued:
            if seq.uid == uid and seq.status == "finished":
                seq.status = "cancelled"
                return True
        for seq in self._running.values():
            if seq.uid == uid and seq.status == "finished":
                seq.status = "cancelled"
                return True
        return False

    def _sweep_aborts(self) -> None:
        """Retire cancelled/deadline-expired sequences (queued AND running)
        at the top of every step, so an abort can never outlive one step
        boundary. Queued sequences hold no blocks and retire directly;
        running ones go through ``_release`` (KV blocks + slot freed) unless
        the fused pipeline still references them (``refs`` > 0), in which
        case ``_reconcile_oldest`` releases them as the chunks drain."""
        now = None
        for seq in (*self._queued, *self._running.values()):
            if seq.status == "finished" and seq.deadline:
                if now is None:
                    now = time.perf_counter()
                if now >= seq.deadline:
                    seq.status = "timeout"
        aborted = [s for s in self._queued if s.status != "finished"]
        if aborted:
            self._queued = [s for s in self._queued if s.status == "finished"]
            for seq in aborted:
                self._results[seq.uid] = seq
                if self.telemetry.enabled:
                    self._emit_request_span(seq)
        for seq in list(self._running.values()):
            if seq.status != "finished" and seq.refs == 0:
                self._release(seq)

    # ------------------------------------------------------------------ step
    def _worst_case_blocks(self, seq: _SeqState) -> int:
        total = len(seq.prompt) + seq.max_new_tokens
        return -(-total // self.cfg.block_size)

    # ---------------------------------------------------------- prefix cache
    def _match_prefix(self, prompt: list[int]) -> list[int]:
        """Longest cached full-block prefix of ``prompt``: walk the hash
        chain block by block until the first miss. Capped one token short of
        the full prompt — the first generated token needs the LAST prompt
        position's logits, which only a real forward produces, and recomputing
        that token's KV must land in a fresh (unshared) block — so at least
        the prompt's final block always prefills."""
        bs = self.cfg.block_size
        max_blocks = (len(prompt) - 1) // bs
        alloc = self.allocator
        blocks: list[int] = []
        key = None
        for i in range(max_blocks):
            key = (key, tuple(prompt[i * bs:(i + 1) * bs]))
            b = alloc.lookup(key)
            if b is None:
                break
            blocks.append(b)
        return blocks

    def cached_prefix_len(self, prompt_tokens) -> int:
        """Tokens of ``prompt_tokens`` the prefix cache could serve right now
        (block-aligned, always < len(prompt)). Read-only — no refcount or
        LRU mutation — so the serving router can probe it for admission math
        from another thread; the answer is advisory (the cache can evict
        between probe and admission) and admission re-checks under the
        engine's own reservation accounting."""
        if not self.cfg.enable_prefix_cache:
            return 0
        prompt = [int(t) for t in np.asarray(prompt_tokens).reshape(-1)]
        if not prompt:
            return 0
        return len(self._match_prefix(prompt)) * self.cfg.block_size

    def _publish_prompt_blocks(self, seq: _SeqState) -> None:
        """Publish the retired sequence's full prompt blocks into the prefix
        index (refcount handling stays in ``free``: published blocks fall
        into the evictable LRU instead of the free list when their last
        referent drops). Only blocks whose KV was actually scheduled count —
        a cancelled request mid-prefill publishes just its computed region."""
        bs = self.cfg.block_size
        n_full = min(seq.pos, len(seq.prompt)) // bs
        key = None
        track = seq.cost is not None
        for i in range(n_full):
            key = (key, tuple(seq.prompt[i * bs:(i + 1) * bs]))
            self.allocator.publish(seq.blocks[i], key)
            if track:
                # record the publisher so retained-prefix occupancy and
                # cross-tenant splices can be billed to the right party
                self._block_tenant[seq.blocks[i]] = seq.tenant

    # ------------------------------------- KV transfer (disaggregated serving)
    def set_prefix_listener(self, listener) -> None:
        """Attach a publish/evict listener (the cluster prefix index) to the
        allocator; survives ``reset_state`` (reinstalled on the fresh
        allocator, with ``listener.on_reset()`` telling the index to drop
        this replica's entries)."""
        self._prefix_listener = listener
        self.allocator.listener = listener

    def kv_bytes_per_token(self) -> int:
        """Bytes of paged-cache state one token position occupies across all
        cache leaves — the bytes side of the transfer-vs-prefill cost model."""
        bs = self.cfg.block_size
        total = 0
        for a in jax.tree_util.tree_leaves(self.cache):
            per_block = int(a.shape[0]) * int(np.prod(a.shape[2:])) \
                * a.dtype.itemsize
            total += per_block // bs
        return total

    def _block_bytes(self) -> int:
        """Bytes one KV block occupies across all cache leaves (cached)."""
        if self._kv_block_bytes is None:
            self._kv_block_bytes = \
                self.kv_bytes_per_token() * self.cfg.block_size
        return self._kv_block_bytes

    def _build_cache(self):
        """Build the paged KV pool: the family's plain fp pool when quant is
        off (bit-identical to the pre-quant engine), else the low-bit
        ``QuantizedKV`` pool built from ``eval_shape`` (no transient fp
        allocation at the full pool size)."""
        if self._kvq is None:
            return self.spec.init_paged_cache_fn(
                self.cfg.num_blocks, self.cfg.block_size, self.dtype)
        return kvquant.build_quantized_paged_cache(
            self.spec.init_paged_cache_fn, self.cfg.num_blocks,
            self.cfg.block_size, self.dtype, self._kvq)

    def kv_quant_stats(self) -> dict | None:
        """Low-bit KV summary for bench/telemetry readers; None = quant off.
        ``resident_multiplier_vs_fp16`` is the blocks-per-HBM-byte gain the
        acceptance bar measures (fp16 block bytes / quantized block bytes)."""
        if self._kvq is None:
            return None
        bb = self._block_bytes()
        return {
            "codec": self._kvq_name,
            "block_bytes": bb,
            "fp16_block_bytes": self._fp16_block_bytes,
            "fp_block_bytes": self._fp_block_bytes,
            "resident_multiplier_vs_fp16": self._fp16_block_bytes / bb,
            "blocks_allocated_total": self._kvq_alloc_total(),
            "bytes_saved_total":
                self._kvq_alloc_total() * (self._fp_block_bytes - bb),
        }

    def _kvq_alloc_total(self) -> int:
        """Cumulative blocks allocated over the engine's lifetime (survives
        reset_state's allocator replacement via the accumulated base)."""
        return self._kvq_blocks_allocated + self.allocator.allocated_total

    # ------------------------------------------------------- memory ledger
    def _register_memory_owners(self) -> None:
        """Attribute this engine's long-lived device allocations to ledger
        owners. Providers close over a weakref so a retired engine is never
        pinned by the process-wide ledger (a dead ref returns None, which
        the ledger prunes). Called at construction AND retried from the
        per-step telemetry hook: telemetry is often configured after the
        engine is built (the training engine has the same lazy pattern),
        and an engine that never registers would make every census read
        ~100% unattributed. The handle cache makes re-entry a no-op."""
        led = self.telemetry.memledger
        if led is None or self._memledger_handles is not None:
            return
        h = {
            "params": led.register("params", "ragged/model_params",
                                   self.params),
            "kv_pool": led.register("kv_pool", "ragged/paged_kv_cache",
                                    self.cache),
            "device_sched_state": led.register(
                "device_sched_state", "ragged/slot_rows+block_table",
                (self._dev_state, self._bt_dev, self._slot_toks)),
        }
        if self._hist_dev is not None:
            h["spec_lanes"] = led.register(
                "spec_lanes", "ragged/spec_token_history", self._hist_dev)
        self._memledger_handles = h
        ref = weakref.ref(self)

        def _staging_bytes():
            eng = ref()
            if eng is None:
                return None
            return sum(len(b) for b, _ in eng._staging_cache.values())

        def _prefix_retained_bytes():
            eng = ref()
            if eng is None:
                return None
            return eng.allocator.retained_blocks * eng._block_bytes()

        def _handoff_bytes():
            eng = ref()
            if eng is None:
                return None
            return sum(len(s.blocks) for s in eng._handoffs.values()) \
                * eng._block_bytes()

        led.register_provider("staging_buffers", "ragged/staging_cache",
                              _staging_bytes)
        if self._kvtier is not None:
            def _host_tier_bytes():
                eng = ref()
                if eng is None or eng._kvtier is None:
                    return None
                return eng._kvtier.host_nbytes

            def _disk_tier_bytes():
                eng = ref()
                if eng is None or eng._kvtier is None:
                    return None
                return eng._kvtier.disk_nbytes

            # off-device owners: host-RAM/disk bytes show in the breakdown
            # and gauges but are EXCLUDED from the census reconciliation
            # against jax.live_arrays() — they are not device bytes, and
            # counting them would fake overattribution
            led.register_provider("host_kv_tier", "ragged/kvtier_host_arena",
                                  _host_tier_bytes, offdevice=True)
            led.register_provider("disk_kv_tier", "ragged/kvtier_disk_spill",
                                  _disk_tier_bytes, offdevice=True)
        # retained prefix blocks and parked handoff blocks live INSIDE the
        # kv_pool arrays registered above — carve-outs, so the breakdown
        # shows them as their own owners while the attributed total still
        # counts each pool byte exactly once
        led.register_provider("prefix_cache_retained", "ragged/prefix_lru",
                              _prefix_retained_bytes, carveout_of="kv_pool")
        led.register_provider("kv_handoff", "ragged/parked_handoffs",
                              _handoff_bytes, carveout_of="kv_pool")

    def _refresh_memory_handles(self) -> None:
        """Re-measure ledger handles after crash containment rebuilt the
        cache/state arrays (the old buffers are garbage now)."""
        led = self.telemetry.memledger
        h = self._memledger_handles
        if led is None or h is None:
            return
        led.update(h["kv_pool"], self.cache)
        led.update(h["device_sched_state"],
                   (self._dev_state, self._bt_dev, self._slot_toks))
        if "spec_lanes" in h:
            led.update(h["spec_lanes"], self._hist_dev)

    def _note_oom(self, seam: str, exc: BaseException) -> None:
        """OOM forensics: snapshot the per-owner breakdown + census into a
        crash-report JSON the moment RESOURCE_EXHAUSTED surfaces (never
        raises; marks the exception so nested seams report once)."""
        if getattr(exc, "_oom_recorded", False):
            return
        try:
            exc._oom_recorded = True
        except Exception:
            pass
        path = record_oom(seam, exc, context={
            "running": len(self._running),
            "queued": len(self._queued),
            "free_blocks": self.allocator.free_blocks,
            "reserved_blocks": self._reserved,
            "retained_blocks": self.allocator.retained_blocks,
            "degraded_mode": self.degraded_mode,
        })
        if path is not None:
            self.last_oom_report = path

    # --------------------------------------------- headroom-driven admission
    def _device_memory_stats(self) -> dict:
        if self._mem_stats_fn is not None:
            try:
                return self._mem_stats_fn() or {}
            except Exception:
                return {}
        try:
            from deepspeed_tpu.accelerator.real_accelerator import (
                get_accelerator,
            )

            return get_accelerator().memory_stats() or {}
        except Exception:
            return {}

    def admission_headroom_blocks(self) -> int:
        """MEASURED free-byte headroom expressed in KV blocks, net of the
        pool's own preallocated footprint: the pool's allocatable blocks
        (free list + evictable prefix LRU) are bytes the device already
        funds, so admission drawing from them consumes no new HBM and must
        never be gated by a full-looking device. Only a deficit beyond what
        the pool could fund — other owners eating the guard band — shrinks
        the answer. -1 = unknown (no ``bytes_limit`` reported, or headroom
        admission disabled) — callers must fall back to the static
        block-count path, bit-identically."""
        cfg = self.cfg
        if not cfg.headroom_admission:
            return -1
        stats = self._device_memory_stats()
        limit = int(stats.get("bytes_limit") or 0)
        if limit <= 0:
            return -1
        bb = max(1, self._block_bytes())
        free = limit - int(stats.get("bytes_in_use") or 0)
        pool_funded = self.allocator.free_blocks * bb
        usable = free + pool_funded - int(cfg.headroom_guard_fraction * limit)
        return max(0, usable // bb)

    def _enforce_retained_budget(self) -> int:
        """Shed the prefix-cache LRU under POOL-level pressure: retention
        may hold only what outstanding reservations don't need, i.e. evict
        until the free list alone covers ``self._reserved``. Deliberately
        not a device-byte budget — evicting a retained block returns it to
        the preallocated pool's free list and frees zero HBM, so a
        measured-byte budget here would wipe the cache on a full device
        without recovering anything. When reservations already fit the free
        list this is a no-op (static-path parity)."""
        alloc = self.allocator
        budget = alloc.free_blocks - self._reserved
        if budget >= alloc.retained_blocks:
            return 0
        evicted = alloc.shrink_retained(budget)
        if evicted and self.telemetry.enabled:
            self.telemetry.counter(
                "prefix_cache_headroom_evictions_total",
                "cached blocks evicted so the pool free list covers "
                "outstanding admission reservations",
            ).inc(evicted)
        return evicted

    def _kv_jits(self):
        if "g" not in self._kv_gather_jits:
            self._kv_gather_jits["g"] = jax.jit(
                lambda c, i: jax.tree_util.tree_map(lambda a: a[:, i], c))
            # donated: the scatter replaces self.cache in place
            self._kv_scatter_jits["s"] = jax.jit(
                lambda c, i, p: jax.tree_util.tree_map(
                    lambda a, pa: a.at[:, i].set(pa.astype(a.dtype)), c, p),
                donate_argnums=(0,))
        return self._kv_gather_jits["g"], self._kv_scatter_jits["s"]

    def _gather_blocks(self, blocks: list[int]):
        """Read the KV rows of ``blocks`` back to host numpy (pow2-bucketed
        index so the gather compiles O(log max_blocks_per_seq) times; pad
        rows re-read the scratch block and are sliced off)."""
        g, _ = self._kv_jits()
        n = len(blocks)
        r = 1
        while r < n:
            r *= 2
        idx = np.zeros(r, np.int32)
        idx[:n] = blocks
        out = g(self.cache, jnp.asarray(idx))
        return jax.tree_util.tree_map(lambda a: np.asarray(a[:, :n]), out)

    def _scatter_blocks(self, blocks: list[int], payload) -> None:
        """Write transferred KV payloads into ``blocks`` (donated in-place
        update of the paged cache; pad rows land in the scratch block)."""
        _, s = self._kv_jits()
        n = len(blocks)
        r = 1
        while r < n:
            r *= 2
        idx = np.zeros(r, np.int32)
        idx[:n] = blocks
        if r != n:
            payload = jax.tree_util.tree_map(
                lambda a: np.concatenate(
                    [a, np.zeros((a.shape[0], r - n) + a.shape[2:], a.dtype)],
                    axis=1),
                payload)
        self.h2d_bytes += idx.nbytes + sum(
            int(a.nbytes) for a in jax.tree_util.tree_leaves(payload))
        self.cache = s(self.cache, jnp.asarray(idx), payload)

    def export_handoff(self, uid) -> KVHandoff | None:
        """Turn a finished prefill-stage request (``put(handoff=True)``) into
        a transferable KVHandoff record, then retire its blocks locally
        (publishing the prompt blocks into this replica's prefix cache
        first, exactly like a normal retirement). None if ``uid`` has no
        parked handoff state (cancelled / timed out / already exported)."""
        seq = self._handoffs.pop(uid, None)
        if seq is None:
            return None
        bs = self.cfg.block_size
        # canonical resume point: feeding token_at(pos) at position pos
        # produces generated index pos - len(prompt) + 1, so the decode
        # side must resume one position behind the newest emitted token.
        # (Speculative dispatch may have scheduled KV further; re-writing
        # that cell on resume is masked until the position is reached.)
        pos = len(seq.prompt) + len(seq.generated) - 1
        n_ctx = -(-pos // bs)
        payload = self._gather_blocks(seq.blocks[:n_ctx])
        self.kv_blocks_exported += n_ctx
        tok = seq.token_at(pos) if pos >= len(seq.prompt) else 0
        iv = np.asarray([tok, pos, seq.seed, len(seq.prompt), seq.top_k],
                        np.int32)
        fv = np.asarray([seq.temperature, seq.top_p], np.float32)
        rem = (max(0.0, seq.deadline - time.perf_counter())
               if seq.deadline else 0.0)
        rec = KVHandoff(
            uid=seq.uid, prompt=list(seq.prompt),
            generated=list(seq.generated), pos=pos,
            max_new_tokens=seq.handoff_budget or seq.max_new_tokens,
            eos_token_id=seq.eos_token_id, temperature=seq.temperature,
            top_k=seq.top_k, top_p=seq.top_p, seed=seq.seed,
            deadline_remaining_s=rem, block_payload=payload,
            row_iv=iv, row_fv=fv,
            traceparent=(format_traceparent(seq.trace)
                         if seq.trace is not None else None),
            codec=self._kvq_name,
            tenant=seq.tenant, sla_class=seq.sla_class)
        if seq.cost is not None:
            # settle the parked occupancy and bill the exported payload
            self._cost_tick()
            seq.cost.handoff_export_bytes += rec.nbytes
        if self.cfg.enable_prefix_cache:
            self._publish_prompt_blocks(seq)
        self.allocator.free(seq.blocks)
        seq.blocks = []
        self._finalize_cost(seq)
        if self.telemetry.enabled:
            self.telemetry.counter(
                "kv_transfer_blocks_total",
                "KV blocks moved by handoff/prefix transfers",
            ).inc(n_ctx, direction="export")
        return rec

    def discard_handoff(self, uid) -> bool:
        """Release a parked handoff without exporting it (the cluster's
        failure paths: transfer cancelled, decode side gone)."""
        seq = self._handoffs.pop(uid, None)
        if seq is None:
            return False
        if seq.cost is not None:
            self._cost_tick()
        if self.cfg.enable_prefix_cache:
            self._publish_prompt_blocks(seq)
        self.allocator.free(seq.blocks)
        seq.blocks = []
        self._finalize_cost(seq)
        return True

    def import_handoff(self, h: KVHandoff) -> bool:
        """Adopt a prefill replica's handoff: allocate fresh blocks, scatter
        the KV payload, seed the slot's device row from the record's PR-4
        row snapshot, and resume decode token-identically. Returns False
        when no slot or insufficient unreserved blocks are available right
        now (the cluster falls back to a cold submit); raises ValueError for
        requests this engine could never serve."""
        cfg = self.cfg
        bs = cfg.block_size
        if getattr(h, "codec", "off") != self._kvq_name:
            # scattering a payload quantized under a different codec would
            # dequantize garbage (or splice int8 bytes as fp) — never
            # servable here, so raise (the loop surfaces import_rejected
            # and the cluster falls back to a cold submit)
            raise ValueError(
                f"handoff KV codec {getattr(h, 'codec', 'off')!r} does not "
                f"match this engine's quant config {self._kvq_name!r}")
        prompt = [int(t) for t in h.prompt]
        total = len(prompt) + int(h.max_new_tokens)
        if total > cfg.max_seq_len:
            raise ValueError(
                f"handoff length {total} exceeds engine max_seq_len "
                f"{cfg.max_seq_len}")
        worst = -(-total // bs)
        if worst > min(cfg.num_blocks - 1, cfg.max_blocks_per_seq):
            raise ValueError(
                f"handoff needs {worst} KV blocks but at most "
                f"{min(cfg.num_blocks - 1, cfg.max_blocks_per_seq)} are "
                "available per sequence")
        pos = int(h.pos)
        n_ctx = -(-pos // bs)
        if h.n_blocks != n_ctx:
            raise ValueError(
                f"handoff payload covers {h.n_blocks} blocks but pos={pos} "
                f"needs {n_ctx}")
        if not self._free_slots:
            return False
        if worst > self.allocator.free_blocks - self._reserved:
            return False
        seq = _SeqState(
            uid=h.uid, prompt=prompt, max_new_tokens=int(h.max_new_tokens),
            eos_token_id=h.eos_token_id, temperature=float(h.temperature),
            top_k=int(h.top_k), top_p=float(h.top_p), seed=int(h.seed),
            generated=list(h.generated), pos=pos,
            deadline=(time.perf_counter() + h.deadline_remaining_s)
            if h.deadline_remaining_s else 0.0,
            t_enqueue=time.perf_counter() if self.telemetry.enabled else 0.0,
            tenant=str(getattr(h, "tenant", "default")),
            sla_class=str(getattr(h, "sla_class", "interactive")),
        )
        cm = self.telemetry.costmeter
        if cm is not None:
            seq.cost = cm.start(seq.tenant, seq.sla_class)
            seq.cost.handoff_import_bytes += h.nbytes
        if self._tracer.enabled and h.traceparent:
            # adopt the prefill replica's trace: this request's decode-side
            # spans parent under the exporting span, so the fleet-merged
            # timeline shows ONE trace_id across both replicas
            seq.trace = self._tracer.extract(h.traceparent)
        self._results.pop(h.uid, None)  # supersede any stale retired record
        blocks = self.allocator.allocate(n_ctx)
        self._scatter_blocks(blocks, h.block_payload)
        self.kv_blocks_imported += n_ctx
        if self.telemetry.enabled:
            self.telemetry.counter(
                "kv_transfer_blocks_total",
                "KV blocks moved by handoff/prefix transfers",
            ).inc(n_ctx, direction="import")
        seq.blocks = blocks
        if seq.finished:
            # the prefill stage already hit EOS (or the budget was 1):
            # nothing to decode — retire immediately, seeding the local
            # prefix cache with the transferred prompt blocks
            if cfg.enable_prefix_cache:
                self._publish_prompt_blocks(seq)
            self.allocator.free(blocks)
            seq.blocks = []
            self._finalize_cost(seq)
            self._results[seq.uid] = seq
            return True
        slot = self._free_slots.pop()
        seq.slot = slot
        seq.reserved_remaining = worst - n_ctx
        self._reserved += seq.reserved_remaining
        self.block_tables[slot, :] = 0
        self.block_tables[slot, :n_ctx] = blocks
        self._bt_dirty.add(slot)
        self._slot_feed[slot] = False
        self._running[slot] = seq
        if cfg.device_state:
            # the record's device-row snapshot IS the slot row (PR-4 format);
            # only the slot index is local
            iv = np.asarray(h.row_iv, np.int32)
            fv = np.asarray(h.row_fv, np.float32)
            self.h2d_bytes += iv.nbytes + fv.nbytes + 4
            self._dev_state = self._slot_row_jit(
                self._dev_state, np.int32(slot), iv, fv)
        # draft history is NOT part of the handoff row format: the decode
        # side rebuilds it from prompt + generated before the slot's first
        # speculative dispatch
        self._hist_stale[slot] = True
        return True

    def export_prefix(self, prompt_tokens, trace=None) -> PrefixPayload | None:
        """Export the longest locally-cached full-block prefix of a prompt
        as a transferable payload (cluster prefix transfer: the holder
        ships published blocks to the replica the router actually picked).
        None when nothing is cached. ``trace`` (a TraceContext) stamps the
        payload's ``traceparent`` so the importer's span links back to the
        requesting trace across processes."""
        if not self.cfg.enable_prefix_cache:
            return None
        prompt = [int(t) for t in np.asarray(prompt_tokens).reshape(-1)]
        if not prompt:
            return None
        if self._kvtier is not None:
            # a demoted chain is still this replica's to export: promote it
            # back to HBM first so the cluster index's tier-aware promises
            # stay serveable
            self._tier_promote(prompt)
        hit = self._match_prefix(prompt)
        if not hit:
            return None
        self.allocator.acquire(hit)  # pin against eviction during the gather
        try:
            payload = self._gather_blocks(hit)
        finally:
            self.allocator.free(hit)
        self.kv_blocks_exported += len(hit)
        if self.telemetry.enabled:
            self.telemetry.counter(
                "kv_transfer_blocks_total",
                "KV blocks moved by handoff/prefix transfers",
            ).inc(len(hit), direction="export")
        return PrefixPayload(
            tokens=prompt[:len(hit) * self.cfg.block_size],
            block_payload=payload,
            traceparent=(format_traceparent(trace)
                         if trace is not None else None),
            codec=self._kvq_name)

    def import_prefix(self, payload: PrefixPayload | None) -> int:
        """Install transferred prefix blocks into the local prefix cache
        (allocate → scatter → publish under the re-derived hash chain →
        refcount-0 into the evictable LRU, so the import stays strictly
        free-memory-funded). Returns the contiguous-from-root token count
        now cached locally. Already-published chain links are kept (dedupe);
        imports past the unreserved budget are dropped, never forced."""
        if payload is None or not self.cfg.enable_prefix_cache:
            return 0
        if getattr(payload, "codec", "off") != self._kvq_name:
            # prefix transfer is opportunistic — a codec mismatch is a
            # graceful miss (the importer just prefills), unlike handoff
            # adoption where mid-stream state makes it a hard error
            return 0
        t_imp0 = (time.perf_counter()
                  if self._tracer.enabled and payload.traceparent else 0.0)
        bs = self.cfg.block_size
        tokens = [int(t) for t in payload.tokens]
        n = min(payload.n_blocks, len(tokens) // bs)
        alloc = self.allocator
        keys = []
        missing = []
        key = None
        for i in range(n):
            key = (key, tuple(tokens[i * bs:(i + 1) * bs]))
            keys.append(key)
            if alloc.lookup(key) is None:
                missing.append(i)
        budget = max(0, alloc.free_blocks - self._reserved)
        missing = missing[:budget]
        if missing:
            blocks = alloc.allocate(len(missing))
            midx = np.asarray(missing)
            self._scatter_blocks(
                blocks,
                jax.tree_util.tree_map(lambda a: a[:, midx],
                                       payload.block_payload))
            for b, i in zip(blocks, missing):
                alloc.publish(b, keys[i])
            alloc.free(blocks)  # refcount 0 + published -> evictable LRU
            self.kv_blocks_imported += len(blocks)
            if self.telemetry.enabled:
                self.telemetry.counter(
                    "kv_transfer_blocks_total",
                    "KV blocks moved by handoff/prefix transfers",
                ).inc(len(blocks), direction="import")
        m = 0
        for k in keys:
            if alloc.lookup(k) is None:
                break
            m += 1
        if t_imp0:
            # span-link back to the exporting request's trace: the import
            # renders on this replica's track under the exporter's trace_id
            ctx = self._tracer.extract(payload.traceparent)
            self._tracer.finish(ctx, "kv/prefix_import", t_imp0,
                                time.perf_counter(),
                                blocks=len(missing), tokens=m * bs)
        return m * bs

    # --------------------------------- hierarchical KV tiering (kvtier.py)
    def _demote_block(self, block: int, key) -> bool:
        """Allocator demote hook: gather one evicted block's payload
        device->host and park it in the tier store. Runs on the engine
        thread inside ``_evict_lru`` while the payload is still intact;
        True = captured (the cluster index hears a demotion, not a drop)."""
        store = self._kvtier
        if store is None:
            return False
        try:
            payload = self._gather_blocks([block])
        except Exception:  # noqa: BLE001 - a failed gather is a plain evict
            return False
        ok = store.demote(key, payload)
        if ok:
            cm = self.telemetry.costmeter
            if cm is not None:
                # the demoted payload is the publishing tenant's working set
                # moving tier-ward; the publisher carries the byte charge
                tenant = self._block_tenant.get(block)
                if tenant is not None:
                    cm.demote_bytes(tenant, self._block_bytes())
        return ok

    def _chain_keys(self, prompt: list[int]) -> list:
        """The prompt's full-block hash-chain keys, root-first, capped one
        token short of the prompt exactly like ``_match_prefix``."""
        bs = self.cfg.block_size
        keys = []
        key = None
        for i in range((len(prompt) - 1) // bs):
            key = (key, tuple(prompt[i * bs:(i + 1) * bs]))
            keys.append(key)
        return keys

    def _tier_promote(self, prompt: list[int]) -> int:
        """Restore demoted chain links of ``prompt`` from the host/disk
        tiers back into the HBM prefix index, in chain order, when the
        cost model says the restore beats re-prefilling them. The restore
        is the ``import_prefix`` template — allocate -> scatter -> publish
        -> refcount-0 into the evictable LRU — so a subsequent
        ``_match_prefix`` splices promoted blocks exactly like blocks that
        never left HBM (token identity is free). Returns blocks promoted.

        Budget discipline matches ``import_prefix``: promotion draws only
        from unreserved allocatable blocks, and the allocation itself may
        demote colder LRU entries — the tiers churn, admission never
        starves."""
        store = self._kvtier
        if store is None:
            return 0
        bs = self.cfg.block_size
        alloc = self.allocator
        t0 = time.perf_counter()
        # contiguous-from-root restorable run: links already in HBM pass
        # through; the first link in neither HBM nor a tier ends the chain
        cand: list[tuple[Any, Any, int]] = []  # (key, payload, tier)
        for key in self._chain_keys(prompt):
            if alloc.lookup(key) is not None:
                continue
            tier = store.tier_of(key)
            if tier == 0:
                break  # held nowhere: the contiguous chain ends here
            if not store.should_restore(bs, tier):
                # a held link the cost model declines also ends the run —
                # splicing past a gap is impossible anyway
                store.restore_declined += 1
                break
            got = store.fetch(key)
            if got is None:
                break  # raced an overflow drop between tier_of and fetch
            cand.append((key, got[0], got[1]))
        budget = max(0, alloc.free_blocks - self._reserved)
        cand = cand[:budget]
        if not cand:
            return 0
        blocks = alloc.allocate(len(cand))
        payload = jax.tree_util.tree_map(
            lambda *xs: np.concatenate(xs, axis=1), *[p for _, p, _ in cand])
        self._scatter_blocks(blocks, payload)
        for b, (key, _, _) in zip(blocks, cand):
            alloc.publish(b, key)
        alloc.free(blocks)  # refcount 0 + published -> evictable LRU (MRU)
        dt = time.perf_counter() - t0
        tiers = [t for _, _, t in cand]
        store.note_restored(tiers, dt)
        if self.telemetry.enabled:
            self.telemetry.histogram(
                "kvtier_restore_seconds",
                "wall time of one tiered prefix restore (gather from tier, "
                "scatter to HBM, publish)",
            ).observe(dt, tier="disk" if 2 in tiers else "host")
        return len(cand)

    def _tier_admit(self, seq: _SeqState) -> None:
        """Admission-time tier pass, just before ``_match_prefix``: resolve
        the request's async prefetch (hit when staging finished during the
        queue wait, abandoned when admission outran it) and run the
        synchronous promotion — cheap when the prefetch landed, a full
        tier read when it didn't. Either way ``_match_prefix`` then sees
        the restored links in the ordinary HBM index."""
        store = self._kvtier
        keys = self._chain_keys(seq.prompt)
        if not keys:
            return
        store.note_admission(keys[-1])
        promoted = self._tier_promote(seq.prompt)
        if promoted and seq.cost is not None:
            # the admitting request is who needed the restore: it carries
            # the promote-byte charge (restored bytes re-entering HBM)
            seq.cost.tier_promote_bytes += promoted * self._block_bytes()

    def tier_prefetch_async(self, prompt_tokens) -> bool:
        """Advisory cross-thread prefetch kick (the serving router calls
        this at placement): queue a background staging job for the prompt's
        chain links missing from HBM so their restore overlaps the queue
        wait. Thread-safe — touches only the tier store (its own lock) and
        the same racy-but-safe read-only index probes
        ``cached_prefix_len`` already makes off-thread."""
        store = self._kvtier
        if store is None or not self.cfg.kv_tier_prefetch:
            return False
        prompt = [int(t) for t in np.asarray(prompt_tokens).reshape(-1)]
        keys = self._chain_keys(prompt)
        if not keys:
            return False
        pending = [k for k in keys if self.allocator.lookup(k) is None]
        if not pending:
            return False
        return store.prefetch(pending, sig=keys[-1])

    def kv_tier_stats(self) -> dict | None:
        """Tier store counters/occupancy (None when tiering is off).
        Thread-safe: the store snapshots under its own lock, so the
        frontend's ``/debug/memory`` can read it off-thread."""
        return None if self._kvtier is None else self._kvtier.stats()

    def _ensure_capacity(self, seq: _SeqState, upto: int) -> bool:
        """Grow seq's block table to cover positions [0, upto); False if the
        pool can't satisfy it right now. Admitted sequences draw from their
        admission-time reservation, so this cannot fail for them."""
        need = -(-upto // self.cfg.block_size) - len(seq.blocks)
        if need <= 0:
            return True
        if need > self.allocator.free_blocks:
            return False
        if len(seq.blocks) + need > self.cfg.max_blocks_per_seq:
            return False
        if self._faults.enabled:
            try:
                self._faults.fire(POINT_ALLOC, request_id=str(seq.uid))
            except Exception as e:
                if is_resource_exhausted(e):
                    self._note_oom("alloc", e)
                raise
        new = self.allocator.allocate(need)
        start = len(seq.blocks)
        seq.blocks.extend(new)
        drawn = min(seq.reserved_remaining, len(new))
        seq.reserved_remaining -= drawn
        self._reserved -= drawn
        self.block_tables[seq.slot, start:start + len(new)] = new
        self._bt_dirty.add(seq.slot)
        return True

    @staticmethod
    def _stamp_emission(seq: _SeqState, now: float) -> None:
        if not seq.t_first_token:
            seq.t_first_token = now
        seq.t_last_token = now

    def _release(self, seq: _SeqState) -> None:
        self._reserved -= seq.reserved_remaining  # return unused reservation
        seq.reserved_remaining = 0
        if seq.cost is not None:
            # close the occupancy integral over this sequence's final slice
            # before its blocks return to the pool
            self._cost_tick()
        if seq.handoff and seq.status == "finished":
            # prefill-stage retirement: PARK the KV blocks (refcounts held)
            # for export_handoff() instead of freeing them — only the slot
            # and reservation return to the pool. Cancel/timeout/error paths
            # fall through to the normal free below.
            self.block_tables[seq.slot, :] = 0
            self._bt_dirty.add(seq.slot)
            self._free_slots.append(seq.slot)
            del self._running[seq.slot]
            seq.slot = -1
            self._handoffs[seq.uid] = seq
            self._results[seq.uid] = seq
            if self.telemetry.enabled:
                self._emit_request_span(seq)
            return
        if self.cfg.enable_prefix_cache:
            # publish BEFORE free: blocks whose last referent drops here land
            # in the evictable LRU instead of the free list
            self._publish_prompt_blocks(seq)
        self.allocator.free(seq.blocks)
        seq.blocks = []
        self.block_tables[seq.slot, :] = 0
        self._bt_dirty.add(seq.slot)
        self._free_slots.append(seq.slot)
        del self._running[seq.slot]
        seq.slot = -1
        self._results[seq.uid] = seq
        if self.telemetry.enabled:
            self._emit_request_span(seq)

    def _emit_request_span(self, seq: _SeqState) -> None:
        """One request-lifecycle span at completion: queue wait, TTFT, mean
        per-token decode latency, preemption count (FastGen's serving SLO
        metrics, machine-readable)."""
        tel = self.telemetry
        n_gen = len(seq.generated)
        ttft = (seq.t_first_token - seq.t_enqueue
                if seq.t_first_token and seq.t_enqueue else None)
        queue_wait = (seq.t_admit - seq.t_enqueue
                      if seq.t_admit and seq.t_enqueue else None)
        # mean inter-token latency after the first token; chunked dispatch
        # (run-ahead / fused pipeline) amortizes inside the mean
        decode_latency = ((seq.t_last_token - seq.t_first_token) / (n_gen - 1)
                          if n_gen > 1 and seq.t_first_token else None)
        dur = (seq.t_last_token - seq.t_enqueue
               if seq.t_last_token and seq.t_enqueue else 0.0)
        cost_attrs = {}
        if seq.cost is not None:
            if queue_wait is not None:
                seq.cost.queue_wait_s = max(0.0, queue_wait)
            cost_attrs = seq.cost.span_attrs()
        tel.emit_span(
            "inference/request", dur, uid=str(seq.uid),
            status=seq.status,
            queue_wait_s=queue_wait, ttft_s=ttft,
            decode_latency_s=decode_latency,
            prompt_tokens=len(seq.prompt), new_tokens=n_gen,
            preemptions=seq.preemptions, **cost_attrs)
        if seq.status == "cancelled":
            tel.counter("inference_requests_cancelled_total",
                        "requests aborted via cancel()").inc()
        elif seq.status == "timeout":
            tel.counter("inference_requests_timeout_total",
                        "requests expired past their deadline").inc()
        tel.counter("inference_requests_total", "requests completed").inc()
        tel.counter("inference_tokens_generated_total",
                    "tokens generated").inc(n_gen)
        if seq.preemptions:
            tel.counter("inference_preemptions_total",
                        "decode steps stalled on KV-pool pressure").inc(
                            seq.preemptions)
        if ttft is not None:
            tel.histogram("inference_ttft_seconds",
                          "time to first token").observe(ttft)
            tel.observe_slo("ttft", ttft, sla_class=seq.sla_class)
        if decode_latency is not None:
            tel.histogram("inference_decode_latency_seconds",
                          "mean inter-token decode latency").observe(
                              decode_latency)
            tel.observe_slo("decode_latency", decode_latency,
                            sla_class=seq.sla_class)
        if seq.trace is not None:
            # close the request's umbrella span: every queue/admission/
            # dispatch/readback child recorded along the way nests under it
            t_end = seq.t_last_token or time.perf_counter()
            t_start = seq.t_enqueue or t_end
            self._tracer.finish(
                seq.trace, "engine/request", t_start, t_end,
                uid=str(seq.uid), status=seq.status,
                prompt_tokens=len(seq.prompt), new_tokens=n_gen,
                ttft_s=ttft, preemptions=seq.preemptions or None)
            if not (seq.handoff and seq.status == "finished"):
                seq.trace = None  # released: nothing records under it now
            # a finished prefill-stage seq keeps its context parked with the
            # KV blocks: export_handoff stamps it as the record's traceparent
            # so the decode replica's spans stitch under this trace
        if not (seq.handoff and seq.status == "finished"):
            # a parked handoff keeps accruing block-seconds until export/
            # discard retires its blocks; everyone else settles up now
            self._finalize_cost(seq)

    def _finalize_cost(self, seq: _SeqState) -> None:
        """Fold the request's RequestCost into the meter exactly once."""
        cost = seq.cost
        if cost is None:
            return
        seq.cost = None
        cm = self.telemetry.costmeter
        if cm is not None:
            if not cost.queue_wait_s and seq.t_admit and seq.t_enqueue:
                cost.queue_wait_s = max(0.0, seq.t_admit - seq.t_enqueue)
            cm.observe(cost)

    def _cost_tick(self) -> None:
        """Advance the KV occupancy integral: charge every block-holding
        sequence (running + parked handoffs) and the retained prefix
        carveout (credited to publishing tenants) for the slice since the
        last tick. Called at the seams where block ownership changes —
        admission, release, handoff export/discard — plus the periodic
        step-telemetry sampler so long decodes accrue continuously."""
        cm = self.telemetry.costmeter
        if cm is None:
            return
        now = time.perf_counter()
        last = self._cost_last_tick
        self._cost_last_tick = now
        if not last:
            return  # first tick only establishes the baseline
        dt = now - last
        if dt <= 0.0:
            return
        live = [(s.cost, len(s.blocks)) for s in self._running.values()
                if s.cost is not None and s.blocks]
        for s in self._handoffs.values():
            if s.cost is not None and s.blocks:
                live.append((s.cost, len(s.blocks)))
        alloc = self.allocator
        retained: list[tuple[str, int]] = []
        if alloc._lru:
            bt = self._block_tenant
            counts: dict[str, int] = {}
            for b in alloc._lru:
                t = bt.get(b)
                if t is not None:
                    counts[t] = counts.get(t, 0) + 1
            retained = list(counts.items())
        cm.tick(dt, live, retained, alloc.busy_blocks)

    def _cost_fair_index(self, cm) -> int:
        """Index of the queued request admission should try next under the
        fair-share policy: the first whose tenant is at/under its fair share
        of outstanding blocks. Single-tenant queues (and queues where every
        tenant is over — everyone equally hungry) return 0, i.e. plain FIFO."""
        q = self._queued
        first = q[0].tenant
        if all(s.tenant == first for s in q):
            return 0
        for i, s in enumerate(q):
            share, fair = cm.outstanding_share(s.tenant)
            if share <= fair + 1e-9:
                return i
        return 0

    def _flops_per_token_value(self) -> float:
        """Analytic forward FLOPs per token (lazy; one profile per engine)."""
        if self._flops_per_token is None:
            try:
                from deepspeed_tpu.profiling.flops_profiler import (
                    get_model_profile,
                )
                prof = get_model_profile(self.spec, 1, 128,
                                         with_compiled=False)
                self._flops_per_token = float(prof.flops_fwd) / 128.0
            except Exception:
                self._flops_per_token = 0.0  # profile unavailable: tokens
                # still counted, FLOPs column reads 0 rather than failing
        return self._flops_per_token

    def _build_step(self) -> Callable:
        fwd = self.spec.ragged_forward_fn

        def step_fn(params, cache, tokens, slots, positions, block_tables):
            return fwd(params, tokens, slots, positions, block_tables, cache)

        return jax.jit(step_fn, donate_argnums=(1,))

    def _build_decode_chunk(self) -> Callable:
        """K fused decode steps over the paged cache: one dispatch, next
        token (greedy or per-request sampled) fed back on device, KV
        scattered per step. ``K`` and the sampled? flag are static (jit
        specializes per (K, batch, sampled) triple)."""
        fwd = self.spec.ragged_forward_fn
        from functools import partial

        @partial(jax.jit, static_argnums=(0, 1, 2, 3), donate_argnums=(5,))
        def chunk_fn(k, sampled, has_tk, has_tp, params, cache, tokens, slots,
                     positions, block_tables, root, seeds, gen0, temp, topk,
                     topp):
            def pick(lg, r):
                if not sampled:
                    return jnp.argmax(
                        lg.astype(jnp.float32), axis=-1).astype(jnp.int32)
                from deepspeed_tpu.inference.sampling import sample_tokens

                return sample_tokens(lg, r, temp,
                                     top_k=topk if has_tk else None,
                                     top_p=topp if has_tp else None)[0]

            def one(carry, i):
                cache, toks, pos = carry
                logits, cache = fwd(params, toks, slots, pos, block_tables, cache)
                from deepspeed_tpu.inference.sampling import per_request_keys
                nxt = pick(logits, per_request_keys(root, seeds, gen0 + i))
                return (cache, nxt, pos + 1), nxt

            (cache, _, _), out = jax.lax.scan(
                one, (cache, tokens, positions), jnp.arange(k))
            return out, cache  # out: [K, T] generated tokens

        return chunk_fn

    # ------------------------------------------- device-resident dispatch
    def _write_slot_row(self, seq: _SeqState) -> None:
        """Admission hook: write one slot's persistent device row in place
        (donated updater; ~32 bytes H2D instead of per-step re-packing).
        ``pos`` starts past any spliced cached prefix; at admission ``tok``
        is reset (the prompt-completing dispatch publishes the first feed
        token). When the watchdog rebuilds a mid-decode sequence's row,
        ``pos`` is already past the prompt and the host-known token at that
        position seeds the device feed instead."""
        tok = seq.token_at(seq.pos) if seq.pos >= len(seq.prompt) else 0
        iv = np.asarray([tok, seq.pos, seq.seed, len(seq.prompt), seq.top_k],
                        np.int32)
        fv = np.asarray([seq.temperature, seq.top_p], np.float32)
        self.h2d_bytes += iv.nbytes + fv.nbytes + 4
        self._dev_state = self._slot_row_jit(
            self._dev_state, np.int32(seq.slot), iv, fv)
        self._hist_stale[seq.slot] = True

    def _sync_bt(self) -> None:
        """Delta-upload block-table rows dirtied since the last dispatch
        (allocation growth, prefix splice, release) into the device-resident
        table. Row count is pow2-bucketed so the scatter compiles
        O(log max_seqs) times; padding index rows re-write the always-zero
        scratch row."""
        if not self._bt_dirty:
            return
        rows = sorted(self._bt_dirty)
        self._bt_dirty.clear()
        r = 1
        while r < len(rows):
            r *= 2
        idx = np.full(r, self.cfg.max_seqs, np.int32)
        idx[:len(rows)] = rows
        vals = np.zeros((r, self.cfg.max_blocks_per_seq), np.int32)
        vals[:len(rows)] = self.block_tables[rows]
        self.h2d_bytes += idx.nbytes + vals.nbytes
        self._bt_dev = self._bt_row_jit(self._bt_dev, jnp.asarray(idx),
                                        jnp.asarray(vals))

    def _stage(self, arr: np.ndarray):
        """Upload ONE packed int32 staging buffer for a dispatch, skipping
        the H2D copy entirely when the bytes match the previous dispatch at
        this size — the steady-decode case: slots/flags planes are static
        across steps and tokens/positions live on device, so the whole
        buffer byte-compares equal."""
        if self._faults.enabled:
            self._faults.fire(POINT_H2D)
        arr = np.ascontiguousarray(arr, np.int32)
        raw = arr.tobytes()
        hit = self._staging_cache.get(arr.shape[0])
        if hit is not None and hit[0] == raw:
            return hit[1]
        dev = jnp.asarray(arr)
        self._staging_cache[arr.shape[0]] = (raw, dev)
        self.h2d_bytes += arr.nbytes
        return dev

    def _h2d(self, arr: np.ndarray):
        """Legacy-path upload helper: jnp.asarray + H2D byte accounting, so
        the host-staged and device-resident paths report comparable
        ``h2d_bytes`` to the bench and telemetry."""
        if self._faults.enabled:
            self._faults.fire(POINT_H2D)
        self.h2d_bytes += arr.nbytes
        return jnp.asarray(arr)

    def _note_dispatch(self, t0: float) -> None:
        """Per-dispatch overhead epilogue: host staging wall time (packing +
        upload + dispatch enqueue, NOT device execution) into the plain
        counter and, when enabled, the ``ragged_dispatch_host_ms``
        histogram."""
        dt = time.perf_counter() - t0
        self.host_stage_ns += int(dt * 1e9)
        self.dispatch_count += 1
        if self.telemetry.enabled:
            self.telemetry.histogram(
                "ragged_dispatch_host_ms",
                "host-side staging time per ragged dispatch",
                buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                         50.0)).observe(dt * 1e3)

    def _trace_spans(self, t0: float, t1: float, pairs, **attrs) -> None:
        """Record one child span per traced sequence over the window
        [t0, t1]. ``pairs`` is ``[(seq, span_name, tokens)]`` — callers
        build it (and call this) only when ``self._tracer.enabled``, so the
        untraced hot path allocates nothing."""
        tr = self._tracer
        for seq, name, ntok in pairs:
            tr.record(seq.trace, name, t0, t1, tokens=ntok, **attrs)

    def _note_program(self, kind: str, novel: bool) -> None:
        """Compile observability: every dispatch notes whether its jitted
        program already existed (warm) or had to be created (cold — the
        request's shape fell outside the cached bucket ladder, so XLA is
        compiling mid-serve). Feeds the ``warmup_coverage`` gauge and the
        per-family miss counter; ``warmup()`` zeroes the running totals so
        coverage reflects post-warmup traffic only."""
        self.program_dispatches += 1
        if not novel:
            return
        self.program_cold_dispatches += 1
        if self.telemetry.enabled:
            self.telemetry.counter(
                "ragged_program_cache_misses_total",
                "dispatches that created a new jitted program (shape "
                "outside the cached bucket ladder)").inc(kind=kind)

    def _get_dev_step(self, t: int, nd: int, nt: int, w: int, sampled: bool,
                      has_tk: bool, has_tp: bool):
        """Device-resident SplitFuse step (plain or tiled): feed tokens and
        positions gathered from the persistent slot rows (flag bit 0), pick
        next tokens ON DEVICE (greedy or per-request sampled, keys derived
        from device seed/position/prompt-len rows), and update the slot
        rows in place — the host touches only the packed staging buffer and
        the eventual token readback. Statics: (t_total, nd, nt, table
        width, sampling-filter flags)."""
        key = (t, nd, nt, w, sampled, has_tk, has_tp)
        fn = self._dev_step_jits.get(key)
        self._note_program("dev_step", fn is None)
        if fn is not None:
            return fn
        fwd = self.spec.ragged_forward_fn
        ct = self.cfg.prefill_tile if self._use_tiles else 0
        max_seqs = self.cfg.max_seqs
        ntl = max(nt, 1)

        def step_fn(params, cache, state, bt_full, staged, root):
            from deepspeed_tpu.inference.sampling import (keys_for_positions,
                                                          sample_tokens)
            tok_st, pos_st, seed_st, plen_st, temp_st, topk_st, topp_st = state
            tokens = staged[0:t]
            slots = staged[t:2 * t]
            positions = staged[2 * t:3 * t]
            flags = staged[3 * t:4 * t]
            feed = (flags & 1) > 0
            real = slots != max_seqs
            tokens = jnp.where(feed, tok_st[slots], tokens)
            positions = jnp.where(feed & real, pos_st[slots], positions)
            bt = bt_full[:, :w] if w < bt_full.shape[1] else bt_full
            if ct:
                ts = staged[4 * t:4 * t + ntl]
                tp_ = staged[4 * t + ntl:4 * t + 2 * ntl]
                tv = staged[4 * t + 2 * ntl:4 * t + 3 * ntl]
                logits, cache = fwd(params, tokens, slots, positions, bt,
                                    cache, prefill_tiles=(nd, ts, tp_, tv, ct))
            else:
                logits, cache = fwd(params, tokens, slots, positions, bt,
                                    cache)
            if sampled:
                keys = keys_for_positions(root, seed_st[slots], positions,
                                          plen_st[slots])
                picked, _ = sample_tokens(
                    logits, keys, temp_st[slots],
                    top_k=topk_st[slots] if has_tk else None,
                    top_p=topp_st[slots] if has_tp else None)
            else:
                picked = jnp.argmax(logits.astype(jnp.float32),
                                    axis=-1).astype(jnp.int32)
            em = ((flags & 2) > 0) & real
            sl_t = jnp.where(em, slots, max_seqs)
            tok_st = tok_st.at[sl_t].set(jnp.where(em, picked, tok_st[sl_t]))
            sl_p = jnp.where(real, slots, max_seqs)
            pos_st = pos_st.at[sl_p].max(jnp.where(real, positions + 1, 0))
            state = (tok_st, pos_st, seed_st, plen_st, temp_st, topk_st,
                     topp_st)
            return picked, state, cache

        fn = jax.jit(step_fn, donate_argnums=(1, 2))
        self._dev_step_jits[key] = fn
        return fn

    def _get_dev_chunk(self, k: int, t: int, w: int, sampled: bool,
                       has_tk: bool, has_tp: bool):
        """Device-resident decode run-ahead: K fused decode steps whose
        feed token, start position, and per-request sampling parameters are
        all gathered from the persistent slot rows — the staging buffer is
        just the slot ids, which byte-compare equal across a steady decode
        run (zero upload)."""
        key = (k, t, w, sampled, has_tk, has_tp)
        fn = self._dev_chunk_jits.get(key)
        self._note_program("dev_chunk", fn is None)
        if fn is not None:
            return fn
        fwd = self.spec.ragged_forward_fn
        max_seqs = self.cfg.max_seqs

        def chunk_fn(params, cache, state, bt_full, staged, root):
            from deepspeed_tpu.inference.sampling import (per_request_keys,
                                                          sample_tokens)
            tok_st, pos_st, seed_st, plen_st, temp_st, topk_st, topp_st = state
            slots = staged[:t]
            real = slots != max_seqs
            bt = bt_full[:, :w] if w < bt_full.shape[1] else bt_full
            toks0 = tok_st[slots]
            pos0 = jnp.where(real, pos_st[slots], 0)
            seeds = seed_st[slots]
            gen0 = pos0 - plen_st[slots] + 1
            temp = temp_st[slots]
            topk = topk_st[slots]
            topp = topp_st[slots]

            def pick(lg, r):
                if not sampled:
                    return jnp.argmax(lg.astype(jnp.float32),
                                      axis=-1).astype(jnp.int32)
                return sample_tokens(lg, r, temp,
                                     top_k=topk if has_tk else None,
                                     top_p=topp if has_tp else None)[0]

            def one(carry, i):
                cache, toks, pos = carry
                logits, cache = fwd(params, toks, slots, pos, bt, cache)
                nxt = pick(logits, per_request_keys(root, seeds, gen0 + i))
                return (cache, nxt, pos + 1), nxt

            (cache, last, _), out = jax.lax.scan(
                one, (cache, toks0, pos0), jnp.arange(k))
            sl = jnp.where(real, slots, max_seqs)
            tok_st = tok_st.at[sl].set(jnp.where(real, last, tok_st[sl]))
            pos_st = pos_st.at[sl].add(jnp.where(real, k, 0))
            state = (tok_st, pos_st, seed_st, plen_st, temp_st, topk_st,
                     topp_st)
            return out, state, cache

        fn = jax.jit(chunk_fn, donate_argnums=(1, 2))
        self._dev_chunk_jits[key] = fn
        return fn

    def _dispatch_chunk_device(self) -> bool:
        """Device-state analog of ``_try_decode_run_ahead``: same
        eligibility and capacity rules, but the dispatch stages only slot
        ids and the tokens land in a pending record instead of blocking on
        readback."""
        cfg = self.cfg
        k_max = cfg.decode_run_ahead
        seqs = [s for s in self._running.values() if not s.finished]
        if not seqs or any(not s.in_decode for s in seqs):
            return False
        if self._queued and self._free_slots:
            k_max = min(k_max, cfg.run_ahead_admission_cap)
            if k_max < 2:
                return False
        # remaining tokens still SCHEDULABLE (pos-based: generated lags the
        # schedule by the pending window, pos is the ground truth here)
        rem = min(len(s.prompt) + s.max_new_tokens - s.pos for s in seqs)
        k = min(k_max, rem)
        while k >= 2 and not all(self._ensure_capacity(s, s.pos + k)
                                 for s in seqs):
            k -= 1
        if k < 2:
            return False
        k = 1 << (k.bit_length() - 1)
        t0 = time.perf_counter()
        t = len(seqs)
        bucket = next(b for b in self._buckets if b >= t)
        slots = np.full(bucket, cfg.max_seqs, np.int32)
        sampled = has_tk = has_tp = False
        for j, s in enumerate(seqs):
            slots[j] = s.slot
            sampled = sampled or s.temperature > 0.0
            has_tk = has_tk or s.top_k > 0
            has_tp = has_tp or s.top_p < 1.0
        max_pos = max(s.pos + k - 1 for s in seqs)
        self._sync_bt()
        staged = self._stage(slots)
        fn = self._get_dev_chunk(k, bucket, self._table_width(max_pos),
                                 sampled, sampled and has_tk,
                                 sampled and has_tp)
        if self._faults.enabled:
            self._faults.fire(POINT_DISPATCH)
        out, self._dev_state, self.cache = fn(
            self.params, self.cache, self._dev_state, self._bt_dev, staged,
            self._sample_root)
        emits = []
        for s in seqs:
            s.pos += k
            s.refs += 1
            self._slot_feed[s.slot] = True
            self._hist_stale[s.slot] = True
            emits.append((s, k))
        self.tokens_scheduled += k * t
        self.tokens_padded += k * (bucket - t)
        self._pending.append({"kind": "chunk", "out": out, "emits": emits,
                              "participants": seqs})
        self._note_dispatch(t0)
        if self._tracer.enabled:
            self._trace_spans(t0, time.perf_counter(),
                              [(s, "engine/decode", k) for s in seqs],
                              mode="dev_run_ahead")
        return True

    # ------------------------------------- device-side multi-step scheduler
    def _get_dev_sched(self, k: int, t: int, w: int, sampled: bool,
                       has_tk: bool, has_tp: bool):
        """Multi-step decode scheduler with DEVICE-SIDE retirement (+
        optional self-speculation): a ``lax.while_loop`` over up to ``k``
        decode iterations that retires rows on EOS/length inside the
        program — retired rows mask to the scratch slot, the loop exits
        early once every row is done — and returns per-row ``steps_taken``
        so the host only reconciles.

        The staging buffer is ``[slots | eos | limit]`` (``limit`` = last
        feed position, ``prompt_len + max_new - 1``, constant per request),
        so steady decode byte-compares equal and uploads NOTHING; feed
        token and position come from the persistent slot rows, and per-row
        step budgets are derived on device as ``limit - pos``. Rows the
        host believes live but the device already retired (pipelined
        dispatch after an EOS pick) re-derive ``done`` from their
        persistent token row, emit zero steps, and cost no compute.

        With ``cfg.spec_draft`` > 0 each iteration proposes up to D tokens
        per row from the device-resident history (prompt lookup), verifies
        them in the SAME forward via ``speculative_lane_layout``, and
        surfaces the exact-match acceptance prefix + the target's bonus
        pick — emitting up to D+1 tokens per iteration while staying
        bit-identical to plain decoding (greedy and seeded)."""
        d = self.cfg.spec_draft
        key = (k, t, w, sampled, has_tk, has_tp)
        fn = self._dev_sched_jits.get(key)
        self._note_program("dev_sched", fn is None)
        if fn is not None:
            return fn
        fwd = self.spec.ragged_forward_fn
        max_seqs = self.cfg.max_seqs
        ngram = self.cfg.spec_ngram
        lanes = 1 + d

        def sched_body(params, cache, state, hist, bt_full, staged, root):
            from deepspeed_tpu.inference.sampling import (
                accept_drafts, keys_for_positions, propose_ngram_drafts,
                sample_tokens)
            from deepspeed_tpu.models.paged import speculative_lane_layout
            tok_st, pos_st, seed_st, plen_st, temp_st, topk_st, topp_st = state
            slots = staged[:t]
            eos = staged[t:2 * t]
            limit = staged[2 * t:3 * t]
            real = slots != max_seqs
            bt = bt_full[:, :w] if w < bt_full.shape[1] else bt_full
            toks0 = tok_st[slots]
            pos0 = jnp.where(real, pos_st[slots], 0)
            seeds = seed_st[slots]
            plen = plen_st[slots]
            temp = temp_st[slots]
            topk = topk_st[slots]
            topp = topp_st[slots]
            # per-row step budget; the host guaranteed KV capacity for
            # exactly min(k, limit - pos) feeds, so cap marks the first
            # position WITHOUT an allocated block
            bud = jnp.where(real, jnp.clip(limit - pos0, 0, k), 0)
            cap = pos0 + bud
            # device-side retirement of rows the host optimistically
            # re-dispatched: the persistent token row already holds EOS
            done0 = ~real | (bud <= 0) | ((eos >= 0) & (toks0 == eos))

            def rep(x):  # row value -> per-verify-lane (row-major lanes)
                return jnp.repeat(x, lanes)

            def pick_lanes(lg, fpos_raw):
                if not sampled:
                    return jnp.argmax(lg.astype(jnp.float32),
                                      axis=-1).astype(jnp.int32)
                keys = keys_for_positions(root, rep(seeds), fpos_raw,
                                          rep(plen))
                return sample_tokens(lg, keys, rep(temp),
                                     top_k=rep(topk) if has_tk else None,
                                     top_p=rep(topp) if has_tp else None)[0]

            lane_i = jnp.arange(lanes)[None, :]
            col_i = jnp.broadcast_to(jnp.arange(t)[:, None], (t, lanes))

            def body(c):
                if d:
                    cache, toks, pos, emitted, done, out, prop, acc, hist = c
                else:
                    cache, toks, pos, emitted, done, out, prop, acc = c
                    hist = None
                live = ~done
                if d:
                    draft, _ = propose_ngram_drafts(hist[slots], pos, ngram,
                                                    d)
                else:
                    draft = None
                ftok, fslot, fpos, fraw = speculative_lane_layout(
                    toks, draft, pos, live, cap, slots, max_seqs)
                lg, cache = fwd(params, ftok, fslot, fpos, bt, cache)
                picked = pick_lanes(lg, fraw).reshape(t, lanes)
                n_emit, n_acc = accept_drafts(
                    draft if d else jnp.zeros((t, 0), jnp.int32), picked,
                    jnp.where(live, bud - emitted, 0), eos)
                sel = lane_i < n_emit[:, None]
                # surfaced tokens land at out rows emitted..emitted+n-1;
                # unselected lanes scatter into dump row k
                tgt = jnp.where(sel, emitted[:, None] + lane_i, k)
                out = out.at[tgt, col_i].set(picked)
                if d:
                    # emitted token i is the token at context position
                    # pos+1+i: append to the history the draft reads
                    hpos = jnp.where(sel, pos[:, None] + 1 + lane_i, 0)
                    hslot = jnp.where(sel, slots[:, None], max_seqs)
                    hist = hist.at[hslot, hpos].set(picked)
                last = jnp.take_along_axis(
                    picked, jnp.clip(n_emit - 1, 0, lanes - 1)[:, None],
                    axis=1)[:, 0]
                toks = jnp.where(n_emit > 0, last, toks)
                pos = pos + n_emit
                emitted = emitted + n_emit
                hit_eos = (eos >= 0) & (last == eos) & (n_emit > 0)
                done = done | hit_eos | (emitted >= bud)
                if d:
                    prop = prop + jnp.sum(
                        jnp.where(live, d, 0)).astype(jnp.int32)
                    acc = acc + jnp.sum(n_acc).astype(jnp.int32)
                r = (cache, toks, pos, emitted, done, out, prop, acc)
                return r + ((hist,) if d else ())

            zero_i = jnp.zeros((t,), jnp.int32)
            carry = (cache, toks0, pos0, zero_i, done0,
                     jnp.full((k + 1, t), -1, jnp.int32),
                     jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
            if d:
                carry = carry + (hist,)
            carry = jax.lax.while_loop(
                lambda c: jnp.any(~c[4]), body, carry)
            cache, toks, pos, emitted, _, out, prop, acc = carry[:8]
            if d:
                hist = carry[8]
            sl = jnp.where(real, slots, max_seqs)
            tok_st = tok_st.at[sl].set(jnp.where(real, toks, tok_st[sl]))
            pos_st = pos_st.at[sl].set(jnp.where(real, pos, pos_st[sl]))
            state = (tok_st, pos_st, seed_st, plen_st, temp_st, topk_st,
                     topp_st)
            return out[:k], emitted, prop, acc, state, hist, cache

        if d:
            fn = jax.jit(sched_body, donate_argnums=(1, 2, 3))
        else:
            def nohist(params, cache, state, bt_full, staged, root):
                out, steps, _, _, state, _, cache = sched_body(
                    params, cache, state, None, bt_full, staged, root)
                return out, steps, state, cache

            fn = jax.jit(nohist, donate_argnums=(1, 2))
        self._dev_sched_jits[key] = fn
        return fn

    def _upload_hist(self, seq: _SeqState) -> None:
        """Re-seed one slot's device history row from the host's complete
        view (prompt + generated). Only legal when the slot has no
        outstanding dispatches (refs drained) — otherwise host ``generated``
        lags the device position row and the rebuilt history would hold a
        hole right where the draft matcher reads."""
        row = np.zeros(self.cfg.max_seq_len, np.int32)
        toks = list(seq.prompt) + list(seq.generated)
        row[:len(toks)] = toks
        self.h2d_bytes += row.nbytes + 4
        self._hist_dev = self._hist_row_jit(
            self._hist_dev, np.int32(seq.slot), row)
        self._hist_stale[seq.slot] = False

    def _dispatch_sched_device(self) -> bool:
        """Dispatch one multi-step scheduler program when every running
        sequence is decoding. Mirrors ``_dispatch_chunk_device``'s
        eligibility/admission rules but budgets PER ROW (rows near their
        length limit no longer cap the whole chunk — the program retires
        them in place), advances host positions optimistically by each
        row's own budget, and queues a pending record carrying the
        per-row ``steps_taken`` readback."""
        cfg = self.cfg
        k_max = cfg.sched_steps
        seqs = [s for s in self._running.values() if not s.finished]
        if not seqs or any(not s.in_decode for s in seqs):
            return False
        if self._queued and self._free_slots:
            # bounded chunk under admission pressure, like run-ahead
            k_max = min(k_max, cfg.run_ahead_admission_cap)
            if k_max < 1:
                return False
        plan = []
        max_bud = 0
        for s in seqs:
            bud = len(s.prompt) + s.max_new_tokens - 1 - s.pos
            if bud <= 0:
                continue  # fully scheduled; retires as pending reconciles
            plan.append(s)
            max_bud = max(max_bud, min(bud, k_max))
        if not plan:
            return False
        # pow2 round DOWN: the device derives each row's step count as
        # min(k, limit - pos), so k must never exceed the capacity the
        # host actually reserved below
        k = 1 << (max_bud.bit_length() - 1)
        kept = []
        for s in plan:
            k_s = min(k, len(s.prompt) + s.max_new_tokens - 1 - s.pos)
            if not self._ensure_capacity(s, s.pos + k_s):
                s.preemptions += 1
                self.preemptions += 1
                continue
            kept.append((s, k_s))
        if not kept:
            return False
        if cfg.spec_draft:
            stale = [s for s, _ in kept if self._hist_stale[s.slot]]
            if any(s.refs for s in stale):
                self._sched_wait = True
                return False
            for s in stale:
                self._upload_hist(s)
        t0 = time.perf_counter()
        t = len(kept)
        bucket = next(b for b in self._buckets if b >= t)
        slots = np.full(bucket, cfg.max_seqs, np.int32)
        eos = np.full(bucket, -1, np.int32)
        limit = np.zeros(bucket, np.int32)
        sampled = has_tk = has_tp = False
        max_pos = 0
        for j, (s, k_s) in enumerate(kept):
            slots[j] = s.slot
            if s.eos_token_id is not None:
                eos[j] = s.eos_token_id
            limit[j] = len(s.prompt) + s.max_new_tokens - 1
            sampled = sampled or s.temperature > 0.0
            has_tk = has_tk or s.top_k > 0
            has_tp = has_tp or s.top_p < 1.0
            max_pos = max(max_pos, s.pos + k_s - 1)
        self._sync_bt()
        staged = self._stage(np.concatenate([slots, eos, limit]))
        fn = self._get_dev_sched(k, bucket, self._table_width(max_pos),
                                 sampled, sampled and has_tk,
                                 sampled and has_tp)
        if self._faults.enabled:
            self._faults.fire(POINT_DISPATCH)
        if cfg.spec_draft:
            out, steps, prop, acc, self._dev_state, self._hist_dev, \
                self.cache = fn(
                    self.params, self.cache, self._dev_state, self._hist_dev,
                    self._bt_dev, staged, self._sample_root)
        else:
            out, steps, self._dev_state, self.cache = fn(
                self.params, self.cache, self._dev_state, self._bt_dev,
                staged, self._sample_root)
            prop = acc = None
        emits = []
        sched_tok = 0
        for s, k_s in kept:
            # optimistic: the device may retire the row earlier on EOS;
            # the overshoot is never rewound — the sequence finishes at
            # reconcile and releases once its refs drain
            s.pos += k_s
            s.refs += 1
            self._slot_feed[s.slot] = True
            emits.append((s, k_s))
            sched_tok += k_s
        self.tokens_scheduled += sched_tok
        self.tokens_padded += k * bucket - sched_tok
        self._pending.append({"kind": "sched", "out": out, "steps": steps,
                              "prop": prop, "acc": acc, "emits": emits,
                              "participants": [s for s, _ in kept]})
        self._note_dispatch(t0)
        if self._tracer.enabled:
            self._trace_spans(t0, time.perf_counter(),
                              [(s, "engine/decode", ks) for s, ks in kept],
                              mode="dev_sched")
        return True

    def _dispatch_step_device(self) -> bool:
        """Device-state analog of the plain/tiled SplitFuse step: schedule
        decodes + prefill chunks exactly as the legacy path does, but stage
        them as one packed buffer (decode rows carry no token/position —
        those live on device), dispatch the device-resident step program,
        and queue the picked-token readback as a pending record. Returns
        False when nothing is schedulable."""
        cfg = self.cfg
        ct = cfg.prefill_tile if self._use_tiles else 0
        budget = cfg.max_tokens_per_step
        t0 = time.perf_counter()
        trace_on = self._tracer.enabled
        tpairs = [] if trace_on else None
        size = budget + ct
        tokens = np.zeros(size, np.int32)
        slots = np.full(size, cfg.max_seqs, np.int32)
        positions = np.zeros(size, np.int32)
        flags = np.zeros(size, np.int32)
        emit: list[tuple[int, _SeqState]] = []
        max_pos = 0
        dec_cap = min(budget, cfg.max_seqs) if ct else budget
        n_dec = 0
        for seq in list(self._running.values()):
            if seq.finished or not seq.in_decode or n_dec >= dec_cap:
                continue
            # the feed at limit-1 yields the final budgeted token; sched
            # mode uses the exact bound (its own budgets already do), the
            # legacy modes keep the historical +1 slop (extra token is
            # discarded at reconcile)
            lim = len(seq.prompt) + seq.max_new_tokens
            if cfg.sched_steps >= 2:
                lim -= 1
            if seq.pos >= lim:
                continue  # fully scheduled; retires as pending reconciles
            if not self._ensure_capacity(seq, seq.pos + 1):
                seq.preemptions += 1
                self.preemptions += 1
                continue
            slots[n_dec] = seq.slot
            flags[n_dec] = 3  # feed token+position from device state | emit
            emit.append((n_dec, seq))
            if trace_on:
                tpairs.append((seq, "engine/decode", 1))
            max_pos = max(max_pos, seq.pos)
            seq.pos += 1
            n_dec += 1

        ts = tpz = tv = None
        if ct:
            nd = 0 if n_dec == 0 else next(b for b in self._dec_buckets
                                           if b >= n_dec)
            chunks, nt = self._plan_prefill_tiles(nd, budget)
            ts = np.full(max(nt, 1), cfg.max_seqs, np.int32)
            tpz = np.zeros(max(nt, 1), np.int32)
            tv = np.zeros(max(nt, 1), np.int32)
            sched = 0
            for seq, tile0, take in chunks:
                start = nd + tile0 * ct
                sl = slice(start, start + take)
                tokens[sl] = seq.prompt[seq.pos:seq.pos + take]
                slots[sl] = seq.slot
                positions[sl] = np.arange(seq.pos, seq.pos + take,
                                          dtype=np.int32)
                for ti in range(-(-take // ct)):
                    ts[tile0 + ti] = seq.slot
                    tpz[tile0 + ti] = seq.pos + ti * ct
                    tv[tile0 + ti] = min(ct, take - ti * ct)
                max_pos = max(max_pos, seq.pos + take - 1)
                seq.pos += take
                sched += take
                if trace_on:
                    tpairs.append((seq, "engine/prefill", take))
                if seq.pos == len(seq.prompt):
                    flags[start + take - 1] |= 2
                    emit.append((start + take - 1, seq))
                    self._slot_feed[seq.slot] = True
            n = n_dec + sched
            t_total = nd + nt * ct
        else:
            nd = nt = 0
            n = n_dec
            for seq in list(self._running.values()):
                if seq.finished or seq.in_decode or n >= budget:
                    continue
                take = min(budget - n, len(seq.prompt) - seq.pos)
                while take and not self._ensure_capacity(seq, seq.pos + take):
                    take -= 1  # partial chunk under pool pressure
                if take <= 0:
                    continue
                sl = slice(n, n + take)
                tokens[sl] = seq.prompt[seq.pos:seq.pos + take]
                slots[sl] = seq.slot
                positions[sl] = np.arange(seq.pos, seq.pos + take,
                                          dtype=np.int32)
                max_pos = max(max_pos, seq.pos + take - 1)
                seq.pos += take
                n += take
                if trace_on:
                    tpairs.append((seq, "engine/prefill", take))
                if seq.pos == len(seq.prompt):
                    flags[n - 1] |= 2
                    emit.append((n - 1, seq))
                    self._slot_feed[seq.slot] = True
            t_total = 0 if n == 0 else next(b for b in self._buckets
                                            if b >= n)
        if n == 0:
            return False
        self.tokens_scheduled += n
        self.tokens_padded += t_total - n
        sampled = any(s.temperature > 0.0 for _, s in emit)
        has_tk = sampled and any(s.top_k > 0 for _, s in emit)
        has_tp = sampled and any(s.top_p < 1.0 for _, s in emit)
        parts = [tokens[:t_total], slots[:t_total], positions[:t_total],
                 flags[:t_total]]
        if ct:
            parts += [ts, tpz, tv]
        self._sync_bt()
        staged = self._stage(np.concatenate(parts))
        fn = self._get_dev_step(t_total, nd, nt, self._table_width(max_pos),
                                sampled, has_tk, has_tp)
        if self._faults.enabled:
            self._faults.fire(POINT_DISPATCH)
        picked, self._dev_state, self.cache = fn(
            self.params, self.cache, self._dev_state, self._bt_dev, staged,
            self._sample_root)
        participants: dict[int, _SeqState] = {}
        for _, seq in emit:
            participants[seq.slot] = seq
        for seq in participants.values():
            seq.refs += 1
            self._hist_stale[seq.slot] = True
        self._pending.append({"kind": "step", "picked": picked,
                              "emit": emit,
                              "participants": list(participants.values())})
        self._note_dispatch(t0)
        if trace_on:
            self._trace_spans(t0, time.perf_counter(), tpairs,
                              mode="dev_step")
        return True

    def _reconcile_pending(self) -> dict:
        """Read back the OLDEST pending dispatch's tokens and fold them
        into host state (EOS/max_new enforcement via ``_append_tokens``;
        release deferred until a sequence's last pending reference
        drains — the non-fused modes' double-buffer reconcile)."""
        if self._faults.enabled:
            self._faults.fire(POINT_READBACK)
        rec = self._pending.pop(0)
        t0 = time.perf_counter()
        out: dict = {}
        if rec["kind"] == "step":
            picked = np.asarray(rec["picked"])
            t1 = time.perf_counter()
            self.readback_ns += int((t1 - t0) * 1e9)
            if self._tracer.enabled:
                self._trace_spans(t0, t1, [(s, "engine/readback", 1)
                                           for _, s in rec["emit"]])
            for row, seq in rec["emit"]:
                self._append_tokens(seq, [int(picked[row])], out)
        elif rec["kind"] == "sched":
            toks = np.asarray(rec["out"])    # [K, bucket]
            steps = np.asarray(rec["steps"])  # [bucket] device steps_taken
            t1 = time.perf_counter()
            self.readback_ns += int((t1 - t0) * 1e9)
            if self._tracer.enabled:
                self._trace_spans(t0, t1, [(s, "engine/readback", ks)
                                           for s, ks in rec["emits"]])
            for j, (seq, _ks) in enumerate(rec["emits"]):
                n = int(steps[j])
                if n:
                    self._append_tokens(seq, toks[:n, j], out)
            if rec["prop"] is not None:
                p = int(np.asarray(rec["prop"]))
                a = int(np.asarray(rec["acc"]))
                self.spec_proposed += p
                self.spec_accepted += a
                if p:
                    # the device returns one aggregate (proposed, accepted)
                    # per sched dispatch; apportion to tenants proportionally
                    # to each sequence's committed steps this dispatch
                    total_n = float(sum(int(steps[j])
                                        for j in range(len(rec["emits"]))))
                    if total_n > 0.0:
                        for j, (seq, _ks) in enumerate(rec["emits"]):
                            if seq.cost is None:
                                continue
                            frac = int(steps[j]) / total_n
                            seq.cost.spec_proposed += p * frac
                            seq.cost.spec_accepted += a * frac
                if self.telemetry.enabled and p:
                    self.telemetry.counter(
                        "spec_tokens_proposed_total",
                        "draft tokens proposed by self-speculative "
                        "decode").inc(p)
                    self.telemetry.counter(
                        "spec_tokens_accepted_total",
                        "draft tokens accepted by exact-match "
                        "verification").inc(a)
        else:
            toks = np.asarray(rec["out"])  # [K, bucket]
            t1 = time.perf_counter()
            self.readback_ns += int((t1 - t0) * 1e9)
            if self._tracer.enabled:
                self._trace_spans(t0, t1, [(s, "engine/readback", k)
                                           for s, k in rec["emits"]])
            for j, (seq, k) in enumerate(rec["emits"]):
                self._append_tokens(seq, toks[:k, j], out)
        for seq in rec["participants"]:
            seq.refs -= 1
            if seq.finished and seq.refs == 0 and seq.slot >= 0:
                self._slot_feed[seq.slot] = False
                self._release(seq)
        return out

    def _step_device(self) -> dict:
        """One device-resident turn for the plain/tiled/run-ahead modes:
        dispatch one step if anything is schedulable, then reconcile the
        oldest pending dispatch once the window holds two — so the blocking
        ``np.asarray`` readback of step t overlaps the device executing
        step t+1."""
        self._admit_queued()
        dispatched = False
        self._sched_wait = False
        if self.cfg.sched_steps >= 2:
            dispatched = self._dispatch_sched_device()
        if not dispatched and not self._sched_wait:
            if self.cfg.decode_run_ahead >= 2:
                dispatched = self._dispatch_chunk_device()
            if not dispatched:
                dispatched = self._dispatch_step_device()
        if self._pending and (not dispatched or len(self._pending) >= 2):
            return self._reconcile_pending()
        if not dispatched and not self._pending and (
                self._queued or self._running):
            self._deadlock_guard(0)
        return {}

    def _try_decode_run_ahead(self) -> dict | None:
        """Fused multi-step decode when the scheduler is quiescent: every
        running sequence is decoding and no admission can happen (queue empty
        or no free slot). Returns the emit dict, or None to fall back to the
        single SplitFuse step."""
        k_max = self.cfg.decode_run_ahead
        seqs = list(self._running.values())
        if k_max < 2 or not seqs or any(not s.in_decode for s in seqs):
            return None
        if self._queued and self._free_slots:
            # a queued request has a slot but the pool can't cover its
            # reservation (step() already admitted everything admittable):
            # fuse a BOUNDED chunk — decode progress is what frees blocks
            k_max = min(k_max, self.cfg.run_ahead_admission_cap)
            if k_max < 2:
                return None
        k = min(k_max, min(s.max_new_tokens - len(s.generated) for s in seqs))
        while k >= 2 and not all(self._ensure_capacity(s, s.pos + k)
                                 for s in seqs):
            k -= 1  # pool pressure: partial growth is kept, retry smaller
        if k < 2:
            return None
        # round k DOWN to a power of two: jit specializes per (k, batch), and
        # arbitrary residuals (47, 45, 31, ...) would each compile a fresh
        # K-step scan — the bucketing discipline every other dimension uses
        k = 1 << (k.bit_length() - 1)
        t0 = time.perf_counter()
        t = len(seqs)
        bucket = next(b for b in self._buckets if b >= t)
        tokens = np.zeros(bucket, np.int32)
        slots = np.full(bucket, self.cfg.max_seqs, np.int32)
        positions = np.zeros(bucket, np.int32)
        seeds = np.zeros(bucket, np.int32)
        gen0 = np.zeros(bucket, np.int32)
        temp = np.zeros(bucket, np.float32)
        topk = np.zeros(bucket, np.int32)
        topp = np.ones(bucket, np.float32)
        sampled = False
        for j, s in enumerate(seqs):
            tokens[j] = s.token_at(s.pos)
            slots[j] = s.slot
            positions[j] = s.pos
            # feeding token_at(pos) produces generated[pos+1 - len(prompt)]
            seeds[j] = s.seed
            gen0[j] = s.pos - len(s.prompt) + 1
            temp[j], topk[j], topp[j] = s.temperature, s.top_k, s.top_p
            sampled = sampled or s.temperature > 0.0
        if self._chunk_jit is None:
            self._chunk_jit = self._build_decode_chunk()
        max_pos = max(s.pos + k - 1 for s in seqs)
        has_tk = bool(topk.any())
        has_tp = bool((topp < 1.0).any())
        # jit specializes per (statics, shapes); track the key ourselves so
        # cold dispatches are observable (no explicit program dict here)
        ckey = (k, sampled, has_tk, has_tp, bucket,
                self._table_width(max_pos))
        self._note_program("chunk", ckey not in self._chunk_keys)
        self._chunk_keys.add(ckey)
        if self._faults.enabled:
            self._faults.fire(POINT_DISPATCH)
        out, self.cache = self._chunk_jit(
            k, sampled, has_tk, has_tp,
            self.params, self.cache,
            self._h2d(tokens), self._h2d(slots), self._h2d(positions),
            self._h2d(self._table_view(max_pos)), self._sample_root,
            self._h2d(seeds), self._h2d(gen0),
            self._h2d(temp), self._h2d(topk), self._h2d(topp),
        )
        self._note_dispatch(t0)
        t1 = time.perf_counter()
        out = np.asarray(out)  # [K, bucket]
        t2 = time.perf_counter()
        self.readback_ns += int((t2 - t1) * 1e9)
        if self._tracer.enabled:
            self._trace_spans(t0, t1, [(s, "engine/decode", k) for s in seqs],
                              mode="run_ahead")
            self._trace_spans(t1, t2,
                              [(s, "engine/readback", k) for s in seqs])
        self.tokens_scheduled += k * t
        self.tokens_padded += k * (bucket - t)
        emit: dict = {}
        now = time.perf_counter() if self.telemetry.enabled else 0.0
        for j, s in enumerate(seqs):
            for i in range(k):
                tok = int(out[i, j])
                s.generated.append(tok)
                s.pos += 1
                emit[s.uid] = tok
                if now:
                    self._stamp_emission(s, now)
                if s.finished:
                    break  # tokens past EOS stay in the pool; freed on release
            if s.finished:
                self._release(s)
        return emit

    def _table_view(self, max_pos: int):
        """Slice the block table to the bucketed block count covering
        ``max_pos`` (the highest position any token in this dispatch will
        touch). The Pallas kernels grid their KV loop over the TABLE WIDTH,
        so a full-width table makes every token pay ``max_blocks_per_seq``
        grid steps regardless of its context (the round-4 bandwidth finding);
        slicing host-side bounds the grid by the batch's ACTUAL context.

        Short tables pass through whole: every distinct width is a fresh
        program shape, and on a remote-compile transport a handful of extra
        compiles costs far more than the grid steps it saves (measured: the
        full-width 18-block table beats a 2/4/8/16-bucket ladder end to
        end). Power-of-4 buckets keep the long-context compile count tiny."""
        return self.block_tables[:, :self._table_width(max_pos)]

    def _table_width(self, max_pos: int) -> int:
        """Bucketed block-table width covering ``max_pos`` (the shared
        bucketing behind ``_table_view``; the device-resident path keeps the
        full table on device and bakes this width into the program as a
        static so the kernel grid is bounded without any per-step upload)."""
        mb = self.cfg.max_blocks_per_seq
        if mb <= 64:
            return mb
        need = max_pos // self.cfg.block_size + 1
        b = 16
        while b < need:
            b *= 4
        return min(b, mb)

    def _plan_prefill_tiles(self, nd: int, budget: int):
        """Pick tile-aligned prompt chunks for this step (shared by the
        legacy tiled step and the fused pipeline — the tile-capacity walk,
        the capacity backoff under pool pressure, and the power-of-2 tile
        rounding with its non-power-of-2 cap fixup live HERE only).

        Returns ``(chunks, nt)``: ``chunks`` is ``[(seq, tile0, take)]``
        with ``tile0`` the chunk's first tile index relative to the tile
        region; ``nt`` the padded tile count. Does NOT advance ``seq.pos`` —
        callers fill their token arrays from the current pos, then advance.
        """
        ct = self.cfg.prefill_tile
        ntiles_cap = max(0, (budget - nd) // ct)
        tiles_used = 0
        chunks: list[tuple[_SeqState, int, int]] = []
        for seq in list(self._running.values()):
            if seq.finished or seq.in_decode or tiles_used >= ntiles_cap:
                continue
            avail = (ntiles_cap - tiles_used) * ct
            take = min(avail, len(seq.prompt) - seq.pos)
            while take and not self._ensure_capacity(seq, seq.pos + take):
                take -= 1  # partial chunk under pool pressure
            if take <= 0:
                continue
            chunks.append((seq, tiles_used, take))
            tiles_used += -(-take // ct)
        if tiles_used == 0:
            return chunks, 0
        nt = 1
        while nt < tiles_used:
            nt *= 2
        nt = min(nt, max(1, ntiles_cap))
        if nt < tiles_used:  # cap can be non-power-of-2
            nt = tiles_used
        return chunks, nt

    # ------------------------------------------------- fused mixed pipeline
    def _get_fused_chunk(self, k: int, nd: int, nt: int, sampled: bool,
                         has_tk: bool = False, has_tp: bool = False):
        """One program = one mixed SplitFuse step + (k-1) decode steps for
        the decode region, next tokens fed back on device (the FastGen
        multi-step idiom, reference ``engine_v2.py:30`` + the SplitFuse
        policy of ``blogs/deepspeed-fastgen/README.md:28`` — generalized so
        arrivals never break the fusion: the prompt chunk rides step 0 of
        the same dispatched program the decodes run ahead in).

        Rows [0, nd) are the decode region (padding rows -> scratch);
        rows [nd, T) the prefill region (tile-aligned when ``nt`` > 0).
        ``slot_toks`` [max_seqs+1] carries each slot's latest emitted token
        ACROSS programs, so chunk t+1's decode feed never needs chunk t's
        host readback (``feed_sel`` picks device feed vs fresh host token).
        Statics: (k, nd, nt, sampled, has_tk, has_tp); jit specializes per
        bucket set.
        """
        key = (k, nd, nt, sampled, has_tk, has_tp)
        fn = self._fused_jits.get(key)
        self._note_program("fused", fn is None)
        if fn is not None:
            return fn
        fwd = self.spec.ragged_forward_fn
        ct = self.cfg.prefill_tile
        max_seqs = self.cfg.max_seqs

        def pick(logits, keys, temp, tk, tp_):
            if not sampled:
                return jnp.argmax(
                    logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
            from deepspeed_tpu.inference.sampling import sample_tokens

            toks, _ = sample_tokens(logits, keys, temp,
                                    top_k=tk if has_tk else None,
                                    top_p=tp_ if has_tp else None)
            return toks

        def chunk_fn(params, cache, slot_toks, tokens, slots, positions,
                     feed_sel, dec_remaining, eos_ids, pf_last_mask, ts, tp,
                     tv, block_tables, root, seeds, gidx, temp, topk, topp):
            from deepspeed_tpu.inference.sampling import per_request_keys
            if nd:
                fed = jnp.where(feed_sel > 0, slot_toks[slots[:nd]],
                                tokens[:nd])
                tokens = tokens.at[:nd].set(fed)
                # mid-chunk retirement, entry case: a pipelined chunk can be
                # dispatched before the host reconciles a row's EOS pick —
                # its device feed token IS the EOS. Mask the row to the
                # scratch slot for the whole chunk (no real-state writes, no
                # surfaced tokens) instead of running it dead for k steps.
                done0 = (fed == eos_ids[:nd]) & (eos_ids[:nd] >= 0)
                slots = slots.at[:nd].set(
                    jnp.where(done0, max_seqs, slots[:nd]))
                positions = positions.at[:nd].set(
                    jnp.where(done0, 0, positions[:nd]))
            if nt:
                logits, cache = fwd(params, tokens, slots, positions,
                                    block_tables, cache,
                                    prefill_tiles=(nd, ts, tp, tv, ct))
            else:
                logits, cache = fwd(params, tokens, slots, positions,
                                    block_tables, cache)
            tok0 = pick(logits, per_request_keys(root, seeds, gidx),
                        temp, topk, topp)
            st = slot_toks
            t_total = tokens.shape[0]
            if t_total > nd:
                # prompt-completing rows publish their first generated token
                mask = pf_last_mask[nd:] > 0
                sl_pf = jnp.where(mask, slots[nd:], max_seqs)
                st = st.at[sl_pf].set(
                    jnp.where(mask, tok0[nd:], st[sl_pf]))
            if nd:
                # mid-chunk retirement, in-scan case: a row that picks its
                # EOS stops running (scratch-routed like frozen rows) and
                # its remaining steps surface -1 sentinels, never tokens
                eosd = eos_ids[:nd]
                dec0 = jnp.where(done0, -1, tok0[:nd])
                last_feed = tok0[:nd]
            if nd and k > 1:
                def one(carry, i):
                    cache, toks, pos, done = carry
                    active = (i < dec_remaining) & ~done
                    # frozen rows (k_s exhausted) must not touch real state:
                    # slot -> max_seqs routes their KV writes to the all-zero
                    # scratch row of the block table (block 0, never
                    # allocated), and the position is clamped to 0 so it can
                    # never index past any real sequence's table extent —
                    # without the clamp a frozen row's still-advancing
                    # ``pos`` overruns its retired table row and only
                    # gather clamping hides it
                    s = jnp.where(active, slots[:nd], max_seqs)
                    p = jnp.where(active, pos, 0)
                    lg, cache = fwd(params, toks, s, p, block_tables, cache)
                    r = per_request_keys(root, seeds[:nd], gidx[:nd] + i)
                    nxt = pick(lg, r, temp[:nd], topk[:nd], topp[:nd])
                    # frozen/retired rows keep their last token (feed
                    # stability); only live picks are surfaced
                    nxt = jnp.where(active, nxt, toks)
                    done = done | (active & (nxt == eosd) & (eosd >= 0))
                    return (cache, nxt, pos + 1, done), \
                        jnp.where(active, nxt, -1)

                hit0 = done0 | ((tok0[:nd] == eosd) & (eosd >= 0))
                (cache, last_feed, _, _), rest = jax.lax.scan(
                    one, (cache, tok0[:nd], positions[:nd] + 1, hit0),
                    jnp.arange(1, k))
                dec_toks = jnp.concatenate([dec0[None], rest], axis=0)
            else:
                dec_toks = (dec0[None] if nd
                            else jnp.zeros((1, 0), jnp.int32))
            if nd:
                # next chunk's device feed: the final carry token — equal to
                # the k_s-th emitted token for full rows, the frozen token
                # for short rows, the EOS for mid-scan-retired rows (done0
                # rows scatter to scratch via their masked slot)
                st = st.at[slots[:nd]].set(last_feed)
            return dec_toks, tok0, st, cache

        fn = jax.jit(chunk_fn, donate_argnums=(1, 2))
        self._fused_jits[key] = fn
        return fn

    def _width_ladder(self) -> list[int]:
        """Block-table widths ``_table_width`` can actually dispatch (jit
        caches are shape-keyed; warming the wrong width warms nothing)."""
        mb = self.cfg.max_blocks_per_seq
        if mb <= 64:
            return [mb]
        widths, b = [], 16
        while b < mb:
            widths.append(b)
            b *= 4
        widths.append(mb)
        return widths

    def warmup(self, sampled: bool = False, has_tk: bool = False,
               has_tp: bool = False) -> int:
        """Precompile the engine's multi-step program zoos via
        ``lower().compile()`` (no execution, no engine state touched): the
        fused-chunk family when ``fused_chunk`` >= 2 and the multi-step
        scheduler family when ``sched_steps`` >= 2. On a remote-compile
        transport every NOVEL combo otherwise costs seconds of compilation
        in the middle of serving — measured as 4-5 s stalls that dominated
        staggered-arrival latency. Returns the number of programs compiled.
        Greedy combos by default; call again with ``sampled``/filter flags
        for sampling workloads."""
        n = 0
        if self.cfg.fused_chunk >= 2:
            n += self._warmup_fused(sampled, has_tk, has_tp)
        if self.cfg.sched_steps >= 2 and self.cfg.device_state:
            n += self._warmup_sched(sampled, has_tk, has_tp)
        # warmup's own program-cache fills are not serve-time misses: reset
        # the dispatch baseline so warmup_coverage reflects live traffic only
        self._warmed = True
        self.program_dispatches = 0
        self.program_cold_dispatches = 0
        return n

    def _warmup_sched(self, sampled: bool, has_tk: bool,
                      has_tp: bool) -> int:
        """Lower the multi-step scheduler programs the dispatcher can reach:
        k is the pow2 round-DOWN of the deepest per-row budget (every pow2
        <= sched_steps), t the bucket for 1..max_seqs rows, width from the
        table ladder."""
        cfg = self.cfg
        ks = set()
        p = 1
        while p <= cfg.sched_steps:
            ks.add(p)
            p *= 2
        bmax = next(b for b in self._buckets if b >= cfg.max_seqs)
        buckets = [b for b in self._buckets if b <= bmax]
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self.params)
        cache_abs = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self.cache)
        state_abs = tuple(
            jax.ShapeDtypeStruct((cfg.max_seqs + 1,), dt)
            for dt in (jnp.int32, jnp.int32, jnp.int32, jnp.int32,
                       jnp.float32, jnp.int32, jnp.float32))
        btf_abs = jax.ShapeDtypeStruct(self.block_tables.shape, jnp.int32)
        hist_abs = jax.ShapeDtypeStruct(
            (cfg.max_seqs + 1, cfg.max_seq_len), jnp.int32)
        rng_abs = jax.ShapeDtypeStruct((2,), jnp.uint32)
        n = 0
        for kk in sorted(ks):
            for b in buckets:
                for w in self._width_ladder():
                    try:
                        fn = self._get_dev_sched(kk, b, w, sampled,
                                                 sampled and has_tk,
                                                 sampled and has_tp)
                        staged_abs = jax.ShapeDtypeStruct((3 * b,),
                                                          jnp.int32)
                        if cfg.spec_draft:
                            fn.lower(abstract, cache_abs, state_abs,
                                     hist_abs, btf_abs, staged_abs,
                                     rng_abs).compile()
                        else:
                            fn.lower(abstract, cache_abs, state_abs,
                                     btf_abs, staged_abs,
                                     rng_abs).compile()
                        n += 1
                    except Exception as e:  # pragma: no cover
                        from deepspeed_tpu.utils.logging import logger

                        logger.warning(
                            "warmup: sched combo (k=%s t=%s w=%s) failed "
                            "to precompile: %s", kk, b, w, e)
        return n

    def _warmup_fused(self, sampled: bool, has_tk: bool,
                      has_tp: bool) -> int:
        cfg = self.cfg
        ct = cfg.prefill_tile if self._use_tiles else 0
        k = cfg.fused_chunk
        nd_full = next(b for b in self._dec_buckets
                       if b >= min(cfg.max_seqs, cfg.max_tokens_per_step))
        combos: set = set()
        # the dispatcher caps its scan depth at min(k, pow2-roundup of the
        # deepest remaining budget), so tail batches (everyone nearly done)
        # hit smaller-k programs too
        ks = {k}
        p = 1
        while p < k:
            ks.add(p)
            p *= 2
        if ct:
            cap0 = max(1, (cfg.max_tokens_per_step - 0) // ct)
            capd = max(1, (cfg.max_tokens_per_step - nd_full) // ct)

            def nts(cap):
                vals = {cap}
                b = 1
                while b <= cap:
                    vals.add(b)
                    b *= 2
                return vals

            for nt in nts(cap0):
                combos.add((1, 0, nt))
            for kk in ks:
                for nt in nts(capd) | {0}:
                    combos.add((kk, nd_full, nt))
        else:
            for b in [0] + self._buckets:
                combos.add((1, 0, b) if b else None)
                for kk in ks:
                    combos.add((kk, nd_full, b))
            combos.discard(None)
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self.params)
        cache_abs = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self.cache)
        st_abs = jax.ShapeDtypeStruct((cfg.max_seqs + 1,), jnp.int32)
        widths = self._width_ladder()
        rng_abs = jax.ShapeDtypeStruct((2,), jnp.uint32)
        n = 0
        combos = {(kk, nd, nt, w) for kk, nd, nt in combos for w in widths}
        for kk, nd, nt, w in sorted(combos):
            bt_abs = jax.ShapeDtypeStruct(
                (self.block_tables.shape[0], w), jnp.int32)
            if ct:
                t_total = nd + nt * ct
            else:
                t_total = nd if nt == 0 else nt  # flat: nt carries the bucket
            if t_total <= 0 or t_total < nd \
                    or t_total > cfg.max_tokens_per_step + nd:
                continue
            i32 = lambda s: jax.ShapeDtypeStruct((s,), jnp.int32)  # noqa: E731
            f32 = lambda s: jax.ShapeDtypeStruct((s,), jnp.float32)  # noqa: E731
            nt_prog = nt if ct else 0
            try:
                if cfg.device_state:
                    # device-resident variant: full-width table on device,
                    # packed staging buffer, persistent state tuple
                    state_abs = tuple(
                        jax.ShapeDtypeStruct((cfg.max_seqs + 1,), dt)
                        for dt in (jnp.int32, jnp.int32, jnp.int32,
                                   jnp.int32, jnp.float32, jnp.int32,
                                   jnp.float32))
                    btf_abs = jax.ShapeDtypeStruct(
                        self.block_tables.shape, jnp.int32)
                    slen = 4 * t_total + 2 * max(nd, 1)
                    if nt_prog:
                        slen += 3 * max(nt_prog, 1)
                    fn = self._get_dev_fused(t_total, kk, nd, nt_prog, w,
                                             sampled, has_tk, has_tp)
                    fn.lower(abstract, cache_abs, state_abs, btf_abs,
                             i32(slen), rng_abs).compile()
                else:
                    fn = self._get_fused_chunk(kk, nd, nt_prog, sampled,
                                               has_tk, has_tp)
                    fn.lower(
                        abstract, cache_abs, st_abs,
                        i32(t_total), i32(t_total), i32(t_total),
                        i32(max(nd, 1)), i32(max(nd, 1)), i32(max(nd, 1)),
                        i32(t_total),
                        i32(max(nt_prog, 1)), i32(max(nt_prog, 1)),
                        i32(max(nt_prog, 1)),
                        bt_abs, rng_abs, i32(t_total), i32(t_total),
                        f32(t_total), i32(t_total), f32(t_total),
                    ).compile()
                n += 1
            except Exception as e:  # pragma: no cover - environment-specific
                from deepspeed_tpu.utils.logging import logger

                logger.warning("warmup: combo (k=%s nd=%s nt=%s) failed to "
                               "precompile: %s", kk, nd, nt, e)
        return n

    def _dispatch_fused(self) -> bool:
        """Schedule + dispatch ONE fused chunk from host state (no readback).
        Returns False when nothing is schedulable."""
        self._admit_queued()
        t0 = time.perf_counter()
        cfg = self.cfg
        k_max = cfg.fused_chunk
        ct = cfg.prefill_tile if self._use_tiles else 0
        budget = cfg.max_tokens_per_step

        decs: list[tuple[_SeqState, int]] = []
        for seq in list(self._running.values()):
            if seq.finished or not seq.in_decode:
                continue
            rem = seq.max_new_tokens - (seq.pos - len(seq.prompt))
            if rem <= 0:
                continue
            k_s = min(k_max, rem)
            if not self._ensure_capacity(seq, seq.pos + k_s):
                continue  # admitted seqs cannot hit this (reservation)
            decs.append((seq, k_s))
            if len(decs) >= min(budget, cfg.max_seqs):
                break
        # the decode region is all-or-nothing (0 or one fixed bucket):
        # per-count buckets looked cheaper per step but every (k, nd, nt,
        # width) combo is a separate compiled program, and on a remote-
        # compile transport the staggered-arrival shape zoo cost seconds of
        # mid-serve compilation per novel combo — far more than the padded
        # rows cost (they ride the scratch slot). Capped by the token
        # budget so max_seqs > budget configs still honor SplitFuse.
        nd_cap = min(cfg.max_seqs, budget)
        nd = (0 if not decs
              else next(b for b in self._dec_buckets if b >= nd_cap))

        # prefill chunks after the decode region
        chunks: list[tuple[_SeqState, int, int]] = []  # (seq, start, take)
        if ct:
            tile_chunks, nt = self._plan_prefill_tiles(nd, budget)
            chunks = [(seq, nd + tile0 * ct, take)
                      for seq, tile0, take in tile_chunks]
            t_total = nd + nt * ct
        else:
            nt = 0
            fill = nd
            for seq in list(self._running.values()):
                if seq.finished or seq.in_decode or fill >= budget:
                    continue
                take = min(budget - fill, len(seq.prompt) - seq.pos)
                while take and not self._ensure_capacity(seq, seq.pos + take):
                    take -= 1
                if take <= 0:
                    continue
                chunks.append((seq, fill, take))
                fill += take
            t_total = (nd if fill == nd
                       else next(b for b in self._buckets if b >= fill))
        if not decs and not chunks:
            return False

        # cap the scan depth at what the decode region can actually use —
        # rows with k_s < k freeze early, so steps past max(k_s) are pure
        # scratch-row work. Round UP to a power of two: k is a static jit
        # arg and arbitrary residuals would each compile a fresh program.
        if decs:
            k = min(k_max, 1 << (max(ks for _, ks in decs) - 1).bit_length())
        else:
            k = 1
        if cfg.device_state:
            return self._dispatch_fused_device(decs, chunks, nd, nt, k,
                                               t_total, t0)
        tokens = np.zeros(max(t_total, 1), np.int32)
        slots = np.full(max(t_total, 1), cfg.max_seqs, np.int32)
        positions = np.zeros(max(t_total, 1), np.int32)
        feed_sel = np.zeros(max(nd, 1), np.int32)
        dec_remaining = np.zeros(max(nd, 1), np.int32)
        eos_row = np.full(max(nd, 1), -1, np.int32)
        pf_last = np.zeros(max(t_total, 1), np.int32)
        seeds = np.zeros(max(t_total, 1), np.int32)
        gidx = np.zeros(max(t_total, 1), np.int32)
        temp = np.zeros(max(t_total, 1), np.float32)
        topk = np.zeros(max(t_total, 1), np.int32)
        topp = np.ones(max(t_total, 1), np.float32)
        sampled = False

        for j, (seq, k_s) in enumerate(decs):
            slots[j] = seq.slot
            positions[j] = seq.pos
            dec_remaining[j] = k_s
            if seq.eos_token_id is not None:
                eos_row[j] = seq.eos_token_id
            # step 0 feeds token_at(pos) -> emits generated index
            # pos - len(prompt) + 1; scan step i emits that + i
            seeds[j] = seq.seed
            gidx[j] = seq.pos - len(seq.prompt) + 1
            temp[j], topk[j], topp[j] = seq.temperature, seq.top_k, seq.top_p
            sampled = sampled or seq.temperature > 0.0
            if self._slot_feed[seq.slot]:
                feed_sel[j] = 1
            else:
                gen_idx = seq.pos - len(seq.prompt)
                if gen_idx > len(seq.generated) - 1 and gen_idx != -1:
                    raise RuntimeError(
                        "fused scheduler: host token unavailable and no "
                        f"device feed for uid={seq.uid!r} (pos={seq.pos})")
                tokens[j] = seq.token_at(seq.pos)

        pf_done: list[tuple[int, _SeqState]] = []
        ts = np.full(max(nt, 1), cfg.max_seqs, np.int32)
        tpos = np.zeros(max(nt, 1), np.int32)
        tval = np.zeros(max(nt, 1), np.int32)
        for seq, start, take in chunks:
            sl = slice(start, start + take)
            tokens[sl] = seq.prompt[seq.pos:seq.pos + take]
            slots[sl] = seq.slot
            positions[sl] = np.arange(seq.pos, seq.pos + take, dtype=np.int32)
            # only the prompt-completing row's pick is kept (generated
            # index 0, which gidx already holds); other rows' are discarded
            seeds[sl] = seq.seed
            temp[sl], topk[sl], topp[sl] = (seq.temperature, seq.top_k,
                                            seq.top_p)
            sampled = sampled or seq.temperature > 0.0
            if ct:
                tile0 = (start - nd) // ct
                for t in range(-(-take // ct)):
                    ts[tile0 + t] = seq.slot
                    tpos[tile0 + t] = seq.pos + t * ct
                    tval[tile0 + t] = min(ct, take - t * ct)
            if seq.pos + take == len(seq.prompt):
                pf_last[start + take - 1] = 1
                pf_done.append((start + take - 1, seq))
            seq.pos += take

        # telemetry: step-0 real tokens + scan-step active decode tokens
        n0 = len(decs) + sum(c[2] for c in chunks)
        active_scan = sum(k_s - 1 for _, k_s in decs)
        self.tokens_scheduled += n0 + active_scan
        self.tokens_padded += (t_total - n0) + (k - 1) * nd - active_scan

        max_pos = max(
            [seq.pos + k_s - 1 for seq, k_s in decs]
            + [seq.pos - 1 for seq, _, _ in chunks], default=0)
        fn = self._get_fused_chunk(k, nd, nt, sampled,
                                   bool(topk.any()),
                                   bool((topp < 1.0).any()))
        if self._faults.enabled:
            self._faults.fire(POINT_DISPATCH)
        dec_toks, tok0, self._slot_toks, self.cache = fn(
            self.params, self.cache, self._slot_toks,
            self._h2d(tokens), self._h2d(slots), self._h2d(positions),
            self._h2d(feed_sel), self._h2d(dec_remaining),
            self._h2d(eos_row), self._h2d(pf_last), self._h2d(ts),
            self._h2d(tpos), self._h2d(tval),
            self._h2d(self._table_view(max_pos)),
            self._sample_root, self._h2d(seeds), self._h2d(gidx),
            self._h2d(temp), self._h2d(topk), self._h2d(topp),
        )
        self._note_dispatch(t0)
        if self._tracer.enabled:
            t1 = time.perf_counter()
            self._trace_spans(
                t0, t1,
                [(s, "engine/decode", ks) for s, ks in decs]
                + [(s, "engine/prefill", take) for s, _, take in chunks],
                mode="fused")

        participants: dict[int, _SeqState] = {}
        for seq, k_s in decs:
            seq.pos += k_s
            self._slot_feed[seq.slot] = True
            participants[seq.slot] = seq
        for row, seq in pf_done:
            self._slot_feed[seq.slot] = True
            participants[seq.slot] = seq
        for seq, _, _ in chunks:
            participants[seq.slot] = seq
        for seq in participants.values():
            seq.refs += 1
            self._hist_stale[seq.slot] = True
        self._inflight_chunks.append({
            "dec_toks": dec_toks, "tok0": tok0,
            "decs": decs, "pf_done": pf_done,
            "participants": list(participants.values()),
        })
        return True

    def _get_dev_fused(self, t: int, k: int, nd: int, nt: int, w: int,
                       sampled: bool, has_tk: bool, has_tp: bool):
        """Device-resident fused mixed chunk: same program structure as
        ``_get_fused_chunk`` (step 0 mixed SplitFuse + k-1 decode scan
        steps, ``pf_last`` rows publishing their first generated token),
        but feed tokens, positions, seeds, and sampling parameters are all
        gathered from the persistent slot rows instead of host arrays, and
        the slot rows (token + position) update in place. The staging
        buffer shrinks to [tokens | slots | positions | flags | dec_rem
        (| tile metadata)] — constant bytes across steady decode chunks."""
        key = (t, k, nd, nt, w, sampled, has_tk, has_tp)
        fn = self._dev_fused_jits.get(key)
        self._note_program("dev_fused", fn is None)
        if fn is not None:
            return fn
        fwd = self.spec.ragged_forward_fn
        ct = self.cfg.prefill_tile
        max_seqs = self.cfg.max_seqs
        ndl = max(nd, 1)
        ntl = max(nt, 1)

        def pick(logits, keys, temp, tk, tp_):
            if not sampled:
                return jnp.argmax(
                    logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
            from deepspeed_tpu.inference.sampling import sample_tokens

            return sample_tokens(logits, keys, temp,
                                 top_k=tk if has_tk else None,
                                 top_p=tp_ if has_tp else None)[0]

        def chunk_fn(params, cache, state, bt_full, staged, root):
            from deepspeed_tpu.inference.sampling import (keys_for_positions,
                                                          per_request_keys)
            tok_st, pos_st, seed_st, plen_st, temp_st, topk_st, topp_st = state
            tokens = staged[0:t]
            slots = staged[t:2 * t]
            positions = staged[2 * t:3 * t]
            flags = staged[3 * t:4 * t]
            dec_rem = staged[4 * t:4 * t + ndl]
            eos_ids = staged[4 * t + ndl:4 * t + 2 * ndl]
            feed = (flags & 1) > 0
            live0 = slots != max_seqs
            tokens = jnp.where(feed, tok_st[slots], tokens)
            positions = jnp.where(feed & live0, pos_st[slots], positions)
            if nd:
                # mid-chunk retirement, entry case (see _get_fused_chunk):
                # a row whose device feed is already its EOS masks to the
                # scratch slot for the whole chunk
                done0 = (tokens[:nd] == eos_ids[:nd]) & (eos_ids[:nd] >= 0)
                slots = slots.at[:nd].set(
                    jnp.where(done0, max_seqs, slots[:nd]))
                positions = positions.at[:nd].set(
                    jnp.where(done0, 0, positions[:nd]))
            real = slots != max_seqs
            seeds = seed_st[slots]
            temp = temp_st[slots]
            topk = topk_st[slots]
            topp = topp_st[slots]
            gidx = positions - plen_st[slots] + 1
            bt = bt_full[:, :w] if w < bt_full.shape[1] else bt_full
            if nt:
                ts = staged[4 * t + 2 * ndl:4 * t + 2 * ndl + ntl]
                tp_ = staged[4 * t + 2 * ndl + ntl:4 * t + 2 * ndl + 2 * ntl]
                tv = staged[4 * t + 2 * ndl + 2 * ntl:
                            4 * t + 2 * ndl + 3 * ntl]
                logits, cache = fwd(params, tokens, slots, positions, bt,
                                    cache, prefill_tiles=(nd, ts, tp_, tv, ct))
            else:
                logits, cache = fwd(params, tokens, slots, positions, bt,
                                    cache)
            tok0 = pick(logits,
                        keys_for_positions(root, seeds, positions,
                                           plen_st[slots]),
                        temp, topk, topp)
            if t > nd:
                # prompt-completing rows publish their first generated token
                mask = (flags[nd:] & 2) > 0
                sl_pf = jnp.where(mask, slots[nd:], max_seqs)
                tok_st = tok_st.at[sl_pf].set(
                    jnp.where(mask, tok0[nd:], tok_st[sl_pf]))
                mpf = real[nd:]
                sl_p = jnp.where(mpf, slots[nd:], max_seqs)
                pos_st = pos_st.at[sl_p].max(
                    jnp.where(mpf, positions[nd:] + 1, 0))
            if nd:
                # mid-chunk retirement, in-scan case (see _get_fused_chunk)
                eosd = eos_ids[:nd]
                dec0 = jnp.where(done0, -1, tok0[:nd])
                last_feed = tok0[:nd]
            if nd and k > 1:
                def one(carry, i):
                    cache, toks, pos, done = carry
                    active = (i < dec_rem) & ~done
                    # frozen/retired rows -> scratch (see _get_fused_chunk)
                    s = jnp.where(active, slots[:nd], max_seqs)
                    p = jnp.where(active, pos, 0)
                    lg, cache = fwd(params, toks, s, p, bt, cache)
                    r = per_request_keys(root, seeds[:nd], gidx[:nd] + i)
                    nxt = pick(lg, r, temp[:nd], topk[:nd], topp[:nd])
                    nxt = jnp.where(active, nxt, toks)
                    done = done | (active & (nxt == eosd) & (eosd >= 0))
                    return (cache, nxt, pos + 1, done), \
                        jnp.where(active, nxt, -1)

                hit0 = done0 | ((tok0[:nd] == eosd) & (eosd >= 0))
                (cache, last_feed, _, _), rest = jax.lax.scan(
                    one, (cache, tok0[:nd], positions[:nd] + 1, hit0),
                    jnp.arange(1, k))
                dec_toks = jnp.concatenate([dec0[None], rest], axis=0)
            else:
                dec_toks = (dec0[None] if nd
                            else jnp.zeros((1, 0), jnp.int32))
            if nd:
                rd = real[:nd]  # done0 rows already masked -> scratch
                sl_d = jnp.where(rd, slots[:nd], max_seqs)
                tok_st = tok_st.at[sl_d].set(
                    jnp.where(rd, last_feed, tok_st[sl_d]))
                pos_st = pos_st.at[sl_d].add(
                    jnp.where(rd, jnp.minimum(dec_rem, k), 0))
            state = (tok_st, pos_st, seed_st, plen_st, temp_st, topk_st,
                     topp_st)
            return dec_toks, tok0, state, cache

        fn = jax.jit(chunk_fn, donate_argnums=(1, 2))
        self._dev_fused_jits[key] = fn
        return fn

    def _dispatch_fused_device(self, decs, chunks, nd: int, nt: int, k: int,
                               t_total: int, t0: float) -> bool:
        """Stage + dispatch one fused chunk against the device-resident
        slot rows: decode rows carry only slot + feed flag (token,
        position, and sampling params are gathered on device), prefill
        rows the usual token runs — all in ONE packed staging buffer that
        byte-compares equal across steady decode chunks (zero upload)."""
        cfg = self.cfg
        ct = cfg.prefill_tile if self._use_tiles else 0
        tokens = np.zeros(max(t_total, 1), np.int32)
        slots = np.full(max(t_total, 1), cfg.max_seqs, np.int32)
        positions = np.zeros(max(t_total, 1), np.int32)
        flags = np.zeros(max(t_total, 1), np.int32)
        dec_remaining = np.zeros(max(nd, 1), np.int32)
        eos_row = np.full(max(nd, 1), -1, np.int32)
        sampled = has_tk = has_tp = False
        max_pos = 0
        for j, (seq, k_s) in enumerate(decs):
            slots[j] = seq.slot
            flags[j] = 1  # feed token + position from device state
            dec_remaining[j] = k_s
            if seq.eos_token_id is not None:
                eos_row[j] = seq.eos_token_id
            sampled = sampled or seq.temperature > 0.0
            has_tk = has_tk or seq.top_k > 0
            has_tp = has_tp or seq.top_p < 1.0
            max_pos = max(max_pos, seq.pos + k_s - 1)
        pf_done: list[tuple[int, _SeqState]] = []
        ts = np.full(max(nt, 1), cfg.max_seqs, np.int32)
        tpos = np.zeros(max(nt, 1), np.int32)
        tval = np.zeros(max(nt, 1), np.int32)
        for seq, start, take in chunks:
            sl = slice(start, start + take)
            tokens[sl] = seq.prompt[seq.pos:seq.pos + take]
            slots[sl] = seq.slot
            positions[sl] = np.arange(seq.pos, seq.pos + take, dtype=np.int32)
            sampled = sampled or seq.temperature > 0.0
            has_tk = has_tk or seq.top_k > 0
            has_tp = has_tp or seq.top_p < 1.0
            if ct:
                tile0 = (start - nd) // ct
                for ti in range(-(-take // ct)):
                    ts[tile0 + ti] = seq.slot
                    tpos[tile0 + ti] = seq.pos + ti * ct
                    tval[tile0 + ti] = min(ct, take - ti * ct)
            if seq.pos + take == len(seq.prompt):
                flags[start + take - 1] |= 2
                pf_done.append((start + take - 1, seq))
            max_pos = max(max_pos, seq.pos + take - 1)
            seq.pos += take

        n0 = len(decs) + sum(c[2] for c in chunks)
        active_scan = sum(k_s - 1 for _, k_s in decs)
        self.tokens_scheduled += n0 + active_scan
        self.tokens_padded += (t_total - n0) + (k - 1) * nd - active_scan

        parts = [tokens, slots, positions, flags, dec_remaining, eos_row]
        if nt:
            parts += [ts, tpos, tval]
        self._sync_bt()
        staged = self._stage(np.concatenate(parts))
        fn = self._get_dev_fused(max(t_total, 1), k, nd, nt,
                                 self._table_width(max_pos), sampled,
                                 sampled and has_tk, sampled and has_tp)
        if self._faults.enabled:
            self._faults.fire(POINT_DISPATCH)
        dec_toks, tok0, self._dev_state, self.cache = fn(
            self.params, self.cache, self._dev_state, self._bt_dev, staged,
            self._sample_root)

        participants: dict[int, _SeqState] = {}
        for seq, k_s in decs:
            seq.pos += k_s
            self._slot_feed[seq.slot] = True
            participants[seq.slot] = seq
        for _row, seq in pf_done:
            self._slot_feed[seq.slot] = True
            participants[seq.slot] = seq
        for seq, _, _ in chunks:
            participants[seq.slot] = seq
        for seq in participants.values():
            seq.refs += 1
            self._hist_stale[seq.slot] = True
        self._inflight_chunks.append({
            "dec_toks": dec_toks, "tok0": tok0,
            "decs": decs, "pf_done": pf_done,
            "participants": list(participants.values()),
        })
        self._note_dispatch(t0)
        if self._tracer.enabled:
            t1 = time.perf_counter()
            self._trace_spans(
                t0, t1,
                [(s, "engine/decode", ks) for s, ks in decs]
                + [(s, "engine/prefill", take) for s, _, take in chunks],
                mode="dev_fused")
        return True

    def _append_tokens(self, seq: _SeqState, toks, out: dict) -> None:
        now = time.perf_counter() if self.telemetry.enabled else 0.0
        if seq.cost is not None and not seq.finished:
            # single choke point every dispatch mode funnels emitted tokens
            # through: one dispatch participation, len(toks) decode tokens
            seq.cost.decode_dispatches += 1
            seq.cost.decode_tokens += len(toks)
        for t in toks:
            if seq.finished:
                break  # post-EOS speculation: discard
            seq.generated.append(int(t))
            out[seq.uid] = int(t)
            self.tokens_emitted += 1
            if now:
                self._stamp_emission(seq, now)

    def _reconcile_oldest(self) -> dict:
        """Read back the OLDEST in-flight chunk's tokens and fold them into
        host state (EOS/max_new enforcement, deferred release)."""
        if self._faults.enabled:
            self._faults.fire(POINT_READBACK)
        rec = self._inflight_chunks.pop(0)
        t0 = time.perf_counter()
        dec_toks = np.asarray(rec["dec_toks"])
        tok0 = np.asarray(rec["tok0"])
        t1 = time.perf_counter()
        self.readback_ns += int((t1 - t0) * 1e9)
        if self._tracer.enabled:
            self._trace_spans(
                t0, t1,
                [(s, "engine/readback", ks) for s, ks in rec["decs"]]
                + [(s, "engine/readback", 1) for _, s in rec["pf_done"]])
        out: dict = {}
        for row, seq in rec["pf_done"]:
            self._append_tokens(seq, [int(tok0[row])], out)
        for j, (seq, k_s) in enumerate(rec["decs"]):
            self._append_tokens(seq, dec_toks[:k_s, j], out)
        for seq in rec["participants"]:
            seq.refs -= 1
            if seq.finished and seq.refs == 0 and seq.slot >= 0:
                self._slot_feed[seq.slot] = False
                self._release(seq)
        return out

    def _step_fused(self) -> dict:
        """One fused-pipeline turn: keep the dispatch window full, reconcile
        the oldest chunk when the window is full (or nothing new can be
        dispatched). Bounded speculation: at most ``pipeline_depth`` chunks
        of tokens are unreconciled at any time."""
        dispatched = False
        while len(self._inflight_chunks) < self.cfg.pipeline_depth:
            if not self._dispatch_fused():
                break
            dispatched = True
        if self._inflight_chunks and (
                not dispatched
                or len(self._inflight_chunks) >= self.cfg.pipeline_depth):
            return self._reconcile_oldest()
        if not dispatched and not self._inflight_chunks:
            self._deadlock_guard(0)
        return {}

    def _sched_eligible(self) -> bool:
        """Whether a multi-step scheduler turn could engage right now:
        everything running is decoding and admission pressure does not
        forbid a chunk (same preconditions ``_dispatch_sched_device``
        checks before planning)."""
        seqs = [s for s in self._running.values() if not s.finished]
        if not seqs or any(not s.in_decode for s in seqs):
            return False
        if self._queued and self._free_slots and \
                min(self.cfg.sched_steps,
                    self.cfg.run_ahead_admission_cap) < 1:
            return False
        return True

    def _step_fused_sched(self) -> dict:
        """Fused pipeline with the multi-step scheduler layered on top:
        mixed prefill+decode waves run through the fused-chunk program;
        once the batch is all-decode the turn switches to the scheduler
        dispatch (device-side retirement, optional speculation). The two
        in-flight queues never interleave — each family's window drains
        fully before the other dispatches — so reconcile order stays FIFO
        per sequence."""
        self._admit_queued()
        if self._sched_eligible():
            if self._inflight_chunks:
                return self._reconcile_oldest()
            return self._step_device()
        if self._pending:
            return self._reconcile_pending()
        return self._step_fused()

    def drain(self) -> dict:
        """Reconcile every in-flight chunk (a flush point for callers that
        need host-complete state)."""
        out: dict = {}
        while self._inflight_chunks:
            out.update(self._reconcile_oldest())
        while self._pending:
            out.update(self._reconcile_pending())
        return out

    def _schedule_decodes(self, budget: int, tokens, slots, positions,
                          emit) -> int:
        """Pass 1: ongoing decodes first (latency priority, FastGen policy).
        Writes into the arrays from index 0, returns the count."""
        n = 0
        for seq in list(self._running.values()):
            if not seq.in_decode or n >= budget:
                continue
            if not self._ensure_capacity(seq, seq.pos + 1):
                # pool pressure: this seq stalls (is preempted) for one step
                seq.preemptions += 1
                self.preemptions += 1
                continue
            tokens[n] = seq.token_at(seq.pos)
            slots[n] = seq.slot
            positions[n] = seq.pos
            emit.append((n, seq))
            seq.pos += 1
            n += 1
        return n

    def _admit_queued(self) -> None:
        """Pass 2: admit queued requests while slots remain (their prompt
        chunks are scheduled by pass 3); admission reserves the request's
        worst-case block count so admitted work always finishes.

        With the prefix cache on, admission first splices the longest cached
        full-block prefix into the sequence's block table (refcounts bumped
        via ``acquire``) and reserves only the REMAINDER — a hit both skips
        prefill compute and shrinks the reservation, raising effective
        capacity. ``seq.pos`` starts past the cached region, so the tail
        prefill (always >= 1 token, see ``_match_prefix``) produces the
        first token exactly as a cold prompt's final chunk would."""
        use_cache = self.cfg.enable_prefix_cache
        headroom = -1
        self._headroom_wait = False
        if self._queued:
            # measured free-byte headroom (net of the pool's preallocated
            # footprint — pool-funded blocks are never gated) rides
            # alongside the static block count; -1 (unknown backend or
            # knob off) keeps the static path bit-identical. The prefix
            # LRU sheds under pool pressure first so retention never
            # starves admission's reservations.
            headroom = self.admission_headroom_blocks()
            if headroom >= 0:
                self._enforce_retained_budget()
        cm = self.telemetry.costmeter
        if cm is not None and self._queued:
            # advance the occupancy integral before any splice moves blocks
            # between the retained carveout and a live sequence
            self._cost_tick()
        while self._queued and self._free_slots:
            qidx = 0
            if cm is not None and len(self._queued) > 1:
                # fair-share admission: prefer the first queued request
                # whose tenant is at/under its fair share of live blocks.
                # With one tenant (or one queued request) the pick is index
                # 0 — byte-identical FIFO admission order.
                qidx = self._cost_fair_index(cm)
            seq = self._queued[qidx]
            t_adm0 = time.perf_counter() if seq.trace is not None else 0.0
            worst = self._worst_case_blocks(seq)
            if headroom >= 0 and worst > headroom:
                # even counting the pool's own allocatable blocks the
                # device can't fund the worst case: external HBM pressure.
                # Wait for it to lift (flagged so the deadlock guard knows
                # this stall is externally resolvable, not a livelock —
                # and starts the stall-duration alarm clock)
                self._headroom_wait = True
                break
            if use_cache and self._kvtier is not None:
                # tiered restore first (prefetch resolution + cost-model
                # promotion): _match_prefix below then finds promoted links
                # in the ordinary HBM index, so the splice — and the tokens
                # — are identical to blocks that never left HBM
                self._tier_admit(seq)
            hit: list[int] = self._match_prefix(seq.prompt) if use_cache else []
            if hit:
                # take the references first: free_blocks counts refcount-0
                # cached blocks as allocatable, so the remainder check below
                # must see them already claimed
                self.allocator.acquire(hit)
                worst -= len(hit)
            if worst > self.allocator.free_blocks - self._reserved:
                if hit:
                    # deref back; published blocks re-enter the LRU (at the
                    # MRU end — they were just asked for)
                    self.allocator.free(hit)
                break  # pool pressure: retry admission as blocks free up
            self._queued.pop(qidx)
            if seq.expected_cached and len(hit) * self.cfg.block_size \
                    < seq.expected_cached:
                # the placement-time cached_prefix_tokens probe promised more
                # splice than admission found (LRU eviction in between):
                # proceed as a cold/shorter prefill — the re-match above IS
                # the re-validation — and make the over-credit observable
                self.prefix_stale_probes += 1
                if self.telemetry.enabled:
                    self.telemetry.counter(
                        "prefix_probe_stale_total",
                        "admissions whose placement-time prefix probe "
                        "over-credited cached_tokens",
                    ).inc()
            seq.slot = self._free_slots.pop()
            seq.reserved_remaining = worst
            self._reserved += worst
            if headroom >= 0:
                # this admission will draw from the pool; clamp at 0 so the
                # cap stays armed for the rest of the pass
                headroom = max(0, headroom - worst)
            if hit:
                seq.blocks = list(hit)
                seq.cached_prefix = len(hit) * self.cfg.block_size
                seq.pos = seq.cached_prefix
                self.block_tables[seq.slot, :len(hit)] = hit
                self._bt_dirty.add(seq.slot)
            if seq.cost is not None:
                # prefill is charged at admission: tokens the device will
                # actually prefill (splice-skipped prefix excluded) times the
                # analytic per-token forward FLOPs
                n_pref = max(0, len(seq.prompt) - seq.pos)
                seq.cost.prefill_tokens += n_pref
                seq.cost.prefill_flops += n_pref * self._flops_per_token_value()
                if hit:
                    # cross-tenant prefix reuse: debit the consumer, credit
                    # each publishing tenant block-for-block
                    transfers: dict[str, int] = {}
                    for b in hit:
                        pub = self._block_tenant.get(b)
                        if pub is not None and pub != seq.tenant:
                            transfers[pub] = transfers.get(pub, 0) + 1
                    # the transfer lands straight in the ledger (the
                    # publisher's request is usually long gone); the
                    # consumer's RequestCost must NOT also carry the debit
                    # or finalize would double-fold it
                    for pub, nblk in transfers.items():
                        cm.prefix_transfer(pub, seq.tenant, nblk)
            self._running[seq.slot] = seq
            if self.cfg.device_state:
                self._write_slot_row(seq)
            if use_cache:
                tel = self.telemetry
                if hit:
                    self.prefix_hits += 1
                    self.prefix_tokens_reused += seq.cached_prefix
                    if tel.enabled:
                        tel.counter("prefix_cache_hits_total",
                                    "admissions with a cached prefix").inc()
                        tel.counter(
                            "prefix_tokens_reused_total",
                            "prompt tokens served from cached KV blocks",
                        ).inc(seq.cached_prefix)
                else:
                    self.prefix_misses += 1
                    if tel.enabled:
                        tel.counter("prefix_cache_misses_total",
                                    "admissions with no cached prefix").inc()
            if self.telemetry.enabled:
                seq.t_admit = time.perf_counter()
                if seq.trace is not None:
                    tr = self._tracer
                    # queue wait (enqueue -> admission pickup) and the
                    # admission work itself (prefix match + splice +
                    # reservation), both children of the request span
                    if seq.t_enqueue:
                        tr.record(seq.trace, "request/queue",
                                  seq.t_enqueue, t_adm0)
                    tr.record(seq.trace, "request/admission",
                              t_adm0, seq.t_admit, slot=seq.slot,
                              blocks_reserved=seq.reserved_remaining,
                              cached_prefix_tokens=seq.cached_prefix or None)
        if not self._headroom_wait:
            # pass ended unpinned (admitted, empty queue, or plain pool
            # pressure): the stall-duration alarm clock rearms
            self._headroom_stall_ticks = 0

    def _emit_tokens(self, logits, emit) -> dict:
        """Shared step epilogue: pick at the emit indices (greedy, or the
        request's sampling config), extend the sequences, release finished
        ones."""
        out: dict = {}
        if emit:
            if self._faults.enabled:
                self._faults.fire(POINT_READBACK)
            t0 = time.perf_counter()
            idx = np.asarray([i for i, _ in emit])
            if any(seq.temperature > 0.0 for _, seq in emit):
                # jitted (cached per active-filter set; specializes per emit
                # count): eager sampling here would be ~a dozen separate
                # dispatches on a path whose whole cost model is dispatch
                # count, and unconditional top-k/top-p would sort the vocab
                # twice per step even for plain-temperature requests
                tk = np.asarray([s.top_k for _, s in emit], np.int32)
                tp = np.asarray([s.top_p for _, s in emit], np.float32)
                fkey = (bool(tk.any()), bool((tp < 1.0).any()))
                if not hasattr(self, "_sample_jits"):
                    self._sample_jits = {}
                skey = ("sample", fkey, len(emit))
                self._note_program("sample", skey not in self._step_keys)
                self._step_keys.add(skey)
                if fkey not in self._sample_jits:
                    from deepspeed_tpu.inference.sampling import (
                        per_request_keys, sample_tokens)

                    has_tk, has_tp = fkey
                    self._sample_jits[fkey] = jax.jit(
                        lambda lg, root, seeds, gidx, t, tk, tp: sample_tokens(
                            lg, per_request_keys(root, seeds, gidx), t,
                            top_k=tk if has_tk else None,
                            top_p=tp if has_tp else None)[0])
                picked = np.asarray(self._sample_jits[fkey](
                    logits[idx], self._sample_root,
                    np.asarray([s.seed for _, s in emit], np.int32),
                    np.asarray([len(s.generated) for _, s in emit], np.int32),
                    np.asarray([s.temperature for _, s in emit], np.float32),
                    tk, tp))
            else:
                picked = np.asarray(
                    jnp.argmax(logits[idx].astype(jnp.float32), axis=-1))
            t1 = time.perf_counter()
            self.readback_ns += int((t1 - t0) * 1e9)
            if self._tracer.enabled:
                self._trace_spans(t0, t1, [(s, "engine/readback", 1)
                                           for _, s in emit])
            now = time.perf_counter() if self.telemetry.enabled else 0.0
            for (_, seq), tok in zip(emit, picked):
                seq.generated.append(int(tok))
                out[seq.uid] = int(tok)
                self.tokens_emitted += 1
                if seq.cost is not None:
                    seq.cost.decode_tokens += 1
                    seq.cost.decode_dispatches += 1
                if now:
                    self._stamp_emission(seq, now)
                if seq.finished:
                    self._release(seq)
        return out

    def _deadlock_guard(self, n: int) -> None:
        if n > 0:
            self._headroom_stall_ticks = 0
            return
        if n == 0:
            if self._headroom_wait:
                # not a livelock: admission is pinned by measured device
                # headroom, which another owner freeing bytes can lift —
                # idle this tick instead of declaring deadlock. But a wait
                # that never lifts must not become a silent forever-hang:
                # after headroom_stall_alarm_ticks consecutive idle ticks
                # the stall alarm raises with the measured picture.
                self._headroom_stall_ticks += 1
                if self.telemetry.enabled:
                    self.telemetry.counter(
                        "kv_headroom_stalls_total",
                        "scheduler ticks idled because measured free-byte "
                        "headroom cannot fund any queued admission").inc()
                alarm = self.cfg.headroom_stall_alarm_ticks
                if alarm and self._headroom_stall_ticks >= alarm:
                    stats = self._device_memory_stats()
                    raise RuntimeError(
                        "headroom admission stalled: measured free-byte "
                        f"headroom funded no admission for {alarm} "
                        "consecutive scheduler ticks "
                        f"(queued={len(self._queued)} "
                        f"free_blocks={self.allocator.free_blocks} "
                        f"bytes_in_use={stats.get('bytes_in_use')} "
                        f"bytes_limit={stats.get('bytes_limit')}); another "
                        "HBM owner is pinning the device — lower "
                        "headroom_guard_fraction, free the external "
                        "allocation, or disable headroom_admission"
                    )
                return
            # has_work but nothing schedulable: every sequence is stalled on
            # KV-pool capacity and nothing can ever free a block — a silent
            # livelock without this guard. (The reference avoids this state
            # with conservative admission; we surface it instead.)
            raise RuntimeError(
                "KV pool deadlock: all sequences stalled waiting for blocks "
                f"({self.allocator.free_blocks} free of "
                f"{self.cfg.num_blocks - 1} usable); enlarge num_blocks or "
                "lower max_seqs/max_new_tokens"
            )

    # ------------------------------------------------- dispatch watchdog
    def _recover_device_path(self) -> None:
        """Re-anchor the engine on host ground truth after a failed step:
        discard ALL unread speculation (pending readbacks + in-flight fused
        chunks — partially draining them could interleave token order) and
        rewind every running sequence's schedule position to what its
        host-visible ``generated`` list proves was delivered. Re-running
        the discarded positions rewrites identical KV and — because token
        ``g`` of a request samples from a key derived only from (seed, g) —
        re-picks identical tokens, so recovery is invisible in the output
        stream. Injected faults fire BEFORE a jitted call consumes its
        donated buffers, and a real mid-execution failure raises out of the
        dispatch before the host bindings are swapped, so cache/state
        references here are the pre-dispatch values."""
        self._pending.clear()
        self._inflight_chunks.clear()
        self._staging_cache.clear()
        self._slot_feed[:] = False
        for seq in self._running.values():
            seq.refs = 0
            g = len(seq.generated)
            if g:
                # decode invariant: feeding token_at(pos) at position pos
                # produces generated index pos - len(prompt) + 1
                seq.pos = len(seq.prompt) + g - 1
            elif seq.pos >= len(seq.prompt):
                # prompt fully scheduled but its first token never landed:
                # re-run the final prompt position (>= cached_prefix, so
                # shared prefix blocks are never rewritten)
                seq.pos = len(seq.prompt) - 1
            else:
                # mid-prefill: re-prefill the uncached tail (idempotent)
                seq.pos = seq.cached_prefix
        # device mirrors are stale by construction now: rebuild the block
        # table wholesale and re-seed the slot rows from host truth
        self._bt_dirty.clear()
        self._bt_dev = jnp.asarray(self.block_tables)
        self._hist_stale[:] = True
        self._sched_wait = False
        if self.cfg.device_state:
            for seq in self._running.values():
                self._write_slot_row(seq)
        # sequences whose release was deferred on in-flight refs would
        # otherwise never retire (every scheduler loop skips finished seqs)
        for seq in list(self._running.values()):
            if seq.finished:
                self._release(seq)

    def _maybe_degrade(self, exc: Exception) -> bool:
        """Walk one rung down the degradation ladder once failures repeat:
        full device-resident path -> host-staged kill-switch path
        (``device_state`` off) -> plain single-program SplitFuse step
        (fused/run-ahead/tiles off). Returns True when a rung was taken;
        every rung is token-identical (pinned by the mode-parity tests), so
        degradation costs dispatch efficiency, never output."""
        cfg = self.cfg
        if not cfg.degrade_after or self._consec_failures < cfg.degrade_after:
            return False
        reason = f"{type(exc).__name__}: {exc}"
        if cfg.device_state:
            cfg.device_state = False
            self.degraded_mode = 1
            rung = "host-staged fallback (device_state off)"
        elif (cfg.fused_chunk or cfg.decode_run_ahead or cfg.prefill_tile
              or self._use_tiles):
            cfg.fused_chunk = 0
            cfg.decode_run_ahead = 0
            cfg.prefill_tile = 0
            self._use_tiles = False
            self.degraded_mode = 2
            rung = "plain-step fallback (fused/run-ahead/tiles off)"
        else:
            return False  # already at the bottom rung
        self.degraded_reason = reason
        self._consec_failures = 0
        log_dist(
            f"ragged watchdog: degrading to {rung} after repeated "
            f"device-path failures ({reason})", ranks=[0])
        tel = self.telemetry
        if tel.enabled:
            tel.gauge(
                "degraded_mode",
                "0 full | 1 host-staged fallback | 2 plain-step fallback",
            ).set(self.degraded_mode)
            tel.event("inference/degraded", mode=self.degraded_mode,
                      reason=reason)
        return True

    def _backoff(self, attempt: int) -> None:
        cfg = self.cfg
        base = min(cfg.retry_backoff_max_s,
                   cfg.retry_backoff_s * (2 ** (attempt - 1)))
        time.sleep(base * (1.0 + cfg.retry_jitter * self._retry_rng.random()))

    def _step_watched(self) -> dict:
        """Run ``_step_impl`` under the dispatch watchdog: transient
        failures (see ``faults.classify_transient``) recover host state and
        retry in place with exponential backoff + jitter; repeated failure
        walks the degradation ladder (each rung resets the retry budget);
        fatal errors and an exhausted budget escalate to the caller (the
        engine loop's crash containment)."""
        cfg = self.cfg
        attempts = 0
        while True:
            t0 = time.perf_counter()
            try:
                out = self._step_impl()
            except Exception as e:
                oom = is_resource_exhausted(e)
                if not oom and not classify_transient(e):
                    raise
                attempts += 1
                self.step_failures += 1
                self._consec_failures += 1
                if self.telemetry.enabled:
                    self.telemetry.counter(
                        "dispatch_retries_total",
                        "transient step failures recovered by the "
                        "watchdog").inc(kind=type(e).__name__)
                log_dist(
                    f"ragged watchdog: transient step failure "
                    f"({type(e).__name__}: {e}); attempt {attempts}",
                    ranks=[0])
                if oom:
                    # OOM forensics: snapshot the ledger breakdown before
                    # any recovery mutates it, then hand the ladder a hint —
                    # retrying the exact same program into the exact same
                    # full device is pointless, shedding device-resident
                    # state is the move that frees bytes
                    self._note_oom("dispatch", e)
                    if cfg.degrade_after:
                        self._consec_failures = max(
                            self._consec_failures, cfg.degrade_after)
                self._recover_device_path()
                if self._maybe_degrade(e):
                    attempts = 0  # a fresh rung gets a fresh retry budget
                    continue
                if attempts > max(0, cfg.dispatch_retries):
                    raise
                self.step_retries += 1
                self._backoff(attempts)
                continue
            if cfg.step_deadline_s and \
                    time.perf_counter() - t0 > cfg.step_deadline_s:
                # the step completed but blew its wall-clock budget: the
                # work is kept, yet it counts toward degradation — a
                # limping device path should fall back before it stalls
                # the whole serving loop
                self._consec_failures += 1
                if self.telemetry.enabled:
                    self.telemetry.counter(
                        "dispatch_deadline_exceeded_total",
                        "steps exceeding cfg.step_deadline_s").inc()
                self._maybe_degrade(TimeoutError(
                    f"step exceeded deadline {cfg.step_deadline_s:g}s"))
            else:
                self._consec_failures = 0
            return out

    def reset_state(self) -> int:
        """Crash containment (serving/engine_loop.py): rebuild every piece
        of mutable engine state after a poisoned step — fresh KV cache and
        allocator, zeroed block tables and device mirrors — keeping params
        and all compiled programs. Every queued/running request is retired
        with ``status='error'`` (the loop surfaces structured errors for
        them); returns how many were failed."""
        failed = 0
        if self.telemetry.costmeter is not None:
            self._cost_tick()  # settle the occupancy integral's last slice
        for seq in (*self._queued, *self._running.values()):
            seq.status = "error"
            seq.blocks = []
            seq.reserved_remaining = 0
            seq.refs = 0
            seq.slot = -1
            self._results[seq.uid] = seq
            failed += 1
            if self.telemetry.enabled:
                self._emit_request_span(seq)
            self._finalize_cost(seq)
        for seq in self._handoffs.values():
            seq.status = "error"
            seq.blocks = []
            seq.slot = -1
            self._results[seq.uid] = seq
            failed += 1
            self._finalize_cost(seq)
        self._handoffs.clear()
        self._queued = []
        self._running = {}
        self._pending.clear()
        self._inflight_chunks.clear()
        self._staging_cache.clear()
        self._kvq_blocks_allocated += self.allocator.allocated_total
        self.allocator = BlockedAllocator(self.cfg.num_blocks)
        if self._kvtier is not None:
            # the tier store SURVIVES reset: its records are keyed by exact
            # token chains, valid for any allocator generation of the same
            # params — demoted prefixes stay restorable after containment
            self.allocator.demote_hook = self._demote_block
        if self._prefix_listener is not None:
            # fresh allocator has no published keys: tell the cluster index
            # to forget this replica, then keep listening
            self.allocator.listener = self._prefix_listener
            self._prefix_listener.on_reset()
        self.block_tables[:] = 0
        self._bt_dirty.clear()
        self._bt_dev = jnp.asarray(self.block_tables)
        self._free_slots = list(range(self.cfg.max_seqs - 1, -1, -1))
        self._reserved = 0
        self._slot_feed[:] = False
        s1 = self.cfg.max_seqs + 1
        self._slot_toks = jnp.zeros(s1, jnp.int32)
        self._dev_state = (
            jnp.zeros(s1, jnp.int32), jnp.zeros(s1, jnp.int32),
            jnp.zeros(s1, jnp.int32), jnp.zeros(s1, jnp.int32),
            jnp.zeros(s1, jnp.float32), jnp.zeros(s1, jnp.int32),
            jnp.ones(s1, jnp.float32),
        )
        self._hist_dev = (jnp.zeros((s1, self.cfg.max_seq_len), jnp.int32)
                          if self.cfg.spec_draft else None)
        self._hist_stale[:] = True
        self._sched_wait = False
        self._block_tenant.clear()  # fresh allocator: stale block ids
        self._cost_last_tick = 0.0
        self.cache = self._build_cache()
        self._consec_failures = 0
        self._refresh_memory_handles()
        if failed:
            log_dist(
                f"ragged engine: state reset failed {failed} in-flight "
                "request(s)", ranks=[0])
        return failed

    def step(self) -> dict:
        """One SplitFuse step. Returns {uid: token} for sequences that emitted
        a token this step (under decode run-ahead / the fused pipeline: the
        LAST token of each sequence's chunk; the full stream is in the
        per-sequence state). Runs under the dispatch watchdog: transient
        device-path failures are retried (and eventually degraded) in
        place, so callers only ever see fatal errors."""
        if not self.has_work:
            return {}
        out = self._step_watched()
        if self.telemetry.enabled:
            self._sample_step_telemetry()
        return out

    def _sample_step_telemetry(self) -> None:
        """Scheduler-state gauges after each step: KV-page occupancy, queue
        depth, cumulative dispatch/padding counters."""
        tel = self.telemetry
        if self._memledger_handles is None and tel.memledger is not None:
            # ledger configured after engine construction: register now
            # (mirrors the training engine's lazy first-step registration)
            self._register_memory_owners()
        if tel.costmeter is not None:
            # long decodes accrue block-seconds continuously, not only at
            # admission/release seams
            self._cost_tick()
        usable = self.cfg.num_blocks - 1  # block 0 is scratch
        free = self.allocator.free_blocks
        g = tel.gauge
        g("kv_pages_free", "free KV blocks").set(free)
        g("kv_page_occupancy",
          "fraction of usable KV blocks in use").set(
              (usable - free) / max(usable, 1))
        g("inference_queue_depth", "requests waiting for admission").set(
            len(self._queued))
        g("inference_running_seqs", "admitted sequences").set(
            len(self._running))
        g("inference_tokens_scheduled", "useful token-slots scheduled").set(
            self.tokens_scheduled)
        g("inference_tokens_padded", "padding token-slots scheduled").set(
            self.tokens_padded)
        g("inference_dispatch_count", "device dispatches issued").set(
            self.dispatch_count)
        if self.tokens_emitted:
            g("ragged_dispatches_per_token",
              "device dispatches divided by tokens emitted (multi-step "
              "scheduling + speculation drive this toward 0)").set(
                  self.dispatch_count / self.tokens_emitted)
        if self.spec_proposed:
            g("spec_acceptance_rate",
              "accepted / proposed draft tokens (cumulative)").set(
                  self.spec_accepted / self.spec_proposed)
        g("degraded_mode",
          "0 full | 1 host-staged fallback | 2 plain-step fallback").set(
              self.degraded_mode)
        if self.h2d_bytes > self._h2d_seen:
            tel.counter(
                "ragged_h2d_bytes_total",
                "bytes staged host-to-device by ragged dispatches").inc(
                    self.h2d_bytes - self._h2d_seen)
            self._h2d_seen = self.h2d_bytes
        if self.program_dispatches:
            g("ragged_warmup_coverage",
              "fraction of dispatches served by an already-built jitted "
              "program (1.0 = no serve-time compiles since warmup)").set(
                  1.0 - self.program_cold_dispatches
                  / self.program_dispatches)
        tel.note_program_cache_size(
            len(self._tiled_jits) + len(self._fused_jits)
            + len(self._dev_step_jits) + len(self._dev_chunk_jits)
            + len(self._dev_fused_jits) + len(self._dev_sched_jits)
            + len(self._chunk_keys) + len(self._step_keys))
        if self.cfg.enable_prefix_cache:
            alloc = self.allocator
            bb = self._block_bytes()
            if alloc.evictions > self._evictions_seen:
                delta = alloc.evictions - self._evictions_seen
                tel.counter(
                    "prefix_cache_evictions_total",
                    "cached KV blocks reclaimed under pool pressure",
                ).inc(delta)
                tel.counter(
                    "prefix_cache_evicted_bytes_total",
                    "HBM bytes reclaimed from the prefix cache",
                ).inc(delta * bb)
                self._evictions_seen = alloc.evictions
            g("prefix_cache_blocks_published",
              "KV blocks registered in the prefix index").set(
                  alloc.cached_blocks)
            g("prefix_cache_blocks_retained",
              "refcount-0 cached blocks held from the free list").set(
                  alloc.retained_blocks)
            g("prefix_cache_retained_bytes",
              "HBM bytes pinned by refcount-0 cached blocks").set(
                  alloc.retained_blocks * bb)
            decided = self.prefix_hits + self.prefix_misses
            g("prefix_cache_hit_rate",
              "fraction of admissions with a cached prefix").set(
                  self.prefix_hits / decided if decided else 0.0)
        if self._kvtier is not None:
            st = self._kvtier.stats()
            g("kvtier_bytes", "bytes parked in the KV tier").set(
                st["host_bytes"], tier="host")
            g("kvtier_bytes", "bytes parked in the KV tier").set(
                st["disk_bytes"], tier="disk")
            g("kvtier_blocks", "KV blocks parked in the tier").set(
                st["host_blocks"], tier="host")
            g("kvtier_blocks", "KV blocks parked in the tier").set(
                st["disk_blocks"], tier="disk")
            seen = self._kvtier_seen
            for name, help_ in (
                ("demotions", "KV blocks demoted HBM->host on eviction"),
                ("spills", "KV blocks spilled host->disk on overflow"),
                ("promotions", "KV blocks promoted back into HBM"),
                ("prefetch_hits",
                 "admissions whose tier prefetch finished in time"),
                ("prefetch_abandoned",
                 "admissions that outran their tier prefetch"),
            ):
                delta = st[name] - seen.get(name, 0)
                if delta > 0:
                    tel.counter(f"kvtier_{name}_total", help_).inc(delta)
                    seen[name] = st[name]
        g("kvquant_enabled",
          "low-bit KV pool active (1 = quantized, 0 = fp pool)").set(
              1.0 if self._kvq is not None else 0.0, codec=self._kvq_name)
        if self._kvq is not None:
            saved = self._kvq_alloc_total() \
                * (self._fp_block_bytes - self._block_bytes())
            delta = saved - self._kvquant_saved_seen
            if delta > 0:
                tel.counter(
                    "kvquant_bytes_saved_total",
                    "HBM bytes the low-bit pool saved vs the fp pool, "
                    "accumulated over allocated blocks",
                ).inc(delta, codec=self._kvq_name)
                self._kvquant_saved_seen = saved
            g("kvquant_block_multiplier",
              "resident KV blocks per HBM byte vs an fp16 pool").set(
                  self._fp16_block_bytes / max(1, self._block_bytes()),
                  codec=self._kvq_name)
        hb = self.admission_headroom_blocks()
        if hb >= 0:
            g("kv_headroom_blocks",
              "KV blocks fundable from measured free-byte headroom").set(hb)
        tel.sample_memory(step=self.dispatch_count)

    def _step_impl(self) -> dict:
        self._sweep_aborts()
        if not self.has_work:
            return {}  # the sweep retired everything schedulable
        if self.cfg.fused_chunk >= 2:
            if self.cfg.sched_steps >= 2 and self.cfg.device_state:
                return self._step_fused_sched()
            return self._step_fused()
        if self.cfg.device_state:
            return self._step_device()
        # admission FIRST: a newly admitted sequence is in prefill, which
        # disables run-ahead for this step — so queued requests are admitted
        # within one step whenever a slot + pool reservation exist, and the
        # admission-capped run-ahead below only governs the pool-blocked case
        # (without this order, capped chunks re-fire back-to-back and starve
        # admission for up to a whole generation)
        self._admit_queued()
        ahead = self._try_decode_run_ahead()
        if ahead is not None:
            return ahead
        if self._use_tiles:
            return self._step_tiled()
        t0 = time.perf_counter()
        budget = self.cfg.max_tokens_per_step
        tokens = np.zeros(budget, np.int32)
        slots = np.full(budget, self.cfg.max_seqs, np.int32)  # padding row
        positions = np.zeros(budget, np.int32)
        emit: list[tuple[int, _SeqState]] = []
        n = self._schedule_decodes(budget, tokens, slots, positions, emit)
        trace_on = self._tracer.enabled
        # emit holds exactly the decode rows at this point
        tpairs = ([(s, "engine/decode", 1) for _, s in emit]
                  if trace_on else None)

        # 3) prefill chunks for running prompts within the remaining budget
        for seq in list(self._running.values()):
            if seq.in_decode or n >= budget:
                continue
            take = min(budget - n, len(seq.prompt) - seq.pos)
            while take and not self._ensure_capacity(seq, seq.pos + take):
                take -= 1  # partial chunk under pool pressure
            if take <= 0:
                continue
            sl = slice(n, n + take)
            tokens[sl] = seq.prompt[seq.pos:seq.pos + take]
            slots[sl] = seq.slot
            positions[sl] = np.arange(seq.pos, seq.pos + take, dtype=np.int32)
            seq.pos += take
            n += take
            if trace_on:
                tpairs.append((seq, "engine/prefill", take))
            if seq.pos == len(seq.prompt):
                emit.append((n - 1, seq))  # last prompt token -> first new token

        self._deadlock_guard(n)
        bucket = next(b for b in self._buckets if b >= n)
        self.tokens_scheduled += n
        self.tokens_padded += bucket - n

        max_pos = int(positions[:n].max(initial=0))
        skey = ("step", bucket, self._table_width(max_pos))
        self._note_program("step", skey not in self._step_keys)
        self._step_keys.add(skey)
        if self._faults.enabled:
            self._faults.fire(POINT_DISPATCH)
        logits, self.cache = self._step_jit(
            self.params, self.cache,
            self._h2d(tokens[:bucket]), self._h2d(slots[:bucket]),
            self._h2d(positions[:bucket]),
            self._h2d(self._table_view(max_pos)),
        )
        self._note_dispatch(t0)
        if trace_on:
            self._trace_spans(t0, time.perf_counter(), tpairs, mode="step")
        return self._emit_tokens(logits, emit)

    def _get_tiled_step(self, nd: int, nt: int):
        """Jitted step with a static (decode-count, tile-count) split; one
        program per bucket pair."""
        key = (nd, nt)
        fn = self._tiled_jits.get(key)
        self._note_program("tiled", fn is None)
        if fn is None:
            fwd = self.spec.ragged_forward_fn
            ct = self.cfg.prefill_tile

            def step_fn(params, cache, tokens, slots, positions, ts, tp, tv, bt):
                return fwd(params, tokens, slots, positions, bt, cache,
                           prefill_tiles=(nd, ts, tp, tv, ct))

            fn = jax.jit(step_fn, donate_argnums=(1,))
            self._tiled_jits[key] = fn
        return fn

    def _step_tiled(self) -> dict:
        """One SplitFuse step with tile-aligned prefill layout: tokens
        [0, ND) are decodes (bucketed), the rest are prefill chunks laid at
        tile boundaries so the tiled kernel fetches each KV block once per
        tile (see RaggedConfig.prefill_tile)."""
        ct = self.cfg.prefill_tile
        budget = self.cfg.max_tokens_per_step
        t0 = time.perf_counter()
        tokens = np.zeros(budget + ct, np.int32)
        slots = np.full(budget + ct, self.cfg.max_seqs, np.int32)
        positions = np.zeros(budget + ct, np.int32)
        emit: list[tuple[int, _SeqState]] = []
        n_dec = self._schedule_decodes(min(budget, self.cfg.max_seqs),
                                       tokens, slots, positions, emit)
        trace_on = self._tracer.enabled
        # emit holds exactly the decode rows at this point
        tpairs = ([(s, "engine/decode", 1) for _, s in emit]
                  if trace_on else None)
        self._admit_queued()
        nd = 0 if n_dec == 0 else next(b for b in self._dec_buckets
                                       if b >= n_dec)

        # prefill chunks at tile-aligned offsets after the decode region
        # (planner shared with the fused pipeline)
        chunks, nt = self._plan_prefill_tiles(nd, budget)
        sched = 0
        for seq, tile0, take in chunks:
            start = nd + tile0 * ct
            tokens[start:start + take] = seq.prompt[seq.pos:seq.pos + take]
            slots[start:start + take] = seq.slot
            positions[start:start + take] = np.arange(
                seq.pos, seq.pos + take, dtype=np.int32)
            seq.pos += take
            sched += take
            if trace_on:
                tpairs.append((seq, "engine/prefill", take))
            if seq.pos == len(seq.prompt):
                emit.append((start + take - 1, seq))
        self._deadlock_guard(n_dec + sched)
        total = nd + nt * ct
        # per-tile metadata (pad tiles: scratch row, valid=0)
        ts = np.full(max(nt, 1), self.cfg.max_seqs, np.int32)
        tp = np.zeros(max(nt, 1), np.int32)
        tv = np.zeros(max(nt, 1), np.int32)
        for seq, tile0, take in chunks:
            pos0 = positions[nd + tile0 * ct]
            for t in range(-(-take // ct)):
                ts[tile0 + t] = seq.slot
                tp[tile0 + t] = pos0 + t * ct
                tv[tile0 + t] = min(ct, take - t * ct)

        self.tokens_scheduled += n_dec + sched
        self.tokens_padded += total - n_dec - sched

        step_fn = self._get_tiled_step(nd, nt)
        max_pos = int(positions[:total].max(initial=0)) if total else 0
        if self._faults.enabled:
            self._faults.fire(POINT_DISPATCH)
        logits, self.cache = step_fn(
            self.params, self.cache,
            self._h2d(tokens[:total]), self._h2d(slots[:total]),
            self._h2d(positions[:total]),
            self._h2d(ts[:max(nt, 1)]), self._h2d(tp[:max(nt, 1)]),
            self._h2d(tv[:max(nt, 1)]),
            self._h2d(self._table_view(max_pos)),
        )
        self._note_dispatch(t0)
        if trace_on:
            self._trace_spans(t0, time.perf_counter(), tpairs, mode="tiled")
        return self._emit_tokens(logits, emit)

    # ------------------------------------------------------------------ convenience
    def generate_all(self, max_steps: int = 10_000) -> dict:
        """Drive ``step()`` until all queued/admitted work finishes; returns
        {uid: generated token list}."""
        for _ in range(max_steps):
            if not self.has_work:
                break
            self.step()
        if self.has_work:
            raise RuntimeError(f"work left after {max_steps} steps")
        return {uid: list(seq.generated) for uid, seq in self._results.items()}
