"""Low-bit paged KV cache: the *block* is the unit of quantization.

Everywhere a KV block lives — the HBM pool, the host/disk tiers
(``inference/kvtier.py``), the prefix-cache retained set, the KVHandoff
wire format (``serving/cluster.py``) — it is stored as a
:class:`QuantizedKV` pair ``(q, s)``: the payload in a 1-byte storage
dtype plus one scale per (token-row, kv-head). Quantization happens ONCE,
at write time inside ``models/paged.write_kv_paged``; dequantization is
fused into the jitted gather on the decode/prefill hot path
(``ops/attention.paged_attention``), so fp copies of pool blocks are
per-dispatch transients XLA fuses away, never residents. Because rows
quantize independently, the incremental scatter stays exact: rewriting
one token's row never re-rounds a neighbour.

Codecs (role parity with the reference's KV quantization in
``inference/v2`` and ZeRO++'s qgZ discipline of compressing ON the wire,
not beside it — see EQuARX for the native-XLA version of the same move):

- ``int8``: symmetric per-row-per-head absmax scaling, payload ``int8``.
- ``fp8``: e4m3 emulated via ``ml_dtypes.float8_e4m3fn`` storage with the
  same absmax pre-scale (amax -> 448); on TPU generations with native fp8
  the storage dtype is already the right one.

With f16 scales at head_dim 64 a block costs ``1 + 2/64`` bytes/element
— ~1.94x the resident blocks per HBM byte vs an fp16 pool (>= the 1.8x
acceptance floor), and the same multiplier applies to handoff bytes,
tier bytes and admission headroom because every consumer derives from
``kv_bytes_per_token()`` over the quantized pytree.

The subsystem is gated by a measured drift budget, not exact parity:
bounded greedy token-match rate and spec-decode accept-rate drift vs the
fp16 path (``DRIFT_BUDGET``); ``quant="off"`` (the default) keeps the
engine bit-identical to the unquantized path — the pool is then a plain
array pytree and none of this module's jitted code runs.

The quantized TP logits collective (``quantized_logits_all_gather``)
reuses the packed-collective discipline of ``comm/quantized_collectives``
for the inference side: the vocab-sharded logits all-gather carries an
int8 payload + per-shard scales instead of fp values, an explicit
shard_map region whose HLO all-gather operand is ``s8`` (assertable the
same way the training wire is).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

DRIFT_BUDGET = {
    # greedy continuations: fraction of position-wise matching tokens
    # (prefix agreement) vs the fp16 path
    "greedy_match_min": 0.95,
    # |accept_rate(quant) - accept_rate(fp16)| for spec-decode drafts
    "spec_accept_drift_max": 0.02,
}


class KVQCodec(NamedTuple):
    """One KV-block codec: 1-byte storage + per-row-per-head scales."""

    name: str
    storage: str        # numpy dtype name of the payload
    scale: str          # numpy dtype name of the scales
    qmax: float         # absmax maps onto +-qmax

    @property
    def storage_dtype(self):
        return np.dtype(self.storage)

    @property
    def scale_dtype(self):
        return np.dtype(self.scale)


CODECS = {
    "int8": KVQCodec("int8", "int8", "float16", 127.0),
    # e4m3 finite max is 448; absmax pre-scaling uses the full range
    "fp8": KVQCodec("fp8", "float8_e4m3fn", "float16", 448.0),
}


def get_codec(name: str) -> KVQCodec:
    try:
        return CODECS[name]
    except KeyError:
        raise ValueError(
            f"unknown KV codec {name!r}; supported: {sorted(CODECS)}"
        ) from None


# --------------------------------------------------------------- row codec
def quantize_kv_rows(x: jnp.ndarray, codec: KVQCodec):
    """Quantize KV rows along the last (head_dim) axis: ``x [..., D] ->
    (q storage [..., D], s scale [...])``. The scale is rounded to its
    storage dtype BEFORE the divide so write and read use the identical
    value (no double-rounding skew between quantize and dequantize)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    s = jnp.where(amax > 0, amax / codec.qmax, 1.0).astype(codec.scale_dtype)
    y = xf / s.astype(jnp.float32)[..., None]
    # clip covers both codecs: int8 range, and e4m3 saturation (the f16
    # scale rounds, so y can peek past qmax by one ulp)
    y = jnp.clip(y, -codec.qmax, codec.qmax)
    if codec.name == "int8":
        q = jnp.round(y).astype(jnp.int8)
    else:
        q = y.astype(jnp.dtype(codec.storage))
    return q, s


def dequantize_kv_rows(q: jnp.ndarray, s: jnp.ndarray,
                       dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of :func:`quantize_kv_rows` (fused into the gather)."""
    return (q.astype(jnp.float32)
            * s.astype(jnp.float32)[..., None]).astype(dtype)


# ------------------------------------------------------------- the pytree
class QuantizedKV:
    """A quantized KV pool (or any block-axis slice of one) as a registered
    pytree node, modeled on ``ops/quantizer.QuantizedWeight``.

    Children ``(q, s)`` flow through jit / lax.scan / tree_map / donation;
    static aux ``(codec, dtype)`` ride along every transform, so a scan
    slice of the full ``[L, nb, bs, Hkv, D]`` pool is itself a QuantizedKV
    over ``[nb, bs, Hkv, D]``. The properties keep existing model/engine
    code shape-compatible without edits:

    - ``.shape`` is the payload shape (``kc.shape[1]`` is still the block
      size, ``k_pool.shape[2]`` still the kv-head count per layer slice);
    - ``.dtype`` is the COMPUTE dtype (``cache["k"].dtype`` still picks
      the activation dtype for the forward);
    - ``.nbytes`` is payload + scales, so ``kv_bytes_per_token()``, the
      memledger owners, the tier cost models and ``KVHandoff.nbytes`` are
      quantization-aware for free.

    Picklable (handoff wire format, disk-tier records): arrays are
    pickled as numpy so a record written from device memory reads back
    host-side.
    """

    is_quantized_kv = True

    def __init__(self, q, s, codec: str, dtype: str):
        self.q = q
        self.s = s
        self.codec = codec
        self._dtype_name = dtype

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.s), (self.codec, self._dtype_name)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    # -- array-compatibility surface ---------------------------------------
    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return np.dtype(self._dtype_name)

    @property
    def nbytes(self):
        return int(self.q.nbytes) + int(self.s.nbytes)

    def __repr__(self):
        return (f"QuantizedKV(codec={self.codec!r}, shape={self.shape}, "
                f"dtype={self._dtype_name})")

    # -- pool ops (the two touch points of the paged contract) -------------
    def scatter_rows(self, blk, off, rows):
        """Quantize-at-write: scatter new KV rows ``[T, Hkv, D]`` into
        ``(block, offset)`` cells of a per-layer pool ``[nb, bs, Hkv, D]``
        (``models/paged.write_kv_paged``)."""
        codec = get_codec(self.codec)
        q_rows, s_rows = quantize_kv_rows(rows, codec)
        return QuantizedKV(
            self.q.at[blk, off].set(q_rows),
            self.s.at[blk, off].set(s_rows),
            self.codec, self._dtype_name)

    def gather_dequant(self, tables):
        """Dequant fused into the gather: ``tables [T, MB]`` over a
        per-layer pool returns fp32 context ``[T, MB, bs, Hkv, D]`` —
        a per-dispatch transient inside the attention program, fused by
        XLA with the surrounding einsum (``ops/attention``)."""
        return dequantize_kv_rows(self.q[tables], self.s[tables])

    # -- pickling (handoff / disk spill payloads) --------------------------
    def __getstate__(self):
        return {"q": np.asarray(self.q), "s": np.asarray(self.s),
                "codec": self.codec, "dtype": self._dtype_name}

    def __setstate__(self, state):
        self.q = state["q"]
        self.s = state["s"]
        self.codec = state["codec"]
        self._dtype_name = state["dtype"]


jax.tree_util.register_pytree_node(
    QuantizedKV,
    lambda t: t.tree_flatten(),
    QuantizedKV.tree_unflatten,
)


# --------------------------------------------------------- pool construction
def build_quantized_paged_cache(init_fn, num_blocks: int, block_size: int,
                                dtype, codec: KVQCodec):
    """Build the quantized pool DIRECTLY at storage precision: the model's
    ``init_paged_cache_fn`` is only ``eval_shape``-d, so no transient fp
    pool is ever allocated (the whole point is not to pay the fp footprint
    even once at startup)."""
    # close over the args: block counts and dtype are static, not tracers
    struct = jax.eval_shape(lambda: init_fn(num_blocks, block_size, dtype))

    def to_q(leaf):
        return QuantizedKV(
            jnp.zeros(leaf.shape, codec.storage_dtype),
            jnp.zeros(leaf.shape[:-1], codec.scale_dtype),
            codec.name, np.dtype(leaf.dtype).name)

    return jax.tree_util.tree_map(to_q, struct)


def paged_block_bytes(init_fn, num_blocks: int, block_size: int, dtype) -> int:
    """Bytes one UNQUANTIZED block (all layers, k+v) would cost at
    ``dtype`` — the baseline for the bytes-saved counter and the
    resident-block multiplier, computed from shapes only."""
    struct = jax.eval_shape(lambda: init_fn(num_blocks, block_size, dtype))
    total = 0
    for leaf in jax.tree_util.tree_leaves(struct):
        total += (int(leaf.shape[0]) * int(np.prod(leaf.shape[2:]))
                  * np.dtype(leaf.dtype).itemsize)
    return total


# ------------------------------------------------------------ config surface
class ParsedQuant(NamedTuple):
    kv: KVQCodec | None   # KV-block codec (None = fp pool)
    woq_bits: int         # weight-only quant bits (0 = dense weights)
    qcol: bool            # quantize the TP inference collectives


def parse_quant(spec) -> ParsedQuant:
    """Parse the ONE low-bit config surface (``RaggedConfig.quant``).

    Grammar: ``"off"`` | ``"int8"`` | ``"fp8"`` | ``"woq8"`` | ``"woq4"``
    | ``"qcol"``, joined with ``+`` — e.g. ``"int8+woq8+qcol"`` buys the
    full low-bit serving path. ``None``/empty means off.
    """
    if spec is None:
        return ParsedQuant(None, 0, False)
    if not isinstance(spec, str):
        raise ValueError(f"quant must be a string, got {type(spec).__name__}")
    kv, woq, qcol = None, 0, False
    for part in spec.split("+"):
        part = part.strip().lower()
        if part in ("", "off", "none"):
            continue
        elif part in CODECS:
            if kv is not None:
                raise ValueError(f"quant={spec!r}: more than one KV codec")
            kv = CODECS[part]
        elif part in ("woq8", "woq4"):
            if woq:
                raise ValueError(f"quant={spec!r}: more than one woq spec")
            woq = int(part[3:])
        elif part == "qcol":
            qcol = True
        else:
            raise ValueError(
                f"quant={spec!r}: unknown component {part!r}; grammar: "
                "off | int8 | fp8 | woq8 | woq4 | qcol joined with '+'")
    return ParsedQuant(kv, woq, qcol)


# ------------------------------------------------- quantized TP collective
def quantized_logits_all_gather(x: jnp.ndarray, mesh, axis: str = "tensor"):
    """Quantize the vocab-sharded logits all-gather of sharded inference.

    GSPMD inserts the gather implicitly when the sampler consumes
    tensor-sharded logits; this replaces it with an EXPLICIT shard_map
    region (the ``comm/quantized_collectives`` discipline) whose wire
    operand is the int8 payload + one f32 scale per (row, shard) — so the
    collective moves ~1/2 (bf16) to ~1/4 (f32) of the bytes, assertable
    in the compiled HLO as an ``s8`` all-gather operand.

    Identity when there is no mesh, no ``axis`` dimension, a trivial
    shard count, or a vocab that doesn't split evenly (the quantized wire
    is an optimization, never a requirement).
    """
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.ops.quantizer import dequantize_rows, quantize_rows

    if mesh is None:
        return x
    n = dict(getattr(mesh, "shape", {})).get(axis, 1)
    if n <= 1 or x.shape[-1] % n:
        return x
    local = x.shape[-1] // n
    gather_dim = x.ndim - 1
    spec_in = P(*([None] * gather_dim), axis)

    def body(xs):
        # one scale per row per shard: block == the local shard width
        q, s = quantize_rows(xs, block=local)
        qg = jax.lax.all_gather(q, axis, axis=gather_dim, tiled=True)
        sg = jax.lax.all_gather(s, axis, axis=gather_dim, tiled=True)
        return dequantize_rows(qg, sg, x.dtype, block=local)

    from deepspeed_tpu.utils.compat import shard_map_compat

    mapped = shard_map_compat(body, mesh=mesh, in_specs=(spec_in,),
                              out_specs=P(), axis_names={axis},
                              check_vma=False)
    return mapped(x)


# ------------------------------------------------------------ drift metrics
def token_match_rate(want: dict, got: dict) -> float:
    """Greedy drift gauge: position-wise prefix agreement of generated
    token lists, averaged over sequences (1.0 = token-identical)."""
    total = match = 0
    for uid, ref in want.items():
        have = got.get(uid) or []
        total += len(ref)
        for a, b in zip(ref, have):
            if a != b:
                break
            match += 1
    return match / total if total else 1.0


def drift_verdict(greedy_match: float, spec_accept_drift: float | None,
                  budget: dict | None = None) -> dict:
    """The gate CI/bench applies: measured drift vs ``DRIFT_BUDGET``."""
    b = dict(DRIFT_BUDGET, **(budget or {}))
    ok = greedy_match >= b["greedy_match_min"]
    if spec_accept_drift is not None:
        ok = ok and spec_accept_drift <= b["spec_accept_drift_max"]
    return {
        "ok": bool(ok),
        "greedy_token_match_rate": round(float(greedy_match), 4),
        "spec_accept_rate_drift": (None if spec_accept_drift is None
                                   else round(float(spec_accept_drift), 4)),
        "budget": b,
    }
