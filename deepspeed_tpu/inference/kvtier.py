"""Hierarchical KV-cache tiering: HBM → host RAM → disk.

The paged prefix cache (``inference/ragged.py``) is strictly free-HBM-funded:
when the allocator's LRU runs dry the evicted block's KV is simply gone and
the next request re-prefills it from scratch. This module turns that eviction
into a *demotion* down a three-tier store, the same memory-hierarchy
discipline the reference framework applies to optimizer/parameter state
(swap_tensor pinned pools, ZeRO-Infinity NVMe):

- **tier 0** — the device-resident block pool itself (owned by the engine;
  this module never touches device memory).
- **tier 1** — :class:`HostTier`, a bounded host-RAM arena. The engine's
  demote hook gathers the evicted block's payload device→host (the same
  jitted block-row gather ``export_handoff`` uses) and parks it here keyed
  by the block's exact hash-chain key.
- **tier 2** — :class:`DiskTier`, a spill directory fed by tier-1 overflow.
  Records are written with the checkpoint commit protocol (same-dir temp +
  fsync + ``os.replace``) and length+sha256 framing, so a torn or corrupted
  record can never splice wrong KV — it is detected and discarded.

Promotion back to HBM is cost-model driven: :func:`restore_beats_prefill`
compares the tier-crossing byte time against re-running prefill for the same
tokens (the PR 8 ``transfer_beats_prefill`` model applied to tier bandwidth
instead of wire bandwidth), and is conservative on unknowns — a non-positive
bandwidth or prefill rate never restores. The engine performs the actual
restore through its standard allocate→scatter→publish path, so a promoted
block re-enters the tier-0 LRU exactly as if it had never left and the
admission splice (and therefore the emitted tokens) is bit-identical either
way.

Async prefetch: the serving router calls ``prefetch()`` at placement time
with the chain keys the chosen replica is missing from HBM; a worker thread
stages matching disk records up into the host arena so the admission-time
restore only pays the host→device hop. A prefetch that has not finished by
admission is *abandoned* (the admission pass restores synchronously or
re-prefills) — token-identical either way, only the latency differs.

Everything here is plain host state behind one lock; the module never
imports the engine, so ``ragged.py`` can import the framing helpers for
``KVHandoff.to_bytes``/``from_bytes`` without a cycle.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import queue
import struct
import threading
import time
from typing import Any

import numpy as np

__all__ = [
    "DiskTier",
    "HostTier",
    "KVCodecMismatch",
    "KVTierStore",
    "frame_bytes",
    "restore_beats_prefill",
    "unframe_bytes",
]


class KVCodecMismatch(ValueError):
    """A persisted KV record was written under a different quantization
    codec than this engine runs (``RaggedConfig.quant``). Dequantizing it
    anyway would splice numerically wrong KV, so reads RAISE instead of
    missing — unlike corruption, which reads as a miss, a codec mismatch
    is a configuration error the operator must see."""

# framing magics: one for tier-2 spill records, one for serialized KVHandoff
# payloads (shared integrity check, distinct container types)
RECORD_MAGIC = b"KVT2"
HANDOFF_MAGIC = b"KVH1"
_FRAME_HEADER = struct.Struct("<Q")  # u64 body length, then sha256, then body


# --------------------------------------------------------------- framing
def frame_bytes(body: bytes) -> bytes:
    """Wrap ``body`` in length+sha256 framing: u64 little-endian length,
    32-byte sha256 digest, then the body. Shared by the disk tier's spill
    records and ``KVHandoff.to_bytes`` so every serialized KV payload in the
    system carries the same end-to-end integrity check."""
    return _FRAME_HEADER.pack(len(body)) + hashlib.sha256(body).digest() + body


def unframe_bytes(buf: bytes, offset: int = 0) -> tuple[bytes, int]:
    """Inverse of :func:`frame_bytes` starting at ``offset``: returns
    ``(body, next_offset)``. Raises ValueError on a torn (short) or
    corrupted (digest mismatch) frame — callers treat that as "record does
    not exist", never as data."""
    head = offset + _FRAME_HEADER.size
    if len(buf) < head + 32:
        raise ValueError("torn frame: truncated header")
    (length,) = _FRAME_HEADER.unpack_from(buf, offset)
    digest = bytes(buf[head:head + 32])
    end = head + 32 + length
    if len(buf) < end:
        raise ValueError("torn frame: truncated body")
    body = bytes(buf[head + 32:end])
    if hashlib.sha256(body).digest() != digest:
        raise ValueError("corrupt frame: sha256 mismatch")
    return body, end


# ------------------------------------------------------------ cost model
def restore_beats_prefill(tokens: int, bytes_per_token: int,
                          tier_gbps: float,
                          prefill_tokens_per_s: float) -> bool:
    """True when moving ``tokens`` worth of cached KV across a tier
    boundary is cheaper than re-prefilling those tokens — the bytes-vs-FLOPs
    estimate of ``serving.cluster.transfer_beats_prefill`` with the tier's
    bandwidth in place of the wire's. Conservative on unknowns: non-positive
    token counts, bandwidths, or prefill rates never restore (an unknown
    (-1) bandwidth must not flip the inequality by going negative)."""
    if tokens <= 0 or tier_gbps <= 0 or prefill_tokens_per_s <= 0:
        return False
    move_s = tokens * bytes_per_token * 8.0 / (tier_gbps * 1e9)
    return move_s < tokens / prefill_tokens_per_s


def _payload_nbytes(payload: Any) -> int:
    """Total bytes across a (numpy) payload pytree without importing jax at
    module load: walk nested dict/list/tuple containers."""
    if payload is None:
        return 0
    if isinstance(payload, dict):
        return sum(_payload_nbytes(v) for v in payload.values())
    if isinstance(payload, (list, tuple)):
        return sum(_payload_nbytes(v) for v in payload)
    return int(getattr(payload, "nbytes", 0))


def _key_digest(key: Any) -> str:
    """Stable filename digest for a hash-chain key. The digest only NAMES
    the record; ``DiskTier.get`` verifies the stored exact key against the
    requested one, so a digest collision degrades to a miss, never a wrong
    splice."""
    return hashlib.sha256(repr(key).encode("utf-8")).hexdigest()[:40]


# ---------------------------------------------------------------- tier 1
class HostTier:
    """Bounded host-RAM arena of demoted KV block payloads, LRU→MRU
    (dict insertion order, same discipline as the allocator's device LRU).
    Not thread-safe on its own — :class:`KVTierStore` serializes access."""

    def __init__(self, budget_blocks: int):
        self.budget_blocks = max(0, int(budget_blocks))
        self._store: dict[Any, Any] = {}   # chain key -> payload, LRU->MRU
        self.nbytes = 0

    def __len__(self) -> int:
        return len(self._store)

    def __contains__(self, key) -> bool:
        return key in self._store

    def get(self, key, touch: bool = True):
        payload = self._store.get(key)
        if payload is not None and touch:
            del self._store[key]
            self._store[key] = payload  # re-insert at the MRU end
        return payload

    def put(self, key, payload) -> list[tuple[Any, Any]]:
        """Insert (or touch) ``key``; returns the LRU entries shed to honor
        the block budget — the caller spills them to the disk tier or drops
        them. A re-inserted key keeps the existing payload (same chain key
        = same KV content for the same model)."""
        if self.budget_blocks <= 0:
            return [(key, payload)]
        if key in self._store:
            existing = self._store.pop(key)
            self._store[key] = existing  # touch to MRU; same key = same KV
            return []
        self._store[key] = payload
        self.nbytes += _payload_nbytes(payload)
        shed: list[tuple[Any, Any]] = []
        while len(self._store) > self.budget_blocks:
            old_key = next(iter(self._store))
            old_payload = self._store.pop(old_key)
            self.nbytes -= _payload_nbytes(old_payload)
            shed.append((old_key, old_payload))
        return shed

    def pop(self, key):
        payload = self._store.pop(key, None)
        if payload is not None:
            self.nbytes -= _payload_nbytes(payload)
        return payload

    def clear(self) -> None:
        self._store.clear()
        self.nbytes = 0


# ---------------------------------------------------------------- tier 2
class DiskTier:
    """Spill directory of demoted KV block records (one file per block).

    Record format: ``RECORD_MAGIC`` + frame(pickled ``{"key": chain_key,
    "codec": codec_id}``) + frame(pickled payload pytree), each frame
    length+sha256 checked — for a quantized payload the second frame covers
    BOTH the low-bit tensors and their scale tensors (they pickle as one
    pytree), and the codec id in the first frame pins which codec wrote
    them: reading a spill under a different codec config raises
    :class:`KVCodecMismatch` instead of silently dequantizing wrong.
    Pre-codec records (a bare pickled chain key) read as codec ``"off"``.
    Writes
    follow the checkpoint commit protocol (PR 9): same-directory temp file,
    flush+fsync, atomic ``os.replace``, directory fsync — a crash can leave
    a temp file or a torn record, never a half-visible one, and
    :meth:`sweep` clears both classes of debris at engine startup."""

    SUFFIX = ".kvb"

    def __init__(self, directory: str, budget_blocks: int = 0,
                 codec: str = "off"):
        self.directory = str(directory)
        self.budget_blocks = max(0, int(budget_blocks))
        self.codec = str(codec)
        os.makedirs(self.directory, exist_ok=True)
        self.nbytes = 0
        self.sweep_removed = 0
        # digest -> file size, insertion order oldest->newest (budget LRU)
        self._index: dict[str, int] = {}
        self.sweep_removed = self.sweep()
        self._load_index()

    def _path(self, digest: str) -> str:
        return os.path.join(self.directory, digest + self.SUFFIX)

    def sweep(self) -> int:
        """Remove leftover temp files and torn/corrupt records. Returns how
        many files were deleted. Called at construction (= engine startup);
        idempotent and safe to call again."""
        removed = 0
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return 0
        for name in names:
            path = os.path.join(self.directory, name)
            if ".tmp." in name:
                removed += self._unlink(path)
                continue
            if not name.endswith(self.SUFFIX):
                continue
            try:
                with open(path, "rb") as f:
                    buf = f.read()
                if not buf.startswith(RECORD_MAGIC):
                    raise ValueError("bad magic")
                _, off = unframe_bytes(buf, len(RECORD_MAGIC))
                _, end = unframe_bytes(buf, off)
                if end != len(buf):
                    raise ValueError("trailing bytes")
            except (OSError, ValueError):
                removed += self._unlink(path)
        return removed

    @staticmethod
    def _unlink(path: str) -> int:
        try:
            os.unlink(path)
            return 1
        except OSError:
            return 0

    def _load_index(self) -> None:
        """Rebuild the digest index from surviving records (oldest first by
        mtime so the budget LRU keeps working across restarts)."""
        entries = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if not name.endswith(self.SUFFIX):
                continue
            path = os.path.join(self.directory, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            entries.append((st.st_mtime, name[:-len(self.SUFFIX)], st.st_size))
        for _, digest, size in sorted(entries):
            self._index[digest] = size
            self.nbytes += size

    def __len__(self) -> int:
        return len(self._index)

    def __contains__(self, key) -> bool:
        return _key_digest(key) in self._index

    def put(self, key, payload) -> bool:
        """Atomically persist one demoted block; evicts the oldest records
        past the block budget. False when the budget is 0 (tier disabled)
        or the write failed (spill is best-effort — losing a spill costs a
        re-prefill, never correctness)."""
        if self.budget_blocks <= 0:
            return False
        digest = _key_digest(key)
        if digest in self._index:
            return True  # same chain key = same content: keep the old record
        body = (RECORD_MAGIC
                + frame_bytes(pickle.dumps({"key": key, "codec": self.codec},
                                           protocol=4))
                + frame_bytes(pickle.dumps(payload, protocol=4)))
        path = self._path(digest)
        tmp = os.path.join(self.directory,
                           f".{digest}{self.SUFFIX}.tmp.{os.getpid()}")
        try:
            with open(tmp, "wb") as f:
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._fsync_dir()
        except OSError:
            self._unlink(tmp)
            return False
        self._index[digest] = len(body)
        self.nbytes += len(body)
        while len(self._index) > self.budget_blocks:
            old = next(iter(self._index))
            self.nbytes -= self._index.pop(old)
            self._unlink(self._path(old))
        return True

    def _fsync_dir(self) -> None:
        try:
            fd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass  # platforms without directory fsync

    def get(self, key):
        """Load one record's payload, or None. Every failure mode — missing
        file, torn frame, digest mismatch, or a digest collision where the
        stored exact key differs — reads as a miss, and a corrupt record is
        unlinked so it cannot waste future lookups. The ONE exception is a
        codec mismatch (record written under a different
        ``RaggedConfig.quant``): that RAISES :class:`KVCodecMismatch` — the
        record is intact, the configuration is wrong."""
        digest = _key_digest(key)
        if digest not in self._index:
            return None
        path = self._path(digest)
        try:
            with open(path, "rb") as f:
                buf = f.read()
            if not buf.startswith(RECORD_MAGIC):
                raise ValueError("bad magic")
            key_body, off = unframe_bytes(buf, len(RECORD_MAGIC))
            stored = pickle.loads(key_body)
            if isinstance(stored, dict) and "key" in stored:
                stored_key = stored["key"]
                stored_codec = stored.get("codec", "off")
            else:  # pre-codec record: a bare pickled chain key
                stored_key, stored_codec = stored, "off"
            if stored_key != key:
                return None  # digest collision: a miss, never a wrong splice
            if stored_codec != self.codec:
                raise KVCodecMismatch(
                    f"KV spill record {digest} was written under codec "
                    f"{stored_codec!r} but this engine runs {self.codec!r} "
                    "(RaggedConfig.quant); refusing to dequantize — clear "
                    "the tier directory or match the codec config")
            payload_body, _ = unframe_bytes(buf, off)
            return pickle.loads(payload_body)
        except KVCodecMismatch:
            raise
        except (OSError, ValueError, pickle.UnpicklingError, EOFError):
            self.nbytes -= self._index.pop(digest, 0)
            self._unlink(path)
            return None

    def clear(self) -> None:
        for digest in list(self._index):
            self._unlink(self._path(digest))
        self._index.clear()
        self.nbytes = 0


# ------------------------------------------------------------ the store
class _PrefetchJob:
    __slots__ = ("keys", "done", "cancelled")

    def __init__(self, keys: list):
        self.keys = keys
        self.done = threading.Event()
        self.cancelled = False


class KVTierStore:
    """The tier-1/tier-2 half of the hierarchical KV cache, plus the async
    prefetch worker. Thread-safe: the engine thread demotes/promotes, the
    router thread enqueues prefetches, the worker thread stages disk→host —
    every tier mutation happens under one lock (payloads are small compared
    to the device work around them, and the lock is never held across a
    file read in the hot demote path — spill writes happen on whichever
    thread triggered the overflow, which is the engine thread during
    demotion and the worker during staging)."""

    def __init__(self, host_blocks: int, disk_blocks: int = 0,
                 directory: str = "runs/kvtier",
                 host_gbps: float = 100.0, disk_gbps: float = 8.0,
                 prefill_tokens_per_s: float = 50000.0,
                 bytes_per_token: int = 0, codec: str = "off"):
        self.codec = str(codec)
        self.host = HostTier(host_blocks)
        self.disk = DiskTier(directory, disk_blocks, codec=self.codec) \
            if disk_blocks > 0 else None
        self.host_gbps = float(host_gbps)
        self.disk_gbps = float(disk_gbps)
        self.prefill_tokens_per_s = float(prefill_tokens_per_s)
        self.bytes_per_token = int(bytes_per_token)
        self._lock = threading.RLock()
        # cumulative counters (plain ints so the bench reads them with
        # telemetry off; the engine mirrors them into telemetry counters)
        self.demotions = 0            # blocks parked HBM -> host
        self.spills = 0               # blocks shed host -> disk
        self.spill_drops = 0          # host overflow lost (no/full disk tier)
        self.promotions_host = 0      # blocks restored host -> HBM
        self.promotions_disk = 0      # blocks restored disk -> HBM
        self.promoted_admissions_host = 0  # admissions restored from tier 1
        self.promoted_admissions_disk = 0  # ... with at least one tier-2 block
        self.restore_declined = 0     # chain links the cost model refused
        self.prefetch_jobs = 0
        self.prefetch_hits = 0        # admissions whose prefetch finished
        self.prefetch_abandoned = 0   # ... that arrived before it finished
        self.restore_seconds = 0.0    # cumulative engine-side restore time
        self._jobs: dict[Any, _PrefetchJob] = {}
        self._queue: queue.Queue | None = None
        self._worker: threading.Thread | None = None
        self._closed = False
        # test seam: when set, the worker parks before servicing jobs so the
        # abandoned-prefetch path is deterministically reachable
        self._stall_for_test: threading.Event | None = None

    # ------------------------------------------------------------- queries
    @property
    def promotions(self) -> int:
        return self.promotions_host + self.promotions_disk

    @property
    def sweep_removed(self) -> int:
        return self.disk.sweep_removed if self.disk is not None else 0

    def tier_of(self, key) -> int:
        """1 (host), 2 (disk), or 0 (not in this store)."""
        with self._lock:
            if key in self.host:
                return 1
            if self.disk is not None and key in self.disk:
                return 2
        return 0

    def gbps_of(self, tier: int) -> float:
        return self.host_gbps if tier == 1 else self.disk_gbps

    def should_restore(self, tokens: int, tier: int) -> bool:
        return restore_beats_prefill(tokens, self.bytes_per_token,
                                     self.gbps_of(tier),
                                     self.prefill_tokens_per_s)

    # ------------------------------------------------------------ demotion
    def demote(self, key, payload) -> bool:
        """Park one evicted block's payload in the host arena; LRU overflow
        spills to disk (or is dropped when the disk tier is off/full).
        Called on the engine thread from the allocator's demote hook with
        the payload already gathered to host numpy."""
        with self._lock:
            if self._closed:
                return False
            shed = self.host.put(key, payload)
            self.demotions += 1
            # LRU overflow (or, with a zero host budget, the new block
            # itself) falls through to the disk tier
            for old_key, old_payload in shed:
                if self.disk is not None and self.disk.put(old_key,
                                                           old_payload):
                    self.spills += 1
                else:
                    self.spill_drops += 1
        return True

    # ----------------------------------------------------------- promotion
    def fetch(self, key) -> tuple[Any, int] | None:
        """``(payload, tier)`` for a chain key, host arena first. A disk hit
        returns the payload without staging it into the host arena — the
        caller is about to publish it into HBM, which supersedes both."""
        with self._lock:
            payload = self.host.get(key)
            if payload is not None:
                return payload, 1
            if self.disk is not None:
                payload = self.disk.get(key)
                if payload is not None:
                    return payload, 2
        return None

    def note_restored(self, tiers: list[int], seconds: float) -> None:
        """Engine-side accounting after a successful allocate→scatter→
        publish restore of ``len(tiers)`` blocks."""
        with self._lock:
            n_disk = sum(1 for t in tiers if t == 2)
            self.promotions_disk += n_disk
            self.promotions_host += len(tiers) - n_disk
            if n_disk:
                self.promoted_admissions_disk += 1
            elif tiers:
                self.promoted_admissions_host += 1
            self.restore_seconds += seconds

    # ------------------------------------------------------------ prefetch
    def prefetch(self, keys: list, sig) -> bool:
        """Queue an async staging job for ``keys`` (chain keys missing from
        HBM, chain order): the worker moves matching disk records up into
        the host arena so the admission-time restore only pays the
        host→device hop. Returns False when nothing in this store matches
        (no job, no counters) or a job for ``sig`` is already pending."""
        with self._lock:
            if self._closed or sig in self._jobs:
                return False
            wanted = [k for k in keys if self.tier_of(k) != 0]
            if not wanted:
                return False
            job = _PrefetchJob(wanted)
            self._jobs[sig] = job
            self.prefetch_jobs += 1
            self._ensure_worker()
            self._queue.put(job)
        return True

    def note_admission(self, sig) -> str | None:
        """Resolve the prefetch job for an arriving admission: ``"hit"``
        when staging finished in time, ``"abandoned"`` when the admission
        outran it (the job is cancelled; the synchronous restore path takes
        over — token-identical, only slower), None when no job was queued."""
        with self._lock:
            job = self._jobs.pop(sig, None)
            if job is None:
                return None
            if job.done.is_set():
                self.prefetch_hits += 1
                return "hit"
            job.cancelled = True
            self.prefetch_abandoned += 1
            return "abandoned"

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._queue = queue.Queue()
        self._worker = threading.Thread(
            target=self._run_worker, name="kvtier-prefetch", daemon=True)
        self._worker.start()

    def _run_worker(self) -> None:
        q = self._queue
        while True:
            job = q.get()
            if job is None:
                return
            gate = self._stall_for_test
            if gate is not None:
                gate.wait()
            try:
                self._stage(job)
            except Exception:  # noqa: BLE001 - staging is advisory
                pass
            finally:
                job.done.set()

    def _stage(self, job: _PrefetchJob) -> None:
        for key in job.keys:
            if job.cancelled or self._closed:
                return
            with self._lock:
                if key in self.host or self.disk is None:
                    continue
                payload = self.disk.get(key)
                if payload is None:
                    continue
                # staging must not shed NEWER host entries to make room for
                # an older disk record the admission may not even use: only
                # stage into free host budget
                if len(self.host) < self.host.budget_blocks:
                    self.host.put(key, payload)

    # ---------------------------------------------------------------- misc
    def stats(self) -> dict:
        with self._lock:
            return {
                "host_blocks": len(self.host),
                "host_bytes": int(self.host.nbytes),
                "host_budget_blocks": self.host.budget_blocks,
                "disk_blocks": len(self.disk) if self.disk else 0,
                "disk_bytes": int(self.disk.nbytes) if self.disk else 0,
                "demotions": self.demotions,
                "spills": self.spills,
                "spill_drops": self.spill_drops,
                "promotions": self.promotions,
                "promotions_host": self.promotions_host,
                "promotions_disk": self.promotions_disk,
                "promoted_admissions_host": self.promoted_admissions_host,
                "promoted_admissions_disk": self.promoted_admissions_disk,
                "restore_declined": self.restore_declined,
                "restore_seconds": round(self.restore_seconds, 6),
                "prefetch_jobs": self.prefetch_jobs,
                "prefetch_hits": self.prefetch_hits,
                "prefetch_abandoned": self.prefetch_abandoned,
                "sweep_removed": self.sweep_removed,
                "codec": self.codec,
            }

    @property
    def host_nbytes(self) -> int:
        return int(self.host.nbytes)

    @property
    def disk_nbytes(self) -> int:
        return int(self.disk.nbytes) if self.disk is not None else 0

    def wait_idle(self, timeout: float = 5.0) -> bool:
        """Block until every queued prefetch job finished (tests/ops)."""
        deadline = time.perf_counter() + timeout
        while True:
            with self._lock:
                jobs = [j for j in self._jobs.values()]
            pending = [j for j in jobs if not j.done.is_set()]
            if not pending:
                return True
            if time.perf_counter() >= deadline:
                return False
            pending[0].done.wait(min(0.05, timeout))

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for job in self._jobs.values():
                job.cancelled = True
            self._jobs.clear()
            if self._queue is not None:
                self._queue.put(None)
