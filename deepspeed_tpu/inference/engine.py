"""Inference engine: TP-sharded jitted generation with KV cache.

Role parity with the reference ``inference/engine.py:40 InferenceEngine`` (v1:
TP-sharded kernel-injected generation) — TPU-native shape: the whole
prefill + decode loop is ONE jitted XLA program per (batch, prompt_len,
max_new_tokens) signature; the CUDA-graph capture/replay the reference needs
(``_create_cuda_graph``) is what jit compilation already is on TPU. Tensor
parallelism comes from the same sharding planner as training (AutoTP analog);
the KV cache is a static-shape ring the decode scan updates in place.

Ragged/continuous batching (v2 FastGen analog) lives in
``inference/ragged.py``.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.comm import comm as dist
from deepspeed_tpu.comm.topology import get_topology, topology_initialized
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models.api import ModelSpec, ShardCtx
from deepspeed_tpu.parallel.partition import plan_sharding
from deepspeed_tpu.telemetry import get_telemetry
from deepspeed_tpu.utils.logging import log_dist


class InferenceEngine:
    """Greedy / sampled autoregressive generation over a ModelSpec."""

    def __init__(
        self,
        model,
        mp_size: int = 1,
        dtype=jnp.bfloat16,
        params: Any = None,
        checkpoint: str | None = None,
        seed: int = 0,
        quantize_bits: int = 0,
        quantize_block: int = 256,
        quant: str = "off",
    ):
        if topology_initialized():
            self.topo = get_topology()
        else:
            import jax as _jax

            n = len(_jax.devices())
            self.topo = dist.init_distributed(
                MeshConfig(data=n // mp_size, tensor=mp_size)
            )
        self.ctx = ShardCtx(mesh=self.topo.mesh)
        self.spec: ModelSpec = model(self.ctx) if callable(model) else model
        if self.spec.decode_fn is None or self.spec.init_cache_fn is None:
            raise ValueError(f"model {self.spec.name} has no decode/cache support")
        self.dtype = dtype

        self.plan = plan_sharding(
            self.spec.param_logical_axes,
            jax.eval_shape(self.spec.init_fn, jax.random.PRNGKey(0)),
            self.topo,
            zero_stage=0,
            use_tp=self.topo.size("tensor") > 1,
            dim_units=self.spec.logical_dim_units,
        )
        if params is None:
            params = jax.jit(
                self.spec.init_fn, out_shardings=self.plan.param_shardings
            )(jax.random.PRNGKey(seed))
        else:
            params = jax.device_put(params, self.plan.param_shardings)
        # inference weights in compute dtype (reference dtype=half cast)
        self.params = jax.tree_util.tree_map(
            lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
            params,
        )
        if checkpoint is not None:
            self.load_checkpoint(checkpoint)
        # ONE low-bit config surface shared with the ragged engine
        # (inference/kvquant.py): the woq component merges with the
        # back-compat quantize_bits arg; '+qcol' quantizes the TP logits
        # all-gather; a KV codec only applies to the paged pool, so it is
        # accepted-but-inert on this dense-cache engine (logged).
        from deepspeed_tpu.inference import kvquant

        parsed = kvquant.parse_quant(quant)
        self._qcol = parsed.qcol and self.topo.size("tensor") > 1
        if parsed.kv is not None:
            log_dist(
                f"InferenceEngine: quant KV codec {parsed.kv.name!r} applies "
                "to the paged pool (RaggedInferenceEngine); inert on the "
                "dense-cache engine", ranks=[0])
        # weight-only quantization (reference inference/quantization/ WOQ):
        # >=2D weights stored int8/int4 blockwise, dequantized just in time
        # per scanned layer (models call ops.quantizer.maybe_dequantize)
        self.quantize_bits = int(quantize_bits) or parsed.woq_bits
        self._quantize_block = quantize_block
        if self.quantize_bits:
            self.params = self._quantize(self.params)
        self._gen_cache: dict = {}
        log_dist(
            f"InferenceEngine: model={self.spec.name} tp={self.topo.size('tensor')} "
            f"dtype={jnp.dtype(dtype).name}"
            + (f" woq=int{self.quantize_bits}" if self.quantize_bits else "")
            + (" qcol" if self._qcol else ""),
            ranks=[0],
        )

    def _maybe_qcol(self, logits):
        """'+qcol': route logits through the quantized TP all-gather (an
        explicit int8-wire shard_map region) instead of GSPMD's implicit fp
        gather. Traced inside the jitted generate/forward programs."""
        if not self._qcol:
            return logits
        from deepspeed_tpu.inference import kvquant

        return kvquant.quantized_logits_all_gather(
            logits, self.topo.mesh, axis="tensor")

    def _quantize(self, params):
        from deepspeed_tpu.ops.quantizer import quantize_params

        return jax.jit(
            lambda p: quantize_params(p, bits=self.quantize_bits,
                                      block=self._quantize_block,
                                      skip=tuple(self.spec.woq_skip))
        )(params)

    def load_checkpoint(self, ckpt_dir: str) -> None:
        """Load params saved by ``Engine.save_checkpoint`` (universal layout).

        On a WOQ engine the checkpoint's dense weights load into a fresh
        dense tree and are re-quantized (the live tree's leaves are int8
        values + scales — dense arrays cannot be mapped onto it)."""
        import os

        from deepspeed_tpu.checkpoint import engine as ckpt
        from deepspeed_tpu.checkpoint import serialization as ser

        from deepspeed_tpu.checkpoint import sharded

        target = self.params
        if getattr(self, "quantize_bits", 0):
            # dense load template: zeros with the plan's shapes/shardings
            # (every value is overwritten by the strict loaders; running the
            # real init would waste a full model's compute + memory)
            abstract = jax.eval_shape(self.spec.init_fn, jax.random.PRNGKey(0))
            target = jax.tree_util.tree_map(
                lambda s, sh: jax.device_put(
                    jnp.zeros(s.shape,
                              self.dtype if jnp.issubdtype(s.dtype, jnp.floating)
                              else s.dtype), sh),
                abstract, self.plan.param_shardings)

        tag = ckpt.latest_tag(ckpt_dir)
        model_dir = os.path.join(ckpt_dir, tag) if tag else ckpt_dir
        if sharded.is_sharded(model_dir, "model"):
            # fragments re-placed straight under the inference plan/dtype
            loaded = sharded.load_sharded(target, model_dir, "model")
        else:
            arrays = ser.load_arrays(os.path.join(model_dir, "model.npz"))
            host = ser.arrays_to_tree(
                jax.tree_util.tree_map(np.asarray, target), arrays
            )
            loaded = jax.device_put(host, self.plan.param_shardings)
        if getattr(self, "quantize_bits", 0):
            loaded = self._quantize(loaded)
        self.params = loaded

    # ------------------------------------------------------------------ generate
    def _build_generate(self, batch: int, prompt_len: int, max_new: int,
                        sample: bool, use_penalty: bool, has_tk: bool,
                        has_tp: bool):
        decode = self.spec.decode_fn
        init_cache = self.spec.init_cache_fn
        total = prompt_len + max_new

        def generate_fn(params, tokens, rng, temperature, top_k, top_p,
                        rep_pen):
            from deepspeed_tpu.inference.sampling import (
                sample_tokens,
                update_seen,
            )

            cache = init_cache(batch, total, self.dtype)
            logits, cache = decode(params, tokens, cache, 0)
            last = self._maybe_qcol(
                logits[:, prompt_len - 1]).astype(jnp.float32)
            vocab = last.shape[-1]
            # occurrence mask over the prompt (HF repetition_penalty
            # semantics: penalize everything in the context)
            seen0 = (jnp.zeros((batch, vocab), jnp.bool_)
                     .at[jnp.arange(batch)[:, None], tokens].set(True)
                     if use_penalty else jnp.zeros((batch, 1), jnp.bool_))

            def pick(logits_f, r, seen):
                if not sample and not use_penalty:
                    return jnp.argmax(logits_f, axis=-1).astype(jnp.int32)
                toks, _ = sample_tokens(
                    logits_f, r,
                    temperature if sample else jnp.float32(0.0),
                    # None compiles the top-k/top-p sorts OUT when disabled
                    # (the flags are static in the cache key)
                    top_k=top_k if has_tk else None,
                    top_p=top_p if has_tp else None,
                    repetition_penalty=rep_pen if use_penalty else None,
                    seen_mask=seen if use_penalty else None)
                return toks

            def step(carry, i):
                last, cache, seen = carry
                r = jax.random.fold_in(rng, i)
                tok = pick(last, r, seen)
                if use_penalty:
                    seen = update_seen(seen, tok)
                logits, cache = decode(params, tok[:, None], cache, prompt_len + i)
                return (self._maybe_qcol(logits[:, 0]).astype(jnp.float32),
                        cache, seen), tok

            (_, _, _), toks = jax.lax.scan(
                step, (last, cache, seen0), jnp.arange(max_new))
            return toks.T  # [B, max_new]

        return jax.jit(generate_fn)

    def generate(self, input_ids, max_new_tokens: int = 64, temperature: float = 0.0,
                 seed: int = 0, top_k: int = 0, top_p: float = 1.0,
                 repetition_penalty: float = 1.0):
        """[B, T] prompt -> [B, T + max_new_tokens] (greedy when temperature=0;
        ``top_k``/``top_p``/``repetition_penalty`` follow the reference
        generate surface, ``inference/engine.py:586 _generate`` forwarding HF
        sampling kwargs — see ``inference/sampling.py``).

        Each (B, T, N, sampled?, penalized?) signature compiles once and
        replays (CUDA-graph parity); the sampling VALUES are traced, so
        changing temperature/top_k/top_p never recompiles."""
        input_ids = np.asarray(input_ids)
        b, t = input_ids.shape
        sample = temperature > 0.0
        use_penalty = repetition_penalty != 1.0
        has_tk, has_tp = top_k > 0, top_p < 1.0
        key = (b, t, max_new_tokens, sample, use_penalty, has_tk, has_tp)
        telemetry = get_telemetry()
        t0 = time.perf_counter() if telemetry.enabled else 0.0
        compiled = key in self._gen_cache
        if not compiled:
            self._gen_cache[key] = self._build_generate(
                b, t, max_new_tokens, sample, use_penalty, has_tk, has_tp)
        toks = self._gen_cache[key](
            self.params,
            jnp.asarray(input_ids),
            jax.random.PRNGKey(seed),
            jnp.float32(max(temperature, 1e-6)),
            jnp.int32(top_k),
            jnp.float32(top_p),
            jnp.float32(repetition_penalty),
        )
        toks = np.asarray(toks)
        if telemetry.enabled:
            # the whole prefill+decode program is one dispatch: TTFT/per-token
            # breakdown belongs to the ragged engine; here the span carries
            # batch shape + whether this call paid the compile
            telemetry.emit_span(
                "inference/generate", time.perf_counter() - t0,
                batch=b, prompt_tokens=t, new_tokens=max_new_tokens,
                cached_program=compiled)
            telemetry.counter(
                "inference_tokens_generated_total", "tokens generated").inc(
                    b * max_new_tokens)
        return np.concatenate([input_ids, toks], axis=1)

    def forward(self, input_ids):
        """Plain logits forward (reference ``engine.forward:557``); jitted —
        sharding constraints inside the model require a compiled context."""
        if not hasattr(self, "_fwd_jit"):
            self._fwd_jit = jax.jit(self.spec.forward_fn)
        return self._fwd_jit(self.params, jnp.asarray(input_ids))

    __call__ = forward


def init_inference(model, config: dict | None = None, **kwargs):
    """Reference ``deepspeed.init_inference`` (``__init__.py:328``)."""
    config = dict(config or {})
    config.update(kwargs)
    tp = config.get("tensor_parallel", {})
    mp_size = tp.get("tp_size", config.get("mp_size", 1)) if isinstance(tp, dict) else int(tp)
    dtype_str = str(config.get("dtype", "bf16")).replace("torch.", "").replace(
        "float16", "fp16")
    dtype = {"bf16": jnp.bfloat16, "fp16": jnp.float16, "fp32": jnp.float32}.get(
        dtype_str, jnp.bfloat16)
    # reference WOQ knobs: dtype=torch.int8 or quant: {weight: {num_bits}}
    bits = 0
    if dtype_str in ("int8", "qint8"):
        bits = 8
    quant = config.get("quant")
    quant_str = "off"
    if isinstance(quant, str):  # kvquant grammar: e.g. "int8+woq8+qcol"
        quant_str = quant
    elif isinstance(quant, dict) and quant.get("enabled", True):
        bits = int((quant.get("weight") or {}).get("num_bits", bits or 8))
    return InferenceEngine(
        model,
        mp_size=mp_size,
        dtype=dtype,
        params=config.get("params"),
        checkpoint=config.get("checkpoint"),
        quantize_bits=int(config.get("quantize_bits", bits)),
        quantize_block=int(config.get("quantize_block", 256)),
        quant=quant_str,
    )
