"""Token sampling: temperature / top-k / top-p / repetition penalty.

Role parity with the reference generate surface
(``deepspeed/inference/engine.py:586 _generate`` forwards HF sampling
kwargs — do_sample, temperature, top_k, top_p, repetition_penalty — to the
wrapped module's ``generate``). Here sampling is a jittable primitive the
engines call INSIDE their compiled decode loops, so sampled multi-step decode
(hybrid rollouts, ragged run-ahead) needs no host round trip per token.

All controls are per-row arrays, so one compiled program serves a batch
mixing greedy and sampled requests (the ragged engine's per-request params).

Semantics (matching the HF/reference processors):
- ``temperature`` <= 0 means greedy (argmax); otherwise logits /= temperature.
- ``top_k`` 0 disables; otherwise only the k highest logits stay.
- ``top_p`` >= 1 disables; otherwise the smallest prefix of the
  descending-sorted distribution with cumulative probability >= top_p stays
  (the highest-probability token always stays).
- ``repetition_penalty`` 1.0 disables; otherwise seen tokens' logits are
  divided by the penalty when positive and multiplied when negative (the CTRL
  paper rule HF implements). "Seen" comes from a per-row occurrence mask the
  caller maintains (prompt + generated so far).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG = -1e30


def apply_repetition_penalty(logits, seen_mask, penalty):
    """CTRL-rule repetition penalty. ``logits`` [T, V] fp32; ``seen_mask``
    [T, V] bool/int (nonzero = token occurred in the row's context);
    ``penalty`` [T] fp32 (1.0 = off)."""
    pen = penalty[:, None]
    seen = seen_mask.astype(jnp.bool_)
    penalized = jnp.where(logits > 0, logits / pen, logits * pen)
    return jnp.where(seen & (pen != 1.0), penalized, logits)


def _mask_top_k(logits, top_k):
    """Keep the per-row ``top_k`` highest logits (0 = keep all). ``top_k``
    [T] int32 — per-row variable k via the k-th order statistic."""
    v = logits.shape[-1]
    k = jnp.where(top_k <= 0, v, jnp.minimum(top_k, v)).astype(jnp.int32)
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)
    return jnp.where(logits >= kth, logits, _NEG)


def _mask_top_p(logits, top_p):
    """Nucleus filtering. ``top_p`` [T] fp32 (>= 1 disables). The smallest
    descending-probability prefix with cumulative mass >= top_p survives."""
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # position i survives if the mass BEFORE it is < top_p (so the first
    # token always survives and the prefix reaching top_p is included)
    prev = cum - probs
    keep_sorted = prev < top_p[:, None]
    # threshold value: smallest surviving logit per row
    n_keep = jnp.sum(keep_sorted, axis=-1)  # >= 1
    thr = jnp.take_along_axis(sorted_desc, (n_keep - 1)[:, None], axis=-1)
    disabled = (top_p >= 1.0)[:, None]
    return jnp.where(disabled | (logits >= thr), logits, _NEG)


def sample_tokens(logits, rng, temperature, top_k=None, top_p=None,
                  repetition_penalty=None, seen_mask=None):
    """Pick next tokens for a batch of rows.

    ``logits`` [T, V] (any float dtype); per-row controls broadcast from
    scalars. ``rng`` is either one PRNG key (shared noise source for the
    batch) or a [T, 2] array of per-row keys — per-row keys make a row's
    draw a function of that row alone, which is what batch-invariant
    (prefix-cache-reproducible) sampling needs. Returns (tokens [T] int32,
    logprobs [T] fp32) — the logprob is of the chosen token under the FINAL
    (tempered+filtered) distribution, which is what an RLHF behavior policy
    must record; greedy rows report the untempered log-softmax.
    """
    logits = logits.astype(jnp.float32)
    t = logits.shape[0]
    as_row = lambda x, d: (jnp.broadcast_to(jnp.asarray(x, d), (t,))  # noqa: E731
                           if x is not None else None)
    temperature = as_row(temperature, jnp.float32)
    top_k = as_row(top_k, jnp.int32)
    top_p = as_row(top_p, jnp.float32)
    repetition_penalty = as_row(repetition_penalty, jnp.float32)

    if repetition_penalty is not None and seen_mask is not None:
        logits = apply_repetition_penalty(logits, seen_mask,
                                          repetition_penalty)
    greedy = temperature <= 0.0
    greedy_lp = jax.nn.log_softmax(logits, axis=-1)
    filt = logits / jnp.maximum(temperature, 1e-6)[:, None]
    if top_k is not None:
        filt = _mask_top_k(filt, top_k)
    if top_p is not None:
        filt = _mask_top_p(filt, top_p)
    rng = jnp.asarray(rng)
    if rng.ndim == 2:  # [T, 2] per-row keys
        sampled = jax.vmap(
            lambda r, lg: jax.random.categorical(r, lg)
        )(rng, filt).astype(jnp.int32)
    else:
        sampled = jax.random.categorical(rng, filt, axis=-1).astype(jnp.int32)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    toks = jnp.where(greedy, greedy_tok, sampled)
    lp = jnp.where(greedy,
                   jnp.take_along_axis(greedy_lp, greedy_tok[:, None],
                                       axis=-1)[:, 0],
                   jnp.take_along_axis(jax.nn.log_softmax(filt, axis=-1),
                                       toks[:, None], axis=-1)[:, 0])
    return toks, lp


def per_request_keys(root, seeds, gen_idx):
    """Derive [T, 2] per-row sampling keys from per-request seeds and
    generated-token indices: ``fold_in(fold_in(root, seed), g)``.

    The draw for token ``g`` of a request depends only on (root, seed, g) —
    never on batch composition, dispatch chunking, or engine history — so a
    sampled generation replays identically whether it runs cold, hits the
    prefix cache, or lands in a different dispatch mode."""
    def one(s, g):
        return jax.random.fold_in(jax.random.fold_in(root, s), g)
    return jax.vmap(one)(seeds, gen_idx)


def keys_for_positions(root, seeds, positions, prompt_lens):
    """Per-row sampling keys derived from DEVICE-RESIDENT scheduler rows.

    The token produced by feeding position ``p`` of a request is its
    generated-token index ``p - prompt_len + 1`` (a decode row feeds
    ``generated[p - prompt_len]`` and yields the next one; the prompt's
    final row, ``p = prompt_len - 1``, yields index 0). Computing the index
    on device from the persistent position/prompt-len rows keeps the key
    derivation batch-invariant — identical to ``per_request_keys`` with a
    host-computed ``gen_idx`` — without staging any host array."""
    return per_request_keys(root, seeds, positions - prompt_lens + 1)


def update_seen(seen_mask, tokens):
    """Mark freshly emitted tokens in the occurrence mask ([T, V] x [T])."""
    return seen_mask.at[jnp.arange(tokens.shape[0]), tokens].set(True)


# --------------------------------------------------- speculative decoding
def propose_ngram_drafts(hist, pos, ngram, depth):
    """Prompt-lookup draft source (self-speculation without a draft model):
    for each row, match the ``ngram``-token suffix ending at ``pos`` against
    every earlier window of that row's token history and propose the
    ``depth`` tokens that followed the MOST RECENT match.

    ``hist`` [T, S] int32 — per-row token history (``hist[r, p]`` is the
    token at context position ``p``; positions past the row's frontier hold
    stale/zero values, which is safe because proposals are always verified).
    ``pos`` [T] int32 — index of the newest valid token per row. ``ngram``
    and ``depth`` are Python ints (static under jit).

    Returns ``(draft [T, depth] int32, matched [T] bool)``; unmatched rows
    draft zeros. Pure vectorized jnp — O(ngram * S) compares per row, no
    host round trip, so it runs INSIDE the device scheduler loop reading
    history the loop itself appends to."""
    t, s = hist.shape
    idxs = jnp.arange(s)[None, :]                       # candidate ends j
    match = jnp.ones((t, s), bool)
    for i in range(ngram):                              # static, small
        sfx_i = jnp.take_along_axis(
            hist, jnp.clip(pos - i, 0, s - 1)[:, None], axis=1)  # [T,1]
        cand = jnp.take_along_axis(
            hist, jnp.clip(idxs - i, 0, s - 1), axis=1)          # [T,S]
        match &= cand == sfx_i
    valid = (idxs >= ngram - 1) & (idxs < pos[:, None]) \
        & (pos[:, None] >= ngram - 1)
    jstar = jnp.max(jnp.where(match & valid, idxs, -1), axis=1)  # [T]
    matched = jstar >= 0
    gather = jnp.clip(jstar[:, None] + 1 + jnp.arange(depth)[None, :],
                      0, s - 1)
    draft = jnp.take_along_axis(hist, gather, axis=1)
    return jnp.where(matched[:, None], draft, 0), matched


def accept_drafts(draft, picked, budget, eos):
    """Vectorized acceptance-prefix selection for exact-match speculative
    verification.

    ``picked`` [T, 1+D] are the TARGET model's deterministic picks at the
    verify lanes (lane i is the pick for generated index g+i); ``draft``
    [T, D] the proposed continuation (draft lane i was fed at verify lane
    i+1). A draft token is accepted while it equals the target's own pick
    for that index — so the surfaced stream is bit-identical to plain
    autoregressive decoding (greedy AND seeded: our sampler is a
    deterministic function of (seed, gen_idx), which makes exact-match the
    degenerate rejection sampler whose residual is the target pick itself).
    The first mismatching lane contributes the target's pick as the bonus/
    resample token.

    ``budget`` [T] caps surfaced tokens (remaining emission budget, >= 1
    for live rows); ``eos`` [T] (-1 = none) truncates at the first EOS
    *inclusive*. Returns ``(n_emit [T], n_accepted [T])``: surface
    ``picked[r, :n_emit[r]]``; ``n_accepted`` counts surfaced tokens that
    came from the draft (the speculation win; the +1 bonus is excluded)."""
    t, lanes = picked.shape
    d = lanes - 1
    if d:
        lead = jnp.cumprod((draft == picked[:, :d]).astype(jnp.int32),
                           axis=1)
        a = jnp.sum(lead, axis=1)                       # leading matches
    else:
        a = jnp.zeros((t,), jnp.int32)
    n_emit = jnp.minimum(a + 1, jnp.maximum(budget, 0))
    lane_i = jnp.arange(lanes)[None, :]
    is_eos = (picked == eos[:, None]) & (eos >= 0)[:, None]
    eos_at = jnp.min(jnp.where(is_eos & (lane_i < n_emit[:, None]),
                               lane_i, lanes), axis=1)
    n_emit = jnp.where(eos_at < n_emit, eos_at + 1, n_emit)
    return n_emit.astype(jnp.int32), \
        jnp.minimum(n_emit, a).astype(jnp.int32)
