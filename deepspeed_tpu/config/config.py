"""The framework config tree.

Role parity with the reference's ``runtime/config.py`` (``DeepSpeedConfig``) and its
per-feature sub-configs (``runtime/zero/config.py``, ``precision_config.py``,
``zenflow_config.py``, monitor/comms/flops configs). Same shape: one JSON/dict in,
a validated typed tree out, with the batch-size triangle
(``train_batch_size = micro_batch_size * gradient_accumulation_steps * dp_world``)
resolved centrally.

TPU-first differences: a ``mesh`` section declares named parallelism axes
(data/fsdp/tensor/sequence/expert/pipeline) instead of implicit process groups;
precision is bf16-default; offload targets are host DRAM / NVMe on the TPU-VM.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, ClassVar, Optional, Union

from deepspeed_tpu.config.base import AUTO, ConfigBase, ConfigError, is_auto


# Canonical spellings of the compressed-optimizer family — THE single list
# (ops/optimizers.py dispatch, the engine's two-phase wire switch, and this
# config validation all consume it; a spelling added here is recognized
# everywhere at once).
ONEBIT_ADAM_NAMES = ("onebit_adam", "onebitadam", "1bit-adam", "1bit_adam")
ONEBIT_LAMB_NAMES = ("onebit_lamb", "onebitlamb", "1bit-lamb", "1bit_lamb")
ZERO_ONE_ADAM_NAMES = ("zero_one_adam", "zerooneadam", "01adam", "zoadam")


def is_onebit_family(name: str) -> bool:
    """True for every optimizer whose reference counterpart compresses its
    gradient wire after warmup (1-bit Adam/LAMB, 0/1 Adam)."""
    n = name.lower().replace("-", "_")
    return n in tuple(s.replace("-", "_") for s in
                      ONEBIT_ADAM_NAMES + ONEBIT_LAMB_NAMES
                      + ZERO_ONE_ADAM_NAMES)


@dataclass
class OptimizerConfig(ConfigBase):
    type: str = "adamw"  # adamw | adam | sgd | lion | lamb | adagrad
    params: dict = field(default_factory=dict)

    _SUPPORTED: ClassVar[set] = {
        "adam", "adamw", "sgd", "lion", "lamb", "adagrad", "muon",
        *ONEBIT_ADAM_NAMES, *ONEBIT_LAMB_NAMES, *ZERO_ONE_ADAM_NAMES,
    }

    def _validate(self, path: str = "") -> None:
        if self.type.lower() not in self._SUPPORTED:
            raise ConfigError(f"{path}type: unsupported optimizer '{self.type}' (choose from {sorted(self._SUPPORTED)})")


@dataclass
class SchedulerConfig(ConfigBase):
    """Reference LR schedules: WarmupLR / WarmupDecayLR / WarmupCosineLR / OneCycle / LRRangeTest
    (``runtime/lr_schedules.py``)."""

    type: str = "WarmupLR"
    params: dict = field(default_factory=dict)


@dataclass
class FP16Config(ConfigBase):
    """fp16 + dynamic loss scaling (reference: ``runtime/fp16/loss_scaler.py:187``)."""

    enabled: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0

    _auto_fields: ClassVar[set] = {"enabled"}


@dataclass
class BF16Config(ConfigBase):
    # None = "auto": on unless fp16 is explicitly enabled (TPU-first default).
    enabled: Optional[bool] = None
    # Keep a float32 master copy of params and do the optimizer step in fp32
    # (reference: runtime/bf16_optimizer.py:37).
    master_weights: bool = True

    _auto_fields: ClassVar[set] = {"enabled"}


@dataclass
class OffloadConfig(ConfigBase):
    """Offload tier for optimizer state / params (reference: zero offload + swap_tensor)."""

    device: str = "none"  # none | cpu | nvme
    nvme_path: str = "/tmp/dstpu_nvme"
    pin_memory: bool = True
    buffer_count: int = 4
    # SuperOffload (reference offload_config.py:96 + superoffload_stage3.py:27).
    # device=cpu: keep the hottest sub-groups' optimizer state HBM-resident
    # (hbm_resident_fraction of groups) instead of streaming them; device=nvme:
    # dispatch group updates speculatively — the overflow guard rides along as
    # a device predicate, replacing the reference's CPU-Adam rollback.
    super_offload: bool = False
    hbm_resident_fraction: float = 0.25
    # reference knob: CPU cores for the CPU-Adam worker pool. Accepted for
    # config compatibility; the update math runs on-device here.
    cpuadam_cores_perc: float = 0.8

    def _validate(self, path: str = "") -> None:
        if self.device not in ("none", "cpu", "nvme"):
            raise ConfigError(f"{path}device: must be none|cpu|nvme, got {self.device!r}")
        if not (0.0 <= self.hbm_resident_fraction <= 1.0):
            raise ConfigError(
                f"{path}hbm_resident_fraction: must be in [0, 1], got "
                f"{self.hbm_resident_fraction}")

    @classmethod
    def from_dict(cls, data, path: str = ""):
        data = dict(data or {})
        if "zenflow_topk_ratio" in data:
            from deepspeed_tpu.utils.logging import logger

            logger.warning(
                f"Config field '{path}zenflow_topk_ratio' moved: set "
                "'zero_optimization.zenflow: {enabled: true, topk_ratio: ...}'."
            )
            data.pop("zenflow_topk_ratio")
        return super().from_dict(data, path=path)


@dataclass
class ZenFlowConfig(ConfigBase):
    """ZenFlow importance-aware split update (reference
    ``runtime/zenflow/zenflow_config.py``): hot top-k blocks update on device
    every step; the cold remainder accumulates and applies in one deferred
    windowed update per ``update_interval`` steps. Requires
    ``offload_optimizer.device: cpu``. See ``runtime/zenflow.py``."""

    enabled: bool = False
    topk_ratio: float = 0.05
    update_interval: int = 4
    select_strategy: str = "step"  # step | auto | epoch (all step-based here)
    select_interval: int = 100
    full_warm_up_rounds: int = 1
    # reference knob: run the cold update on a worker process. Accepted for
    # config compatibility; JAX async dispatch already overlaps the deferred
    # cold program with subsequent steps.
    overlap_step: bool = True
    # hot-selection granularity in elements (lane-aligned gathers)
    block: int = 256

    def _validate(self, path: str = "") -> None:
        if not (0.0 < self.topk_ratio <= 1.0):
            raise ConfigError(f"{path}topk_ratio: must be in (0, 1], got {self.topk_ratio}")
        if self.update_interval < 1:
            raise ConfigError(f"{path}update_interval: must be >= 1")
        if self.select_interval < 1:
            raise ConfigError(f"{path}select_interval: must be >= 1")
        if self.full_warm_up_rounds < 1:
            raise ConfigError(
                f"{path}full_warm_up_rounds: must be >= 1 (the first selection "
                "needs one dense step's gradients)")
        if self.select_strategy not in ("step", "auto", "epoch"):
            raise ConfigError(f"{path}select_strategy: must be step|auto|epoch")
        if self.block < 1:
            raise ConfigError(f"{path}block: must be >= 1")

    @classmethod
    def from_dict(cls, data, path: str = ""):
        data = dict(data or {})
        # Reference semantics (zero/config.py:172 Optional[ZenFlowConfig]):
        # the PRESENCE of a zenflow block under zero_optimization enables it
        # (including an empty all-defaults block). With enabled left unset,
        # presence therefore means "on" — otherwise a ported reference config
        # trains dense with no warning. (This classmethod only runs when the
        # user actually wrote a zenflow key; the default_factory path never
        # comes through here.)
        if "enabled" not in data:
            data["enabled"] = True
        # Reference ZenFlowConfig defaults these to "auto"; configure_zenflow
        # resolves them to step-based values. Accept the spelling and map it
        # to this build's step-based defaults.
        if is_auto(data.get("select_interval")):
            data["select_interval"] = cls.select_interval
        if is_auto(data.get("update_interval")):
            data["update_interval"] = cls.update_interval
        return super().from_dict(data, path=path)


@dataclass
class GradOverlapConfig(ConfigBase):
    """Overlap-first data-parallel backward (parallel/grad_overlap.py).

    Partitions the grad tree into size-targeted buckets and reduces each as
    an async ppermute ring inside a shard_map manual region, so later layers'
    backward compute fills earlier buckets' transfer windows (docs/
    TP_OVERLAP.md, "grad-sync overlap"). Off by default; when off the engine
    builds exactly the fused baseline program.
    """

    enabled: bool = False
    # target bucket payload in bytes (fp32 accumulation); rounded DOWN to a
    # power of two at planning time
    bucket_bytes: int = 4 * 2**20
    # ZeRO-1-without-fsdp-axis: each data rank updates only its reduce-
    # scattered grad shard, then all-gathers updated params — optimizer FLOPs
    # and state-touch bytes drop by 1/dp
    sharded_update: bool = True
    # exactness kill switch: route the step through the fused baseline
    # program (bit-identical by construction) while keeping the config
    # surface — for A/B-ing the documented fp-reorder of the ring reduction
    exact: bool = False

    def _validate(self, path: str = "") -> None:
        if self.bucket_bytes < 256:
            raise ConfigError(
                f"{path}bucket_bytes: must be >= 256, got {self.bucket_bytes}")


@dataclass
class ZeroConfig(ConfigBase):
    """ZeRO stages as sharding policy (reference: ``runtime/zero/config.py:401``).

    On TPU the stages are declarative sharding choices over the ``fsdp`` mesh axis:
      0: replicate params/grads/opt-state (pure DP, psum grads)
      1: shard optimizer state
      2: shard optimizer state + gradients (reduce_scatter at the GAS boundary)
      3: shard parameters too (allgather-on-use, per scanned layer block)
    """

    stage: int = 0
    offload_optimizer: OffloadConfig = field(default_factory=OffloadConfig)
    offload_param: OffloadConfig = field(default_factory=OffloadConfig)
    # stage-3 style knobs
    persistence_threshold: int = 0  # params smaller than this stay replicated
    # offload windowing: elements per optimizer sub-group (reference stage3
    # sub_group_size); one group's state is in HBM at a time
    sub_group_size: int = 100_000_000
    # ZeRO++ qgZ: quantized gradient reduction with error feedback
    # (comm/quantized_collectives.py; requires a pure data-parallel mesh)
    quantized_gradients: bool = False
    # wire width for the quantized reduction: 8 (qgZ int8), 4 (nibble-packed)
    # or 1 (sign+scale — the 1-bit Adam/LAMB compressed wire, reference
    # runtime/comm/nccl.py compressed_allreduce). With a 1-bit-family
    # optimizer the engine runs a DENSE wire during the optimizer's warmup
    # (freeze_step) and switches to this width after, matching the reference
    # two-phase protocol.
    quantized_gradients_bits: int = 8
    # ZeRO++ qwZ: int8 blockwise-quantized weight all-gather on the stage-3
    # path (parallel/qwz.py; reference partition_parameters.py:1446 quantized
    # all_gather_coalesced). Halves the dominant stage-3 collective.
    quantized_weights: bool = False
    qwz_block: int = 128
    # ZenFlow split update over the offloaded tier (runtime/zenflow.py)
    zenflow: ZenFlowConfig = field(default_factory=ZenFlowConfig)
    # ZeRO++ hpZ: optimizer+gradient state shards over the FULL world
    # (data x fsdp) while live stage-3 params shard over fsdp only, so param
    # gathers ride the fast intra-group axis (reference
    # partition_parameters.py:1806 secondary partition). Map the reference
    # layout onto the mesh: fsdp = intra-group (ICI), data = across groups.
    hierarchical_partitioning: bool = False
    # MiCS (reference runtime/zero/mics.py:63 MiCS_Init / :361
    # MiCS_Optimizer): bound the ZeRO-3 shard degree to a GROUP of
    # ``mics_shard_size`` devices (< world); params/grads/optimizer state
    # partition within the group and replicate across world/k groups, with
    # cross-group gradient allreduce keeping replicas in sync. On TPU this
    # IS a mesh factorization — fsdp=k (intra-group, rides ICI), data=world/k
    # (replica groups; grads psum there) — which ``initialize`` derives from
    # this knob; the reference's hierarchical cross-group allgather
    # (mics_hierarchical_params_gather) is what XLA's topology-aware
    # collective lowering does by construction. 0 = off.
    mics_shard_size: int = 0
    # Overlap-first DP backward: bucketed async grad rings + optional
    # cross-replica sharded weight update (parallel/grad_overlap.py).
    grad_overlap: GradOverlapConfig = field(default_factory=GradOverlapConfig)

    def _validate(self, path: str = "") -> None:
        if self.stage not in (0, 1, 2, 3):
            raise ConfigError(f"{path}stage: must be 0..3, got {self.stage}")
        if self.quantized_weights and self.stage != 3:
            raise ConfigError(
                f"{path}quantized_weights: qwZ quantizes the stage-3 weight "
                f"all-gather; it requires stage 3 (got stage {self.stage})")
        if self.qwz_block < 1:
            raise ConfigError(f"{path}qwz_block: must be >= 1")
        if self.quantized_gradients_bits not in (1, 4, 8):
            raise ConfigError(
                f"{path}quantized_gradients_bits: must be 1, 4 or 8, got "
                f"{self.quantized_gradients_bits}")
        if self.mics_shard_size < 0:
            raise ConfigError(
                f"{path}mics_shard_size: must be >= 0, got "
                f"{self.mics_shard_size}")
        if self.mics_shard_size > 0 and self.stage != 3:
            raise ConfigError(
                f"{path}mics_shard_size: MiCS bounds the stage-3 shard "
                f"degree; it requires stage 3 (got stage {self.stage})")
        if self.mics_shard_size > 0 and self.hierarchical_partitioning:
            raise ConfigError(
                f"{path}mics_shard_size: MiCS (opt state within the group) "
                "and hierarchical_partitioning (hpZ, opt state over the full "
                "world) prescribe conflicting master layouts; pick one")

    @classmethod
    def from_dict(cls, data, path: str = ""):
        data = dict(data or {})
        # Reference hpZ knob -> hierarchical partitioning (the group size is
        # implied by the mesh's fsdp axis here, not a free integer).
        if "zero_hpz_partition_size" in data:
            from deepspeed_tpu.utils.logging import logger

            hpz = data.pop("zero_hpz_partition_size")
            try:
                hpz_on = int(hpz) > 0
            except (TypeError, ValueError):
                hpz_on = bool(hpz)  # "auto" etc.: treat truthy as enabled
            if hpz_on and "hierarchical_partitioning" not in data:
                logger.warning(
                    f"Config field '{path}zero_hpz_partition_size' maps to "
                    "'hierarchical_partitioning: true' in this build (the "
                    "secondary-partition group is the mesh's fsdp axis)."
                )
                data["hierarchical_partitioning"] = True
        # Reference MiCS gather knob: hierarchical cross-group allgather is
        # what XLA's topology-aware collective lowering already does; accept
        # the key so ported configs load, nothing to configure.
        data.pop("mics_hierarchical_params_gather", None)
        # Reference spellings for qwZ/qgZ (`zero_quantized_weights`,
        # `zero_quantized_gradients`).
        for ref_key, key in (("zero_quantized_weights", "quantized_weights"),
                             ("zero_quantized_gradients", "quantized_gradients")):
            if ref_key in data and key not in data:
                data[key] = data.pop(ref_key)
            else:
                data.pop(ref_key, None)
        # Legacy `cpu_offload` was a bool; translate to an offload tier, not a rename.
        if "cpu_offload" in data:
            from deepspeed_tpu.utils.logging import logger

            logger.warning(
                f"Config field '{path}cpu_offload' is deprecated; use "
                f"'{path}offload_optimizer: {{device: cpu}}'."
            )
            legacy = data.pop("cpu_offload")
            if "offload_optimizer" not in data:
                if isinstance(legacy, bool):
                    data["offload_optimizer"] = {"device": "cpu" if legacy else "none"}
                else:
                    data["offload_optimizer"] = legacy
        return super().from_dict(data, path=path)


@dataclass
class MeshConfig(ConfigBase):
    """Named device-mesh axes. 'auto' (-1) sizes one axis from the device count.

    Axis vocabulary (fixed): data, fsdp, tensor, sequence, expert, pipeline.
    The DP world used in the batch triangle is data*fsdp (both consume batch).
    """

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    expert: int = 1
    pipeline: int = 1
    # axes listed here are laid out over DCN (multi-slice) rather than ICI
    dcn_axes: list = field(default_factory=list)

    # set by Config.from_dict when the user wrote a mesh section; a default
    # (implicit) mesh must never tear down a pre-built topology
    @property
    def is_explicit(self) -> bool:
        return self.__dict__.get("_explicit_instance", False) or self != MeshConfig()

    def mark_explicit(self) -> None:
        self.__dict__["_explicit_instance"] = True

    def _validate(self, path: str = "") -> None:
        for name in ("fsdp", "tensor", "sequence", "expert", "pipeline"):
            if getattr(self, name) < 1:
                raise ConfigError(f"{path}{name}: must be >= 1")
        if self.data < -1 or self.data == 0:
            raise ConfigError(f"{path}data: must be -1 (auto) or >= 1")


@dataclass
class ActivationCheckpointingConfig(ConfigBase):
    """Rematerialization policy (reference: ``runtime/activation_checkpointing/``).

    On TPU this maps to ``jax.checkpoint`` policies on the scanned layer stack.
    """

    enabled: bool = False
    policy: str = "full"  # full | dots_saveable | nothing_saveable | offload_dots

    def _validate(self, path: str = "") -> None:
        if self.policy not in ("full", "dots_saveable", "nothing_saveable", "offload_dots"):
            raise ConfigError(f"{path}policy: unknown remat policy {self.policy!r}")


@dataclass
class MoEConfig(ConfigBase):
    enabled: bool = False
    num_experts: int = 1
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    drop_tokens: bool = True
    aux_loss_coef: float = 0.01
    router_jitter: float = 0.0


@dataclass
class SequenceParallelConfig(ConfigBase):
    """Ulysses / ring attention (reference: ``deepspeed/sequence/``)."""

    mode: str = "ulysses"  # ulysses | ring
    # AutoSP (reference sequence/auto_sp.py): patch the standard attention
    # entry point (jax.nn.dot_product_attention) during tracing so user
    # models not written against ShardCtx get sequence parallelism
    # automatically (parallel/auto_sp.py)
    auto: bool = False
    tiled_mlp: bool = False
    tiled_logits: bool = False
    tile_size: int = 1024  # sequence tokens per ALST compute tile
    # FPDT chunked attention with host-offloaded residuals (reference
    # sequence/fpdt_layer.py): 0 = off; otherwise chunks (>= 2) over the
    # attention-visible sequence — under mode=ulysses that is the FULL
    # post-all-to-all sequence, not the per-rank shard, so size it against
    # the global context length.
    fpdt_chunks: int = 0
    fpdt_offload: bool = True

    def _validate(self, path: str = "") -> None:
        if self.mode not in ("ulysses", "ring"):
            raise ConfigError(f"{path}mode: must be ulysses|ring")
        if self.tile_size <= 0:
            raise ConfigError(f"{path}tile_size: must be positive")
        if self.fpdt_chunks < 0 or self.fpdt_chunks == 1:
            raise ConfigError(
                f"{path}fpdt_chunks: must be 0 (off) or >= 2, got "
                f"{self.fpdt_chunks}")
        if self.fpdt_chunks and self.mode == "ring":
            raise ConfigError(
                f"{path}fpdt_chunks: FPDT composes with mode=ulysses only "
                "(ring already chunks the KV loop across the ring)")


@dataclass
class PipelineConfig(ConfigBase):
    """Pipeline schedule config (reference: ``runtime/pipe/``).

    Two distinct runtimes share this block:

    - the in-jit SPMD pipelines (``parallel/pipeline.py`` /
      ``parallel/pipeline_1f1b.py``), enabled by a ``pipeline`` axis in the
      mesh — one XLA program, ppermute between stages;
    - the MPMD staged runtime (``runtime/pipe/``), enabled by ``stages > 1``
      — S separately-dispatched stage programs with activation send/recv
      over a transport, per-stage params + optimizer shards, crash-safe
      per-stage checkpoints.
    """

    num_microbatches: int = 0  # 0 => use gradient_accumulation_steps
    partition_method: str = "uniform"  # uniform | parameters
    activation_checkpoint_interval: int = 0
    # gpipe: collective forward pipeline + autodiff backward (O(M) stashes)
    # 1f1b:  interleaved schedule, P-deep stash, composes with fsdp
    #        (reference schedule.py:189 TrainSchedule)
    schedule: str = "gpipe"
    # MPMD staged runtime (runtime/pipe/): number of stage programs.
    # 0/1 = off (single-program engine); >1 routes deepspeed_tpu.initialize()
    # to the staged PipeEngine.
    stages: int = 0
    # virtual chunks per stage (interleaved 1F1B when > 1): stage s owns
    # chunks s, s+S, s+2S, ... of the layer range
    interleave: int = 1
    # activation/grad transport between stage programs: inproc = in-process
    # queues (one thread per stage, CPU-testable); device = reserved for
    # jax.device_put / collective-permute transports
    transport: str = "inproc"

    def _validate(self, path: str = "") -> None:
        if self.schedule not in ("gpipe", "1f1b"):
            raise ConfigError(f"{path}schedule: must be gpipe|1f1b")
        if self.stages < 0:
            raise ConfigError(f"{path}stages: must be >= 0, got {self.stages}")
        if self.interleave < 1:
            raise ConfigError(
                f"{path}interleave: must be >= 1, got {self.interleave}")
        if self.interleave > 1 and self.schedule != "1f1b":
            raise ConfigError(
                f"{path}interleave: interleaved chunks require "
                f"schedule='1f1b' (got {self.schedule!r})")
        if self.transport not in ("inproc", "device"):
            raise ConfigError(f"{path}transport: must be inproc|device")


@dataclass
class TensorParallelConfig(ConfigBase):
    """AutoTP equivalent (reference: ``module_inject/auto_tp.py``): declarative
    sharding-rule overrides applied to model params/activations."""

    enabled: bool = False
    rules: dict = field(default_factory=dict)  # param-name regex -> axis name


@dataclass
class MonitorConfig(ConfigBase):
    enabled: bool = False
    tensorboard: dict = field(default_factory=dict)  # {enabled, output_path, job_name}
    csv_monitor: dict = field(default_factory=dict)
    wandb: dict = field(default_factory=dict)
    comet: dict = field(default_factory=dict)  # {enabled, project, workspace, ...}


@dataclass
class CommsLoggerConfig(ConfigBase):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    prof_ops: list = field(default_factory=list)
    debug: bool = False
    # straggler analysis: warn when a collective's max/min latency across
    # processes exceeds this ratio
    straggler_warn_ratio: float = 2.0

    def _validate(self, path: str = "") -> None:
        if self.straggler_warn_ratio < 1.0:
            raise ConfigError(
                f"{path}straggler_warn_ratio: must be >= 1.0, got "
                f"{self.straggler_warn_ratio}")


@dataclass
class TelemetryConfig(ConfigBase):
    """Structured telemetry bus (``deepspeed_tpu/telemetry/``, see
    docs/OBSERVABILITY.md): metrics registry + span/event log with pluggable
    exporters. Disabled, every emit path is a single flag check."""

    enabled: bool = False
    # JSONL event-log sink (step spans, request spans, HBM watermarks, final
    # registry snapshot); None/"" disables the file sink
    jsonl_path: Optional[str] = None
    # {enabled, host, port}: Prometheus text exposition on a stdlib HTTP
    # server (port 0 = ephemeral)
    prometheus: dict = field(default_factory=dict)
    # sample accelerator.memory_stats() into hbm_* gauges every step
    hbm_watermarks: bool = True
    # mirror scalar telemetry events into the monitor writers (TensorBoard/
    # CSV/W&B/Comet become one sink among the exporters)
    monitor_sink: bool = False
    # flush the file sink every N emitted records
    flush_interval_events: int = 100
    # {enabled, interconnect_gbps, peak_tflops, use_cost_analysis,
    # profile_interval_steps, profile_dir, profile_keep}: training step
    # anatomy (telemetry/stepscope.py) — per-phase decomposition spans,
    # MFU attribution, overlap + goodput gauges. Enabling it settles every
    # step (microscope mode) and implies the trace ring on.
    # profile_interval_steps > 0 additionally opens a device-timeline
    # capture window (telemetry/devprof.py) every N steps: measured overlap
    # / wire-time / idle metrics, device ops merged into the trace ring;
    # capture dirs rotate under profile_dir (default runs/devprof, keep
    # profile_keep=4 most recent). Capture-bearing steps are excluded from
    # throughput and anatomy averages like recompile-bearing steps.
    stepscope: dict = field(default_factory=dict)
    # {enabled, census_interval_steps, drift_threshold, drift_consecutive,
    # report_dir} or bare true: HBM memory ledger (telemetry/memledger.py) —
    # per-owner byte attribution, jax.live_arrays() leak census, OOM crash
    # reports, headroom-driven admission inputs
    memledger: dict = field(default_factory=dict)

    def _validate(self, path: str = "") -> None:
        if self.flush_interval_events < 1:
            raise ConfigError(
                f"{path}flush_interval_events: must be >= 1, got "
                f"{self.flush_interval_events}")


@dataclass
class FlopsProfilerConfig(ConfigBase):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


@dataclass
class CheckpointConfig(ConfigBase):
    use_node_local_storage: bool = False
    tag_validation: str = "warn"  # ignore | warn | fail
    load_universal: bool = False
    async_save: bool = False
    keep_n_latest: int = 0  # 0 = keep all

    def _validate(self, path: str = "") -> None:
        if self.tag_validation.lower() not in ("ignore", "warn", "fail"):
            raise ConfigError(f"{path}tag_validation: must be ignore|warn|fail")


@dataclass
class ProgressiveLayerDropConfig(ConfigBase):
    """PLD schedule (reference ``runtime/progressive_layer_drop.py`` +
    ds_config key ``progressive_layer_drop``)."""

    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001

    def _validate(self, path: str = "") -> None:
        if not (0.0 < self.theta <= 1.0):
            raise ConfigError(f"{path}theta: must be in (0, 1], got {self.theta}")


@dataclass
class EigenvalueConfig(ConfigBase):
    """Curvature probe (reference ``runtime/eigenvalue.py`` + engine
    ``eigenvalue`` config block): blockwise top-Hessian-eigenvalue power
    iteration, used to modulate quantization/compression schedules."""

    enabled: bool = False
    verbose: bool = False
    max_iter: int = 100
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = 1
    layer_name: str = "layers"
    layer_num: int = 0


@dataclass
class RandomLTDConfig(ConfigBase):
    """Random layerwise token dropping (reference ``runtime/data_pipeline/
    data_routing/basic_layer.py`` + ``csrc/random_ltd``): each decoder layer
    processes a random subset of tokens, ramping from ``start_keep_ratio``
    of the sequence back to 1.0 over ``total_steps`` (the reference's
    seq-length schedule). Kept counts are bucketed to ``bucket`` tokens —
    each bucket value is one compiled program."""

    enabled: bool = False
    start_keep_ratio: float = 0.5
    total_steps: int = 1000
    bucket: int = 64

    def _validate(self, path: str = "") -> None:
        if not 0.0 < self.start_keep_ratio <= 1.0:
            raise ConfigError(
                f"{path}start_keep_ratio: must be in (0, 1], got "
                f"{self.start_keep_ratio}")
        if self.total_steps < 1:
            raise ConfigError(f"{path}total_steps: must be >= 1")
        if self.bucket < 1:
            raise ConfigError(f"{path}bucket: must be >= 1")


@dataclass
class DataEfficiencyConfig(ConfigBase):
    enabled: bool = False
    curriculum_learning: dict = field(default_factory=dict)
    random_ltd: RandomLTDConfig = field(default_factory=RandomLTDConfig)


@dataclass
class TracingConfig(ConfigBase):
    """jax.profiler capture window (reference: nvtx instrumentation +
    ``utils/nvtx.py``; traces view in TensorBoard/XProf)."""

    enabled: bool = False
    trace_dir: str = "/tmp/dstpu_trace"
    start_step: int = 2   # skip compile steps
    num_steps: int = 3

    def _validate(self, path: str = "") -> None:
        if self.num_steps < 1:
            raise ConfigError(f"{path}num_steps: must be >= 1")


@dataclass
class DebugConfig(ConfigBase):
    """Semantic sanity checks + NaN trapping (reference §5.2:
    ``enable_sanity_checks``, CheckOverflow, debug-nans style checks)."""

    # trap the first NaN-producing op with a traceback (jax debug_nans)
    nans: bool = False
    # host-side batch validation each step (shapes, dtypes, divisibility)
    sanity_checks: bool = False


@dataclass
class SentinelConfig(ConfigBase):
    """Self-healing training (``runtime/sentinel.py``, see
    docs/FAULT_TOLERANCE.md "Training: self-healing"): a divergence verdict
    fused into the jitted train step (loss vs. rolling EMA + k·σ, grad-norm
    vs. rolling quantile, consecutive-skip streak), a quarantine →
    rollback-and-replay → reduce-lr/halt policy ladder, a dispatch watchdog,
    and a per-worker heartbeat file the elastic agent polls. Off by default:
    the disabled engine traces the exact same step program as before."""

    enabled: bool = False
    # ---- verdict thresholds (device-side, computed in the fused step)
    warmup_steps: int = 20          # accepted steps before the loss gate arms
    loss_ema_beta: float = 0.9      # EMA decay for loss mean/variance
    loss_sigma_k: float = 4.0       # anomalous when loss > ema + k*sigma
    loss_rel_floor: float = 0.05    # sigma floor as a fraction of |ema|
    grad_window: int = 32           # rolling grad-norm ring length
    grad_quantile: float = 0.95     # ring quantile the gate compares against
    grad_quantile_mult: float = 8.0 # anomalous when gnorm > mult * quantile
    # streak escalation threshold; matches precision.update_loss_scale
    # semantics exactly (streak resets to 0 on any accepted step, the way
    # good_steps resets on a single overflow)
    max_consecutive_skips: int = 5
    # ---- policy ladder (host-side, acts on settled verdicts)
    window_steps: int = 50          # strikes within this window escalate
    rollback: bool = True           # rung 2: restore + replay (else skip rung)
    checkpoint_dir: Optional[str] = None  # ladder restores from this save_dir
    on_third_strike: str = "halt"   # halt | reduce-lr
    lr_backoff: float = 0.5         # reduce-lr multiplier per backoff
    max_wedges: int = 3             # wedge timeouts in the window before halt
    report_dir: str = "sentinel_reports"  # forensics JSON directory
    state_dir: Optional[str] = None # quarantine persistence + heartbeat files
    # ---- liveness
    dispatch_timeout_s: float = 0.0 # >0: per-step settle under this deadline
    heartbeat_interval_s: float = 1.0  # min seconds between heartbeat writes

    def _validate(self, path: str = "") -> None:
        if self.on_third_strike not in ("halt", "reduce-lr"):
            raise ConfigError(
                f"{path}on_third_strike: must be halt|reduce-lr, got "
                f"{self.on_third_strike!r}")
        if not (0.0 < self.loss_ema_beta < 1.0):
            raise ConfigError(
                f"{path}loss_ema_beta: must be in (0, 1), got "
                f"{self.loss_ema_beta}")
        if self.grad_window < 4:
            raise ConfigError(
                f"{path}grad_window: must be >= 4, got {self.grad_window}")
        if not (0.0 < self.grad_quantile < 1.0):
            raise ConfigError(
                f"{path}grad_quantile: must be in (0, 1), got "
                f"{self.grad_quantile}")
        if not (0.0 < self.lr_backoff < 1.0):
            raise ConfigError(
                f"{path}lr_backoff: must be in (0, 1), got {self.lr_backoff}")
        if self.window_steps < 1:
            raise ConfigError(f"{path}window_steps: must be >= 1")


@dataclass
class AutotuningConfig(ConfigBase):
    """Tuned-profile loading at startup (reference ds_config
    ``autotuning`` block; docs/AUTOTUNING.md). When enabled,
    ``deepspeed_tpu.initialize`` looks up the persisted profile for
    (model fingerprint, topology, ``workload``) under ``profile_dir`` and
    fills knobs the config file did not write — explicit config values
    always win over tuned ones."""

    enabled: bool = False
    profile_dir: str = os.path.join("runs", "autotune")
    # workload class the profile was tuned on (one model can carry distinct
    # profiles for e.g. "default" vs "long-context" training recipes)
    workload: str = "default"


@dataclass
class Config(ConfigBase):
    """Top-level framework config (reference: ``DeepSpeedConfig``)."""

    train_batch_size: Union[int, str, None] = None
    train_micro_batch_size_per_device: Union[int, str, None] = None
    gradient_accumulation_steps: Union[int, str, None] = None
    steps_per_print: int = 10
    gradient_clipping: float = 0.0
    seed: int = 1234
    communication_data_type: Optional[str] = None  # e.g. "fp32" grad-reduce dtype
    prescale_gradients: bool = False
    sequence_length: Union[int, None] = None  # used by SP sharding + MFU accounting

    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    scheduler: Optional[SchedulerConfig] = None
    fp16: FP16Config = field(default_factory=FP16Config)
    bf16: BF16Config = field(default_factory=BF16Config)
    zero_optimization: ZeroConfig = field(default_factory=ZeroConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    activation_checkpointing: ActivationCheckpointingConfig = field(
        default_factory=ActivationCheckpointingConfig
    )
    moe: MoEConfig = field(default_factory=MoEConfig)
    sequence_parallel: SequenceParallelConfig = field(default_factory=SequenceParallelConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    tensor_parallel: TensorParallelConfig = field(default_factory=TensorParallelConfig)
    monitor: MonitorConfig = field(default_factory=MonitorConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    comms_logger: CommsLoggerConfig = field(default_factory=CommsLoggerConfig)
    flops_profiler: FlopsProfilerConfig = field(default_factory=FlopsProfilerConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    data_efficiency: DataEfficiencyConfig = field(default_factory=DataEfficiencyConfig)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    debug: DebugConfig = field(default_factory=DebugConfig)
    progressive_layer_drop: ProgressiveLayerDropConfig = field(
        default_factory=ProgressiveLayerDropConfig)
    eigenvalue: EigenvalueConfig = field(default_factory=EigenvalueConfig)
    sentinel: SentinelConfig = field(default_factory=SentinelConfig)
    autotuning: AutotuningConfig = field(default_factory=AutotuningConfig)
    # reference ds_config["compression_training"] shape, parsed by
    # deepspeed_tpu.compression.CompressionConfig (QAT + pruning schedules)
    compression_training: dict = field(default_factory=dict)

    _auto_fields: ClassVar[set] = {
        "train_batch_size",
        "train_micro_batch_size_per_device",
        "gradient_accumulation_steps",
    }
    _deprecated: ClassVar[dict] = {
        "train_micro_batch_size_per_gpu": "train_micro_batch_size_per_device",
        "zero": "zero_optimization",
    }

    @classmethod
    def from_dict(cls, data, path: str = ""):
        data = dict(data or {})
        # the reference takes `zenflow` at the top level of ds_config
        # (engine.py:391-396 glue); it lives under zero_optimization here
        if "zenflow" in data:
            zf = data.pop("zenflow")
            if isinstance(zf, dict):
                # presence of the block means "on" in the reference
                zf = {"enabled": True, **zf}
            # hoist into whichever spelling the user wrote — creating
            # 'zero_optimization' next to a legacy 'zero' block would make the
            # deprecation migration discard the user's 'zero' contents
            zo_key = "zero" if ("zero" in data
                                and "zero_optimization" not in data) else "zero_optimization"
            zo = dict(data.get(zo_key) or {})
            zo.setdefault("zenflow", zf)
            data[zo_key] = zo
        mesh_written = "mesh" in data
        obj = super().from_dict(data, path=path)
        if mesh_written:
            obj.mesh.mark_explicit()
        return obj

    # ------------------------------------------------------------------ batch triangle
    def resolve_batch_sizes(self, dp_world_size: int) -> None:
        """Resolve train_batch = micro_batch * GAS * dp_world (reference: runtime/config.py).

        Any one of the three may be omitted/'auto'; the others determine it.
        """
        tb = None if is_auto(self.train_batch_size) else self.train_batch_size
        mb = None if is_auto(self.train_micro_batch_size_per_device) else self.train_micro_batch_size_per_device
        gas = None if is_auto(self.gradient_accumulation_steps) else self.gradient_accumulation_steps

        if tb is not None and mb is not None and gas is None:
            gas, rem = divmod(tb, mb * dp_world_size)
            if rem:
                raise ConfigError(
                    f"train_batch_size {tb} not divisible by micro_batch {mb} * dp_world {dp_world_size}"
                )
        elif tb is not None and gas is not None and mb is None:
            mb, rem = divmod(tb, gas * dp_world_size)
            if rem:
                raise ConfigError(
                    f"train_batch_size {tb} not divisible by GAS {gas} * dp_world {dp_world_size}"
                )
        elif mb is not None and tb is None:
            gas = gas if gas is not None else 1
            tb = mb * gas * dp_world_size
        elif tb is not None and mb is None and gas is None:
            gas = 1
            mb, rem = divmod(tb, dp_world_size)
            if rem:
                raise ConfigError(
                    f"train_batch_size {tb} not divisible by dp_world {dp_world_size}"
                )
        elif tb is not None and mb is not None and gas is not None:
            if tb != mb * gas * dp_world_size:
                raise ConfigError(
                    f"Inconsistent batch triangle: train_batch_size {tb} != "
                    f"micro {mb} * GAS {gas} * dp_world {dp_world_size}"
                )
        elif tb is None and mb is None:
            raise ConfigError(
                "Provide at least train_micro_batch_size_per_device or train_batch_size"
            )
        if gas is None:
            gas = 1
        if mb is None:
            raise ConfigError("Could not resolve micro batch size")
        self.train_batch_size = int(tb)
        self.train_micro_batch_size_per_device = int(mb)
        self.gradient_accumulation_steps = int(gas)

    def _validate(self, path: str = "") -> None:
        # reference: engine.py:1386 _assert_valid_mixed_precision_config.
        # bf16 defaults to auto (None): on unless fp16 was explicitly enabled.
        if self.fp16.enabled is True and self.bf16.enabled is True:
            raise ConfigError("fp16 and bf16 cannot both be enabled")
        if self.bf16.enabled is None:
            self.bf16.enabled = not (self.fp16.enabled is True)

    @property
    def precision_name(self) -> str:
        if self.fp16.enabled is True:
            return "fp16"
        if self.bf16.enabled is True:
            return "bf16"
        return "fp32"

    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        if self.fp16.enabled is True:
            return jnp.float16
        if self.bf16.enabled in (True, None):
            return jnp.bfloat16
        return jnp.float32


def load_config(config: Union[str, dict, Config, None]) -> Config:
    """Accept a path to JSON, a dict, or an already-built Config."""
    if config is None:
        return Config()
    if isinstance(config, Config):
        return config
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    return Config.from_dict(config)
