"""Config infrastructure: dataclass tree with "auto" values and deprecation aliases.

Role parity with the reference's ``runtime/config_utils.py`` (``DeepSpeedConfigModel``):
- nested dict/JSON -> typed config objects,
- ``"auto"`` placeholder values resolved later (by the engine or autotuner),
- deprecated field names migrated with a warning,
- unknown keys rejected with a helpful error.

Implemented on plain dataclasses (no pydantic dependency) so the framework has a
single, hermetic config stack.
"""

from __future__ import annotations

import copy
import dataclasses
import json
import typing
from dataclasses import dataclass, field, fields
from typing import Any

from deepspeed_tpu.utils.logging import logger

AUTO = "auto"


class ConfigError(ValueError):
    pass


def is_auto(value: Any) -> bool:
    return isinstance(value, str) and value == AUTO


@dataclass
class ConfigBase:
    """Base for all config nodes.

    Subclasses declare dataclass fields; class attributes:
      ``_deprecated``: mapping old_name -> new_name (value forwarded, warning logged)
      ``_auto_fields``: field names allowed to hold the literal "auto"
    """

    _deprecated: typing.ClassVar[dict[str, str]] = {}
    _auto_fields: typing.ClassVar[set[str]] = set()

    @classmethod
    def from_dict(cls, data: dict[str, Any] | None, path: str = "") -> "ConfigBase":
        data = copy.deepcopy(data) if data else {}
        if not isinstance(data, dict):
            raise ConfigError(f"{path or cls.__name__}: expected a dict, got {type(data).__name__}")

        # Deprecation migration (reference: config_utils.py:23-51).
        for old, new in cls._deprecated.items():
            if old in data:
                logger.warning(
                    f"Config field '{path}{old}' is deprecated; use '{path}{new}' instead."
                )
                if new not in data:
                    data[new] = data.pop(old)
                else:
                    data.pop(old)

        known = {f.name: f for f in fields(cls) if not f.name.startswith("_")}
        unknown = [k for k in data if k not in known]
        if unknown:
            raise ConfigError(
                f"{path or cls.__name__}: unknown config key(s) {unknown}; valid keys: {sorted(known)}"
            )

        kwargs: dict[str, Any] = {}
        hints = typing.get_type_hints(cls)
        for name, f in known.items():
            if name not in data:
                continue
            value = data[name]
            if is_auto(value):
                if name not in cls._auto_fields:
                    raise ConfigError(f"{path}{name}: 'auto' is not supported for this field")
                kwargs[name] = AUTO
                continue
            ftype = hints.get(name, f.type)
            kwargs[name] = _coerce(value, ftype, f"{path}{name}")
        obj = cls(**kwargs)
        obj._validate(path)
        return obj

    def _validate(self, path: str = "") -> None:  # override in subclasses
        pass

    def to_dict(self) -> dict[str, Any]:
        out = {}
        for f in fields(self):
            if f.name.startswith("_"):
                continue
            v = getattr(self, f.name)
            out[f.name] = v.to_dict() if isinstance(v, ConfigBase) else copy.deepcopy(v)
        return out

    def dumps(self) -> str:
        return json.dumps(self.to_dict(), indent=2, default=str)


def _coerce(value: Any, ftype: Any, path: str) -> Any:
    origin = typing.get_origin(ftype)
    args = typing.get_args(ftype)

    # Optional[T] / unions: try each arm.
    if origin is typing.Union:
        if value is None and type(None) in args:
            return None
        errors = []
        for arm in args:
            if arm is type(None):
                continue
            try:
                return _coerce(value, arm, path)
            except (ConfigError, TypeError, ValueError) as e:
                errors.append(str(e))
        raise ConfigError(f"{path}: no union arm matched value {value!r}: {errors}")

    if isinstance(ftype, type) and issubclass(ftype, ConfigBase):
        return ftype.from_dict(value, path=f"{path}.")

    if origin in (list, tuple):
        elem = args[0] if args else Any
        seq = [_coerce(v, elem, f"{path}[{i}]") for i, v in enumerate(value)]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        return dict(value)

    if ftype is bool:
        if isinstance(value, bool):
            return value
        raise ConfigError(f"{path}: expected bool, got {value!r}")
    if ftype is int:
        if isinstance(value, bool) or not isinstance(value, (int, float)) or int(value) != value:
            raise ConfigError(f"{path}: expected int, got {value!r}")
        return int(value)
    if ftype is float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ConfigError(f"{path}: expected float, got {value!r}")
        return float(value)
    if ftype is str:
        if not isinstance(value, str):
            raise ConfigError(f"{path}: expected str, got {value!r}")
        return value
    return value


def config_field(default=dataclasses.MISSING, default_factory=dataclasses.MISSING, **kw):
    if default_factory is not dataclasses.MISSING:
        return field(default_factory=default_factory, **kw)
    return field(default=default, **kw)
