"""The telemetry bus: one process-local singleton joining the metrics
registry, the span/event log, the HBM watermark sampler, and the exporters.

Disabled (the default) it is a no-op behind a single ``if not self.enabled``
flag check on every emit path — no clocks read, no dicts written — so the
training/inference hot paths pay nothing until a run opts in via the
``telemetry: {...}`` config block (see docs/OBSERVABILITY.md).

Event records share one shape across sinks::

    {"type": "span"|"event"|"gauge"|"snapshot", "name": ..., "ts": <unix s>,
     "step": <optional>, "dur_s": <spans>, ...free-form attrs...}
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from deepspeed_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from deepspeed_tpu.telemetry.tracing import Tracer


def _as_cfg_dict(cfg) -> dict:
    if cfg is None:
        return {}
    if isinstance(cfg, dict):
        return dict(cfg)
    if hasattr(cfg, "to_dict"):
        return dict(cfg.to_dict())
    # plain dataclass / namespace
    return {k: v for k, v in vars(cfg).items() if not k.startswith("_")}


class Telemetry:
    """Process-local telemetry bus (module singleton: ``TELEMETRY``)."""

    def __init__(self):
        self.enabled = False
        self.registry = MetricsRegistry()
        # the tracer object is permanent (engines cache a reference at
        # construction); only its ``enabled`` flag toggles with configure()
        self.tracer = Tracer(self.registry)
        self._slo = None
        self._costmeter = None
        self._compile_watch = None
        self._memledger = None
        self._fleet = None
        self._sinks: list = []
        self._prometheus = None
        self._sampler = None
        self._hbm_watermarks = True
        self._flush_interval = 100
        self._since_flush = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------- configure
    def configure(self, cfg=None, monitor=None, **overrides) -> "Telemetry":
        """(Re)build sinks from a ``TelemetryConfig`` / dict / kwargs.

        Idempotent: reconfiguring tears down the previous sinks and HTTP
        server first, so multiple engines in one process share one bus.
        """
        opts = _as_cfg_dict(cfg)
        opts.update(overrides)
        with self._lock:
            self._teardown_locked()
            self.enabled = bool(opts.get("enabled", True))
            if not self.enabled:
                return self
            self._hbm_watermarks = bool(opts.get("hbm_watermarks", True))
            self._flush_interval = max(1, int(opts.get("flush_interval_events",
                                                       100)))
            jsonl_path = opts.get("jsonl_path")
            if jsonl_path:
                from deepspeed_tpu.telemetry.exporters import JsonlSink

                self._sinks.append(JsonlSink(str(jsonl_path)))
            if opts.get("monitor_sink") and monitor is not None:
                from deepspeed_tpu.telemetry.exporters import MonitorSink

                self._sinks.append(MonitorSink(monitor))
            prom = opts.get("prometheus") or {}
            if prom.get("enabled"):
                from deepspeed_tpu.telemetry.exporters import PrometheusExporter

                self._prometheus = PrometheusExporter(
                    self.registry,
                    host=str(prom.get("host", "127.0.0.1")),
                    port=int(prom.get("port", 9464)),
                )
            tracing = opts.get("tracing") or {}
            if tracing is True:
                tracing = {"enabled": True}
            stepscope = opts.get("stepscope") or {}
            if stepscope is True:
                stepscope = {"enabled": True}
            if stepscope.get("enabled") and not tracing.get("enabled"):
                # step-anatomy spans land in the trace ring; an enabled
                # stepscope without explicit tracing opts implies tracing on
                tracing = {"enabled": True}
            if tracing.get("enabled"):
                self.tracer.configure(
                    enabled=True,
                    sample_rate=float(tracing.get("sample_rate", 1.0)),
                    ring_capacity=int(tracing.get("ring_capacity", 4096)),
                )
            cm = opts.get("costmeter") or {}
            if cm is True:
                cm = {"enabled": True}
            slo = opts.get("slo") or {}
            if slo is True:
                slo = {"enabled": True}
            if slo.get("enabled"):
                from deepspeed_tpu.telemetry.slo import (
                    SloMonitor,
                    default_class_objectives,
                    default_objectives,
                )

                # per-SLA-class objectives: explicit per-class threshold
                # dicts, bare True for the defaults, or implied by an
                # enabled costmeter (class accounting is its whole point)
                classes = slo.get("classes")
                if classes is None and cm.get("enabled"):
                    classes = True
                class_objs = None
                if classes is True:
                    class_objs = default_class_objectives(
                        window_s=float(slo.get("window_s", 300.0)),
                        target=float(slo.get("target", 0.99)))
                elif classes:
                    class_objs = {
                        cls: default_objectives(
                            ttft_threshold_s=float(
                                c.get("ttft_threshold_s", 0.5)),
                            decode_threshold_s=float(
                                c.get("decode_threshold_s", 0.05)),
                            target=float(c.get("target",
                                               slo.get("target", 0.99))),
                            window_s=float(c.get("window_s",
                                                 slo.get("window_s", 300.0))),
                        ) for cls, c in classes.items()}
                self._slo = SloMonitor(
                    default_objectives(
                        ttft_threshold_s=float(
                            slo.get("ttft_threshold_s", 0.5)),
                        decode_threshold_s=float(
                            slo.get("decode_threshold_s", 0.05)),
                        target=float(slo.get("target", 0.99)),
                        window_s=float(slo.get("window_s", 300.0)),
                    ),
                    self.registry,
                    burn_threshold=float(slo.get("burn_threshold", 1.0)),
                    replica=slo.get("replica"),
                    class_objectives=class_objs,
                )
                self._slo.refresh_gauges()
            if cm.get("enabled"):
                from deepspeed_tpu.telemetry.costmeter import CostMeter

                self._costmeter = CostMeter(
                    self.registry,
                    max_tenants=int(cm.get("max_tenants", 32)),
                    window_s=float(cm.get("window_s", 300.0)),
                    top_k=int(cm.get("top_k", 10)),
                    fairness_weight=float(cm.get("fairness_weight", 1.0)),
                )
            if opts.get("compile_metrics", True):
                from deepspeed_tpu.telemetry.compile_watch import CompileWatch

                self._compile_watch = CompileWatch(self.registry).install()
            fleet = opts.get("fleet") or {}
            if fleet is True:
                fleet = {"enabled": True}
            if fleet.get("enabled"):
                from deepspeed_tpu.telemetry.fleet import FleetReporter

                self._fleet = FleetReporter(
                    self,
                    out_dir=str(fleet.get("dir", "runs/fleet")),
                    worker=fleet.get("worker"),
                    labels=fleet.get("labels"),
                    interval_s=float(fleet.get("interval_s", 0.0)),
                    spill_traces=bool(fleet.get("spill_traces", True)),
                ).start()
            ml = opts.get("memledger") or {}
            if ml is True:
                ml = {"enabled": True}
            if ml.get("enabled"):
                from deepspeed_tpu.telemetry.memledger import MemoryLedger

                self._memledger = MemoryLedger(
                    self,
                    census_interval_steps=int(
                        ml.get("census_interval_steps", 50)),
                    drift_threshold=float(ml.get("drift_threshold", 0.05)),
                    drift_consecutive=int(ml.get("drift_consecutive", 3)),
                    report_dir=str(ml.get("report_dir", "oom_reports")),
                )
        self.event("telemetry/configured",
                   sinks=[type(s).__name__ for s in self._sinks],
                   prometheus_port=(self._prometheus.port
                                    if self._prometheus else None),
                   tracing=self.tracer.enabled,
                   slo=self._slo is not None,
                   costmeter=self._costmeter is not None,
                   memledger=self._memledger is not None,
                   fleet=(self._fleet.worker if self._fleet else None))
        return self

    @property
    def prometheus_port(self) -> int | None:
        return self._prometheus.port if self._prometheus else None

    # ------------------------------------------------------------- metrics
    def counter(self, name: str, help: str = "") -> Counter:
        return self.registry.counter(name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self.registry.gauge(name, help)

    def histogram(self, name: str, help: str = "", **kw) -> Histogram:
        return self.registry.histogram(name, help, **kw)

    # ------------------------------------------------------------- events
    def emit(self, record: dict) -> None:
        """Append one record to every sink (stamps ``ts`` if absent)."""
        if not self.enabled:
            return
        record.setdefault("ts", time.time())
        for sink in self._sinks:
            try:
                sink.emit(record)
            except Exception:
                pass  # a broken sink must never take down the step loop
        self._since_flush += 1
        if self._since_flush >= self._flush_interval:
            self.flush()

    def event(self, name: str, step: int | None = None, **attrs) -> None:
        if not self.enabled:
            return
        record = {"type": "event", "name": name}
        if step is not None:
            record["step"] = int(step)
        record.update({k: v for k, v in attrs.items() if v is not None})
        self.emit(record)

    def emit_span(self, name: str, dur_s: float, step: int | None = None,
                  **attrs) -> None:
        """Record a pre-measured span: JSONL record + latency histogram."""
        if not self.enabled:
            return
        record = {"type": "span", "name": name, "dur_s": float(dur_s)}
        if step is not None:
            record["step"] = int(step)
        record.update({k: v for k, v in attrs.items() if v is not None})
        self.emit(record)
        self.registry.histogram(
            "span_seconds", "span durations by name").observe(dur_s, name=name)

    @contextmanager
    def span(self, name: str, step: int | None = None, **attrs):
        """Context manager measuring wall clock around a block."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.emit_span(name, time.perf_counter() - t0, step=step, **attrs)

    def sample_memory(self, step: int | None = None) -> dict:
        """Per-step HBM watermark gauges (no device sync)."""
        if not self.enabled:
            return {}
        led = self._memledger
        if led is not None:
            led.maybe_census(step)
        if not self._hbm_watermarks:
            return {}
        if self._sampler is None:
            from deepspeed_tpu.telemetry.memory import HbmWatermarkSampler

            self._sampler = HbmWatermarkSampler(self)
        return self._sampler.sample(step)

    @property
    def memledger(self):
        """The configured :class:`MemoryLedger`, or None (hot paths guard
        on this one attribute read — off means zero allocations)."""
        return self._memledger

    # ------------------------------------------------------------- tracing
    def export_chrome_trace(self, trace_id: str | None = None) -> dict:
        """Chrome trace-event JSON of the span ring (Perfetto-loadable)."""
        return self.tracer.export_chrome(trace_id)

    def dump_trace(self, path: str | None = None,
                   trace_id: str | None = None, fleet=False) -> dict:
        """Export the span ring as Chrome trace JSON; writes ``path`` when
        given, returns the trace dict either way.

        ``fleet=True`` merges every worker's spilled ring from the
        configured fleet dir (or pass a fleet-dir path as ``fleet``) into
        ONE timeline with a per-process track per worker — see
        :func:`deepspeed_tpu.telemetry.fleet.merge_fleet_traces`.
        """
        if fleet:
            from deepspeed_tpu.telemetry.fleet import merge_fleet_traces

            fleet_dir = fleet if isinstance(fleet, str) else (
                self._fleet.out_dir if self._fleet is not None
                else "runs/fleet")
            trace = merge_fleet_traces(fleet_dir, local_tracer=self.tracer,
                                       trace_id=trace_id)
            if path is not None:
                import json
                import os

                parent = os.path.dirname(os.path.abspath(path))
                if parent:
                    os.makedirs(parent, exist_ok=True)
                with open(path, "w") as f:
                    json.dump(trace, f)
            return trace
        if path is None:
            return self.tracer.export_chrome(trace_id)
        return self.tracer.dump(path, trace_id)

    # ------------------------------------------------------------- fleet
    @property
    def fleet(self):
        """The configured :class:`FleetReporter`, or None (hot paths guard
        on this one attribute read)."""
        return self._fleet

    # ------------------------------------------------------------- slo
    @property
    def slo(self):
        """The configured :class:`SloMonitor`, or None."""
        return self._slo

    def observe_slo(self, objective: str, value_s: float,
                    sla_class: str | None = None) -> None:
        """Record a request latency against an SLO objective (no-op when
        no monitor is configured). ``sla_class`` additionally scores the
        sample against that class's own objectives when configured."""
        slo = self._slo
        if slo is not None:
            slo.record(objective, value_s, sla_class=sla_class)

    # ------------------------------------------------------------- costmeter
    @property
    def costmeter(self):
        """The configured :class:`CostMeter`, or None (the engine guards
        every metering seam on this one attribute read — off means zero
        costmeter code runs)."""
        return self._costmeter

    # ------------------------------------------------------------- compile
    @property
    def compile_watch(self):
        """The installed :class:`CompileWatch`, or None."""
        return self._compile_watch

    def note_program_cache_size(self, n_programs: int) -> None:
        """Feed the compile watch's cache-size-delta fallback (no-op when
        jax.monitoring listeners are active)."""
        cw = self._compile_watch
        if cw is not None:
            cw.note_cache_size(n_programs)

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """The full registry as plain data (JSON-serializable)."""
        return {"ts": time.time(), "metrics": self.registry.snapshot()}

    def dump(self, path: str) -> dict:
        """Persist ``snapshot()`` as a JSON file; returns the snapshot."""
        import json
        import os

        snap = self.snapshot()
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(snap, f, indent=2, default=str)
        return snap

    # ------------------------------------------------------------- lifecycle
    def flush(self) -> None:
        self._since_flush = 0
        for sink in self._sinks:
            try:
                sink.flush()
            except Exception:
                pass

    def close(self) -> None:
        """Emit a final registry snapshot record, then tear down all sinks."""
        if self.enabled and self._sinks:
            self.emit({"type": "snapshot", **self.snapshot()})
        with self._lock:
            self._teardown_locked()
        self.enabled = False

    def reset(self) -> None:
        """Back to the pristine disabled state (test isolation)."""
        with self._lock:
            self._teardown_locked()
        self.enabled = False
        self.registry.reset()

    def _teardown_locked(self) -> None:
        for sink in self._sinks:
            try:
                sink.close()
            except Exception:
                pass
        self._sinks = []
        if self._prometheus is not None:
            try:
                self._prometheus.close()
            except Exception:
                pass
            self._prometheus = None
        self._sampler = None
        self._memledger = None
        if self._fleet is not None:
            try:
                self._fleet.stop(final_flush=False)
            except Exception:
                pass
            self._fleet = None
        self._since_flush = 0
        self.tracer.reset()
        self._slo = None
        self._costmeter = None
        if self._compile_watch is not None:
            try:
                self._compile_watch.uninstall()
            except Exception:
                pass
            self._compile_watch = None


TELEMETRY = Telemetry()
