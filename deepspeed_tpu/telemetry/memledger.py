"""Framework-wide HBM memory ledger: per-owner byte attribution, a
``jax.live_arrays()`` leak census, OOM forensics, and headroom math.

The telemetry stack measures *time* exhaustively (request traces, training
stepscope); this module is the matching byte-truth layer. Every long-lived
device allocation registers under an **owner tag** from a fixed taxonomy —
``params``, ``optimizer_shards``, ``grads``, ``kv_pool``,
``prefix_cache_retained``, ``device_sched_state``, ``staging_buffers``,
``kv_handoff``, ``spec_lanes`` — with pytree-computed nbytes. Two attribution
shapes exist:

- **handles** (``register`` / ``update`` / ``release``): a fixed allocation
  whose size changes only at explicit lifecycle events (params, the paged KV
  pool, device scheduler rows);
- **providers** (``register_provider``): pool-style owners whose byte count
  is derived state (prefix-cache retained blocks × block bytes, parked
  handoff blocks, the staging cache) — a zero-argument callable read at
  gauge-refresh time, held via weakref-style None-pruning so a dead engine
  never leaks through the ledger. A provider whose bytes are a *subset* of
  another owner's allocation (retained/handoff blocks live inside the
  ``kv_pool`` arrays) registers with ``carveout_of``: its bytes move out of
  the parent's attribution instead of adding to the total, so
  ``attributed_bytes`` counts each real byte exactly once.

``census()`` sums every live jax array in the process and reconciles it
against the ledger: ``memory_unattributed_bytes = live − attributed`` is a
live leak detector — a steadily growing gap is an allocation nobody owns.
The drift alarm fires (``memledger_drift_alarms_total``) when the
unattributed fraction exceeds a threshold for N *consecutive* censuses, so a
transient spike (a step's temps caught mid-flight) never pages anyone.

Per-compiled-program temp/activation footprints ride along via
``note_program(key, compiled)`` using the same ``cost_analysis`` /
``memory_analysis`` idiom as profiling/flops_profiler.py, keyed on the
engine's existing specialization keys — so "how much scratch does program X
need" is recorded once per compile, not guessed.

**OOM forensics** (``record_oom``): when a ``RESOURCE_EXHAUSTED`` surfaces
at a dispatch/alloc/engine seam, the full per-owner breakdown + census +
device watermarks are snapshotted into a crash-report JSON under
``report_dir`` and ``oom_events_total{seam=}`` bumps — the postmortem is
written the instant the body is warm, not reconstructed from gauges later.

Off is free: the ledger only exists when the ``telemetry.memledger`` config
block enables it; every hot-path call site guards on
``telemetry.memledger is None`` (one attribute read, zero allocations).
"""

from __future__ import annotations

import json
import os
import threading
import time

# The owner taxonomy. Fixed and small on purpose: gauges stay low-
# cardinality and a breakdown is readable at a glance. New subsystems claim
# an existing owner before minting a new one.
OWNERS = (
    "params",                  # model weights (train master / serving cast)
    "optimizer_shards",        # optimizer state (resident groups only)
    "grads",                   # persistent gradient buffers (accumulators)
    "kv_pool",                 # the paged KV cache block pool
    "prefix_cache_retained",   # refcount-0 published blocks held in the LRU
    "device_sched_state",      # device-resident scheduler rows/block table
    "staging_buffers",         # H2D staging + checkpoint host snapshots
    "kv_handoff",              # parked KV blocks awaiting disagg export
    "spec_lanes",              # speculative-decode history/draft state
    "host_kv_tier",            # demoted KV blocks in the host-RAM arena
    "disk_kv_tier",            # demoted KV blocks spilled to disk
)

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "OUT OF MEMORY", "OUT_OF_MEMORY")


def is_resource_exhausted(exc: BaseException) -> bool:
    """True when ``exc`` is an out-of-device-memory failure (XLA/PJRT
    surfaces these as RESOURCE_EXHAUSTED status text). Shared by the
    dispatch watchdog and the engine seams so every layer agrees on what
    counts as an OOM."""
    msg = f"{type(exc).__name__}: {exc}".upper()
    return any(m in msg for m in _OOM_MARKERS)


def tree_nbytes(tree) -> int:
    """Total bytes across a pytree's array leaves (ints pass through)."""
    if tree is None:
        return 0
    if isinstance(tree, (int, float)):
        return int(tree)
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb is None:
            size = getattr(leaf, "size", None)
            itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", None)
            nb = size * itemsize if size is not None and itemsize else 0
        total += int(nb)
    return total


class LedgerHandle:
    """One registered allocation (returned by ``MemoryLedger.register``)."""

    __slots__ = ("owner", "name", "nbytes", "_live")

    def __init__(self, owner: str, name: str, nbytes: int):
        self.owner = owner
        self.name = name
        self.nbytes = int(nbytes)
        self._live = True

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"LedgerHandle({self.owner}/{self.name}: {self.nbytes}B)"


class MemoryLedger:
    """Per-owner byte attribution + live-array census (owned by the
    ``Telemetry`` singleton; one per process)."""

    def __init__(self, telemetry, *,
                 census_interval_steps: int = 50,
                 drift_threshold: float = 0.05,
                 drift_consecutive: int = 3,
                 report_dir: str = "oom_reports"):
        self.telemetry = telemetry
        self.census_interval_steps = max(1, int(census_interval_steps))
        self.drift_threshold = float(drift_threshold)
        self.drift_consecutive = max(1, int(drift_consecutive))
        self.report_dir = str(report_dir)
        self._lock = threading.Lock()
        self._handles: list[LedgerHandle] = []
        self._providers: list[list] = []  # [owner, name, fn] (fn->None prunes)
        self._programs: dict[str, dict] = {}
        self._drift_streak = 0
        self.drift_alarms = 0
        self._steps_since_census = 0
        self._last_census: dict | None = None
        self._oom_seq = 0
        self.oom_reports: list[str] = []

    # ------------------------------------------------------------- handles
    def register(self, owner: str, name: str, tree_or_nbytes) -> LedgerHandle:
        """Attribute an allocation to ``owner``; nbytes is pytree-summed.
        Returns a handle for later ``update``/``release``."""
        if owner not in OWNERS:
            raise ValueError(f"unknown memory owner {owner!r} (taxonomy: "
                             f"{OWNERS})")
        h = LedgerHandle(owner, name, tree_nbytes(tree_or_nbytes))
        with self._lock:
            self._handles.append(h)
        return h

    def update(self, handle: LedgerHandle, tree_or_nbytes) -> None:
        """Re-measure a handle after the underlying allocation was swapped
        (e.g. the KV cache rebuilt by crash containment)."""
        handle.nbytes = tree_nbytes(tree_or_nbytes)

    def release(self, handle: LedgerHandle) -> None:
        """Drop a handle's attribution (the allocation was freed)."""
        with self._lock:
            handle._live = False
            handle.nbytes = 0
            try:
                self._handles.remove(handle)
            except ValueError:
                pass  # double release is harmless

    def register_provider(self, owner: str, name: str, fn,
                          carveout_of: str | None = None,
                          offdevice: bool = False) -> None:
        """Attribute a *derived* byte count: ``fn()`` is read at every gauge
        refresh / census / breakdown. A provider returning None is pruned
        (the weakref-holding idiom: closures over ``weakref.ref(engine)``
        return None once the engine dies, so the ledger never pins it).

        ``carveout_of`` marks the provider as a *subset* of another owner's
        already-registered bytes (prefix-LRU retained blocks and parked
        handoff blocks live inside the ``kv_pool`` arrays): the bytes show
        under the provider's own owner in the breakdown but are subtracted
        from the parent, so the attributed total counts each real byte
        exactly once — double-counting would inflate ``attributed_bytes``
        past the census and shrink the unattributed leak signal the census
        exists to catch.

        ``offdevice`` marks bytes that do NOT live in device memory (the
        host-RAM/disk KV tiers): they appear in the breakdown and the
        ``memory_bytes{owner=}`` gauges, but the census reconciliation
        against ``jax.live_arrays()`` excludes them — host bytes counted
        against a device census would read as phantom overattribution."""
        if owner not in OWNERS:
            raise ValueError(f"unknown memory owner {owner!r}")
        if carveout_of is not None and carveout_of not in OWNERS:
            raise ValueError(f"unknown carveout parent {carveout_of!r}")
        with self._lock:
            self._providers.append([owner, name, fn, carveout_of, offdevice])

    # ------------------------------------------------------------ programs
    def note_program(self, key, compiled) -> dict | None:
        """Record one compiled program's temp/activation footprint from its
        ``memory_analysis()`` / ``cost_analysis()`` (AOT objects or anything
        quacking like them), keyed by the caller's specialization key."""
        key = str(key)
        with self._lock:
            if key in self._programs:
                return self._programs[key]
        fp: dict = {}
        try:
            ma = compiled.memory_analysis()
            for attr in ("temp_size_in_bytes", "argument_size_in_bytes",
                         "output_size_in_bytes", "generated_code_size_in_bytes"):
                v = getattr(ma, attr, None)
                if v is not None:
                    fp[attr.replace("_size_in_bytes", "_bytes")] = int(v)
        except Exception:
            pass
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if ca and "flops" in ca:
                fp["flops"] = float(ca["flops"])
        except Exception:
            pass
        if not fp:
            return None
        with self._lock:
            self._programs[key] = fp
        tel = self.telemetry
        if fp.get("temp_bytes") is not None and tel.enabled:
            tel.gauge(
                "program_temp_bytes",
                "per-compiled-program temp/activation footprint",
            ).set(fp["temp_bytes"], program=key[:80])
        return fp

    def program_footprints(self) -> dict:
        with self._lock:
            return {k: dict(v) for k, v in self._programs.items()}

    # ----------------------------------------------------------- breakdown
    def owner_bytes(self, *, device_only: bool = False) -> dict:
        """``{owner: attributed_bytes}`` over every live handle + provider
        (all owners present, zero-filled, so dashboards never miss series).
        Carve-out providers move bytes out of their parent owner rather
        than adding new ones, so the dict sums to each real byte once.
        ``device_only=True`` skips off-device providers (host/disk KV
        tiers) — the census reconciles that variant against the device's
        live arrays."""
        out = {o: 0 for o in OWNERS}
        with self._lock:
            handles = list(self._handles)
            providers = list(self._providers)
        for h in handles:
            out[h.owner] += h.nbytes
        dead = []
        for p in providers:
            if device_only and p[4]:
                continue
            try:
                v = p[2]()
            except Exception:
                v = 0
            if v is None:
                dead.append(p)
                continue
            v = int(v)
            parent = p[3]
            if parent is not None:
                # a subset of the parent's bytes changes attribution, not
                # the total; never drive the parent negative (an over-
                # reporting carve-out would then shrink the sum and show
                # up as census overattribution — its own smell)
                v = min(v, max(0, out[parent]))
                out[parent] -= v
            out[p[0]] += v
        if dead:
            with self._lock:
                self._providers = [p for p in self._providers if p not in dead]
        return out

    def attributed_bytes(self) -> int:
        return sum(self.owner_bytes().values())

    def breakdown(self) -> dict:
        """Full attribution snapshot (the ``/debug/memory`` payload body)."""
        owners = self.owner_bytes()
        with self._lock:
            entries = [
                {"owner": h.owner, "name": h.name, "nbytes": h.nbytes}
                for h in self._handles
            ]
            providers = [
                {"owner": o, "name": n,
                 **({"carveout_of": c} if c else {}),
                 **({"offdevice": True} if d else {})}
                for o, n, _, c, d in self._providers
            ]
        return {
            "owners": owners,
            "attributed_bytes": sum(owners.values()),
            "entries": entries,
            "providers": providers,
            "programs": dict(self._programs),
        }

    # -------------------------------------------------------------- census
    def census(self, step: int | None = None, *,
               update_state: bool = True) -> dict:
        """Reconcile ledger vs reality: sum every live jax array, compute
        the unattributed gap, update gauges, and run the drift alarm.

        ``update_state=False`` is the read-only variant for the
        ``/debug/memory`` endpoint and OOM forensics: it reports the same
        reconciliation but never touches the drift-alarm state machine —
        the alarm's "N *consecutive* censuses" semantics belong to the
        step-loop cadence, and a scrape at an arbitrary cadence mutating
        ``_drift_streak`` would fire or suppress it spuriously."""
        import jax

        live_bytes = 0
        live_count = 0
        for a in jax.live_arrays():
            try:
                live_bytes += int(a.nbytes)
                live_count += 1
            except Exception:
                continue
        owners = self.owner_bytes()
        # reconcile DEVICE bytes only: the host-RAM/disk KV tiers are real
        # attributed bytes for the breakdown, but they are invisible to
        # jax.live_arrays() and would read as phantom overattribution here
        attributed = sum(self.owner_bytes(device_only=True).values())
        offdevice = max(0, sum(owners.values()) - attributed)
        unattributed = max(0, live_bytes - attributed)
        # attribution exceeding the census means stale handles (e.g. a
        # donated buffer whose handle was never updated) — its own smell
        overattributed = max(0, attributed - live_bytes)
        frac = unattributed / live_bytes if live_bytes else 0.0
        alarm = False
        if update_state:
            with self._lock:  # the endpoint thread races the step loop
                if frac > self.drift_threshold:
                    self._drift_streak += 1
                    if self._drift_streak >= self.drift_consecutive:
                        alarm = True
                        self.drift_alarms += 1
                        self._drift_streak = 0
                else:
                    self._drift_streak = 0
        out = {
            "live_bytes": live_bytes,
            "live_arrays": live_count,
            "attributed_bytes": attributed,
            "offdevice_bytes": offdevice,
            "unattributed_bytes": unattributed,
            "overattributed_bytes": overattributed,
            "unattributed_fraction": round(frac, 6),
            "drift_alarm": alarm,
            "drift_alarms_total": self.drift_alarms,
        }
        if update_state:
            self._last_census = out
        tel = self.telemetry
        if tel.enabled:
            g = tel.gauge
            g("memory_census_bytes",
              "total bytes across jax.live_arrays()").set(live_bytes)
            g("memory_unattributed_bytes",
              "live-array bytes no ledger owner claims (leak detector)"
              ).set(unattributed)
            g("memory_overattributed_bytes",
              "ledger bytes exceeding the live-array census (stale handles)"
              ).set(overattributed)
            if alarm:
                tel.counter(
                    "memledger_drift_alarms_total",
                    "censuses where the unattributed fraction stayed above "
                    "threshold for drift_consecutive rounds").inc()
                tel.event("memledger/drift_alarm", step=step,
                          unattributed_bytes=unattributed,
                          fraction=round(frac, 4))
        self.refresh_gauges(owners)
        return out

    def maybe_census(self, step: int | None = None) -> dict | None:
        """Census every ``census_interval_steps`` calls (the per-step hook);
        gauge refresh happens every call — it is just dict reads."""
        self._steps_since_census += 1
        if self._steps_since_census >= self.census_interval_steps:
            self._steps_since_census = 0
            return self.census(step)
        self.refresh_gauges()
        return None

    def refresh_gauges(self, owners: dict | None = None) -> None:
        """Write ``memory_bytes{owner=}`` + push a Perfetto counter-track
        sample when tracing is live."""
        tel = self.telemetry
        if not tel.enabled:
            return
        if owners is None:
            owners = self.owner_bytes()
        gauge = tel.gauge("memory_bytes",
                          "ledger-attributed device bytes by owner")
        for owner, nbytes in owners.items():
            gauge.set(nbytes, owner=owner)
        tracer = tel.tracer
        if tracer.enabled:
            tracer.counter_sample(
                "memory_bytes", {o: b for o, b in owners.items() if b})

    # ------------------------------------------------------------ endpoint
    def debug_payload(self) -> dict:
        """The ``GET /debug/memory`` response: breakdown + fresh census +
        device watermarks in one JSON-serializable dict."""
        payload = self.breakdown()
        # read-only census: scraping the endpoint must not perturb the
        # step-loop drift-alarm state machine
        payload["census"] = self.census(update_state=False)
        payload["device"] = self._device_stats()
        payload["enabled"] = True
        return payload

    @staticmethod
    def _device_stats() -> dict:
        try:
            from deepspeed_tpu.accelerator.real_accelerator import (
                get_accelerator,
            )

            return dict(get_accelerator().memory_stats() or {})
        except Exception:
            return {}

    # ------------------------------------------------------------ forensics
    def oom_report(self, seam: str, exc: BaseException | None = None,
                   context: dict | None = None) -> str | None:
        """Snapshot the full breakdown + census into a crash-report JSON.
        Never raises — forensics must not worsen the failure it documents."""
        try:
            with self._lock:
                self._oom_seq += 1
                seq = self._oom_seq
            report = {
                "type": "oom_report",
                "seam": seam,
                "ts": time.time(),
                "pid": os.getpid(),
                "error": f"{type(exc).__name__}: {exc}" if exc else None,
                "context": context or {},
                **self.breakdown(),
            }
            # read-only: forensics must document the drift state, not
            # advance it (an OOM mid-window would otherwise skew the
            # consecutive-census alarm)
            report["census"] = self.census(update_state=False)
            report["device"] = self._device_stats()
            os.makedirs(self.report_dir, exist_ok=True)
            path = os.path.join(
                self.report_dir,
                f"oom_{seam.replace('/', '_').replace('.', '_')}"
                f"_{os.getpid()}_{seq}.json")
            with open(path, "w") as f:
                json.dump(report, f, indent=2, default=str)
            with self._lock:
                self.oom_reports.append(path)
            tel = self.telemetry
            if tel.enabled:
                tel.event("memledger/oom", seam=seam, report=path,
                          attributed_bytes=report["attributed_bytes"])
            return path
        except Exception:
            return None

    # ------------------------------------------------------------ headroom
    @staticmethod
    def free_headroom_bytes(stats: dict | None = None,
                            guard_fraction: float = 0.05) -> int:
        """Measured free device bytes minus a guard band; -1 = unknown
        (backend reports no ``bytes_limit``, e.g. the CPU test accelerator)."""
        if stats is None:
            stats = MemoryLedger._device_stats()
        limit = int(stats.get("bytes_limit") or 0)
        if limit <= 0:
            return -1
        free = limit - int(stats.get("bytes_in_use") or 0)
        return max(0, free - int(guard_fraction * limit))


def record_oom(seam: str, exc: BaseException | None = None,
               context: dict | None = None) -> str | None:
    """Module-level OOM hook for the dispatch/alloc/engine seams: bump
    ``oom_events_total{seam=}`` and, when the ledger is live, write the
    crash-report JSON. Returns the report path (or None). Never raises."""
    try:
        from deepspeed_tpu.telemetry import get_telemetry

        tel = get_telemetry()
        if tel.enabled:
            tel.counter(
                "oom_events_total",
                "RESOURCE_EXHAUSTED failures caught at engine seams",
            ).inc(seam=seam)
        led = tel.memledger
        if led is None:
            return None
        return led.oom_report(seam, exc, context)
    except Exception:
        return None
