"""Unified structured telemetry (docs/OBSERVABILITY.md).

One process-local bus joins what the reference scatters across monitor/
comms-logging/timer prints: a typed metrics registry (counters, gauges,
histograms with labels), a span/event log (training step spans, inference
request lifecycles, checkpoint durations), a per-step HBM watermark sampler,
and pluggable exporters (JSONL file sink, Prometheus text exposition over
stdlib HTTP, and the existing ``MonitorMaster`` as a bridge sink).

Typical use::

    from deepspeed_tpu import telemetry

    telemetry.configure(enabled=True, jsonl_path="/tmp/run.jsonl",
                        prometheus={"enabled": True, "port": 9464})
    telemetry.get_telemetry().counter("my_events_total").inc()
    ...
    telemetry.get_telemetry().dump("/tmp/run_metrics.json")

Training runs enable it declaratively via the ``telemetry: {...}`` config
block; ``deepspeed_tpu.initialize`` wires the engine emit points.
"""

from deepspeed_tpu.telemetry.core import TELEMETRY, Telemetry  # noqa: F401
from deepspeed_tpu.telemetry.costmeter import (  # noqa: F401
    CostMeter,
    OTHER_TENANT,
    RequestCost,
    TenantLedger,
)
from deepspeed_tpu.telemetry.devprof import (  # noqa: F401
    DeviceProfiler,
    capture_serving,
    classify_op,
    derive_timeline,
    merge_into_ring,
    parse_chrome_trace,
)
from deepspeed_tpu.telemetry.fleet import (  # noqa: F401
    FleetAggregator,
    FleetReporter,
    merge_fleet_traces,
    merge_metric_snapshots,
    render_federated_prometheus,
)
from deepspeed_tpu.telemetry.memledger import (  # noqa: F401
    MemoryLedger,
    OWNERS as MEMORY_OWNERS,
    is_resource_exhausted,
    record_oom,
    tree_nbytes,
)
from deepspeed_tpu.telemetry.registry import (  # noqa: F401
    BYTE_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from deepspeed_tpu.telemetry.stepscope import StepScope  # noqa: F401
from deepspeed_tpu.telemetry.slo import (  # noqa: F401
    SloMonitor,
    SloObjective,
    default_class_objectives,
    default_objectives,
)
from deepspeed_tpu.telemetry.tracing import (  # noqa: F401
    TraceContext,
    Tracer,
    format_traceparent,
    parse_traceparent,
)


def get_telemetry() -> Telemetry:
    return TELEMETRY


def configure(cfg=None, monitor=None, **overrides) -> Telemetry:
    """Configure the process singleton (see :meth:`Telemetry.configure`)."""
    return TELEMETRY.configure(cfg, monitor=monitor, **overrides)


def snapshot() -> dict:
    return TELEMETRY.snapshot()


def dump(path: str) -> dict:
    return TELEMETRY.dump(path)


def dump_trace(path: str | None = None, trace_id: str | None = None,
               fleet=False) -> dict:
    """Export the request-trace span ring as Chrome trace-event JSON
    (Perfetto-loadable); writes ``path`` when given. ``fleet=True`` (or a
    fleet-dir path) merges every worker's spilled ring into ONE timeline
    with per-process tracks."""
    return TELEMETRY.dump_trace(path, trace_id, fleet=fleet)
