"""SLO burn-rate monitoring over rolling request-latency windows.

An objective is "fraction of requests whose latency is under a threshold
must be at least ``target``" — e.g. 99% of requests see TTFT < 200 ms over
the last 5 minutes. The *error budget* is ``1 - target``; the *burn rate*
is the observed bad fraction divided by that budget. Burn rate 1.0 means
the budget is being consumed exactly as fast as it accrues; sustained
burn above ``burn_threshold`` flips the objective to "breaching", which
the serving frontend reflects in ``/healthz`` (status "degraded" — the
replica still serves, but the balancer/operator is told tail latency is
out of budget before users file tickets).

Objectives ship with defaults for the two latencies the ragged engine
already measures per request (``_emit_request_span``): TTFT and mean
per-token decode latency. Samples live in per-objective deques pruned to
the window on every record/read, so memory is bounded by arrival rate x
window and an idle replica decays back to healthy as bad samples age out.

Gauges per objective (labelled ``objective=<name>``):

- ``slo_burn_rate``       bad_fraction / error_budget over the window
- ``slo_good_fraction``   fraction of in-window requests under threshold
- ``slo_window_requests`` sample count backing the estimate
- ``slo_breaching``       1 if burn rate > burn_threshold (min samples met)
"""

from __future__ import annotations

import threading
import time
from collections import deque

# below this many in-window samples a breach verdict is noise, not signal
MIN_SAMPLES = 5


class SloObjective:
    """One rolling-window latency objective."""

    __slots__ = ("name", "threshold_s", "target", "window_s")

    def __init__(self, name: str, threshold_s: float, target: float = 0.99,
                 window_s: float = 300.0):
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        if threshold_s < 0.0:
            raise ValueError(f"threshold_s must be >= 0, got {threshold_s}")
        self.name = name
        self.threshold_s = float(threshold_s)
        self.target = float(target)
        self.window_s = float(window_s)


def default_objectives(ttft_threshold_s: float = 0.5,
                       decode_threshold_s: float = 0.05,
                       target: float = 0.99,
                       window_s: float = 300.0) -> list[SloObjective]:
    """The two objectives the ragged engine reports natively: time to
    first token, and mean per-token decode latency."""
    return [
        SloObjective("ttft", ttft_threshold_s, target, window_s),
        SloObjective("decode_latency", decode_threshold_s, target, window_s),
    ]


def default_class_objectives(window_s: float = 300.0,
                             target: float = 0.99) -> dict:
    """Per-SLA-class objective sets (docs/SERVING.md request ``sla_class``):
    interactive requests are held to the tight thresholds, batch to relaxed
    ones — each class burns its own error budget so a batch backlog cannot
    mask an interactive-tail regression (or vice versa)."""
    return {
        "interactive": default_objectives(
            ttft_threshold_s=0.5, decode_threshold_s=0.05,
            target=target, window_s=window_s),
        "batch": default_objectives(
            ttft_threshold_s=5.0, decode_threshold_s=0.25,
            target=target, window_s=window_s),
    }


class SloMonitor:
    """Records (timestamp, good?) samples per objective and publishes
    burn-rate gauges into the metrics registry at record and scrape time."""

    MIN_SAMPLES = MIN_SAMPLES

    def __init__(self, objectives, registry, burn_threshold: float = 1.0,
                 replica: str | None = None, class_objectives=None):
        self._objectives = {o.name: o for o in objectives}
        self._samples = {o.name: deque() for o in objectives}
        # per-SLA-class objective sets: {sla_class: [SloObjective, ...]}.
        # Class samples live in their own windows keyed (class, name) and
        # publish {objective=,sla_class=} series; the base (classless)
        # series keeps seeing every record so existing dashboards hold.
        self._class_objectives = {
            cls: {o.name: o for o in objs}
            for cls, objs in (class_objectives or {}).items()}
        self._class_samples = {
            (cls, name): deque()
            for cls, objs in self._class_objectives.items()
            for name in objs}
        self._registry = registry
        self.burn_threshold = float(burn_threshold)
        # distinct replicas' monitors sharing one process (and therefore
        # one registry) publish disjoint series via the replica= label;
        # unnamed monitors keep the bare {objective=} series
        self.replica = str(replica) if replica else None
        self._lock = threading.Lock()

    @property
    def objectives(self):
        return dict(self._objectives)

    # ------------------------------------------------------------- recording
    def record(self, name: str, value_s: float, now: float | None = None,
               sla_class: str | None = None):
        """Record one request latency against objective ``name`` (unknown
        names are ignored so callers need no registration handshake).
        ``sla_class`` additionally scores the sample against that class's
        own threshold/window when class objectives are configured."""
        t = time.monotonic() if now is None else now
        obj = self._objectives.get(name)
        if obj is not None:
            with self._lock:
                window = self._samples[name]
                window.append((t, value_s <= obj.threshold_s))
                self._prune_locked(name, t)
            self._publish(name, t)
        if sla_class is not None:
            cobj = self._class_objectives.get(sla_class, {}).get(name)
            if cobj is not None:
                with self._lock:
                    window = self._class_samples[(sla_class, name)]
                    window.append((t, value_s <= cobj.threshold_s))
                    self._prune_locked(name, t, sla_class)
                self._publish(name, t, sla_class)

    def _prune_locked(self, name: str, now: float,
                      sla_class: str | None = None) -> None:
        if sla_class is None:
            window = self._samples[name]
            horizon = now - self._objectives[name].window_s
        else:
            window = self._class_samples[(sla_class, name)]
            horizon = now - self._class_objectives[sla_class][name].window_s
        while window and window[0][0] < horizon:
            window.popleft()

    # --------------------------------------------------------------- queries
    def stats(self, name: str, now: float | None = None,
              sla_class: str | None = None) -> dict:
        """``{count, good_fraction, burn_rate, breaching}`` for one
        objective over its current window."""
        if sla_class is None:
            obj = self._objectives[name]
        else:
            obj = self._class_objectives[sla_class][name]
        t = time.monotonic() if now is None else now
        with self._lock:
            self._prune_locked(name, t, sla_class)
            if sla_class is None:
                window = list(self._samples[name])
            else:
                window = list(self._class_samples[(sla_class, name)])
        count = len(window)
        good = sum(1 for _, ok in window if ok)
        good_fraction = good / count if count else 1.0
        budget = 1.0 - obj.target
        burn = (1.0 - good_fraction) / budget if budget > 0 else 0.0
        breaching = count >= self.MIN_SAMPLES and burn > self.burn_threshold
        return {
            "count": count,
            "good_fraction": good_fraction,
            "burn_rate": burn,
            "breaching": breaching,
            "threshold_s": obj.threshold_s,
            "target": obj.target,
            "window_s": obj.window_s,
        }

    def breaching(self) -> bool:
        if any(self.stats(n)["breaching"] for n in self._objectives):
            return True
        return any(self.stats(n, sla_class=cls)["breaching"]
                   for cls, objs in self._class_objectives.items()
                   for n in objs)

    def breaching_classes(self) -> list[tuple[str, str]]:
        """``(sla_class, objective)`` pairs currently out of budget."""
        return [(cls, n)
                for cls, objs in self._class_objectives.items()
                for n in objs
                if self.stats(n, sla_class=cls)["breaching"]]

    def health(self) -> dict:
        """Per-objective summary embedded in the ``/healthz`` body."""
        out = {n: self.stats(n) for n in self._objectives}
        if self._class_objectives:
            out["by_class"] = {
                cls: {n: self.stats(n, sla_class=cls) for n in objs}
                for cls, objs in self._class_objectives.items()}
        return out

    # --------------------------------------------------------------- gauges
    def _publish(self, name: str, now: float | None = None,
                 sla_class: str | None = None) -> None:
        # the clock must follow the caller's (record passes its timestamp
        # through; a wall-clock prune here would evict replayed samples)
        s = self.stats(name, now, sla_class)
        reg = self._registry
        labels = {"objective": name}
        if sla_class is not None:
            labels["sla_class"] = sla_class
        if self.replica is not None:
            labels["replica"] = self.replica
        reg.gauge("slo_burn_rate",
                  "error-budget burn rate over the rolling window"
                  ).set(s["burn_rate"], **labels)
        reg.gauge("slo_good_fraction",
                  "fraction of in-window requests meeting the objective"
                  ).set(s["good_fraction"], **labels)
        reg.gauge("slo_window_requests",
                  "requests backing the rolling SLO estimate"
                  ).set(s["count"], **labels)
        reg.gauge("slo_breaching",
                  "1 when burn rate exceeds the breach threshold"
                  ).set(1.0 if s["breaching"] else 0.0, **labels)

    def refresh_gauges(self) -> None:
        """Re-publish all gauges (call at scrape time so idle windows decay
        visibly without waiting for the next request)."""
        for name in self._objectives:
            self._publish(name)
        for cls, objs in self._class_objectives.items():
            for name in objs:
                self._publish(name, sla_class=cls)
