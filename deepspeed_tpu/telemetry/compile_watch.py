"""Compile-cache observability: surface silent XLA recompiles as metrics.

The biggest TPU tail-latency hazard in the ragged serving path is a
request whose shape falls outside the warmed pow2 bucket ladders: jax
silently traces + backend-compiles a new program mid-decode and the whole
batch stalls for seconds. None of that is visible in PR 1's metrics.

Primary mechanism: ``jax.monitoring`` listeners. Every backend compile
fires ``/jax/core/compile/backend_compile_duration`` (an in-process jit
cache miss by definition — jax only reaches the backend compiler when no
cached executable exists), and tracing/lowering phases fire sibling
``/jax/core/compile/*_duration`` events; the persistent compilation cache
fires ``/jax/compilation_cache/cache_{hits,misses}``. Listeners are
process-global in jax, so install is idempotent and uninstall removes
*only our* callbacks (never ``clear_event_listeners()``, which would nuke
other tooling's listeners).

Fallback mechanism: on jax builds without usable monitoring hooks the
watch degrades to cache-size deltas — callers report an observed program
-cache size (the ragged engine reports its jitted-program zoo size each
telemetry sample) and any positive delta increments the miss counter with
``source="cache_size_delta"``.

Metrics:

- ``jit_cache_misses_total{source=}``      backend compiles (jit misses)
- ``jit_compile_seconds{phase=}``          histogram of compile durations
- ``persistent_cache_hits_total`` / ``persistent_cache_misses_total``
"""

from __future__ import annotations

import threading

BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
COMPILE_EVENT_PREFIX = "/jax/core/compile/"
PERSISTENT_HIT_EVENT = "/jax/compilation_cache/cache_hits"
PERSISTENT_MISS_EVENT = "/jax/compilation_cache/cache_misses"

# compile times span 10ms CPU traces to multi-minute TPU fusions
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0, 300.0, 600.0)


class CompileWatch:
    """Registers jax.monitoring listeners feeding the metrics registry."""

    def __init__(self, registry):
        self._registry = registry
        self._lock = threading.Lock()
        self._installed = False
        self.fallback = False
        self._last_cache_size: int | None = None
        # bound methods kept so uninstall can remove exactly these
        self._on_duration = self._duration_listener
        self._on_event = self._event_listener

    # ------------------------------------------------------------ listeners
    def _duration_listener(self, event: str, duration: float,
                           **kwargs) -> None:
        if not event.startswith(COMPILE_EVENT_PREFIX):
            return
        phase = event[len(COMPILE_EVENT_PREFIX):] or "unknown"
        if phase.endswith("_duration"):
            phase = phase[: -len("_duration")]
        reg = self._registry
        reg.histogram("jit_compile_seconds",
                      "XLA trace/lower/compile phase durations",
                      buckets=COMPILE_BUCKETS).observe(duration, phase=phase)
        if event == BACKEND_COMPILE_EVENT:
            reg.counter(
                "jit_cache_misses_total",
                "backend compiles observed (each is an in-process jit "
                "cache miss)").inc(source="monitoring")

    def _event_listener(self, event: str, **kwargs) -> None:
        if event == PERSISTENT_HIT_EVENT:
            self._registry.counter(
                "persistent_cache_hits_total",
                "persistent XLA compilation-cache hits").inc()
        elif event == PERSISTENT_MISS_EVENT:
            self._registry.counter(
                "persistent_cache_misses_total",
                "persistent XLA compilation-cache misses").inc()

    # --------------------------------------------------------- install/undo
    def install(self) -> "CompileWatch":
        with self._lock:
            if self._installed:
                return self
            # pre-create the series so /metrics exposes the counter at zero
            # (an operator alerting on it must see it before the first miss)
            self._registry.counter(
                "jit_cache_misses_total",
                "backend compiles observed (each is an in-process jit "
                "cache miss)").inc(0.0, source="monitoring")
            try:
                from jax import monitoring
                monitoring.register_event_duration_secs_listener(
                    self._on_duration)
                monitoring.register_event_listener(self._on_event)
            except Exception:
                self.fallback = True
            self._installed = True
        return self

    def uninstall(self) -> None:
        with self._lock:
            if not self._installed:
                return
            self._installed = False
            if self.fallback:
                return
            try:
                from jax._src import monitoring as m
                m._unregister_event_duration_listener_by_callback(
                    self._on_duration)
                m._unregister_event_listener_by_callback(self._on_event)
            except Exception:
                # best effort across jax versions: drop from the private
                # lists directly rather than clear_event_listeners(),
                # which would remove listeners we don't own
                try:
                    from jax._src import monitoring as m
                    for lst in (m._event_duration_secs_listeners,
                                m._event_listeners):
                        for cb in (self._on_duration, self._on_event):
                            while cb in lst:
                                lst.remove(cb)
                except Exception:
                    pass

    # ------------------------------------------------------------- fallback
    def note_cache_size(self, n_programs: int) -> None:
        """Cache-size-delta fallback: callers report how many jitted
        programs they currently hold; positive deltas count as misses.
        No-op unless listener registration failed."""
        if not self.fallback:
            return
        with self._lock:
            last = self._last_cache_size
            self._last_cache_size = int(n_programs)
        if last is not None and n_programs > last:
            self._registry.counter(
                "jit_cache_misses_total",
                "backend compiles observed (each is an in-process jit "
                "cache miss)").inc(n_programs - last,
                                   source="cache_size_delta")
