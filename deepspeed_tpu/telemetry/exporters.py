"""Pluggable telemetry exporters.

Three sinks over one data model (:mod:`deepspeed_tpu.telemetry.registry` +
the span/event records emitted by :class:`deepspeed_tpu.telemetry.core.Telemetry`):

- :class:`JsonlSink` — append-only JSONL event log (machine-readable run record;
  ``bench.py`` persists one next to its ``BENCH_*.json``).
- :class:`PrometheusExporter` — text exposition format 0.0.4 on a stdlib
  ``ThreadingHTTPServer`` daemon thread (``GET /metrics``); no third-party
  client library required.
- :class:`MonitorSink` — bridges scalar telemetry events back into
  :class:`deepspeed_tpu.monitor.monitor.MonitorMaster` so TensorBoard/CSV/W&B
  writers see the same stream (the reference monitor stack becomes one sink
  among several instead of a separate pipeline).
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from deepspeed_tpu.utils.logging import log_dist


def _json_default(obj):
    # numpy scalars / arrays and anything else that slips into a record
    try:
        import numpy as np

        if isinstance(obj, np.generic):
            return obj.item()
        if isinstance(obj, np.ndarray):
            return obj.tolist()
    except Exception:
        pass
    return str(obj)


class JsonlSink:
    """One JSON object per line; buffered file handle, explicit flush/close."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a")
        self._lock = threading.Lock()

    def emit(self, record: dict) -> None:
        line = json.dumps(record, default=_json_default)
        with self._lock:
            if self._f is not None:
                self._f.write(line + "\n")

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()
                self._f.close()
                self._f = None


class MonitorSink:
    """Adapter: scalar gauge/span records -> ``write_events([(tag, value, step)])``.

    Only records that carry a ``step`` can be plotted by the monitor writers
    (their x-axis); everything else stays JSONL/Prometheus-only.
    """

    def __init__(self, monitor):
        self.monitor = monitor

    def emit(self, record: dict) -> None:
        if not getattr(self.monitor, "enabled", False):
            return
        step = record.get("step")
        if step is None:
            return
        name = record.get("name", "unnamed")
        events = []
        if record.get("type") == "gauge" and "value" in record:
            events.append((f"Telemetry/{name}", float(record["value"]), int(step)))
        elif record.get("type") == "span" and record.get("dur_s") is not None:
            events.append(
                (f"Telemetry/{name}/seconds", float(record["dur_s"]), int(step)))
        if events:
            self.monitor.write_events(events)

    def flush(self) -> None:
        self.monitor.flush()

    def close(self) -> None:
        self.monitor.flush()


class PrometheusExporter:
    """``GET /metrics`` over stdlib http.server; renders the live registry.

    ``port=0`` binds an ephemeral port (tests); the bound port is on ``.port``.
    The server thread is a daemon: it never blocks interpreter exit.
    """

    CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

    def __init__(self, registry, host: str = "127.0.0.1", port: int = 9464):
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self.path.split("?")[0] not in ("/", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = exporter.registry.render_prometheus().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", exporter.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args: Any) -> None:
                pass  # scrapes must not spam training logs

        self.registry = registry
        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="telemetry-prometheus",
            daemon=True)
        self._thread.start()
        log_dist(
            f"telemetry: prometheus endpoint on http://{self.host}:{self.port}/metrics",
            ranks=[0])

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
