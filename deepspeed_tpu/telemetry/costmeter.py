"""Request-level cost attribution and per-tenant metering.

The observability plane can already answer "what is the fleet doing"
(fleet.py), "where did the step go" (stepscope/devprof) and "who owns the
HBM" (memledger) — this module answers "**who is consuming the capacity**".
Every served request accumulates a :class:`RequestCost` record at the seams
the ragged engine already owns:

- prefill tokens x analytic FLOPs/token (``flops_profiler.get_model_profile``)
- decode tokens and host dispatches, speculative lanes charged as proposed
  vs accepted separately
- KV **block-seconds**: the occupancy integral of the request's blocks from
  admission to release, including a retained-prefix carveout credited to
  the *publishing* tenant while its blocks sit in the cache, and a
  credit/debit transfer when another tenant's request splices them
- tier promote/demote bytes, handoff export/import bytes, queue wait

Finished records are folded into a ``request_cost_*{tenant=,sla_class=}``
counter/histogram family and a rolling :class:`TenantLedger`. Label
cardinality is bounded: the meter keeps an LRU of at most ``max_tenants``
distinct tenant label values and folds overflow into ``tenant="__other__"``
(the ledger itself keeps exact per-tenant rows up to a larger hard cap so
`/debug/tenants` stays useful even past the label cap).

Off by default: the meter only exists when
``telemetry.configure(costmeter=...)`` asked for it, every engine seam
guards on one attribute read, and with the meter off the serving hot path
executes zero code from this file (tracemalloc-pinned in
``tests/unit/test_costmeter.py``).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field

# overflow label once the distinct-tenant LRU cap is hit
OTHER_TENANT = "__other__"

# hard bound on exact ledger rows (not metric series) — beyond this even
# /debug/tenants folds into the overflow row
LEDGER_MAX_ROWS = 1024

# per-request block-seconds histogram buckets: 1ms .. ~5 min of one block
BLOCK_SECONDS_BUCKETS = tuple(0.001 * (4 ** p) for p in range(10))


@dataclass
class RequestCost:
    """Per-request resource-consumption record, accumulated in place by the
    engine and folded into the meter exactly once at release."""

    tenant: str = "default"
    sla_class: str = "interactive"
    prefill_tokens: int = 0
    prefill_flops: float = 0.0
    decode_tokens: int = 0
    decode_dispatches: int = 0
    spec_proposed: float = 0.0
    spec_accepted: float = 0.0
    kv_block_seconds: float = 0.0
    prefix_credit_blocks: int = 0   # cached blocks this request published
    prefix_debit_blocks: int = 0    # cached blocks spliced from other tenants
    tier_promote_bytes: int = 0
    tier_demote_bytes: int = 0
    handoff_export_bytes: int = 0
    handoff_import_bytes: int = 0
    queue_wait_s: float = 0.0

    def span_attrs(self) -> dict:
        """Attributes merged into the finished ``inference/request`` span."""
        return {
            "tenant": self.tenant,
            "sla_class": self.sla_class,
            "cost_prefill_flops": self.prefill_flops,
            "cost_decode_dispatches": self.decode_dispatches,
            "cost_kv_block_seconds": round(self.kv_block_seconds, 6),
            "cost_tier_promote_bytes": self.tier_promote_bytes,
            "cost_tier_demote_bytes": self.tier_demote_bytes,
            "cost_handoff_bytes": (self.handoff_export_bytes
                                   + self.handoff_import_bytes),
        }


@dataclass
class _TenantRow:
    """One tenant's cumulative ledger row."""

    tenant: str
    requests: int = 0
    prefill_tokens: int = 0
    prefill_flops: float = 0.0
    decode_tokens: int = 0
    decode_dispatches: int = 0
    spec_proposed: float = 0.0
    spec_accepted: float = 0.0
    kv_block_seconds: float = 0.0
    retained_block_seconds: float = 0.0
    prefix_credit_blocks: int = 0
    prefix_debit_blocks: int = 0
    tier_promote_bytes: int = 0
    tier_demote_bytes: int = 0
    handoff_bytes: int = 0
    queue_wait_s: float = 0.0
    outstanding_blocks: int = 0     # live blocks right now (set each tick)
    by_class: dict = field(default_factory=dict)  # sla_class -> requests

    def as_dict(self) -> dict:
        return {
            "tenant": self.tenant,
            "requests": self.requests,
            "prefill_tokens": self.prefill_tokens,
            "prefill_flops": self.prefill_flops,
            "decode_tokens": self.decode_tokens,
            "decode_dispatches": self.decode_dispatches,
            "spec_proposed": round(self.spec_proposed, 3),
            "spec_accepted": round(self.spec_accepted, 3),
            "kv_block_seconds": round(self.kv_block_seconds, 6),
            "retained_block_seconds": round(self.retained_block_seconds, 6),
            "prefix_credit_blocks": self.prefix_credit_blocks,
            "prefix_debit_blocks": self.prefix_debit_blocks,
            "tier_promote_bytes": self.tier_promote_bytes,
            "tier_demote_bytes": self.tier_demote_bytes,
            "handoff_bytes": self.handoff_bytes,
            "queue_wait_s": round(self.queue_wait_s, 6),
            "outstanding_blocks": self.outstanding_blocks,
            "by_class": dict(self.by_class),
        }


class TenantLedger:
    """Rolling per-tenant aggregator behind `/debug/tenants` and the
    router's fair-share signal. Cumulative rows plus a pruned window of
    recent request finishes for rate estimates."""

    def __init__(self, window_s: float = 300.0,
                 max_rows: int = LEDGER_MAX_ROWS):
        self.window_s = float(window_s)
        self.max_rows = int(max_rows)
        self._rows: dict[str, _TenantRow] = {}
        # (monotonic, tenant, decode_tokens, kv_block_seconds)
        self._recent: deque = deque()
        self._lock = threading.Lock()

    def _row_locked(self, tenant: str) -> _TenantRow:
        row = self._rows.get(tenant)
        if row is None:
            if len(self._rows) >= self.max_rows:
                tenant = OTHER_TENANT
                row = self._rows.get(tenant)
                if row is None:
                    row = self._rows[tenant] = _TenantRow(tenant)
            else:
                row = self._rows[tenant] = _TenantRow(tenant)
        return row

    def charge(self, cost: RequestCost, now: float | None = None) -> None:
        """Fold one finished request into its tenant's row."""
        t = time.monotonic() if now is None else now
        with self._lock:
            row = self._row_locked(cost.tenant)
            row.requests += 1
            row.prefill_tokens += cost.prefill_tokens
            row.prefill_flops += cost.prefill_flops
            row.decode_tokens += cost.decode_tokens
            row.decode_dispatches += cost.decode_dispatches
            row.spec_proposed += cost.spec_proposed
            row.spec_accepted += cost.spec_accepted
            row.kv_block_seconds += cost.kv_block_seconds
            row.prefix_credit_blocks += cost.prefix_credit_blocks
            row.prefix_debit_blocks += cost.prefix_debit_blocks
            row.tier_promote_bytes += cost.tier_promote_bytes
            row.tier_demote_bytes += cost.tier_demote_bytes
            row.handoff_bytes += (cost.handoff_export_bytes
                                  + cost.handoff_import_bytes)
            row.queue_wait_s += cost.queue_wait_s
            cls = cost.sla_class
            row.by_class[cls] = row.by_class.get(cls, 0) + 1
            self._recent.append((t, row.tenant, cost.decode_tokens,
                                 cost.kv_block_seconds))
            self._prune_locked(t)

    def add_retained(self, tenant: str, block_seconds: float) -> None:
        """Credit retained-prefix occupancy to the publishing tenant."""
        with self._lock:
            self._row_locked(tenant).retained_block_seconds += block_seconds

    def transfer(self, publisher: str, consumer: str, blocks: int) -> None:
        """Cross-tenant prefix splice: credit the publisher, debit the
        consumer — symmetric by construction."""
        with self._lock:
            self._row_locked(publisher).prefix_credit_blocks += blocks
            self._row_locked(consumer).prefix_debit_blocks += blocks

    def set_outstanding(self, blocks_by_tenant: dict) -> None:
        """Refresh the live-block view (the fair-share input) each tick."""
        with self._lock:
            for row in self._rows.values():
                row.outstanding_blocks = 0
            for tenant, n in blocks_by_tenant.items():
                self._row_locked(tenant).outstanding_blocks = int(n)

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.window_s
        recent = self._recent
        while recent and recent[0][0] < horizon:
            recent.popleft()

    # --------------------------------------------------------------- queries
    def outstanding_share(self, tenant: str) -> tuple[float, float]:
        """(tenant's share of live blocks, fair share). Fair share is
        ``1 / active_tenants``; with one active tenant both are 1.0, so the
        soft fairness penalty vanishes exactly (single-tenant parity)."""
        with self._lock:
            live = {t: r.outstanding_blocks for t, r in self._rows.items()
                    if r.outstanding_blocks > 0}
            total = sum(live.values())
            if total <= 0 or not live:
                return 0.0, 1.0
            n_active = len(live) if tenant in live else len(live) + 1
            return live.get(tenant, 0) / total, 1.0 / n_active

    def rows(self) -> list[dict]:
        with self._lock:
            return [r.as_dict() for r in self._rows.values()]

    def recent_rates(self, now: float | None = None) -> dict:
        """Per-tenant decode tokens/s and block-seconds/s over the rolling
        window (rates go to zero as an idle tenant ages out)."""
        t = time.monotonic() if now is None else now
        with self._lock:
            self._prune_locked(t)
            out: dict[str, dict] = {}
            for ts, tenant, toks, bs in self._recent:
                d = out.setdefault(tenant, {"decode_tokens": 0,
                                            "kv_block_seconds": 0.0})
                d["decode_tokens"] += toks
                d["kv_block_seconds"] += bs
        w = self.window_s
        return {k: {"decode_tokens_per_s": v["decode_tokens"] / w,
                    "kv_block_seconds_per_s": v["kv_block_seconds"] / w}
                for k, v in out.items()}


class CostMeter:
    """The metering plane: owns the ledger, the bounded-cardinality label
    map, and the ``request_cost_*`` metric family."""

    def __init__(self, registry, max_tenants: int = 32,
                 window_s: float = 300.0, top_k: int = 10,
                 fairness_weight: float = 1.0):
        self._registry = registry
        self.max_tenants = int(max_tenants)
        self.top_k = int(top_k)
        # scales the router's soft fair-share penalty (0 disables steering
        # while keeping measurement on)
        self.fairness_weight = float(fairness_weight)
        self.ledger = TenantLedger(window_s=window_s)
        # LRU of tenant -> label value actually published; once full, new
        # tenants map to OTHER_TENANT (fold counted for the docs/tests)
        self._labels: OrderedDict[str, str] = OrderedDict()
        self._lock = threading.Lock()
        self.label_folds = 0
        reg = registry
        self._c_prefill_tok = reg.counter(
            "request_cost_prefill_tokens_total",
            "prompt tokens prefilled, by tenant")
        self._c_prefill_flops = reg.counter(
            "request_cost_prefill_flops_total",
            "analytic forward FLOPs spent on prefill, by tenant")
        self._c_decode_tok = reg.counter(
            "request_cost_decode_tokens_total",
            "tokens decoded, by tenant")
        self._c_dispatches = reg.counter(
            "request_cost_decode_dispatches_total",
            "host dispatches a request participated in, by tenant")
        self._c_spec_prop = reg.counter(
            "request_cost_spec_proposed_total",
            "speculative draft tokens charged as proposed, by tenant")
        self._c_spec_acc = reg.counter(
            "request_cost_spec_accepted_total",
            "speculative draft tokens charged as accepted, by tenant")
        self._c_block_s = reg.counter(
            "request_cost_kv_block_seconds_total",
            "KV block-seconds consumed (occupancy integral), by tenant")
        self._c_retained_s = reg.counter(
            "request_cost_retained_block_seconds_total",
            "retained-prefix block-seconds credited to the publisher")
        self._c_promote_b = reg.counter(
            "request_cost_tier_promote_bytes_total",
            "KV bytes restored from lower tiers on admission, by tenant")
        self._c_demote_b = reg.counter(
            "request_cost_tier_demote_bytes_total",
            "published KV bytes demoted tier-ward, by publishing tenant")
        self._c_handoff_b = reg.counter(
            "request_cost_handoff_bytes_total",
            "KV handoff bytes moved (export + import), by tenant")
        self._c_queue_s = reg.counter(
            "request_cost_queue_wait_seconds_total",
            "seconds requests waited for admission, by tenant")
        self._c_pool_s = reg.counter(
            "request_cost_pool_block_seconds_total",
            "pool-wide busy block-seconds (the attribution denominator)")
        self._c_folds = reg.counter(
            "request_cost_label_folds_total",
            "requests whose tenant label folded into __other__")
        self._h_block_s = reg.histogram(
            "request_cost_block_seconds", "per-request KV block-seconds",
            buckets=BLOCK_SECONDS_BUCKETS)

    # ----------------------------------------------------------- label cap
    def tenant_label(self, tenant: str) -> str:
        """Bounded-cardinality label for ``tenant``: at most
        ``max_tenants`` distinct values ever reach the registry; later
        tenants fold into ``__other__`` (LRU refresh keeps hot tenants
        labeled through churn)."""
        with self._lock:
            if tenant in self._labels:
                self._labels.move_to_end(tenant)
                return tenant
            if len(self._labels) < self.max_tenants:
                self._labels[tenant] = tenant
                return tenant
            self.label_folds += 1
        self._c_folds.inc()
        return OTHER_TENANT

    # --------------------------------------------------------- accumulation
    def start(self, tenant: str, sla_class: str) -> RequestCost:
        """Fresh per-request record (attached to the engine's seq state)."""
        return RequestCost(tenant=tenant, sla_class=sla_class)

    def tick(self, dt: float, live, retained=None,
             pool_busy_blocks: int = 0) -> None:
        """Advance the occupancy integral by ``dt`` seconds.

        ``live`` iterates ``(RequestCost, n_blocks)`` for every sequence
        currently holding blocks (running, queued-with-reservation and
        parked handoffs alike); ``retained`` iterates
        ``(publisher_tenant, n_blocks)`` for refcount-0 cached blocks.
        ``pool_busy_blocks`` is the allocator's total non-free block count —
        the denominator the per-tenant integrals must sum to (the invariant
        ``tests/unit/test_costmeter.py`` pins at +-5%).
        """
        if dt <= 0.0:
            return
        outstanding: dict[str, int] = {}
        for cost, n in live:
            if n <= 0:
                continue
            cost.kv_block_seconds += n * dt
            outstanding[cost.tenant] = outstanding.get(cost.tenant, 0) + n
        if retained:
            for tenant, n in retained:
                if n <= 0:
                    continue
                self.ledger.add_retained(tenant, n * dt)
                self._c_retained_s.inc(n * dt,
                                       tenant=self.tenant_label(tenant))
                outstanding[tenant] = outstanding.get(tenant, 0) + n
        if pool_busy_blocks > 0:
            self._c_pool_s.inc(pool_busy_blocks * dt)
        self.ledger.set_outstanding(outstanding)

    def prefix_transfer(self, publisher: str, consumer: str,
                        blocks: int) -> None:
        """Cross-request prefix hit across tenants: the consumer's debit is
        the publisher's credit, block for block."""
        if blocks <= 0 or publisher == consumer:
            return
        self.ledger.transfer(publisher, consumer, blocks)

    def observe(self, cost: RequestCost) -> None:
        """Fold one finished request into the ledger and metric family."""
        self.ledger.charge(cost)
        labels = {"tenant": self.tenant_label(cost.tenant),
                  "sla_class": cost.sla_class}
        if cost.prefill_tokens:
            self._c_prefill_tok.inc(cost.prefill_tokens, **labels)
        if cost.prefill_flops:
            self._c_prefill_flops.inc(cost.prefill_flops, **labels)
        if cost.decode_tokens:
            self._c_decode_tok.inc(cost.decode_tokens, **labels)
        if cost.decode_dispatches:
            self._c_dispatches.inc(cost.decode_dispatches, **labels)
        if cost.spec_proposed:
            self._c_spec_prop.inc(cost.spec_proposed, **labels)
        if cost.spec_accepted:
            self._c_spec_acc.inc(cost.spec_accepted, **labels)
        self._c_block_s.inc(cost.kv_block_seconds, **labels)
        if cost.tier_promote_bytes:
            self._c_promote_b.inc(cost.tier_promote_bytes, **labels)
        if cost.tier_demote_bytes:
            # demotions are publisher-attributed, not class-attributed
            self._c_demote_b.inc(cost.tier_demote_bytes,
                                 tenant=labels["tenant"])
        if cost.handoff_export_bytes or cost.handoff_import_bytes:
            self._c_handoff_b.inc(cost.handoff_export_bytes
                                  + cost.handoff_import_bytes, **labels)
        if cost.queue_wait_s:
            self._c_queue_s.inc(cost.queue_wait_s, **labels)
        self._h_block_s.observe(cost.kv_block_seconds, **labels)

    def demote_bytes(self, tenant: str, nbytes: int) -> None:
        """Tier demotion happens after the publishing request finished, so
        it is charged straight to the ledger/counters, not a RequestCost."""
        if nbytes <= 0:
            return
        with self.ledger._lock:
            self.ledger._row_locked(tenant).tier_demote_bytes += nbytes
        self._c_demote_b.inc(nbytes, tenant=self.tenant_label(tenant))

    # ----------------------------------------------------- routing signal
    def outstanding_share(self, tenant: str) -> tuple[float, float]:
        return self.ledger.outstanding_share(tenant)

    # ------------------------------------------------------------- payload
    def debug_payload(self) -> dict:
        """JSON-serializable breakdown for ``GET /debug/tenants``: every
        ledger row plus the top-K tenants by cumulative block-seconds."""
        rows = self.ledger.rows()
        rows.sort(key=lambda r: r["kv_block_seconds"], reverse=True)
        pool_s = self._c_pool_s.value()
        return {
            "enabled": True,
            "tenants": {r["tenant"]: r for r in rows},
            "top_by_block_seconds": [
                {"tenant": r["tenant"],
                 "kv_block_seconds": r["kv_block_seconds"]}
                for r in rows[:self.top_k]],
            "pool_block_seconds": round(pool_s, 6),
            "recent_rates": self.ledger.recent_rates(),
            "distinct_tenant_labels": len(self._labels),
            "label_folds": self.label_folds,
            "max_tenant_labels": self.max_tenants,
        }
