"""Process-local metrics registry: counters, gauges, histograms with labels.

The registry is the shared data model under every exporter (JSONL, Prometheus,
monitor bridge — see :mod:`deepspeed_tpu.telemetry.exporters`): emit points
mutate typed metrics here; exporters only ever *read*. Metric updates are
lock-protected so the Prometheus HTTP thread can render a consistent snapshot
while the training loop mutates concurrently.

Naming follows Prometheus conventions (``snake_case``, ``_total`` counters,
``_seconds``/``_bytes`` units); labels keep cardinality bounded (op names,
span names — never uids or step numbers).
"""

from __future__ import annotations

import re
import threading
from bisect import bisect_left

# latency-oriented default buckets (seconds): sub-ms dispatches up to
# multi-minute checkpoint flushes
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

# pow2 byte-scale buckets for ``*_bytes`` histograms (1 KiB .. 64 GiB):
# staging uploads, KV transfers, checkpoint fragments, OOM-adjacent
# allocation sizes — the seconds-scale defaults would collapse every
# observation into +Inf
BYTE_BUCKETS = tuple(float(2 ** p) for p in range(10, 37, 2))

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_RE = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_metric_name(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(value: float) -> str:
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(key: tuple, extra: tuple = ()) -> str:
    pairs = [f'{_LABEL_RE.sub("_", k)}="{_escape_label_value(v)}"'
             for k, v in (*key, *extra)]
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = sanitize_metric_name(name)
        self.help = help
        self._lock = threading.Lock()
        self._series: dict[tuple, object] = {}

    def clear(self) -> None:
        with self._lock:
            self._series.clear()


class Counter(_Metric):
    """Monotonically increasing value per label set."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment {amount}")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return float(self._series.get(_label_key(labels), 0.0))

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [{"labels": dict(k), "value": v}
                    for k, v in sorted(self._series.items())]

    def render(self) -> list[str]:
        with self._lock:
            return [f"{self.name}{_render_labels(k)} {_fmt(v)}"
                    for k, v in sorted(self._series.items())]


class Gauge(_Metric):
    """Last-write-wins value per label set."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        return float(self._series.get(_label_key(labels), 0.0))

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [{"labels": dict(k), "value": v}
                    for k, v in sorted(self._series.items())]

    def render(self) -> list[str]:
        with self._lock:
            return [f"{self.name}{_render_labels(k)} {_fmt(v)}"
                    for k, v in sorted(self._series.items())]


class Histogram(_Metric):
    """Fixed-bucket distribution per label set (count/sum + per-bucket counts)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", buckets=DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram {self.name}: needs at least one bucket")

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        key = _label_key(labels)
        with self._lock:
            state = self._series.get(key)
            if state is None:
                # [per-bucket counts..., +Inf count], sum, count
                state = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._series[key] = state
            state[0][bisect_left(self.buckets, value)] += 1
            state[1] += value
            state[2] += 1

    def count(self, **labels) -> int:
        state = self._series.get(_label_key(labels))
        return int(state[2]) if state else 0

    def sum(self, **labels) -> float:
        state = self._series.get(_label_key(labels))
        return float(state[1]) if state else 0.0

    def snapshot(self) -> list[dict]:
        with self._lock:
            out = []
            for k, (counts, total, n) in sorted(self._series.items()):
                cum, buckets = 0, {}
                for le, c in zip(self.buckets, counts):
                    cum += c
                    buckets[repr(float(le))] = cum
                buckets["+Inf"] = n
                out.append({"labels": dict(k), "count": n, "sum": total,
                            "buckets": buckets})
            return out

    def render(self) -> list[str]:
        with self._lock:
            lines = []
            for k, (counts, total, n) in sorted(self._series.items()):
                cum = 0
                for le, c in zip(self.buckets, counts):
                    cum += c
                    lines.append(
                        f"{self.name}_bucket"
                        f"{_render_labels(k, (('le', _fmt(le)),))} {cum}")
                lines.append(
                    f"{self.name}_bucket{_render_labels(k, (('le', '+Inf'),))} {n}")
                lines.append(f"{self.name}_sum{_render_labels(k)} {_fmt(total)}")
                lines.append(f"{self.name}_count{_render_labels(k)} {n}")
            return lines


class MetricsRegistry:
    """Get-or-create metric store; the single source every exporter reads."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help: str, **kw):
        name = sanitize_metric_name(name)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def snapshot(self) -> dict:
        with self._lock:
            metrics = list(self._metrics.values())
        return {
            m.name: {"kind": m.kind, "help": m.help, "series": m.snapshot()}
            for m in metrics
        }

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
