"""Device-timeline profiler: measured overlap, wire time, kernel attribution.

Everything stepscope reports about the device is estimated from host-side
timestamps and an analytic wire-time model.  This module captures the actual
device timeline through ``jax.profiler.start_trace``/``stop_trace`` over a
bounded window, classifies every XLA op (collective / compute / copy /
infeed-outfeed), and derives *measured* metrics from the classified intervals:

- ``train_overlap_fraction{source="measured"}`` — collective time overlapped
  with compute divided by total collective time (interval-union math, not
  per-op pairing);
- per-collective wire-time histograms (``devprof_collective_seconds{op=}``);
- H2D/D2H copy seconds, device idle/bubble fraction, and a top-K op table.

Captured device ops are also merged as spans into the host Perfetto trace
ring (telemetry.tracing), parented under the smallest stepscope phase span
that contains them, so host phases and device kernels render as one nested
timeline in ``chrome://tracing`` / Perfetto.

Three triggers exist upstream of this module: the training engine captures a
window every ``telemetry.stepscope.profile_interval_steps`` steps, the
serving frontend exposes ``GET /debug/profile?steps=N`` (via
:func:`capture_serving`), and ``bench.py --mode train-anatomy`` reports
measured-vs-estimated overlap side by side.

Design constraints honoured here:

- **Single capture per process.**  jax allows one active profiler session;
  a module-level non-blocking lock models that, and doubles as the
  concurrent-capture rejection for ``/debug/profile`` (HTTP 409).
- **Backend-independent parser.**  The Chrome-trace parser and all derived
  math are pure stdlib — CPU-only CI exercises the full path against real
  CPU captures and a checked-in synthetic fixture.
- **Zero allocation when off.**  Nothing in this module runs on the hot path
  unless a capture window is open; the engine guards every call site on a
  plain attribute check (pinned by tracemalloc in tests/unit/test_devprof.py).
- **Bounded disk.**  Capture dirs default under ``runs/`` (gitignored) and
  are rotated: at most ``keep`` capture subdirectories survive.

Clock alignment: trace-event timestamps live in the profiler's own
microsecond epoch.  ``begin()`` emits a ``jax.profiler.TraceAnnotation``
anchor and records ``time.perf_counter()`` at that instant; the parser finds
the anchor event and shifts every device op by
``t_anchor_host − anchor_ts_us·1e-6`` so device spans land in the same
perf_counter domain the host Tracer ring uses.
"""

from __future__ import annotations

import glob
import gzip
import json
import logging
import os
import shutil
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.telemetry.tracing import TraceContext, Tracer, _new_span_id

logger = logging.getLogger(__name__)

ANCHOR_NAME = "devprof/anchor"

# One jax profiler session may exist per process (jax raises on a second
# start_trace).  This lock models that limit and backs the HTTP 409 path.
_CAPTURE_LOCK = threading.Lock()

#: Wire-time histogram buckets.  Collective device ops run µs→s; the default
#: telemetry latency buckets start at 0.5 ms and would collapse everything
#: into one bucket on small models.
COLLECTIVE_BUCKETS = (
    1e-6, 5e-6, 1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3,
    1e-2, 5e-2, 0.1, 0.5, 1.0, 5.0,
)

# Op families, matched as prefixes of the normalised family name (lowercase,
# '%' and trailing '.<id>' / '-start' / '-done' stripped).
_COLLECTIVE_PREFIXES = (
    "all-reduce", "allreduce",
    "all-gather", "allgather",
    "reduce-scatter", "reducescatter",
    "all-to-all", "alltoall",
    "collective-permute", "collectivepermute",
    "collective-broadcast",
    "psum", "pmean", "ppermute",
    "send", "recv",
)
_COPY_PREFIXES = ("copy", "memcpy", "transpose-copy", "dynamic-memcpy")
_INFEED_PREFIXES = ("infeed", "outfeed", "host-transfer")

CLASS_COLLECTIVE = "collective"
CLASS_COMPUTE = "compute"
CLASS_COPY = "copy"
CLASS_INFEED = "infeed_outfeed"
OP_CLASSES = (CLASS_COLLECTIVE, CLASS_COMPUTE, CLASS_COPY, CLASS_INFEED)


# --------------------------------------------------------------------------
# Op-name heuristics
# --------------------------------------------------------------------------

def op_family(name: str) -> str:
    """Collapse an HLO op instance name to its bounded-cardinality family.

    ``%all-gather-start.3`` → ``all-gather``; ``fusion.12`` → ``fusion``;
    ``MemcpyH2D`` → ``memcpyh2d``.  Families are what metric labels and
    merged span names are keyed on, so they must stay bounded.
    """
    fam = name.strip().lower().lstrip("%")
    # strip trailing ".<digits>" instance id
    dot = fam.rfind(".")
    if dot > 0 and fam[dot + 1:].isdigit():
        fam = fam[:dot]
    for suffix in ("-start", "-done"):
        if fam.endswith(suffix):
            fam = fam[: -len(suffix)]
    return fam or "unknown"


def classify_op(name: str) -> str:
    """Classify a device op name into collective / compute / copy / infeed."""
    fam = op_family(name)
    for p in _INFEED_PREFIXES:
        if fam.startswith(p):
            return CLASS_INFEED
    for p in _COLLECTIVE_PREFIXES:
        if fam.startswith(p):
            return CLASS_COLLECTIVE
    for p in _COPY_PREFIXES:
        if fam.startswith(p):
            return CLASS_COPY
    if "h2d" in fam or "d2h" in fam:
        return CLASS_COPY
    return CLASS_COMPUTE


def _copy_direction(fam: str) -> str:
    if "h2d" in fam:
        return "h2d"
    if "d2h" in fam:
        return "d2h"
    return "device"


# --------------------------------------------------------------------------
# Chrome-trace parsing (pure stdlib; exercised against the synthetic fixture)
# --------------------------------------------------------------------------

def parse_chrome_trace(
    trace: Dict[str, Any], anchor_name: str = ANCHOR_NAME
) -> Tuple[List[Dict[str, Any]], Optional[float]]:
    """Walk Chrome trace events and extract the device-op timeline.

    Returns ``(ops, anchor_ts_us)``.  Each op is a dict with keys
    ``name``/``family``/``cls``/``t0``/``t1`` where t0/t1 are seconds in the
    trace's own epoch (shift with :func:`shift_ops` to align clocks).

    A complete event counts as a device op when it carries an ``hlo_op``
    arg (how jax tags XLA ops on CPU/GPU) or when it sits on a thread named
    ``XLA Ops`` of a ``/device:`` process (how TPU device tracks look).
    Restricting the device-pid rule to the "XLA Ops" lane avoids
    double-counting the aggregate "Steps"/"XLA Modules" lanes.
    """
    events = trace.get("traceEvents") or []
    proc_names: Dict[Any, str] = {}
    thread_names: Dict[Tuple[Any, Any], str] = {}
    for ev in events:
        if ev.get("ph") != "M":
            continue
        args = ev.get("args") or {}
        if ev.get("name") == "process_name":
            proc_names[ev.get("pid")] = str(args.get("name", ""))
        elif ev.get("name") == "thread_name":
            thread_names[(ev.get("pid"), ev.get("tid"))] = str(args.get("name", ""))

    ops: List[Dict[str, Any]] = []
    anchor_ts_us: Optional[float] = None
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = ev.get("name")
        ts = ev.get("ts")
        if name is None or ts is None:
            continue
        if name == anchor_name:
            anchor_ts_us = float(ts)
            continue
        args = ev.get("args") or {}
        hlo = args.get("hlo_op")
        if hlo is None:
            pid = ev.get("pid")
            tid = ev.get("tid")
            if "/device:" not in proc_names.get(pid, ""):
                continue
            if "xla ops" not in thread_names.get((pid, tid), "").lower():
                continue
            op_name = str(name)
        else:
            op_name = str(hlo)
        dur = float(ev.get("dur", 0.0) or 0.0)
        if dur <= 0.0:
            continue
        t0 = float(ts) * 1e-6
        fam = op_family(op_name)
        ops.append(
            {
                "name": op_name,
                "family": fam,
                "cls": classify_op(op_name),
                "t0": t0,
                "t1": t0 + dur * 1e-6,
            }
        )
    ops.sort(key=lambda o: o["t0"])
    return ops, anchor_ts_us


def shift_ops(ops: List[Dict[str, Any]], offset_s: float) -> List[Dict[str, Any]]:
    """Shift op timestamps in place by ``offset_s`` (trace → host clock)."""
    for op in ops:
        op["t0"] += offset_s
        op["t1"] += offset_s
    return ops


def load_trace_dir(trace_dir: str) -> Tuple[Optional[Dict[str, Any]], Optional[str]]:
    """Load the newest ``*.trace.json[.gz]`` written under ``trace_dir``.

    jax writes ``<dir>/plugins/profile/<timestamp>/<host>.trace.json.gz``;
    we also accept a flat layout for tests.  Returns ``(trace, path)`` or
    ``(None, None)`` when nothing parseable exists.
    """
    patterns = (
        os.path.join(trace_dir, "plugins", "profile", "*", "*.trace.json.gz"),
        os.path.join(trace_dir, "plugins", "profile", "*", "*.trace.json"),
        os.path.join(trace_dir, "*.trace.json.gz"),
        os.path.join(trace_dir, "*.trace.json"),
    )
    candidates: List[str] = []
    for pat in patterns:
        candidates.extend(glob.glob(pat))
    if not candidates:
        return None, None
    path = max(candidates, key=os.path.getmtime)
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt", encoding="utf-8") as f:
                return json.load(f), path
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f), path
    except (OSError, ValueError) as exc:  # truncated/corrupt capture
        logger.warning("devprof: failed to load trace %s: %s", path, exc)
        return None, None


# --------------------------------------------------------------------------
# Interval math + derived timeline metrics
# --------------------------------------------------------------------------

def _union(intervals: Sequence[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Merge overlapping/touching intervals; returns sorted disjoint spans."""
    if not intervals:
        return []
    ivs = sorted(intervals)
    out = [list(ivs[0])]
    for a, b in ivs[1:]:
        if a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1][1] = b
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def _union_len(intervals: Sequence[Tuple[float, float]]) -> float:
    return sum(b - a for a, b in _union(intervals))


def _intersect_len(iv: Tuple[float, float], union: Sequence[Tuple[float, float]]) -> float:
    a, b = iv
    total = 0.0
    for u0, u1 in union:
        if u1 <= a:
            continue
        if u0 >= b:
            break
        total += min(b, u1) - max(a, u0)
    return total


def derive_timeline(
    ops: Sequence[Dict[str, Any]],
    window: Optional[Tuple[float, float]] = None,
    top_k: int = 12,
) -> Dict[str, Any]:
    """Derive measured metrics from a classified device-op timeline.

    ``overlap_fraction_measured`` is interval-union math: the union of
    compute intervals is intersected with each collective interval; the
    fraction is overlapped-collective-time / total-collective-time.  With no
    collectives in the window (single-device runs) it is vacuously 1.0 —
    there is no wire time to expose.
    """
    class_ivs: Dict[str, List[Tuple[float, float]]] = {c: [] for c in OP_CLASSES}
    class_ops: Dict[str, int] = {c: 0 for c in OP_CLASSES}
    fam_seconds: Dict[str, Dict[str, Any]] = {}
    copy_seconds = {"h2d": 0.0, "d2h": 0.0, "device": 0.0}
    for op in ops:
        cls = op["cls"]
        iv = (op["t0"], op["t1"])
        class_ivs[cls].append(iv)
        class_ops[cls] += 1
        fam = op["family"]
        slot = fam_seconds.setdefault(fam, {"op": fam, "class": cls, "seconds": 0.0, "count": 0})
        slot["seconds"] += iv[1] - iv[0]
        slot["count"] += 1
        if cls == CLASS_COPY:
            copy_seconds[_copy_direction(fam)] += iv[1] - iv[0]

    class_seconds = {c: _union_len(class_ivs[c]) for c in OP_CLASSES}
    compute_union = _union(class_ivs[CLASS_COMPUTE])
    collective_s = sum(b - a for a, b in class_ivs[CLASS_COLLECTIVE])
    overlapped_s = sum(
        _intersect_len(iv, compute_union) for iv in class_ivs[CLASS_COLLECTIVE]
    )
    overlap = (overlapped_s / collective_s) if collective_s > 0.0 else 1.0

    all_ivs = [iv for ivs in class_ivs.values() for iv in ivs]
    busy_s = _union_len(all_ivs)
    if window is None and all_ivs:
        window = (min(a for a, _ in all_ivs), max(b for _, b in all_ivs))
    window_s = (window[1] - window[0]) if window else 0.0
    idle_fraction = (
        max(0.0, 1.0 - busy_s / window_s) if window_s > 0.0 else 0.0
    )

    top_ops = sorted(fam_seconds.values(), key=lambda s: s["seconds"], reverse=True)[:top_k]
    collectives = [s for s in fam_seconds.values() if s["class"] == CLASS_COLLECTIVE]
    collectives.sort(key=lambda s: s["seconds"], reverse=True)
    return {
        "op_count": len(ops),
        "window_s": window_s,
        "device_busy_s": busy_s,
        "idle_fraction": idle_fraction,
        "class_seconds": class_seconds,
        "class_ops": class_ops,
        "collective_seconds": collective_s,
        "collective_overlapped_seconds": overlapped_s,
        "overlap_fraction_measured": overlap,
        "copy_seconds": copy_seconds,
        "top_ops": top_ops,
        "collectives": collectives,
    }


# --------------------------------------------------------------------------
# Merging device ops into the host Perfetto trace ring
# --------------------------------------------------------------------------

_HOST_PARENT_PREFIXES = ("train/phase/", "train/step", "engine/", "request/")


def merge_into_ring(
    tracer: Optional[Tracer],
    ops: Sequence[Dict[str, Any]],
    max_ops: int = 768,
) -> int:
    """Retro-record device ops as spans in the host trace ring.

    Each op is parented under the *smallest* host span (stepscope phase,
    step, or serving span) whose interval contains the op's midpoint, so the
    Perfetto export nests device kernels under the owning host phase.  Ops
    with no containing host span hang off a synthetic ``device/window``
    root.  At most ``max_ops`` ops are merged (largest by duration) so a
    dense capture cannot evict the host spans from the bounded ring.
    """
    if tracer is None or not tracer.enabled or not ops:
        return 0
    hosts = [
        s
        for s in tracer.snapshot()
        if s["name"].startswith(_HOST_PARENT_PREFIXES)
    ]
    host_ivs = [(s["t0"], s["t0"] + s["dur_s"], s) for s in hosts]
    sel = sorted(ops, key=lambda o: o["t1"] - o["t0"], reverse=True)[:max_ops]
    sel.sort(key=lambda o: o["t0"])

    orphan_ctx: Optional[TraceContext] = None
    orphan_window: Optional[List[float]] = None
    merged = 0
    for op in sel:
        mid = 0.5 * (op["t0"] + op["t1"])
        best = None
        best_dur = float("inf")
        for h0, h1, span in host_ivs:
            if h0 <= mid <= h1 and (h1 - h0) < best_dur:
                best, best_dur = span, h1 - h0
        if best is not None:
            ctx = TraceContext(best["trace_id"], _new_span_id(), best["span_id"])
        else:
            if orphan_ctx is None:
                orphan_ctx = TraceContext(uuid.uuid4().hex, _new_span_id(), None)
                orphan_window = [op["t0"], op["t1"]]
            orphan_window[0] = min(orphan_window[0], op["t0"])
            orphan_window[1] = max(orphan_window[1], op["t1"])
            ctx = TraceContext(orphan_ctx.trace_id, _new_span_id(), orphan_ctx.span_id)
        tracer.finish(
            ctx,
            f"device/{op['cls']}/{op['family']}",
            op["t0"],
            op["t1"],
            hlo_op=op["name"],
            device=True,
        )
        merged += 1
    if orphan_ctx is not None:
        tracer.finish(
            orphan_ctx, "device/window", orphan_window[0], orphan_window[1], device=True
        )
    return merged


# --------------------------------------------------------------------------
# Capture driver
# --------------------------------------------------------------------------

class DeviceProfiler:
    """On-demand bounded-window device capture with rotation and metrics.

    Lifecycle: ``begin()`` (acquires the process-wide capture slot, starts
    the jax trace, stamps the clock anchor) → ``stop()`` (ends the jax
    session; call after settling the step so the window closes cleanly) →
    ``finish()`` (parse, derive, export metrics, merge into the trace ring,
    rotate old capture dirs, release the slot).  ``end()`` is
    stop+finish for one-shot use.  All methods are safe to call when no
    capture is active.
    """

    def __init__(
        self,
        telemetry: Any = None,
        out_dir: str = os.path.join("runs", "devprof"),
        keep: int = 4,
        merge_max_ops: int = 768,
    ) -> None:
        self.telemetry = telemetry
        self.out_dir = out_dir
        self.keep = max(1, int(keep))
        self.merge_max_ops = int(merge_max_ops)
        self.capturing = False
        self._stopped = False
        self._seq = 0
        self._dir: Optional[str] = None
        self._tag = "capture"
        self._t_anchor = 0.0
        self._t_begin = 0.0
        self._t_stop = 0.0
        self.last: Optional[Dict[str, Any]] = None

    # -- lifecycle -----------------------------------------------------

    def begin(self, tag: str = "capture") -> bool:
        """Start a capture window; False if one is already active anywhere."""
        if self.capturing:
            return False
        if not _CAPTURE_LOCK.acquire(blocking=False):
            self._count_rejected(tag)
            return False
        self._seq += 1
        # pid-scoped dir name: multiple worker processes sharing one
        # runs/devprof must never collide on cap-{seq} (each process's
        # sequence starts at 1), and rotation below stays per-worker
        cap_dir = os.path.join(self.out_dir,
                               f"cap-{os.getpid()}-{self._seq:06d}")
        try:
            import jax

            os.makedirs(cap_dir, exist_ok=True)
            jax.profiler.start_trace(cap_dir)
            with jax.profiler.TraceAnnotation(ANCHOR_NAME):
                self._t_anchor = time.perf_counter()
        except Exception as exc:  # another session (StepTracer) or no backend
            logger.warning("devprof: start_trace failed (%s); capture skipped", exc)
            shutil.rmtree(cap_dir, ignore_errors=True)
            _CAPTURE_LOCK.release()
            self._count_rejected(tag)
            return False
        self._dir = cap_dir
        self._tag = tag
        self._t_begin = time.perf_counter()
        self._stopped = False
        self.capturing = True
        return True

    def stop(self) -> None:
        """End the jax profiler session (parse deferred to ``finish``)."""
        if not self.capturing or self._stopped:
            return
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as exc:
            logger.warning("devprof: stop_trace failed: %s", exc)
        self._t_stop = time.perf_counter()
        self._stopped = True

    def finish(self, kind: str = "train", tracer: Optional[Tracer] = None) -> Optional[Dict[str, Any]]:
        """Parse the closed window, export metrics, merge, rotate, release."""
        if not self.capturing:
            return None
        if not self._stopped:
            self.stop()
        self.capturing = False
        self._stopped = False
        try:
            trace, path = load_trace_dir(self._dir)
            ops: List[Dict[str, Any]] = []
            anchor_us: Optional[float] = None
            if trace is not None:
                ops, anchor_us = parse_chrome_trace(trace)
            if ops:
                if anchor_us is not None:
                    shift_ops(ops, self._t_anchor - anchor_us * 1e-6)
                else:
                    # no anchor event survived; pin the window end to stop()
                    shift_ops(ops, self._t_stop - max(o["t1"] for o in ops))
            summary = derive_timeline(ops)
            summary["wall_window_s"] = max(0.0, self._t_stop - self._t_begin)
            summary["trigger"] = self._tag
            self._export_metrics(summary, ops, kind)
            merged = 0
            tr = tracer
            if tr is None and self.telemetry is not None:
                tr = getattr(self.telemetry, "tracer", None)
            if tr is not None:
                merged = merge_into_ring(tr, ops, self.merge_max_ops)
            self.last = {
                "kind": kind,
                "summary": summary,
                "ops": ops,
                "merged_spans": merged,
                "trace_path": path,
                "trace_dir": self._dir,
            }
            self._rotate()
            return self.last
        finally:
            self._dir = None
            _CAPTURE_LOCK.release()

    def end(self, kind: str = "train", tracer: Optional[Tracer] = None) -> Optional[Dict[str, Any]]:
        """Convenience: ``stop()`` then ``finish()``."""
        if not self.capturing:
            return None
        self.stop()
        return self.finish(kind=kind, tracer=tracer)

    def abort(self) -> None:
        """Tear down an open window without parsing (error paths)."""
        if not self.capturing:
            return
        self.stop()
        self.capturing = False
        self._stopped = False
        if self._dir:
            shutil.rmtree(self._dir, ignore_errors=True)
            self._dir = None
        _CAPTURE_LOCK.release()

    # -- internals -----------------------------------------------------

    def _rotate(self) -> None:
        # per-worker rotation: only THIS process's captures are eligible —
        # a sibling worker profiling into the same shared dir must never
        # have its captures deleted out from under it
        try:
            caps = sorted(glob.glob(
                os.path.join(self.out_dir, f"cap-{os.getpid()}-*")))
            for stale in caps[: -self.keep]:
                shutil.rmtree(stale, ignore_errors=True)
        except OSError:
            pass

    def _count_rejected(self, tag: str) -> None:
        tel = self.telemetry
        if tel is not None and getattr(tel, "enabled", False):
            tel.registry.counter(
                "devprof_captures_rejected_total",
                "Capture attempts rejected because a profiler session was active.",
            ).inc(1, trigger=tag)

    def _export_metrics(self, summary: Dict[str, Any], ops: Sequence[Dict[str, Any]], kind: str) -> None:
        tel = self.telemetry
        if tel is None or not getattr(tel, "enabled", False):
            return
        reg = tel.registry
        reg.counter(
            "devprof_captures_total", "Completed device-profile capture windows."
        ).inc(1, trigger=summary.get("trigger", "capture"))
        reg.gauge(
            "devprof_overlap_fraction",
            "Measured collective/compute overlap fraction from the last capture.",
        ).set(summary["overlap_fraction_measured"], kind=kind)
        reg.gauge(
            "devprof_idle_fraction",
            "Device idle/bubble fraction of the last capture window.",
        ).set(summary["idle_fraction"], kind=kind)
        g_class = reg.gauge(
            "devprof_class_seconds",
            "Busy seconds per op class in the last capture window.",
        )
        for cls, secs in summary["class_seconds"].items():
            g_class.set(secs, **{"class": cls, "kind": kind})
        c_ops = reg.counter(
            "devprof_ops_total", "Device ops observed across capture windows."
        )
        for cls, n in summary["class_ops"].items():
            if n:
                c_ops.inc(n, **{"class": cls})
        h_coll = reg.histogram(
            "devprof_collective_seconds",
            "Per-collective device wire time (one observation per op).",
            buckets=COLLECTIVE_BUCKETS,
        )
        for op in ops:
            if op["cls"] == CLASS_COLLECTIVE:
                h_coll.observe(op["t1"] - op["t0"], op=op["family"])
        c_copy = reg.counter(
            "devprof_copy_seconds_total", "Copy seconds by direction across captures."
        )
        for direction, secs in summary["copy_seconds"].items():
            if secs:
                c_copy.inc(secs, direction=direction)
        g_top = reg.gauge(
            "devprof_top_op_seconds",
            "Seconds per op family (top-K of the last capture window).",
        )
        for slot in summary["top_ops"]:
            g_top.set(slot["seconds"], op=slot["op"])
        if kind == "train":
            reg.gauge(
                "train_overlap_fraction",
                "Fraction of collective time hidden under compute.",
            ).set(summary["overlap_fraction_measured"], source="measured")


# --------------------------------------------------------------------------
# Serving-side capture (GET /debug/profile)
# --------------------------------------------------------------------------

def capture_serving(
    loops: Sequence[Any],
    steps: int = 8,
    max_wait_s: float = 5.0,
    poll_s: float = 0.005,
    telemetry: Any = None,
    out_dir: str = os.path.join("runs", "devprof"),
    profiler: Optional[DeviceProfiler] = None,
) -> Optional[Dict[str, Any]]:
    """Capture a device window spanning ~``steps`` engine-loop steps.

    Polls the loops' step counters until the requested number of steps has
    elapsed or ``max_wait_s`` passes (idle engines produce an empty but
    valid capture).  Returns a JSON-serializable summary, or None when a
    capture is already in progress (the frontend maps that to HTTP 409).
    """
    prof = profiler or DeviceProfiler(telemetry=telemetry, out_dir=out_dir)

    def _count() -> int:
        return sum(int(getattr(lp, "steps", 0)) for lp in loops)

    base = _count()
    if not prof.begin(tag="http"):
        return None
    t0 = time.perf_counter()
    deadline = t0 + max(0.05, max_wait_s)
    while time.perf_counter() < deadline and _count() - base < steps:
        time.sleep(poll_s)
    observed = _count() - base
    prof.stop()
    res = prof.finish(kind="serving")
    if res is None:
        return None
    return {
        "enabled": True,
        "trigger": "http",
        "requested_steps": int(steps),
        "observed_steps": int(observed),
        "wait_s": round(time.perf_counter() - t0, 6),
        "summary": res["summary"],
        "merged_spans": res["merged_spans"],
        "trace_dir": res["trace_dir"],
    }
