"""Request tracing: W3C trace context, a bounded span ring, Chrome export.

PR 1's span log is *flat* — one record per request at completion — which
answers "how slow" but never "why": a slow completion cannot be decomposed
into queue wait → admission → prefill chunks → decode dispatches → readback.
This module adds the causal layer. A trace is a tree of spans sharing one
128-bit trace id; the serving frontend accepts/creates a ``traceparent``
header (W3C Trace Context), the context threads through router → engine
loop → ragged engine, and every stage records its spans retroactively from
``time.perf_counter()`` stamps it already takes.

Design constraints, in order:

- **Off is free.** The default is off; every emit point guards on a single
  ``tracer.enabled`` attribute read (or a ``seq.trace is not None`` check on
  state that is only ever set while tracing), so the ragged dispatch hot
  path performs zero additional allocations per step — pinned by
  ``tests/unit/test_request_tracing.py``.
- **Bounded.** Finished spans land in a ring (``collections.deque`` with
  ``maxlen``); a forgotten tracer can never OOM a serving replica. Sampling
  is head-based: the keep/drop decision is made once when the trace starts
  (or inherited from the upstream ``traceparent`` sampled flag) and the
  whole tree follows it — no partial trees.
- **Retro-recorded.** Spans are appended *finished* (t0, t1 pairs), so no
  open-span registry is held across threads and a crashed request leaks
  nothing.

Export is Chrome trace-event JSON (``ph: "X"`` complete events, microsecond
timestamps) loadable directly in Perfetto / ``chrome://tracing``, via
``Telemetry.dump_trace()`` or the serving frontend's ``GET /debug/trace``.
Every finished span also feeds the ``trace_span_seconds{name=}`` histogram
in the metrics registry, so span latencies are queryable from Prometheus
without pulling trace JSON.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

# spec: all-zero ids are invalid; version ff is reserved
_ZERO_TRACE = "0" * 32
_ZERO_SPAN = "0" * 16


class TraceContext:
    """One node of a trace tree: (trace_id, span_id, parent_id).

    Handed to a stage *before* its span is recorded so children created
    meanwhile can parent to it — record order is irrelevant to the export.
    """

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: str | None = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"TraceContext({self.trace_id[:8]}…, span={self.span_id}, "
                f"parent={self.parent_id})")


def parse_traceparent(header) -> tuple[str, str, bool] | None:
    """``(trace_id, parent_span_id, sampled)`` from a W3C ``traceparent``
    header, or None if the header is absent/malformed (per spec a broken
    header is ignored and a fresh trace may be started)."""
    if not header or not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == _ZERO_TRACE or span_id == _ZERO_SPAN:
        return None
    return trace_id, span_id, bool(int(flags, 16) & 0x01)


def format_traceparent(ctx: TraceContext, sampled: bool = True) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if sampled else '00'}"


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class Tracer:
    """Process-local span recorder (owned by the ``Telemetry`` singleton).

    All methods are safe to call with the tracer disabled — they return
    None/no-op — but hot paths should guard on ``tracer.enabled`` (one
    attribute read) and skip even the call.
    """

    def __init__(self, registry):
        self.enabled = False
        self.registry = registry
        self.sample_rate = 1.0
        self._ring: deque = deque(maxlen=4096)
        # Perfetto counter-track samples (ph "C" on export): bounded like
        # the span ring so a forgotten tracer can never grow without limit
        self._counters: deque = deque(maxlen=4096)
        self._lock = threading.Lock()
        self._sample_n = 0
        # perf_counter <-> wall-clock anchor for export timestamps
        self._epoch_pc = time.perf_counter()
        self._epoch_unix = time.time()

    # ------------------------------------------------------------ configure
    def configure(self, enabled: bool = True, sample_rate: float = 1.0,
                  ring_capacity: int = 4096) -> "Tracer":
        with self._lock:
            self.enabled = bool(enabled)
            self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
            cap = max(1, int(ring_capacity))
            if cap != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=cap)
            self._sample_n = 0
            self._epoch_pc = time.perf_counter()
            self._epoch_unix = time.time()
        return self

    def reset(self) -> None:
        with self._lock:
            self.enabled = False
            self.sample_rate = 1.0
            self._ring.clear()
            self._counters.clear()
            self._sample_n = 0

    # -------------------------------------------------------------- context
    def _head_sampled(self) -> bool:
        """Deterministic head sampler: admits ``ceil(rate * n)`` of the
        first n roots (no RNG, so tests and replays are stable)."""
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        n = self._sample_n
        self._sample_n = n + 1
        return int((n + 1) * self.sample_rate) > int(n * self.sample_rate)

    def extract(self, traceparent: str | None = None) -> TraceContext | None:
        """Context for a new *server-side root span* from an incoming
        ``traceparent`` header (or None to head-sample a fresh trace).

        Returns None when tracing is off, the upstream explicitly opted out
        (sampled flag 0 — head-based sampling honors the caller's decision),
        or the head sampler drops the trace. A returned context's span id is
        pre-allocated: record children under it first, then ``finish`` it.
        """
        if not self.enabled:
            return None
        parsed = parse_traceparent(traceparent)
        if parsed is not None:
            trace_id, parent_span, sampled = parsed
            if not sampled:
                return None
            return TraceContext(trace_id, _new_span_id(), parent_span)
        if not self._head_sampled():
            return None
        return TraceContext(uuid.uuid4().hex, _new_span_id(), None)

    def begin(self, parent: TraceContext | None) -> TraceContext | None:
        """Allocate a child context under ``parent`` (None passes through,
        so call sites can chain without re-guarding)."""
        if parent is None or not self.enabled:
            return None
        return TraceContext(parent.trace_id, _new_span_id(), parent.span_id)

    # ------------------------------------------------------------ recording
    def finish(self, ctx: TraceContext | None, name: str, t0: float,
               t1: float, **attrs) -> None:
        """Append one finished span for a pre-allocated context. ``t0``/
        ``t1`` are ``time.perf_counter()`` stamps; attrs must be
        JSON-serializable and low-cardinality enough to read."""
        if ctx is None or not self.enabled:
            return
        dur = max(0.0, t1 - t0)
        self._ring.append({
            "name": name, "trace_id": ctx.trace_id, "span_id": ctx.span_id,
            "parent_id": ctx.parent_id, "t0": t0, "dur_s": dur,
            "tid": threading.get_ident(),
            "attrs": {k: v for k, v in attrs.items() if v is not None},
        })
        self.registry.histogram(
            "trace_span_seconds",
            "traced span durations by span name").observe(dur, name=name)

    def record(self, parent: TraceContext | None, name: str, t0: float,
               t1: float, **attrs) -> TraceContext | None:
        """begin + finish in one call; returns the recorded span's context
        so later spans can still parent to it."""
        ctx = self.begin(parent)
        self.finish(ctx, name, t0, t1, **attrs)
        return ctx

    @contextmanager
    def span(self, parent: TraceContext | None, name: str, **attrs):
        """Measure a block as a child span; yields the child context (None
        when not tracing, so nested call sites stay guard-free)."""
        ctx = self.begin(parent)
        if ctx is None:
            yield None
            return
        t0 = time.perf_counter()
        try:
            yield ctx
        finally:
            self.finish(ctx, name, t0, time.perf_counter(), **attrs)

    def counter_sample(self, track: str, values: dict,
                       t: float | None = None) -> None:
        """Record one sample on a Perfetto counter track (memory_bytes per
        owner, KV occupancy, ...). Exported as a ``ph: "C"`` event so the
        trace UI draws a stacked area chart alongside the span tracks."""
        if not self.enabled or not values:
            return
        self._counters.append({
            "track": track,
            "t": time.perf_counter() if t is None else t,
            "values": {str(k): float(v) for k, v in values.items()},
        })

    # -------------------------------------------------------------- export
    def snapshot(self, trace_id: str | None = None) -> list[dict]:
        """Finished spans currently in the ring (oldest first), optionally
        filtered to one trace."""
        spans = list(self._ring)
        if trace_id:
            spans = [s for s in spans if s["trace_id"] == trace_id]
        return spans

    def spill_state(self) -> dict:
        """Everything another process needs to stitch this ring onto a
        shared timeline: the spans + counter samples and the
        ``(perf_counter, unix)`` epoch anchor pair recorded at configure
        time (``telemetry/fleet.py`` maps ``t0`` stamps onto the fleet
        clock as ``epoch_unix + (t0 - epoch_pc)``)."""
        return {
            "epoch_pc": self._epoch_pc,
            "epoch_unix": self._epoch_unix,
            "spans": self.snapshot(),
            "counters": list(self._counters),
        }

    def export_chrome(self, trace_id: str | None = None) -> dict:
        """Chrome trace-event JSON (Perfetto-loadable): one ``ph: "X"``
        complete event per span, microsecond timestamps relative to the
        tracer epoch, thread ids preserved so per-thread tracks nest by
        timestamp containment."""
        pid = os.getpid()
        events = []
        for s in self.snapshot(trace_id):
            args = dict(s["attrs"])
            args["trace_id"] = s["trace_id"]
            args["span_id"] = s["span_id"]
            if s["parent_id"]:
                args["parent_id"] = s["parent_id"]
            events.append({
                "name": s["name"], "ph": "X", "cat": "request",
                "ts": (s["t0"] - self._epoch_pc) * 1e6,
                "dur": s["dur_s"] * 1e6,
                "pid": pid, "tid": s["tid"], "args": args,
            })
        for c in list(self._counters):
            events.append({
                "name": c["track"], "ph": "C", "cat": "memory",
                "ts": (c["t"] - self._epoch_pc) * 1e6,
                "pid": pid, "args": c["values"],
            })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "epoch_unix_s": self._epoch_unix,
                "spans": len(events),
            },
        }

    def dump(self, path: str, trace_id: str | None = None) -> dict:
        trace = self.export_chrome(trace_id)
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(trace, f)
        return trace
