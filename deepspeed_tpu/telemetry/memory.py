"""Per-step HBM watermark sampler backed by ``accelerator.memory_stats()``.

On TPU the stats come from ``device.memory_stats()`` (bytes_in_use /
bytes_limit / peak_bytes_in_use, plus allocator extras like bytes_reserved
and largest_free_block_bytes where the backend reports them); the CPU test
accelerator reports ru_maxrss. Sampling is a host-side dict read — it never
syncs the device — so it is safe to run every step while the async dispatch
pipeline is in flight.

Gauges come in two shapes: the legacy unlabeled aggregates (device 0 /
process, kept for dashboard continuity) and per-device labeled series
(``hbm_device_bytes_in_use{device=}`` ...) so a multi-chip host shows which
chip is actually under pressure — a device-0-only watermark hides an OOM
brewing on device 3. ``hbm_fragmentation_bytes`` (bytes_reserved −
bytes_in_use) and ``hbm_largest_free_block_bytes`` surface allocator shape:
plenty of free bytes with a small largest-free-block is exactly the state
where a big KV allocation still fails.
"""

from __future__ import annotations


class HbmWatermarkSampler:
    """Reads accelerator memory stats into gauges + one JSONL gauge record."""

    GAUGES = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
    EXTRA_GAUGES = ("bytes_reserved", "largest_free_block_bytes")

    def __init__(self, telemetry):
        self._telemetry = telemetry
        self._accelerator = None
        self._broken = False

    def sample(self, step: int | None = None) -> dict:
        if self._broken:
            return {}
        if self._accelerator is None:
            from deepspeed_tpu.accelerator.real_accelerator import get_accelerator

            self._accelerator = get_accelerator()
        try:
            per_device = self._accelerator.memory_stats_all_devices() or []
            stats = per_device[0] if per_device else {}
        except Exception:
            # a backend without memory stats must not take down training
            self._broken = True
            return {}
        tel = self._telemetry
        record = {"type": "gauge", "name": "hbm_watermark"}
        if step is not None:
            record["step"] = int(step)
        for key in self.GAUGES:
            if key in stats:
                value = float(stats[key])
                tel.gauge(f"hbm_{key}", "accelerator memory watermark").set(value)
                record[key] = value
        # per-device labeled series + allocator-shape gauges (only where
        # the backend reports them — absent keys emit nothing, preserving
        # the no-stats-backend silence guarantee above)
        for idx, dev in enumerate(per_device):
            label = str(idx)
            for key in self.GAUGES:
                if key in dev:
                    tel.gauge(
                        f"hbm_device_{key}",
                        "per-device accelerator memory watermark",
                    ).set(float(dev[key]), device=label)
            if "bytes_reserved" in dev and "bytes_in_use" in dev:
                tel.gauge(
                    "hbm_fragmentation_bytes",
                    "allocator bytes reserved but not in use (bytes_reserved"
                    " - bytes_in_use)",
                ).set(float(dev["bytes_reserved"]) - float(dev["bytes_in_use"]),
                      device=label)
            if "largest_free_block_bytes" in dev:
                tel.gauge(
                    "hbm_largest_free_block_bytes",
                    "largest single allocation the backend allocator can "
                    "still satisfy",
                ).set(float(dev["largest_free_block_bytes"]), device=label)
        if "bytes_in_use" in record:
            # MonitorSink plots records with a scalar `value`
            record["value"] = record["bytes_in_use"]
        tel.emit(record)
        return stats
