"""Per-step HBM watermark sampler backed by ``accelerator.memory_stats()``.

On TPU the stats come from ``device.memory_stats()`` (bytes_in_use /
bytes_limit / peak_bytes_in_use); the CPU test accelerator reports ru_maxrss.
Sampling is a host-side dict read — it never syncs the device — so it is safe
to run every step while the async dispatch pipeline is in flight.
"""

from __future__ import annotations


class HbmWatermarkSampler:
    """Reads accelerator memory stats into gauges + one JSONL gauge record."""

    GAUGES = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")

    def __init__(self, telemetry):
        self._telemetry = telemetry
        self._accelerator = None
        self._broken = False

    def sample(self, step: int | None = None) -> dict:
        if self._broken:
            return {}
        if self._accelerator is None:
            from deepspeed_tpu.accelerator.real_accelerator import get_accelerator

            self._accelerator = get_accelerator()
        try:
            stats = self._accelerator.memory_stats() or {}
        except Exception:
            # a backend without memory stats must not take down training
            self._broken = True
            return {}
        tel = self._telemetry
        record = {"type": "gauge", "name": "hbm_watermark"}
        if step is not None:
            record["step"] = int(step)
        for key in self.GAUGES:
            if key in stats:
                value = float(stats[key])
                tel.gauge(f"hbm_{key}", "accelerator memory watermark").set(value)
                record[key] = value
        if "bytes_in_use" in record:
            # MonitorSink plots records with a scalar `value`
            record["value"] = record["bytes_in_use"]
        tel.emit(record)
        return stats
